"""Replica lifecycle for the serving fleet: spawn, watch, restart, drain.

One fleet = one shared workdir + N ``serve`` subprocesses, each launched with
``--port 0`` (the replica binds an ephemeral port and reports it on stdout —
no port races, satellite of server.py's ``bind_ephemeral``) and
``--replica-id i`` (i >= 1: the per-process ledger contract from obs/fleet.py
gives each replica its own ``telemetry-{i}.jsonl`` beside the controller's
canonical ``telemetry.jsonl``, so ``telemetry-report`` merges the whole fleet
from one directory).

:class:`FleetManager` is the resilience supervisor pattern
(resilience/supervisor.py) applied to long-lived replicas instead of a
run-to-completion trainer: a monitor thread reaps dead replicas and relaunches
them with the shared exponential backoff (``resilience.retry.backoff_delay``)
under a per-replica restart budget; a replica that exhausts it is abandoned
(ledgered, never silently) rather than crash-looped forever. Scale-down is a
DRAIN, not a kill: SIGTERM triggers the replica's graceful-drain contract
(accepted requests finish, the final ledger window lands), the router routes
around its ``draining`` status meanwhile, and the monitor reaps the clean
exit. Every lifecycle transition writes a ledger event (``replica_spawn`` /
``replica_ready`` / ``replica_exit`` / ``replica_restart`` /
``replica_drain`` / ``replica_abandoned``).

:class:`ServeFleet` is the whole tier wired together — manager + router
(router.py) + autoscaler (autoscale.py) — behind one ``start()``/
``shutdown()`` pair; the ``serve-fleet`` CLI subcommand is a thin shell
around it. Fault drills ride the existing seam: ``fault_specs={replica_id:
"sigkill@N"}`` passes ``--inject-fault`` to that replica's FIRST launch only
(the relaunch after the drill is clean), which is how the failover tests and
``tools/bench_serve.py --fleet``'s kill soak produce a deterministic
mid-soak replica death.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from tensorflowdistributedlearning_tpu.obs.telemetry import NULL_TELEMETRY
from tensorflowdistributedlearning_tpu.serve.autoscale import (
    FLEET_SCALE_EVENT,
    AutoscaleConfig,
    Autoscaler,
    FleetAutoscaler,
)
from tensorflowdistributedlearning_tpu.serve.registry import (
    DEFAULT_MODEL,
    Registry,
)
from tensorflowdistributedlearning_tpu.serve.router import FleetRouter

logger = logging.getLogger(__name__)

# replica process states
R_STARTING = "starting"
R_LIVE = "live"
R_DRAINING = "draining"
R_BACKOFF = "backoff"  # dead, restart scheduled (non-blocking)
R_ABANDONED = "abandoned"


@dataclasses.dataclass
class FleetConfig:
    """How every replica in the fleet is launched.

    ``artifact_dir`` is the fleet DEFAULT; individual replicas may carry a
    per-replica override (``ReplicaProcess.artifact_dir``, set by
    ``scale_up(artifact_dir=...)``) — the seam the promotion controller
    (serve/promote.py) rolls a candidate artifact through replica by replica.
    An override survives monitor restarts: a canary that dies mid-rollout
    relaunches on the SAME candidate artifact, never silently reverts."""

    artifact_dir: str
    workdir: str
    host: str = "127.0.0.1"
    buckets: Sequence[int] = (1, 4, 16, 64)
    max_wait_ms: float = 5.0
    queue_size: int = 256
    window_secs: float = 15.0
    default_deadline_ms: Optional[float] = None
    slo_p99_ms: Optional[float] = None
    slo_error_budget: float = 0.01
    # supervisor knobs (resilience pattern): per-replica restart budget +
    # the shared backoff schedule
    max_restarts_per_replica: int = 3
    backoff_base_s: float = 0.5
    backoff_max_s: float = 10.0
    spawn_timeout_s: float = 180.0
    # replica_id -> --inject-fault spec for that replica's FIRST launch
    # (drills: "sigkill@N" kills it after N answered requests; restarts are
    # clean so the drill converges instead of crash-looping)
    fault_specs: Optional[Dict[int, str]] = None
    # continuous-learning arms (loop/): when capture_dir is set every
    # replica runs the traffic-capture tee into its OWN subdir
    # ({capture_dir}/replica-{id} — shard sequences stay disjoint; ingest
    # walks recursively), and drift_threshold arms each replica's
    # DriftMonitor against the artifact's stamped baseline
    capture_dir: Optional[str] = None
    capture_fraction: float = 1.0
    capture_quota_mb: float = 64.0
    capture_records_per_shard: int = 64
    drift_threshold: Optional[float] = None
    drift_min_requests: int = 20
    drift_sustain_windows: int = 2
    # shared persistent compile cache (utils/compile_cache.py): every
    # replica points its XLA compiles here, so the FIRST replica of a shape
    # pays the ladder compile and every later spawn (scale-up surge,
    # restart, promotion canary) loads it — time_to_ready_s on the
    # replica_ready event is the measured win
    compile_cache_dir: Optional[str] = None
    # extra environment for replica processes (the bench pins XLA's CPU
    # threading here so replica scaling is honest on a shared host)
    extra_env: Optional[Dict[str, str]] = None
    python: str = sys.executable
    # multi-tenant mode: a loaded serve.registry.Registry. Non-implicit
    # registries make the fleet model-aware — each entry spawns its own
    # replica set (`entry.replicas` of them) with per-entry artifact dir,
    # bucket ladder, SLO, prewarm budget, and visible-device slots;
    # ``artifact_dir`` above then only backs the legacy/implicit path.
    registry: Optional[Registry] = None


class ReplicaProcess:
    """Handle on one replica subprocess."""

    def __init__(self, replica_id: int):
        self.replica_id = int(replica_id)
        self.process: Optional[subprocess.Popen] = None
        self.url: Optional[str] = None
        self.state = R_STARTING
        self.restarts = 0
        self.started_t = time.monotonic()
        self.ready = threading.Event()
        self.exit_code: Optional[int] = None
        # when a scheduled restart becomes due (R_BACKOFF); deadlines, not
        # sleeps, so one replica's backoff never stalls supervision of the
        # rest of the fleet
        self.restart_at: Optional[float] = None
        self.restart_backoff_s: float = 0.0
        # per-replica artifact override (None = the fleet default): persists
        # across restarts, so a promoted canary stays on its candidate
        self.artifact_dir: Optional[str] = None
        # multi-tenant: the registry model this replica serves (None = the
        # legacy single-artifact fleet) and its visible-device mask — both
        # persist across restarts, so a relaunched replica keeps serving the
        # same tenant on the same chips
        self.model: Optional[str] = None
        self.device_mask: Optional[str] = None
        # fault drill for this replica's FIRST launch only (scale_up path)
        self.pending_fault_spec: Optional[str] = None
        # a drain was explicitly requested (scale_down): the decision is
        # final — the monitor must never restart this replica, even if its
        # death raced the reaper into the backoff/restart path
        self.drain_requested = False

    @property
    def pid(self) -> Optional[int]:
        return self.process.pid if self.process is not None else None

    def snapshot(self) -> Dict:
        out = {
            "replica": self.replica_id,
            "state": self.state,
            "url": self.url,
            "pid": self.pid,
            "restarts": self.restarts,
        }
        if self.artifact_dir is not None:
            out["artifact_dir"] = self.artifact_dir
        if self.model is not None:
            out["model"] = self.model
        if self.device_mask is not None:
            out["device_mask"] = self.device_mask
        return out


class FleetManager:
    """Spawns and supervises N ``serve`` replica subprocesses."""

    def __init__(self, config: FleetConfig, *, telemetry=None, seed: int = 0):
        import random

        self.config = config
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self._replicas: Dict[int, ReplicaProcess] = {}
        self._next_id = 1  # the controller is ledger process 0
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._rng = random.Random(seed)  # restart-backoff jitter
        # per-model spawn ordinal: drives round-robin over the entry's
        # declared device_slots so replica i of a model lands on slot
        # i % len(slots) (restarts keep their mask — only fresh spawns draw)
        self._model_ordinals: Dict[str, int] = {}
        os.makedirs(config.workdir, exist_ok=True)

    @property
    def multi_model(self) -> bool:
        """True when a non-implicit registry drives per-model replica sets."""
        reg = self.config.registry
        return reg is not None and not reg.implicit

    # -- launch --------------------------------------------------------------

    def _replica_argv(
        self,
        replica_id: int,
        fault_spec: Optional[str],
        artifact_dir: Optional[str] = None,
        model: Optional[str] = None,
        device_mask: Optional[str] = None,
    ) -> List[str]:
        cfg = self.config
        # a model-bound replica launches from its registry entry: the
        # entry's artifact dir / bucket ladder / SLO / prewarm budget
        # override the fleet defaults (an explicit artifact_dir still wins —
        # that is the promotion controller introducing a canary for this
        # model)
        entry = None
        if model is not None and cfg.registry is not None:
            entry = cfg.registry.entry(model)
        default_dir = entry.artifact_dir if entry is not None else cfg.artifact_dir
        buckets = cfg.buckets
        slo_p99_ms = cfg.slo_p99_ms
        slo_error_budget = cfg.slo_error_budget
        if entry is not None:
            if entry.buckets:
                buckets = entry.buckets
            if entry.slo_p99_ms is not None:
                slo_p99_ms = entry.slo_p99_ms
            if entry.slo_error_budget is not None:
                slo_error_budget = entry.slo_error_budget
        argv = [
            cfg.python, "-m", "tensorflowdistributedlearning_tpu", "serve",
            "--artifact-dir", artifact_dir or default_dir,
            "--workdir", cfg.workdir,
            "--host", cfg.host,
            "--port", "0",
            "--replica-id", str(replica_id),
            "--window-secs", str(cfg.window_secs),
            "--max-wait-ms", str(cfg.max_wait_ms),
            "--queue-size", str(cfg.queue_size),
            "--buckets", *[str(b) for b in buckets],
        ]
        if entry is not None:
            argv += [
                "--model", entry.name,
                "--model-version", str(entry.version),
            ]
            if entry.prewarm_budget is not None:
                argv += ["--prewarm-buckets", str(entry.prewarm_budget)]
        if device_mask:
            argv += ["--visible-devices", device_mask]
        if cfg.default_deadline_ms is not None:
            argv += ["--default-deadline-ms", str(cfg.default_deadline_ms)]
        if slo_p99_ms is not None:
            argv += [
                "--slo-p99-ms", str(slo_p99_ms),
                "--slo-error-budget", str(slo_error_budget),
            ]
        if cfg.capture_dir:
            argv += [
                "--capture-dir",
                os.path.join(cfg.capture_dir, f"replica-{replica_id}"),
                "--capture-fraction", str(cfg.capture_fraction),
                "--capture-quota-mb", str(cfg.capture_quota_mb),
                "--capture-records-per-shard",
                str(cfg.capture_records_per_shard),
            ]
        if cfg.drift_threshold is not None:
            argv += [
                "--drift-threshold", str(cfg.drift_threshold),
                "--drift-min-requests", str(cfg.drift_min_requests),
                "--drift-sustain-windows", str(cfg.drift_sustain_windows),
            ]
        if cfg.compile_cache_dir:
            argv += ["--compile-cache-dir", cfg.compile_cache_dir]
        if fault_spec:
            argv += ["--inject-fault", fault_spec]
        return argv

    def _spawn(
        self,
        replica_id: int,
        *,
        restart_of: Optional[ReplicaProcess] = None,
        artifact_dir: Optional[str] = None,
        fault_spec: Optional[str] = None,
        model: Optional[str] = None,
        device_mask: Optional[str] = None,
    ) -> ReplicaProcess:
        cfg = self.config
        rep = restart_of if restart_of is not None else ReplicaProcess(replica_id)
        if restart_of is None:
            rep.artifact_dir = artifact_dir
            rep.model = model
            rep.device_mask = device_mask
            rep.pending_fault_spec = fault_spec
        rep.state = R_STARTING
        rep.url = None
        rep.ready.clear()
        rep.exit_code = None
        rep.started_t = time.monotonic()
        # fault drills apply to the FIRST launch only — a restarted replica
        # relaunches clean, so a kill drill converges instead of crash-looping
        fault_spec = None
        if restart_of is None:
            if cfg.fault_specs:
                fault_spec = cfg.fault_specs.get(replica_id)
            if fault_spec is None and rep.pending_fault_spec:
                fault_spec = rep.pending_fault_spec
        rep.pending_fault_spec = None
        argv = self._replica_argv(
            replica_id,
            fault_spec,
            artifact_dir=rep.artifact_dir,
            model=rep.model,
            device_mask=rep.device_mask,
        )
        env = dict(os.environ)
        # the child runs `-m tensorflowdistributedlearning_tpu`: make the
        # package importable even when the repo is used from a checkout
        # (tests, dev boxes) rather than a pip install
        pkg_root = os.path.dirname(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        )
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        if cfg.extra_env:
            env.update(cfg.extra_env)
        log_path = os.path.join(cfg.workdir, f"replica-{replica_id}.log")
        log_fh = open(log_path, "ab")
        try:
            rep.process = subprocess.Popen(
                argv,
                stdout=subprocess.PIPE,
                stderr=log_fh,
                env=env,
                text=True,
            )
        finally:
            # Popen dup'd the fd; the parent's handle is no longer needed
            log_fh.close()
        threading.Thread(
            target=self._read_stdout,
            args=(rep, rep.process),
            name=f"replica-{replica_id}-stdout",
            daemon=True,
        ).start()
        self.telemetry.event(
            "replica_spawn",
            replica=replica_id,
            pid=rep.process.pid,
            restart=rep.restarts,
            fault_spec=fault_spec,
            artifact_dir=rep.artifact_dir or cfg.artifact_dir,
            model=rep.model,
            device_mask=rep.device_mask,
        )
        return rep

    def _read_stdout(self, rep: ReplicaProcess, process: subprocess.Popen) -> None:
        """Consume the replica's stdout: the first JSON line carrying
        ``serving`` is the readiness report (with the ephemerally-bound
        endpoint); everything is drained so the pipe can never fill."""
        try:
            for line in process.stdout:
                line = line.strip()
                if not line.startswith("{"):
                    continue
                try:
                    obj = json.loads(line)
                except ValueError:
                    continue
                if "serving" in obj and not rep.ready.is_set():
                    rep.url = obj["serving"]
                    rep.state = R_LIVE
                    rep.ready.set()
                    self.telemetry.event(
                        "replica_ready",
                        replica=rep.replica_id,
                        endpoint=rep.url,
                        pid=process.pid,
                        port=obj.get("port"),
                        # spawn→readiness-line wall time: interpreter boot +
                        # artifact load + ladder warmup — the cold-start
                        # metric the compile cache exists to shrink
                        time_to_ready_s=round(
                            time.monotonic() - rep.started_t, 3
                        ),
                    )
        except (OSError, ValueError):
            pass

    def _draw_device_mask(self, model: Optional[str]) -> Optional[str]:
        """Next visible-device mask for a fresh replica of ``model`` —
        round-robin over the entry's device_slots. Caller holds the lock."""
        if model is None or self.config.registry is None:
            return None
        entry = self.config.registry.entry(model)
        ordinal = self._model_ordinals.get(model, 0)
        self._model_ordinals[model] = ordinal + 1
        return entry.device_slot(ordinal)

    def start(self, n: int) -> "FleetManager":
        """Spawn the fleet, wait for every replica to report ready, start
        the monitor. Raises if any replica fails to come up in time.

        Legacy single-artifact fleets spawn ``n`` identical replicas. With a
        non-implicit registry, each model entry spawns its OWN replica set
        (``entry.replicas`` of them) and ``n`` is ignored — the registry is
        the fleet plan."""
        with self._lock:
            plan: List[Optional[str]] = [None] * n
            if self.multi_model:
                plan = [
                    entry.name
                    for entry in self.config.registry.models.values()
                    for _ in range(entry.replicas)
                ]
            reps = []
            for model in plan:
                rid = self._next_id
                self._next_id += 1
                rep = self._spawn(
                    rid,
                    model=model,
                    device_mask=self._draw_device_mask(model),
                )
                self._replicas[rid] = rep
                reps.append(rep)
        deadline = time.monotonic() + self.config.spawn_timeout_s
        for rep in reps:
            if not rep.ready.wait(max(0.1, deadline - time.monotonic())):
                self.shutdown(drain=False)
                raise RuntimeError(
                    f"replica {rep.replica_id} not ready after "
                    f"{self.config.spawn_timeout_s}s — see "
                    f"{self.config.workdir}/replica-{rep.replica_id}.log"
                )
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="fleet-monitor", daemon=True
        )
        self._monitor.start()
        return self

    # -- live view -----------------------------------------------------------

    def replicas(self) -> List[ReplicaProcess]:
        with self._lock:
            return list(self._replicas.values())

    def endpoints(self) -> List[Tuple[int, str]]:
        """What the router balances over: ready, non-draining replicas."""
        return [
            (rep.replica_id, rep.url)
            for rep in self.replicas()
            if rep.url is not None and rep.state == R_LIVE
        ]

    def counts(self) -> Dict[str, int]:
        by_state: Dict[str, int] = {}
        for rep in self.replicas():
            by_state[rep.state] = by_state.get(rep.state, 0) + 1
        return by_state

    def starting_by_model(self) -> Dict[str, int]:
        """Warming replicas per model — the in-flight capacity the per-model
        autoscaler must count so it never double-orders during a warmup."""
        out: Dict[str, int] = {}
        for rep in self.replicas():
            if rep.state == R_STARTING:
                key = rep.model or DEFAULT_MODEL
                out[key] = out.get(key, 0) + 1
        return out

    # -- scaling -------------------------------------------------------------

    def scale_up(
        self,
        artifact_dir: Optional[str] = None,
        fault_spec: Optional[str] = None,
        model: Optional[str] = None,
    ) -> int:
        """Spawn one more replica (returns its id). Non-blocking: the replica
        warms in the background and joins ``endpoints()`` when ready.
        ``artifact_dir`` overrides the fleet default for THIS replica (and
        its restarts) — how the promotion controller introduces a canary;
        ``fault_spec`` rides its first launch only (drills); ``model`` binds
        the replica to that registry entry (multi-tenant fleets)."""
        with self._lock:
            rid = self._next_id
            self._next_id += 1
            rep = self._spawn(
                rid,
                artifact_dir=artifact_dir,
                fault_spec=fault_spec,
                model=model,
                device_mask=self._draw_device_mask(model),
            )
            self._replicas[rid] = rep
        return rid

    def scale_down(
        self,
        replica_id: Optional[int] = None,
        model: Optional[str] = None,
    ) -> Optional[int]:
        """Drain one replica gracefully (highest-id live one by default;
        ``model`` restricts the pick to that tenant's replica set):
        SIGTERM triggers the serve drain contract, the monitor reaps the
        clean exit. Returns the drained id, or None when nothing matched.

        The drain decision is FINAL: ``drain_requested`` is stamped before
        the signal, and the monitor honors it over its own restart machinery
        — a replica that dies (or already died) while being drained is
        forgotten, never relaunched. A replica currently in restart backoff
        (dead, relaunch scheduled) can also be drained: it has no process to
        signal, so it is simply forgotten and its pending restart cancelled."""
        with self._lock:
            candidates = [
                r
                for r in self._replicas.values()
                if r.state in (R_LIVE, R_STARTING, R_BACKOFF)
            ]
            if model is not None:
                candidates = [r for r in candidates if r.model == model]
            if replica_id is not None:
                candidates = [
                    r for r in candidates if r.replica_id == replica_id
                ]
            elif candidates:
                # never pick a dead-in-backoff replica implicitly: draining
                # a replica that can actually honor SIGTERM beats cancelling
                # a restart the operator cannot see
                signalable = [
                    r
                    for r in candidates
                    if r.state in (R_LIVE, R_STARTING)
                ]
                candidates = signalable or candidates
            if not candidates:
                return None
            rep = max(candidates, key=lambda r: r.replica_id)
            rep.drain_requested = True
            was_backoff = rep.state == R_BACKOFF
            rep.state = R_DRAINING
            if was_backoff:
                # dead already: nothing to signal, cancel the scheduled
                # restart by forgetting the replica outright
                self._replicas.pop(rep.replica_id, None)
        self.telemetry.event(
            "replica_drain", replica=rep.replica_id, pid=rep.pid
        )
        if was_backoff:
            self.telemetry.event(
                "replica_drained", replica=rep.replica_id, rc=rep.exit_code
            )
            return rep.replica_id
        try:
            rep.process.send_signal(signal.SIGTERM)
        except (ProcessLookupError, OSError):
            pass
        return rep.replica_id

    # -- supervision ---------------------------------------------------------

    def _monitor_loop(self) -> None:
        while not self._stop.wait(0.25):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — supervision must never die
                logger.exception("fleet monitor sweep failed")

    def check(self) -> None:
        """One supervision sweep: reap exits, schedule/execute restarts
        (deadline-based backoff — never a sleep, so N replicas dying at once
        recover on the max backoff, not the sum), forget drained replicas."""
        now = time.monotonic()
        for rep in self.replicas():
            if rep.state == R_BACKOFF:
                # the drain decision wins over the reaper: a replica whose
                # death raced an in-flight scale_down into the backoff path
                # must be forgotten, not relaunched
                if rep.drain_requested:
                    self.telemetry.event(
                        "replica_drained",
                        replica=rep.replica_id,
                        rc=rep.exit_code,
                    )
                    with self._lock:
                        self._replicas.pop(rep.replica_id, None)
                    continue
                if now >= (rep.restart_at or 0) and not self._stop.is_set():
                    self._spawn(rep.replica_id, restart_of=rep)
                    self.telemetry.event(
                        "replica_restart",
                        replica=rep.replica_id,
                        attempt=rep.restarts,
                        backoff_s=round(rep.restart_backoff_s, 3),
                    )
                continue
            proc = rep.process
            if proc is None:
                continue
            rc = proc.poll()
            if rc is None:
                continue
            rep.exit_code = rc
            if rep.state == R_DRAINING or rep.drain_requested:
                self.telemetry.event(
                    "replica_drained", replica=rep.replica_id, rc=rc
                )
                with self._lock:
                    self._replicas.pop(rep.replica_id, None)
                continue
            if rep.state == R_ABANDONED:
                continue
            # signal-killed children report -N; surface the conventional form
            rc_conv = 128 - rc if rc < 0 else rc
            self.telemetry.event(
                "replica_exit",
                replica=rep.replica_id,
                rc=rc_conv,
                restarts=rep.restarts,
            )
            if rep.restarts >= self.config.max_restarts_per_replica:
                rep.state = R_ABANDONED
                self.telemetry.event(
                    "replica_abandoned",
                    replica=rep.replica_id,
                    rc=rc_conv,
                    restarts=rep.restarts,
                )
                logger.error(
                    "replica %d abandoned after %d restart(s) (rc=%s)",
                    rep.replica_id, rep.restarts, rc_conv,
                )
                continue
            rep.restarts += 1
            from tensorflowdistributedlearning_tpu.resilience.retry import (
                backoff_delay,
            )

            delay = backoff_delay(
                rep.restarts,
                base_delay_s=self.config.backoff_base_s,
                max_delay_s=self.config.backoff_max_s,
                jitter_frac=0.25,
                rng=self._rng,
            )
            logger.warning(
                "replica %d died (rc=%s) — restart %d/%d in %.2fs",
                rep.replica_id, rc_conv, rep.restarts,
                self.config.max_restarts_per_replica, delay,
            )
            rep.state = R_BACKOFF
            rep.restart_at = now + delay
            rep.restart_backoff_s = delay

    def shutdown(self, drain: bool = True, timeout_s: float = 30.0) -> None:
        """Stop supervision and take the fleet down — SIGTERM everyone (the
        graceful drain) and reap; stragglers past ``timeout_s`` are killed."""
        self._stop.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5)
            self._monitor = None
        reps = self.replicas()
        for rep in reps:
            if rep.process is None or rep.process.poll() is not None:
                continue
            try:
                rep.process.send_signal(
                    signal.SIGTERM if drain else signal.SIGKILL
                )
            except (ProcessLookupError, OSError):
                pass
        deadline = time.monotonic() + timeout_s
        for rep in reps:
            if rep.process is None:
                continue
            try:
                rep.process.wait(max(0.1, deadline - time.monotonic()))
            except subprocess.TimeoutExpired:
                logger.warning(
                    "replica %d did not drain in time — killing",
                    rep.replica_id,
                )
                rep.process.kill()
                try:
                    rep.process.wait(5)
                except subprocess.TimeoutExpired:
                    pass
        with self._lock:
            self._replicas.clear()


class ServeFleet:
    """The whole serving tier: replicas + router + autoscaler, one lifecycle.

    ``start(n)`` brings up n replicas, routes traffic through a
    :class:`FleetRouter`, and (when ``autoscale`` is given) evaluates the
    :class:`Autoscaler` every ``autoscale_interval_s`` against the router's
    live fleet snapshot — each decision is ledgered as a ``fleet_scale``
    event and applied through the manager (spawn / graceful drain)."""

    def __init__(
        self,
        config: FleetConfig,
        *,
        router_host: str = "127.0.0.1",
        router_port: int = 0,
        router_sock=None,
        telemetry=None,
        autoscale: Optional[AutoscaleConfig] = None,
        autoscale_interval_s: float = 2.0,
        poll_interval_s: float = 0.5,
        window_secs: float = 15.0,
        chip_budget: Optional[int] = None,
    ):
        self.config = config
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self.manager = FleetManager(config, telemetry=self.telemetry)
        registry = config.registry
        multi = registry is not None and not registry.implicit
        # multi-tenant: the router sheds by fair-share weight from the
        # registry; every model's weight rides in even if 1.0 (the default)
        model_weights = (
            {name: e.weight for name, e in registry.models.items()}
            if multi
            else None
        )
        self.router = FleetRouter(
            self.manager.endpoints,
            host=router_host,
            port=router_port,
            sock=router_sock,
            telemetry=self.telemetry,
            poll_interval_s=poll_interval_s,
            window_secs=window_secs,
            model_weights=model_weights,
        )
        if autoscale is not None and multi:
            # one state machine per model, each bounded by its entry, all
            # drawing chips from the shared budget
            configs = {}
            chips = {}
            for name, e in registry.models.items():
                configs[name] = dataclasses.replace(
                    autoscale,
                    min_replicas=e.min_replicas,
                    max_replicas=(
                        e.max_replicas
                        if e.max_replicas is not None
                        else max(autoscale.max_replicas, e.min_replicas)
                    ),
                )
                chips[name] = e.chips_per_replica
            self.autoscaler: Optional[object] = FleetAutoscaler(
                configs, chip_budget=chip_budget, chips_per_replica=chips
            )
        elif autoscale is not None:
            self.autoscaler = Autoscaler(autoscale)
        else:
            self.autoscaler = None
        # promotion surface (serve/promote.py): every fleet can roll a
        # candidate artifact through canary/shadow/rollback; the router's
        # /admin/promotion endpoints delegate here (lazy import — promote
        # imports serve pieces, so a module-level import would cycle)
        from tensorflowdistributedlearning_tpu.serve.promote import (
            PromotionController,
        )

        self.promoter = PromotionController(
            self.manager, self.router, telemetry=self.telemetry
        )
        self.router.promoter = self.promoter
        self.autoscale_interval_s = float(autoscale_interval_s)
        self._stop = threading.Event()
        self._autoscale_thread: Optional[threading.Thread] = None
        # shutdown runs from the signal handler's thread AND the CLI's
        # finally block — second entry must be a no-op, not a double drain
        self._shutdown_lock = threading.Lock()
        self._shut_down = False

    @property
    def url(self) -> str:
        return self.router.url

    def start(self, replicas: int) -> "ServeFleet":
        if isinstance(self.autoscaler, Autoscaler):
            cfg = self.autoscaler.config
            replicas = min(max(replicas, cfg.min_replicas), cfg.max_replicas)
        self.manager.start(replicas)
        self.router.start()
        if self.autoscaler is not None:
            self._autoscale_thread = threading.Thread(
                target=self._autoscale_loop, name="fleet-autoscale",
                daemon=True,
            )
            self._autoscale_thread.start()
        fields = {}
        if self.manager.multi_model:
            registry = self.config.registry
            fields["models"] = {
                name: e.replicas for name, e in registry.models.items()
            }
            replicas = sum(fields["models"].values())
        self.telemetry.event(
            "fleet_start",
            router=self.router.url,
            replicas=replicas,
            autoscale=self.autoscaler is not None,
            **fields,
        )
        return self

    def _autoscale_loop(self) -> None:
        while not self._stop.wait(self.autoscale_interval_s):
            try:
                self.autoscale_tick()
            except Exception:  # noqa: BLE001 — scaling must never kill serving
                logger.exception("autoscale evaluation failed")

    def autoscale_tick(self) -> Optional[Dict]:
        """One evaluate-and-apply cycle (also driven directly by tests)."""
        # scaling pauses while a promotion is in flight: scale_down drains
        # the highest-id live replica, which mid-promotion is the canary or
        # the newest candidate — the autoscaler would cancel the rollout it
        # cannot see (and the routing-excluded shadow canary inflates the
        # capacity the idle detector divides by). Promotions are short;
        # pressure resumes scaling the moment the controller finishes.
        if getattr(self.router, "promotion_active", False):
            return None
        snapshot = self.router.fleet_snapshot()
        if isinstance(self.autoscaler, FleetAutoscaler):
            return self._autoscale_tick_multi(snapshot)
        # the router only sees replicas the manager lists as ready, so a
        # spawn still warming (manager state "starting") is invisible to it
        # — merge it in, or the scaler double-orders during every warmup
        snapshot["starting"] = snapshot.get("starting", 0) + (
            self.manager.counts().get(R_STARTING, 0)
        )
        decision = self.autoscaler.evaluate(snapshot)
        if decision is None:
            return None
        # ledger BEFORE applying: if the spawn/drain dies, the intent is
        # still on record
        self.telemetry.event(FLEET_SCALE_EVENT, **decision)
        # apply the FULL delta (the no_capacity emergency jumps straight to
        # min_replicas, not by one)
        delta = decision["to_replicas"] - decision["from_replicas"]
        if decision["action"] == "scale_up":
            for _ in range(max(1, delta)):
                self.manager.scale_up()
        else:
            for _ in range(max(1, -delta)):
                self.manager.scale_down()
        logger.info(
            "fleet_scale: %s %d -> %d (%s)",
            decision["action"], decision["from_replicas"],
            decision["to_replicas"], decision["reason"],
        )
        return decision

    def _autoscale_tick_multi(self, snapshot: Dict) -> Optional[List[Dict]]:
        """Multi-tenant tick: one decision per model, each ledgered and
        applied to THAT model's replica set. ``budget_deferred`` decisions
        are ledgered but apply nothing — the chip budget refused the grow."""
        decisions = self.autoscaler.evaluate(
            snapshot, starting_by_model=self.manager.starting_by_model()
        )
        for decision in decisions:
            # ledger BEFORE applying, same contract as the legacy path
            self.telemetry.event(FLEET_SCALE_EVENT, **decision)
            model = decision["model"]
            delta = decision["to_replicas"] - decision["from_replicas"]
            if decision["action"] == "scale_up":
                for _ in range(max(1, delta)):
                    self.manager.scale_up(model=model)
            elif decision["action"] == "scale_down":
                for _ in range(max(1, -delta)):
                    self.manager.scale_down(model=model)
            logger.info(
                "fleet_scale[%s]: %s %d -> %d (%s)",
                model, decision["action"], decision["from_replicas"],
                decision["to_replicas"], decision["reason"],
            )
        return decisions or None

    def wait(self) -> None:
        self.router.wait()

    def install_signal_handlers(self, signals=None) -> None:
        """SIGTERM/SIGINT = drain the whole fleet then stop the router."""
        import signal as signal_lib

        for sig in signals or (signal_lib.SIGINT, signal_lib.SIGTERM):
            signal_lib.signal(sig, lambda *_: threading.Thread(
                target=self.shutdown, daemon=True
            ).start())

    def shutdown(self) -> None:
        with self._shutdown_lock:
            if self._shut_down:
                return
            self._shut_down = True
        self._stop.set()
        if self._autoscale_thread is not None:
            self._autoscale_thread.join(timeout=5)
            self._autoscale_thread = None
        # an in-flight promotion stops promptly (no rollback: the replicas
        # are being drained out from under it anyway)
        self.promoter.close()
        self.manager.shutdown(drain=True)
        self.router.shutdown()
        self.telemetry.event("fleet_stop")
