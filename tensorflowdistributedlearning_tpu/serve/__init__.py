"""Dynamic-batching inference engine: the serving layer of the stack.

``train/serving.py`` ends at a self-contained StableHLO artifact; this package
is the runtime that turns concurrent client requests into efficient TPU
batches against it, built around the two facts production TPU serving is
designed by (Gemma-on-TPU, arXiv:2605.25645; pjit/TPUv4, arXiv:2204.06514):
batching is where the throughput is, and post-warmup recompiles are where the
goodput goes.

- ``serve.engine``  — :class:`InferenceEngine`: pads requests into a fixed
  ladder of batch buckets (default 1/4/16/64), pre-warms every bucket so
  steady state never compiles, counts per-bucket hits;
- ``serve.batcher`` — :class:`MicroBatcher`: bounded-queue micro-batching
  (``max_batch_size`` / ``max_wait_ms`` coalescing), per-request deadlines,
  explicit backpressure (full queue ⇒ immediate :class:`QueueFullError`);
- ``serve.server``  — :class:`ServingServer`: stdlib ``ThreadingHTTPServer``
  exposing ``/v1/predict`` / ``/healthz`` / ``/metrics``, graceful
  drain-on-shutdown, and ``serve_window`` events in the workdir's
  ``telemetry.jsonl`` (rendered by ``obs.report`` / ``telemetry-report``);
- ``serve.quant_check`` — :func:`run_quant_check`: the accuracy gate between
  a float32 artifact and its bf16/int8 sibling (pinned eval batch,
  per-precision thresholds, ``quant_check`` ledger events).

CLI: ``python -m tensorflowdistributedlearning_tpu serve --artifact-dir D``;
accuracy gate: ``... quantize-check --reference-dir F32 --candidate-dir Q``;
load generator + precision A/B benchmark: ``tools/bench_serve.py [--quant]``.
"""

from tensorflowdistributedlearning_tpu.serve.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    Request,
    ServerClosedError,
)
from tensorflowdistributedlearning_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    InferenceEngine,
    RequestTooLargeError,
)
from tensorflowdistributedlearning_tpu.serve.quant_check import (
    DEFAULT_THRESHOLDS,
    run_quant_check,
)
from tensorflowdistributedlearning_tpu.serve.server import ServingServer

__all__ = [
    "DEFAULT_BUCKETS",
    "DEFAULT_THRESHOLDS",
    "DeadlineExceededError",
    "InferenceEngine",
    "MicroBatcher",
    "QueueFullError",
    "Request",
    "RequestTooLargeError",
    "ServerClosedError",
    "ServingServer",
    "run_quant_check",
]
