"""Dynamic-batching inference engine: the serving layer of the stack.

``train/serving.py`` ends at a self-contained StableHLO artifact; this package
is the runtime that turns concurrent client requests into efficient TPU
batches against it, built around the two facts production TPU serving is
designed by (Gemma-on-TPU, arXiv:2605.25645; pjit/TPUv4, arXiv:2204.06514):
batching is where the throughput is, and post-warmup recompiles are where the
goodput goes.

- ``serve.engine``  — :class:`InferenceEngine`: pads requests into a fixed
  ladder of batch buckets (default 1/4/16/64), pre-warms every bucket so
  steady state never compiles, counts per-bucket hits;
- ``serve.batcher`` — :class:`MicroBatcher`: bounded-queue micro-batching
  (``max_batch_size`` / ``max_wait_ms`` coalescing), per-request deadlines,
  explicit backpressure (full queue ⇒ immediate :class:`QueueFullError`);
- ``serve.server``  — :class:`ServingServer`: stdlib ``ThreadingHTTPServer``
  exposing ``/v1/predict`` / ``/healthz`` / ``/metrics``, graceful
  drain-on-shutdown, and ``serve_window`` events in the workdir's
  ``telemetry.jsonl`` (rendered by ``obs.report`` / ``telemetry-report``);
- ``serve.quant_check`` — :func:`run_quant_check`: the accuracy gate between
  a float32 artifact and its bf16/int8 sibling (pinned eval batch,
  per-precision thresholds, ``quant_check`` ledger events);
- ``serve.fleet``   — :class:`FleetManager` / :class:`ServeFleet`: N replica
  subprocesses (each ``serve --port 0 --replica-id i`` against a shared
  workdir) supervised with restart-on-death and graceful scale-down drain;
- ``serve.router``  — :class:`FleetRouter`: the fleet's HTTP front end —
  load-balances ``/v1/predict`` on live queue depth + windowed p99, routes
  around ``draining``/``degraded``/dead replicas, retries accepted requests
  onto survivors, sheds with 429 + ``Retry-After`` at fleet saturation, and
  aggregates fleet-wide ``/healthz`` + ``/metrics``;
- ``serve.autoscale`` — :class:`Autoscaler`: replica count from sustained
  queue depth, SLO degradation, and shed volume; decisions ledgered as
  ``fleet_scale`` events.
- ``serve.promote`` — :class:`PromotionController`: rolls a candidate
  artifact across a live fleet — quantize-check admission, shadow-compared
  canary (the router duplicates a traffic slice, never answers from it),
  replica-by-replica rollout through drain→relaunch→readmit, automatic
  rollback on accuracy/latency regression or crash-loop — every transition
  a ``promotion_*``/``shadow_window`` ledger event.

CLI: ``python -m tensorflowdistributedlearning_tpu serve --artifact-dir D``
(one replica) or ``serve-fleet --artifact-dir D --replicas N`` (the tier);
accuracy gate: ``... quantize-check --reference-dir F32 --candidate-dir Q``;
load generator + precision/fleet benches: ``tools/bench_serve.py [--quant]
[--fleet]``.
"""

from tensorflowdistributedlearning_tpu.serve.autoscale import (
    AutoscaleConfig,
    Autoscaler,
)
from tensorflowdistributedlearning_tpu.serve.batcher import (
    DeadlineExceededError,
    MicroBatcher,
    QueueFullError,
    Request,
    ServerClosedError,
)
from tensorflowdistributedlearning_tpu.serve.engine import (
    DEFAULT_BUCKETS,
    InferenceEngine,
    RequestTooLargeError,
)
from tensorflowdistributedlearning_tpu.serve.fleet import (
    FleetConfig,
    FleetManager,
    ServeFleet,
)
from tensorflowdistributedlearning_tpu.serve.promote import (
    PromoteConfig,
    PromotionController,
)
from tensorflowdistributedlearning_tpu.serve.quant_check import (
    DEFAULT_THRESHOLDS,
    output_delta,
    run_quant_check,
)
from tensorflowdistributedlearning_tpu.serve.router import FleetRouter
from tensorflowdistributedlearning_tpu.serve.server import (
    ServingServer,
    bind_ephemeral,
)

__all__ = [
    "DEFAULT_BUCKETS",
    "DEFAULT_THRESHOLDS",
    "AutoscaleConfig",
    "Autoscaler",
    "DeadlineExceededError",
    "FleetConfig",
    "FleetManager",
    "FleetRouter",
    "InferenceEngine",
    "MicroBatcher",
    "PromoteConfig",
    "PromotionController",
    "QueueFullError",
    "Request",
    "RequestTooLargeError",
    "ServeFleet",
    "ServerClosedError",
    "ServingServer",
    "bind_ephemeral",
    "output_delta",
    "run_quant_check",
]
