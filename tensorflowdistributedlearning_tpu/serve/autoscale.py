"""Fleet autoscaler: replica count driven by backlog and the SLO error budget.

The router (router.py) knows, every poll, how deep the fleet's queues run,
whether any replica blew its latency SLO (``status: degraded`` — the error
budget the serve tier tracks via ``obs.health.SloTracker``), and how many
requests were shed with 429. This module turns those signals into replica
count decisions; the fleet manager (fleet.py) executes them — spawn on scale
up, graceful drain on scale down.

The state machine is deliberately boring, because flapping autoscalers are
worse than static fleets:

- **pressure** (scale-up signal): mean backlog per unit of capacity at or
  above ``queue_high``, OR any replica SLO-degraded, OR requests shed since
  the last evaluation. Sustained for ``sustain`` consecutive evaluations →
  scale up by one (capacity counts *starting* replicas, so a spawn in
  progress suppresses further scale-ups while it warms);
- **idle** (scale-down signal): mean backlog at or below ``queue_low`` with
  zero degradation and zero shed, sustained for ``sustain`` evaluations →
  scale down by one, executed as a DRAIN (the replica finishes accepted work,
  the router routes around its ``draining`` status, then the process exits);
- everything else is **steady**; a ``cooldown_s`` window after any decision
  blocks the next one, so a scale-up gets to absorb load before the idle
  detector can see the resulting slack and immediately undo it.

Bounds are hard: never below ``min_replicas``, never above ``max_replicas``.
Every decision is returned as a dict the caller ledgers as a ``fleet_scale``
event (rendered by ``telemetry-report``) — the scaling history is part of the
run's story, not an operator's memory.
"""

from __future__ import annotations

import dataclasses
import time
from typing import Callable, Dict, List, Optional

FLEET_SCALE_EVENT = "fleet_scale"

STATE_STEADY = "steady"
STATE_PRESSURE = "pressure"
STATE_IDLE = "idle"


@dataclasses.dataclass
class AutoscaleConfig:
    """Knobs of the scale decision (defaults sized for the CLI's cadence of
    one evaluation every couple of seconds)."""

    min_replicas: int = 1
    max_replicas: int = 4
    # mean (queue depth + in-flight) per unit of capacity that counts as
    # pressure / as idle slack
    queue_high: float = 4.0
    queue_low: float = 0.25
    # consecutive evaluations a signal must persist before acting — one
    # bursty poll must not buy a replica
    sustain: int = 3
    # seconds after a decision during which no further decision fires
    cooldown_s: float = 15.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError(
                f"min_replicas must be >= 1, got {self.min_replicas}"
            )
        if self.max_replicas < self.min_replicas:
            raise ValueError(
                f"max_replicas ({self.max_replicas}) < min_replicas "
                f"({self.min_replicas})"
            )
        if self.queue_low >= self.queue_high:
            raise ValueError(
                f"queue_low ({self.queue_low}) must be < queue_high "
                f"({self.queue_high})"
            )
        if self.sustain < 1:
            raise ValueError(f"sustain must be >= 1, got {self.sustain}")


class Autoscaler:
    """Pure decision core: feed it fleet snapshots, get scale decisions.

    ``evaluate`` consumes the router's ``fleet_snapshot()`` shape (``live``,
    ``starting``, ``degraded``, ``queue_depth_total``, ``shed_total``) and
    returns a decision dict or None. It owns no threads and touches no
    processes — the ServeFleet loop (fleet.py) applies what it decides, which
    is what makes the state machine unit-testable clock-by-clock."""

    def __init__(
        self,
        config: Optional[AutoscaleConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.config = config if config is not None else AutoscaleConfig()
        self._clock = clock
        self._high_streak = 0
        self._low_streak = 0
        self._last_decision_t: Optional[float] = None
        self._last_shed_total = 0
        self.state = STATE_STEADY
        self.decisions: List[Dict] = []

    def evaluate(self, snapshot: Dict) -> Optional[Dict]:
        """One evaluation tick. ``snapshot`` keys consumed: ``live`` (ok +
        degraded replica count), ``starting``, ``degraded``,
        ``queue_depth_total``, ``shed_total`` (cumulative router 429s)."""
        cfg = self.config
        live = int(snapshot.get("live", 0))
        starting = int(snapshot.get("starting", 0))
        degraded = int(snapshot.get("degraded", 0))
        queue_total = float(snapshot.get("queue_depth_total", 0.0))
        shed_total = int(snapshot.get("shed_total", 0))
        shed_delta = max(0, shed_total - self._last_shed_total)
        self._last_shed_total = shed_total

        # capacity includes starting replicas: a spawn already in flight is
        # the response to pressure — do not double-order
        capacity = live + starting
        mean_queue = queue_total / max(1, capacity)
        pressure = (
            mean_queue >= cfg.queue_high or degraded > 0 or shed_delta > 0
        )
        idle = (
            mean_queue <= cfg.queue_low and degraded == 0 and shed_delta == 0
        )
        if pressure:
            self._high_streak += 1
            self._low_streak = 0
            self.state = STATE_PRESSURE
        elif idle:
            self._low_streak += 1
            self._high_streak = 0
            self.state = STATE_IDLE
        else:
            self._high_streak = self._low_streak = 0
            self.state = STATE_STEADY

        now = self._clock()
        # no capacity at all (everything died at once) is an emergency that
        # bypasses BOTH the sustain counter and the cooldown — the fleet
        # manager's restart path normally beats this, but the scaler must
        # never be the reason a dead fleet stays dead
        if capacity == 0 and cfg.min_replicas > 0:
            return self._decide(
                "scale_up", capacity, cfg.min_replicas, "no_capacity",
                mean_queue, shed_delta, degraded, now,
            )
        if (
            self._last_decision_t is not None
            and now - self._last_decision_t < cfg.cooldown_s
        ):
            return None
        if self._high_streak >= cfg.sustain and capacity < cfg.max_replicas:
            reason = (
                "shed"
                if shed_delta
                else ("slo_degraded" if degraded else "queue_depth")
            )
            return self._decide(
                "scale_up", capacity, capacity + 1, reason,
                mean_queue, shed_delta, degraded, now,
            )
        if self._low_streak >= cfg.sustain and capacity > cfg.min_replicas:
            return self._decide(
                "scale_down", capacity, capacity - 1, "idle",
                mean_queue, shed_delta, degraded, now,
            )
        return None

    def _decide(
        self,
        action: str,
        from_replicas: int,
        to_replicas: int,
        reason: str,
        mean_queue: float,
        shed_delta: int,
        degraded: int,
        now: float,
    ) -> Dict:
        self._last_decision_t = now
        self._high_streak = self._low_streak = 0
        decision = {
            "action": action,
            "from_replicas": from_replicas,
            "to_replicas": to_replicas,
            "reason": reason,
            "mean_queue_depth": round(mean_queue, 3),
            "shed_delta": shed_delta,
            "slo_degraded_replicas": degraded,
            "sustain": self.config.sustain,
        }
        self.decisions.append(decision)
        return decision


class FleetAutoscaler:
    """Per-model autoscaling inside one fleet-wide chip budget.

    One :class:`Autoscaler` state machine per registry model (each with its
    own min/max bounds from the model's entry), all drawing replicas from a
    shared pool of chips: a model may scale up only while the fleet's total
    chip claim (``sum over models of replicas * chips_per_replica``) stays
    within ``chip_budget``. A scale-up the budget refuses is returned as an
    explicit ``budget_deferred`` decision (ledgered, not silently dropped) —
    the pressure signal persists, so the capacity is granted the moment
    another model's idle detector releases chips.

    ``evaluate`` consumes the router ``fleet_snapshot()`` with its
    ``models`` sub-dict (per-model live replicas / backlog / shed counters)
    plus the manager's per-model starting counts, and returns the list of
    decisions for this tick, each stamped with its ``model``."""

    def __init__(
        self,
        configs: Dict[str, AutoscaleConfig],
        *,
        chip_budget: Optional[int] = None,
        chips_per_replica: Optional[Dict[str, int]] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if not configs:
            raise ValueError("FleetAutoscaler needs at least one model config")
        self.scalers: Dict[str, Autoscaler] = {
            name: Autoscaler(cfg, clock=clock)
            for name, cfg in configs.items()
        }
        self.chip_budget = chip_budget
        self.chips_per_replica = dict(chips_per_replica or {})
        min_claim = sum(
            cfg.min_replicas * self.chips(name)
            for name, cfg in configs.items()
        )
        if chip_budget is not None and min_claim > chip_budget:
            raise ValueError(
                f"chip_budget {chip_budget} cannot satisfy the models' "
                f"min_replicas floor ({min_claim} chips)"
            )

    def chips(self, model: str) -> int:
        return int(self.chips_per_replica.get(model, 1))

    def _fleet_chips(self, capacities: Dict[str, int]) -> int:
        return sum(n * self.chips(m) for m, n in capacities.items())

    def evaluate(
        self,
        snapshot: Dict,
        *,
        starting_by_model: Optional[Dict[str, int]] = None,
    ) -> List[Dict]:
        """One tick over every model. ``snapshot["models"]`` rows supply the
        per-model signals; models with no row yet (fleet still warming)
        evaluate on zeros, which keeps their min-replica floor enforced."""
        models = snapshot.get("models") or {}
        starting_by_model = starting_by_model or {}
        # capacity census BEFORE any decision: budget math sees the whole
        # fleet, not just the model being evaluated
        capacities = {
            name: int((models.get(name) or {}).get("replicas", 0))
            + int(starting_by_model.get(name, 0))
            for name in self.scalers
        }
        decisions: List[Dict] = []
        for name, scaler in self.scalers.items():
            row = models.get(name) or {}
            sub = {
                "live": int(row.get("replicas", 0)),
                "starting": int(starting_by_model.get(name, 0)),
                # per-model degraded signal: worst replica p99 over the
                # model's own SLO rides in through "degraded" rows when the
                # poller saw them; absent = 0
                "degraded": int(row.get("degraded", 0)),
                "queue_depth_total": float(row.get("queue_depth", 0.0)),
                "shed_total": int(row.get("shed", 0)),
            }
            decision = scaler.evaluate(sub)
            if decision is None:
                continue
            decision["model"] = name
            if decision["action"] == "scale_up":
                grow = decision["to_replicas"] - decision["from_replicas"]
                claimed = self._fleet_chips(capacities)
                needed = grow * self.chips(name)
                if (
                    self.chip_budget is not None
                    and claimed + needed > self.chip_budget
                ):
                    # refuse within budget — explicit, ledgered, retried on
                    # a later tick once chips free up
                    decision["action"] = "budget_deferred"
                    decision["to_replicas"] = decision["from_replicas"]
                    decision["chip_budget"] = self.chip_budget
                    decision["chips_claimed"] = claimed
                    decision["chips_needed"] = needed
                else:
                    capacities[name] += grow
            elif decision["action"] == "scale_down":
                capacities[name] += (
                    decision["to_replicas"] - decision["from_replicas"]
                )
            decisions.append(decision)
        return decisions
