"""Dynamic micro-batching with bounded queueing and explicit backpressure.

The engine (engine.py) makes one batch cheap; this layer decides *which*
requests share it. Concurrent callers submit individually; a single worker
thread coalesces whatever is queued into the largest batch that fits
(``max_batch_size``), waiting at most ``max_wait_ms`` after the first request
so a lone request still meets its latency budget — the classic
throughput/latency dial of server-side batching (TF-Serving's BatchingSession;
Gemma-on-TPU, arXiv:2605.25645 §4).

Batching is **continuous** (the default): the coalesce window is anchored at
the FIRST queued request's *enqueue* time, not at the moment the worker gets
around to it. Requests that arrived while the previous batch was computing
have therefore already spent their coalesce budget waiting, so the next
dispatch goes out immediately instead of idling the device for a fresh
``max_wait_ms`` — under sustained load the worker alternates compute/collect
with zero inserted waits (admission into "the next bucket dispatch", the
continuous-batching semantics of production inference servers). A request
arriving at an idle server still waits up to ``max_wait_ms`` for companions,
so the lone-request latency contract is unchanged. ``continuous=False``
restores the legacy fixed-window behavior (the A/B baseline
``tools/bench_serve.py`` measures against).

Failure discipline, because an inference server melts down by queueing, not by
crashing:

- the queue is **bounded**: a full queue rejects at ``submit`` time with
  :class:`QueueFullError` — an immediate, structured signal the HTTP layer
  maps to 429 so load sheds at the edge instead of growing resident memory;
- every request may carry a **deadline**: requests that expire while queued
  are completed with :class:`DeadlineExceededError` *before* wasting a bucket
  slot on an answer nobody is waiting for;
- ``close(drain=True)`` stops intake (``ServerClosedError``) and lets the
  worker finish everything already accepted — the graceful-shutdown half of
  the HTTP server's drain.

Every decision lands in the engine's registry (requests / completed /
rejected_queue_full / deadline_exceeded / errors counters, ``serve/queue_wait``
histogram, ``serve/queue_depth`` gauge), so the queue-wait vs pad vs compute
latency split is readable from one snapshot.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Deque, List, Optional

import numpy as np

from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.serve.engine import (
    InferenceEngine,
    RequestTooLargeError,
    _tree_map,
)

__all__ = [
    "DeadlineExceededError",
    "MicroBatcher",
    "QueueFullError",
    "RequestTooLargeError",
    "ServerClosedError",
]


class QueueFullError(RuntimeError):
    """Bounded queue at capacity — the structured backpressure signal
    (HTTP 429). Raised synchronously in ``submit``; nothing was enqueued."""


class DeadlineExceededError(TimeoutError):
    """The request's deadline passed while it waited in the queue (HTTP 504)."""


class ServerClosedError(RuntimeError):
    """``submit`` after ``close()`` — the server is draining (HTTP 503)."""


class Request:
    """Future-like handle for one submitted request. ``trace`` (optional) is
    the submitting thread's open span context (obs/trace.py) — the worker
    emits this request's queue_wait/pad/compute spans into that trace after
    the batch runs."""

    __slots__ = (
        "x", "n", "deadline_t", "enqueued_t", "trace",
        "_event", "_result", "_error",
    )

    def __init__(
        self,
        x: np.ndarray,
        deadline_t: Optional[float],
        trace: Optional[trace_lib.TraceContext] = None,
    ):
        self.x = x
        self.n = x.shape[0]
        self.deadline_t = deadline_t
        self.enqueued_t = time.monotonic()
        self.trace = trace
        self._event = threading.Event()
        self._result = None
        self._error: Optional[BaseException] = None

    def done(self) -> bool:
        return self._event.is_set()

    def result(self, timeout: Optional[float] = None):
        """Block for the outcome; raises the request's structured error
        (deadline, shutdown, engine failure) if it had one."""
        if not self._event.wait(timeout):
            raise TimeoutError("request still pending after result() timeout")
        if self._error is not None:
            raise self._error
        return self._result

    def _finish(self, result=None, error: Optional[BaseException] = None):
        self._result, self._error = result, error
        self._event.set()


class MicroBatcher:
    """Coalesces concurrent ``submit`` calls into engine-sized batches."""

    def __init__(
        self,
        engine: InferenceEngine,
        *,
        max_batch_size: Optional[int] = None,
        max_wait_ms: float = 5.0,
        max_queue: int = 256,
        default_deadline_ms: Optional[float] = None,
        continuous: bool = True,
    ):
        self.engine = engine
        # continuous batching: the coalesce window is measured from the head
        # request's enqueue time, so backlog built up during a compute
        # dispatches immediately; False = legacy fixed window from collect time
        self.continuous = bool(continuous)
        self.max_batch_size = min(
            max_batch_size or engine.max_batch_size, engine.max_batch_size
        )
        self.max_wait_s = max(0.0, float(max_wait_ms)) / 1000.0
        self.max_queue = int(max_queue)
        if self.max_queue < 1:
            raise ValueError("max_queue must be >= 1")
        self.default_deadline_ms = default_deadline_ms
        self.registry = engine.registry
        # chip-seconds attribution (obs/capacity.py): when a CostMeter is
        # attached (ServingServer does), every dispatched batch's engine time
        # is split across its member requests by batch-share
        self.cost_meter = None
        self._queue: Deque[Request] = collections.deque()
        self._cond = threading.Condition()
        self._closed = False
        self._worker = threading.Thread(
            target=self._run, name="serve-microbatcher", daemon=True
        )
        self._worker.start()

    # -- client side -------------------------------------------------------

    def submit(
        self,
        x,
        *,
        deadline_ms: Optional[float] = None,
        trace: Optional[trace_lib.TraceContext] = None,
    ) -> Request:
        """Enqueue ``x`` ([n, *example_shape] or one bare example); returns a
        :class:`Request` future. Raises immediately — never queues — when the
        batcher is closed, the request exceeds the largest bucket, or the
        queue is at capacity. ``trace`` threads the caller's span context
        through so the worker can attribute queue/pad/compute time back to
        this request's trace."""
        x = np.asarray(x, self.engine.input_dtype)
        if x.shape == self.engine.example_shape:
            x = x[None]
        if x.shape[1:] != self.engine.example_shape or x.shape[0] < 1:
            raise ValueError(
                f"expected [n, *{self.engine.example_shape}] or a bare "
                f"example, got {x.shape}"
            )
        if x.shape[0] > self.max_batch_size:
            raise RequestTooLargeError(
                f"{x.shape[0]} examples exceeds max_batch_size="
                f"{self.max_batch_size}; chunk the request"
            )
        if deadline_ms is None:
            deadline_ms = self.default_deadline_ms
        deadline_t = (
            time.monotonic() + deadline_ms / 1000.0
            if deadline_ms is not None
            else None
        )
        req = Request(x, deadline_t, trace=trace)
        with self._cond:
            if self._closed:
                raise ServerClosedError("batcher is draining; not accepting requests")
            if len(self._queue) >= self.max_queue:
                self.registry.counter("serve/rejected_queue_full").inc()
                raise QueueFullError(
                    f"request queue full ({self.max_queue} pending)"
                )
            self._queue.append(req)
            self.registry.counter("serve/requests").inc()
            self.registry.gauge("serve/queue_depth").set(len(self._queue))
            self._cond.notify()
        return req

    def close(self, drain: bool = True, timeout: Optional[float] = 30.0) -> None:
        """Stop intake; with ``drain`` the worker finishes every accepted
        request, otherwise pending requests complete with
        :class:`ServerClosedError`. Idempotent."""
        with self._cond:
            self._closed = True
            if not drain:
                while self._queue:
                    self._queue.popleft()._finish(
                        error=ServerClosedError("server shut down before dispatch")
                    )
                self.registry.gauge("serve/queue_depth").set(0)
            self._cond.notify_all()
        self._worker.join(timeout)

    # -- worker side -------------------------------------------------------

    def _expire(self, req: Request) -> None:
        self.registry.counter("serve/deadline_exceeded").inc()
        req._finish(
            error=DeadlineExceededError(
                "deadline expired after "
                f"{(time.monotonic() - req.enqueued_t) * 1000:.1f}ms in queue"
            )
        )

    def _collect(self) -> Optional[List[Request]]:
        """One coalescing window: block for a first request, then gather until
        the batch is full, the wait window closes, or the next request would
        overflow the bucket. Returns None only when closed AND drained."""
        batch: List[Request] = []
        total = 0
        window_end: Optional[float] = None
        with self._cond:
            while True:
                now = time.monotonic()
                while self._queue and (
                    self._queue[0].deadline_t is not None
                    and now > self._queue[0].deadline_t
                ):
                    self._expire(self._queue.popleft())
                if self._queue and total + self._queue[0].n <= self.max_batch_size:
                    req = self._queue.popleft()
                    batch.append(req)
                    total += req.n
                    if window_end is None:
                        # continuous batching: the head request's wait budget
                        # started when IT enqueued — time it spent queued
                        # behind the previous batch's compute counts, so a
                        # backlogged dispatch goes out with no inserted wait
                        window_end = (
                            req.enqueued_t if self.continuous else now
                        ) + self.max_wait_s
                    if total >= self.max_batch_size:
                        break
                    continue
                if batch and self._queue:
                    break  # head-of-line request needs the next batch
                if self._closed:
                    if batch:
                        break
                    if not self._queue:
                        return None
                    continue
                if not batch:
                    self._cond.wait()
                    continue
                remaining = window_end - time.monotonic()
                if remaining <= 0:
                    break
                self._cond.wait(remaining)
            self.registry.gauge("serve/queue_depth").set(len(self._queue))
        return batch

    def _execute(self, batch: List[Request]) -> None:
        now = time.monotonic()
        wall_now = time.time()
        wait_h = self.registry.histogram("serve/queue_wait")
        for req in batch:
            wait_h.record(now - req.enqueued_t)
        x = (
            np.concatenate([r.x for r in batch])
            if len(batch) > 1
            else batch[0].x
        )
        # tracing: a batch span (its own trace) wraps the engine call so the
        # engine's pad/compute spans nest under it; kept only when at least
        # one member request's trace is sampled (partial traces are useless)
        tracer = self.engine.tracer
        traced = [
            r for r in batch if tracer.enabled and r.trace is not None
        ]
        sampled = any(r.trace.sampled for r in traced)
        batch_span = None
        if traced:
            for req in traced:
                tracer.emit(
                    trace_lib.SPAN_QUEUE_WAIT,
                    trace_id=req.trace.trace_id,
                    parent_id=req.trace.span_id,
                    start_t=wall_now - (now - req.enqueued_t),
                    duration_s=now - req.enqueued_t,
                    sampled=req.trace.sampled,
                )
        infer_t0 = time.perf_counter()
        try:
            if traced:
                with tracer.span(
                    trace_lib.SPAN_BATCH,
                    sampled=sampled,
                    attrs={
                        "requests": len(batch),
                        "examples": sum(r.n for r in batch),
                    },
                ) as batch_span:
                    out = self.engine.infer(x)
            else:
                out = self.engine.infer(x)
        except Exception as e:  # noqa: BLE001 — fail the requests, not the worker
            self.registry.counter("serve/errors").inc(len(batch))
            for req in batch:
                req._finish(error=e)
            return
        if self.cost_meter is not None:
            self.cost_meter.add_batch(
                time.perf_counter() - infer_t0, [r.n for r in batch]
            )
        if batch_span is not None:
            self._emit_member_spans(tracer, traced, batch_span)
        offset = 0
        for req in batch:
            lo, hi = offset, offset + req.n
            req._finish(result=_tree_map(lambda a: a[lo:hi], out))
            offset = hi
        self.registry.counter("serve/completed").inc(len(batch))
        self.registry.counter("serve/batches").inc()
        self.registry.counter("serve/batched_examples").inc(offset)

    @staticmethod
    def _emit_member_spans(tracer, traced: List[Request], batch_span) -> None:
        """Mirror the batch's pad/compute spans onto each member request's
        trace: the request timeline reads queue→pad→compute end to end, and
        the ``batch_span_id`` attr links each mirrored span to the shared
        batch trace's compute span (one batch serves many requests, so the
        link is an attribute, not a parent edge)."""
        children = {c.name: c for c in batch_span.children}
        compute = children.get(trace_lib.SPAN_COMPUTE)
        for name in (trace_lib.SPAN_PAD, trace_lib.SPAN_COMPUTE):
            child = children.get(name)
            if child is None:
                continue
            link = {
                "batch_trace_id": batch_span.trace_id,
                "batch_span_id": (
                    compute.span_id if compute is not None else batch_span.span_id
                ),
                **child.attrs,
            }
            for req in traced:
                tracer.emit(
                    name,
                    trace_id=req.trace.trace_id,
                    parent_id=req.trace.span_id,
                    start_t=child.start_t,
                    duration_s=child.duration_s,
                    sampled=req.trace.sampled,
                    attrs=link,
                )

    def _run(self) -> None:
        while True:
            batch = self._collect()
            if batch is None:
                return
            self._execute(batch)
