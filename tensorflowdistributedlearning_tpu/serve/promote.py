"""Promotion controller: canary rollout, shadow traffic, automatic rollback.

The missing edge of the production loop: everything upstream ends at an
artifact directory (best-fold export, quantized siblings, ``quantize-check``
gates) and everything downstream starts at a fleet already serving one.
Nothing connected them — a new artifact reached users at full blast or not
at all. This module is the connector, in the deployment discipline of
"TensorFlow: A system for large-scale machine learning" (arXiv:1605.08695)
and the cost-economics framing of the Gemma-on-TPU serving comparison
(arXiv:2605.25645): a regression caught one replica deep is a rounding
error; caught fleet-deep it is an incident.

:class:`PromotionController` drives a live fleet (``serve/fleet.py`` manager
+ ``serve/router.py``) through a phase machine, every transition a ledger
event ``telemetry-report`` renders as a deployment history:

1. **admission** — offline, before any replica moves: the candidate's
   manifest must parse, and when a reference artifact is given the full
   ``quantize-check`` runs (source-fingerprint pairing + per-precision
   accuracy budgets). A refused candidate never touches the fleet.
2. **canary** — one replica is rolled through the router's existing
   drain→relaunch→readmit path onto the candidate artifact (surge style:
   the canary spawns FIRST, so fleet capacity never dips), and its polled
   ``/healthz`` artifact identity must verify as the candidate fingerprint
   before the phase advances.
3. **shadow** — the router duplicates a configurable slice of accepted
   traffic to the canary, compares outputs (mask IoU / disagreement /
   |delta|, ``quant_check.output_delta``) and latency against the serving
   replica, and NEVER answers clients from it. Each window is a
   ``shadow_window`` ledger event; an empty-traffic window HOLDS the phase
   (no divide-by-zero, no advance on no evidence).
4. **rollout** — remaining incumbents are replaced one at a time
   (spawn-candidate → ready+identity-verified → drain-incumbent), each step
   gated on ledgered deltas through ``obs/compare.py`` noise bands.
5. **complete** — the fleet default artifact flips to the candidate, so
   autoscaler spawns and monitor restarts stay on it.

Rollback is automatic — accuracy regression past the shadow budgets, canary
latency regressed past the noise-banded p99 ratio, canary crash-loop, or an
operator abort — and re-drains every candidate replica back to the incumbent
artifact, restoring a replacement BEFORE draining so the fleet never dips
below strength. If the incumbent artifact itself has vanished mid-promotion
(the one case rollback cannot restore), the controller aborts STRUCTURALLY:
it ledgers the abort and leaves the surviving replicas answering — a mixed
or candidate-only fleet beats a dead one.

Drills ride the existing fault seams: the canary's first launch can carry a
``serve --inject-fault`` spec (``sigkill@N`` kills it mid-shadow); the fleet
monitor restarts it on the SAME candidate artifact, the router's retry path
keeps clients whole, and the controller converges — complete or clean
rollback — distinguishing a single death (tolerated) from a crash-loop
(rolled back).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Dict, List, Optional

logger = logging.getLogger(__name__)

# ledger event kinds (docs/LEDGER_SCHEMA.md "Promotion pipeline")
PROMOTION_START_EVENT = "promotion_start"
PHASE_ADVANCE_EVENT = "phase_advance"
SHADOW_WINDOW_EVENT = "shadow_window"
PROMOTION_ROLLBACK_EVENT = "promotion_rollback"
PROMOTION_COMPLETE_EVENT = "promotion_complete"

# controller states (status()["state"])
S_IDLE = "idle"
S_RUNNING = "running"
S_COMPLETE = "complete"
S_ROLLED_BACK = "rolled_back"
S_REFUSED = "refused"
S_ABORTED = "aborted"  # rollback itself could not restore the incumbent

# replica states mirrored from serve/fleet.py (string constants, not an
# import: fleet.py must stay import-light from here so ServeFleet can own a
# controller without a module cycle)
_R_LIVE = "live"
_R_ABANDONED = "abandoned"


@dataclasses.dataclass
class PromoteConfig:
    """Knobs for one promotion. Accuracy budgets default LOOSER than the
    quantize-check per-precision budgets on purpose: a promoted candidate is
    usually a genuinely different model, so the shadow gate bounds
    behavioral drift and latency rather than demanding near-equality — a
    deliberately large model change needs these loosened explicitly."""

    # shadow phase: duplicate ~fraction of accepted traffic per window of
    # shadow_secs; 0 seconds skips the phase entirely
    shadow_secs: float = 10.0
    shadow_fraction: float = 0.25
    # a window must have compared at least this many requests to be evidence
    # either way; below it the phase HOLDS (another window runs)
    shadow_min_requests: int = 8
    # total time the shadow phase may hold without evidence before the
    # controller gives up and rolls back (a canary nobody exercised is not a
    # promotable canary)
    shadow_max_secs: float = 120.0
    # accuracy budgets on the shadow compare (quant_check.output_delta math)
    shadow_max_abs_delta: float = 0.25
    shadow_max_mean_delta: float = 0.05
    shadow_min_iou: float = 0.90
    shadow_max_disagree: float = 0.10
    # canary non-200s tolerated across a shadow window (shed 429s and
    # transport failures while the canary restarts are counted separately
    # and HOLD rather than roll back)
    shadow_canary_error_tolerance: int = 0
    # latency gate: the canary's p99 against the serving replicas', decided
    # through obs/compare.verdict with this ratio as the noise band — 1.5
    # means "regressed" fires past 1.5x, the promotion-grade width of the
    # compare module's serve-p99 band
    max_p99_ratio: float = 1.5
    # per-rollout-step observation dwell before the gate is evaluated
    observe_secs: float = 2.0
    # canary/candidate replica restarts at or past this = crash loop =
    # rollback (one death is a tolerated blip the supervisor absorbs)
    crash_loop_threshold: int = 2
    ready_timeout_s: float = 180.0
    drain_timeout_s: float = 60.0
    identity_timeout_s: float = 30.0
    poll_interval_s: float = 0.25

    def __post_init__(self):
        if self.shadow_secs < 0:
            raise ValueError("shadow_secs must be >= 0")
        if not 0.0 < self.shadow_fraction <= 1.0:
            raise ValueError("shadow_fraction must be in (0, 1]")
        if self.max_p99_ratio <= 1.0:
            raise ValueError("max_p99_ratio must be > 1.0")
        if self.crash_loop_threshold < 1:
            raise ValueError("crash_loop_threshold must be >= 1")
        if self.shadow_min_requests < 1:
            # 0 would let the first EMPTY window pass every gate vacuously
            # — the knob that silently disables the safety phase
            raise ValueError("shadow_min_requests must be >= 1")


class _Rollback(Exception):
    """Internal control flow: a gate tripped — unwind to rollback."""

    def __init__(self, reason: str, phase: str):
        super().__init__(reason)
        self.reason = reason
        self.phase = phase


class _Terminal(Exception):
    """Raised after a terminal state was already recorded (refusal)."""


class PromotionController:
    """One fleet's promotion state machine (at most one in flight)."""

    def __init__(self, manager, router, *, telemetry=None):
        from tensorflowdistributedlearning_tpu.obs.telemetry import (
            NULL_TELEMETRY,
        )

        self.manager = manager
        self.router = router
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self._lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._abort = threading.Event()
        self._state = S_IDLE
        self._phase = "idle"
        self._reason: Optional[str] = None
        self._history: List[Dict] = []
        self._last_shadow: Optional[Dict] = None
        self._candidate_dir: Optional[str] = None
        self._reference_dir: Optional[str] = None
        self._candidate_identity: Optional[Dict] = None
        self._incumbent_dir: Optional[str] = None
        # multi-tenant: the registry model this promotion targets (None =
        # legacy whole-fleet promotion); scopes every replica filter, so
        # other tenants' replicas are invisible to the rollout
        self._model: Optional[str] = None
        self._config = PromoteConfig()
        self._fault_spec: Optional[str] = None
        self._started_t: Optional[float] = None
        # fleet strength at promotion start — what rollback restores to
        self._orig_count: Optional[int] = None

    # -- public surface ------------------------------------------------------

    def status(self) -> Dict:
        with self._lock:
            out: Dict = {
                "state": self._state,
                "phase": self._phase,
                "candidate_dir": self._candidate_dir,
                "reference_dir": self._reference_dir,
                "incumbent_dir": self._incumbent_dir,
                "history": list(self._history),
            }
            if self._model is not None:
                out["model"] = self._model
            if self._candidate_identity:
                out["candidate"] = self._candidate_identity
            if self._last_shadow:
                out["shadow"] = self._last_shadow
            if self._reason:
                out["reason"] = self._reason
            if self._started_t:
                out["started_t"] = self._started_t
        try:
            out["artifacts"] = self.router.artifact_mix()
        except Exception:  # noqa: BLE001 — status must always answer
            pass
        return out

    def start(
        self,
        candidate_dir: str,
        *,
        reference_dir: Optional[str] = None,
        config: Optional[PromoteConfig] = None,
        fault_spec: Optional[str] = None,
        model: Optional[str] = None,
    ) -> Dict:
        """Launch a promotion in the background; returns the initial status.
        Raises ``RuntimeError`` when one is already in flight.

        ``model`` scopes the promotion to ONE registry entry of a
        multi-tenant fleet: only that model's replicas roll, and completion
        is a registry version flip (other tenants keep serving throughout).
        A multi-model fleet REQUIRES the model name — an unscoped rollout
        would drag every tenant onto one artifact."""
        registry = getattr(self.manager.config, "registry", None)
        if model is not None:
            if registry is None:
                raise ValueError(
                    "promotion names a model but the fleet has no registry"
                )
            incumbent_dir = registry.entry(model).artifact_dir
        else:
            if registry is not None and len(registry) > 1:
                raise ValueError(
                    "multi-model fleet: promotion requires a model name "
                    f"(registry holds {sorted(registry.models)})"
                )
            incumbent_dir = self.manager.config.artifact_dir
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                raise RuntimeError(
                    f"a promotion is already in flight (phase {self._phase})"
                )
            self._abort.clear()
            self._state = S_RUNNING
            self._phase = "admission"
            self._reason = None
            self._history = []
            self._last_shadow = None
            self._candidate_dir = candidate_dir
            self._reference_dir = reference_dir
            self._candidate_identity = None
            self._model = model
            self._incumbent_dir = incumbent_dir
            self._config = config or PromoteConfig()
            self._fault_spec = fault_spec
            self._started_t = time.time()
            self._thread = threading.Thread(
                target=self._run, name="promotion", daemon=True
            )
            self._thread.start()
        return self.status()

    def admin_start(self, payload: Dict) -> Dict:
        """The /admin/promotion POST body → ``start`` (the `promote` CLI's
        wire format). Unknown keys are rejected loudly — a typoed threshold
        silently ignored would be a gate that never fires."""
        payload = dict(payload)
        payload.pop("action", None)
        candidate_dir = payload.pop("candidate_dir", None)
        if not candidate_dir:
            raise ValueError("candidate_dir is required")
        reference_dir = payload.pop("reference_dir", None)
        fault_spec = payload.pop("fault_spec", None)
        model = payload.pop("model", None)
        fields = {f.name for f in dataclasses.fields(PromoteConfig)}
        unknown = set(payload) - fields
        if unknown:
            raise ValueError(
                f"unknown promotion option(s): {sorted(unknown)} "
                f"(valid: {sorted(fields)})"
            )
        config = PromoteConfig(**payload)
        return self.start(
            candidate_dir,
            reference_dir=reference_dir,
            config=config,
            fault_spec=fault_spec,
            model=model,
        )

    def abort(self) -> None:
        """Operator abort: the running promotion unwinds to rollback at its
        next gate check. A no-op when nothing is in flight."""
        self._abort.set()

    def wait(self, timeout: Optional[float] = None) -> bool:
        t = self._thread
        if t is None:
            return True
        t.join(timeout)
        return not t.is_alive()

    def close(self) -> None:
        """Fleet shutdown: stop promptly, don't roll back — the replicas are
        being drained out from under us anyway."""
        self._abort.set()
        self.wait(timeout=5)

    # -- phase machine -------------------------------------------------------

    def _set_phase(self, phase: str, **fields) -> None:
        with self._lock:
            self._phase = phase
            self._history.append(
                {"phase": phase, "t": round(time.time(), 3), **fields}
            )

    def _check_abort(self, phase: str) -> None:
        if self._abort.is_set():
            raise _Rollback("operator abort", phase)

    def _run(self) -> None:
        cfg = self._config
        candidate_dir = self._candidate_dir
        try:
            identity = self._admission()
            self.router.promotion_active = True
            self.telemetry.event(
                PROMOTION_START_EVENT,
                candidate_dir=candidate_dir,
                reference_dir=self._reference_dir,
                dtype=(identity or {}).get("dtype"),
                fingerprint=(identity or {}).get("source_fingerprint"),
                replicas=len(self._live_replicas()),
                shadow_secs=cfg.shadow_secs,
                shadow_fraction=cfg.shadow_fraction,
                model=self._model,
            )
            baseline_p99 = self._fleet_p99()
            # the fleet strength rollback must restore (captured BEFORE the
            # canary makes it N+1)
            self._orig_count = len(self._live_replicas())
            canary = self._canary(identity)
            self._shadow(canary, baseline_p99)
            self._rollout(identity, baseline_p99)
            self._complete(identity)
        except _Terminal:
            pass  # refusal: already ledgered, fleet untouched
        except _Rollback as rb:
            self._rollback(rb.reason, rb.phase)
        except Exception as e:  # noqa: BLE001 — a controller bug must still
            # leave a consistent fleet and a ledgered verdict, never a
            # silently-dead thread mid-rollout
            logger.exception("promotion failed unexpectedly")
            self._rollback(f"internal: {type(e).__name__}: {e}", self._phase)
        finally:
            self.router.promotion_active = False

    # -- phases --------------------------------------------------------------

    def _admission(self) -> Optional[Dict]:
        """Offline gate: manifest parses; with a reference, the full
        quantize-check (fingerprint pairing + accuracy budgets) must pass.
        Refusal never touches the fleet — there is nothing to roll back."""
        from tensorflowdistributedlearning_tpu.train import (
            serving as serving_lib,
        )

        self._set_phase("admission", candidate_dir=self._candidate_dir)
        try:
            manifest = serving_lib.read_manifest(self._candidate_dir)
        except (OSError, ValueError, KeyError) as e:
            self._refuse(f"candidate manifest unreadable: {e}")
        identity = manifest.get("quantization")
        if self._reference_dir:
            from tensorflowdistributedlearning_tpu.serve.quant_check import (
                run_quant_check,
            )

            result = run_quant_check(
                self._reference_dir,
                self._candidate_dir,
                telemetry=self.telemetry,
            )
            if not result["passed"]:
                self._refuse(
                    "quantize-check failed: " + "; ".join(result["failures"])
                )
            if result.get("candidate_summary"):
                # promotion-time drift baseline: the check already ran the
                # candidate over the pinned batch — persist that output
                # distribution into the manifest so the DriftMonitor has a
                # canonical reference without re-running eval
                from tensorflowdistributedlearning_tpu.serve.quant_check import (
                    write_drift_baseline,
                )

                try:
                    write_drift_baseline(
                        self._candidate_dir, result["candidate_summary"]
                    )
                except OSError as e:
                    logger.warning(
                        "could not persist drift baseline into %s: %s",
                        self._candidate_dir,
                        e,
                    )
        with self._lock:
            self._candidate_identity = (
                {
                    "dtype": identity.get("dtype"),
                    "source_fingerprint": identity.get("source_fingerprint"),
                }
                if identity
                else None
            )
        return self._candidate_identity

    def _refuse(self, reason: str) -> None:
        """Admission refusal: terminal, fleet untouched."""
        self.telemetry.event(
            PROMOTION_START_EVENT,
            candidate_dir=self._candidate_dir,
            reference_dir=self._reference_dir,
            refused=True,
        )
        self.telemetry.event(
            PROMOTION_ROLLBACK_EVENT,
            phase="admission",
            reason=reason,
            status=S_REFUSED,
            candidate_dir=self._candidate_dir,
        )
        with self._lock:
            self._state = S_REFUSED
            self._reason = reason
            self._phase = "refused"
        logger.warning("promotion refused at admission: %s", reason)
        raise _Terminal()

    def _canary(self, identity: Optional[Dict]) -> int:
        """Spawn the canary on the candidate artifact, excluded from routing
        (shadow-armed) from the first instant, and verify its polled
        identity IS the candidate before anything advances."""
        cfg = self._config
        self._check_abort("canary")
        self._set_phase("canary")
        rid = self.manager.scale_up(
            artifact_dir=self._candidate_dir,
            fault_spec=self._fault_spec,
            model=self._model,
        )
        # exclusion before readiness: the router must never route a client
        # to the canary, including the poll cycle that first admits it
        self.router.start_shadow(rid, cfg.shadow_fraction)
        self._wait_ready(rid, "canary")
        self._verify_identity(rid, identity, "canary")
        # readiness + identity verified: re-arm (start_shadow resets the
        # stats window) so the shadow windows measure only post-warmup
        # traffic, not spawn-time noise
        self.router.start_shadow(rid, cfg.shadow_fraction)
        self._set_phase("canary_ready", replica=rid)
        self.telemetry.event(
            PHASE_ADVANCE_EVENT,
            phase="canary",
            replica=rid,
            candidate_dir=self._candidate_dir,
            fingerprint=(identity or {}).get("source_fingerprint"),
        )
        return rid

    def _shadow(self, canary_rid: int, baseline_p99: Optional[float]) -> None:
        """Shadow windows until one carries enough evidence to advance —
        or a budget/latency gate rolls the whole thing back. Empty windows
        hold; a canary death mid-window holds too (the supervisor restarts
        it on the same artifact), but a crash-loop rolls back."""
        from tensorflowdistributedlearning_tpu.obs import compare as compare_lib

        cfg = self._config
        if cfg.shadow_secs <= 0:
            self.router.stop_shadow()
            return
        self._set_phase("shadow", replica=canary_rid)
        deadline = time.monotonic() + cfg.shadow_max_secs
        window = 0
        while True:
            self._check_abort("shadow")
            self._abort.wait(cfg.shadow_secs)
            self._check_abort("shadow")
            self._watch_crash_loop("shadow")
            window += 1
            snap = self.router.shadow_snapshot(drain=True) or {}
            snap["window"] = window
            snap["phase"] = "shadow"
            with self._lock:
                self._last_shadow = snap
            self.telemetry.event(SHADOW_WINDOW_EVENT, **snap)
            compared = snap.get("compared", 0)
            if compared >= cfg.shadow_min_requests:
                self._gate_shadow(snap, baseline_p99, compare_lib)
                break
            # not enough evidence: the phase HOLDS — but not forever
            if time.monotonic() >= deadline:
                raise _Rollback(
                    f"shadow starved: {compared} compared request(s) in "
                    f"{cfg.shadow_max_secs:.0f}s (need "
                    f"{cfg.shadow_min_requests})",
                    "shadow",
                )
            logger.info(
                "shadow window %d holds: %d/%d compared",
                window, compared, cfg.shadow_min_requests,
            )
        self.router.stop_shadow()
        self.telemetry.event(
            PHASE_ADVANCE_EVENT,
            phase="shadow_complete",
            replica=canary_rid,
            windows=window,
            compared=snap.get("compared", 0),
        )
        self._set_phase("shadow_complete", windows=window)

    def _gate_shadow(self, snap: Dict, baseline_p99, compare_lib) -> None:
        """The shadow verdict: accuracy budgets (quant_check math) and the
        noise-banded latency ratio. Any trip = rollback with the metric in
        the reason."""
        cfg = self._config
        if snap.get("canary_errors", 0) > cfg.shadow_canary_error_tolerance:
            raise _Rollback(
                f"canary answered {snap['canary_errors']} error(s) on "
                "shadow traffic",
                "shadow",
            )
        if snap.get("max_abs_delta", 0.0) > cfg.shadow_max_abs_delta:
            raise _Rollback(
                f"accuracy: max|delta| {snap['max_abs_delta']} > "
                f"{cfg.shadow_max_abs_delta}",
                "shadow",
            )
        if snap.get("mean_abs_delta", 0.0) > cfg.shadow_max_mean_delta:
            raise _Rollback(
                f"accuracy: mean|delta| {snap['mean_abs_delta']} > "
                f"{cfg.shadow_max_mean_delta}",
                "shadow",
            )
        if (
            snap.get("min_iou") is not None
            and snap["min_iou"] < cfg.shadow_min_iou
        ):
            raise _Rollback(
                f"accuracy: mask IoU {snap['min_iou']} < {cfg.shadow_min_iou}",
                "shadow",
            )
        if (
            snap.get("mean_disagree") is not None
            and snap["mean_disagree"] > cfg.shadow_max_disagree
        ):
            raise _Rollback(
                f"accuracy: disagreement {snap['mean_disagree']} > "
                f"{cfg.shadow_max_disagree}",
                "shadow",
            )
        latency = snap.get("latency_ms") or {}
        primary_p99 = latency.get("primary_p99") or baseline_p99
        canary_p99 = latency.get("canary_p99")
        if primary_p99 and canary_p99:
            lat_verdict = compare_lib.verdict(
                primary_p99,
                canary_p99,
                "lower",
                cfg.max_p99_ratio - 1.0,
                "rel",
            )
            snap["latency_verdict"] = lat_verdict
            if lat_verdict == "regressed":
                raise _Rollback(
                    f"latency: canary p99 {canary_p99}ms vs serving "
                    f"{primary_p99}ms regressed past the "
                    f"{cfg.max_p99_ratio}x band",
                    "shadow",
                )

    def _rollout(
        self, identity: Optional[Dict], baseline_p99: Optional[float]
    ) -> None:
        """Replace remaining incumbents one at a time, spawn-first so fleet
        capacity never dips, each step gated before the next begins."""
        from tensorflowdistributedlearning_tpu.obs import compare as compare_lib

        # the canary (admitted to routing by now) made the fleet N+1 strong;
        # drain one incumbent to return to N, then replace the rest
        incumbents = self._incumbent_replicas()
        first = True
        while incumbents:
            self._check_abort("rollout")
            old = incumbents.pop(0)
            if first:
                first = False
            else:
                new_rid = self.manager.scale_up(
                    artifact_dir=self._candidate_dir, model=self._model
                )
                self._wait_ready(new_rid, "rollout")
                self._verify_identity(new_rid, identity, "rollout")
            self._drain(old.replica_id, "rollout")
            self._observe_gate(baseline_p99, compare_lib)
            remaining = len(self._incumbent_replicas())
            self.telemetry.event(
                PHASE_ADVANCE_EVENT,
                phase="rollout",
                replaced=old.replica_id,
                remaining=remaining,
            )
            self._set_phase(
                "rollout", replaced=old.replica_id, remaining=remaining
            )
            incumbents = self._incumbent_replicas()

    def _observe_gate(self, baseline_p99, compare_lib) -> None:
        """Post-step dwell + gate: candidate replicas must stay healthy and
        the fleet p99 inside the noise-banded ratio of the pre-promotion
        baseline."""
        cfg = self._config
        self._abort.wait(cfg.observe_secs)
        self._check_abort("rollout")
        self._watch_crash_loop("rollout")
        try:
            self.router.poll_once()
        except Exception:  # noqa: BLE001 — the background poller covers this
            pass
        p99 = self._fleet_p99()
        if baseline_p99 and p99:
            lat_verdict = compare_lib.verdict(
                baseline_p99, p99, "lower", cfg.max_p99_ratio - 1.0, "rel"
            )
            if lat_verdict == "regressed":
                raise _Rollback(
                    f"latency: fleet p99 {p99}ms vs baseline "
                    f"{baseline_p99}ms regressed past the "
                    f"{cfg.max_p99_ratio}x band",
                    "rollout",
                )

    def _complete(self, identity: Optional[Dict]) -> None:
        # future spawns (autoscaler, restarts) come up on the candidate:
        # the promotion is durable, not a transient override
        version = None
        registry = getattr(self.manager.config, "registry", None)
        if self._model is not None and registry is not None:
            # the registry flip IS the promotion for a multi-tenant fleet:
            # one entry moves, every other tenant's document line (and
            # replicas) are untouched
            entry = registry.set_version(
                self._model, self._candidate_dir, telemetry=self.telemetry
            )
            version = entry.version
        else:
            self.manager.config.artifact_dir = self._candidate_dir
        self.telemetry.event(
            PROMOTION_COMPLETE_EVENT,
            candidate_dir=self._candidate_dir,
            fingerprint=(identity or {}).get("source_fingerprint"),
            dtype=(identity or {}).get("dtype"),
            replicas=len(self._live_replicas()),
            duration_s=round(time.time() - (self._started_t or time.time()), 3),
            model=self._model,
            version=version,
        )
        with self._lock:
            self._state = S_COMPLETE
            self._phase = "complete"
        logger.info(
            "promotion complete: %s on %s",
            self._model or "fleet", self._candidate_dir,
        )

    # -- rollback ------------------------------------------------------------

    def _rollback(self, reason: str, phase: str) -> None:
        """Re-drain every candidate replica back to the incumbent artifact,
        restore-before-drain so capacity never dips. The unrecoverable case
        — the incumbent artifact is gone — aborts structurally: ledgered,
        surviving replicas left answering, never a dead fleet."""
        if self._state_is_terminal():
            return
        logger.warning("promotion rolling back (%s): %s", phase, reason)
        self._set_phase("rollback", reason=reason)
        try:
            self.router.stop_shadow()
        except Exception:  # noqa: BLE001
            pass
        restored = 0
        drained = 0
        # restore to the strength the fleet had BEFORE the promotion: a
        # shadow-only canary drains without a replacement (the fleet never
        # lost capacity), replaced incumbents each get one back first
        target = self._orig_count or len(self._live_replicas()) or 1
        for rep in self._candidate_replicas():
            need_replacement = len(self._incumbent_replicas()) < target
            if need_replacement:
                new_rid = self.manager.scale_up(
                    artifact_dir=None, model=self._model
                )
                try:
                    self._wait_ready(new_rid, "rollback")
                except _Rollback as e:
                    # the incumbent artifact cannot come back (deleted dir,
                    # broken export): structured abort — forget the failed
                    # replacement, KEEP the candidate replicas serving
                    self.manager.scale_down(new_rid)
                    self.telemetry.event(
                        PROMOTION_ROLLBACK_EVENT,
                        phase=phase,
                        reason=reason,
                        status=S_ABORTED,
                        abort_reason=(
                            "incumbent artifact unavailable during "
                            f"rollback: {e.reason}"
                        ),
                        restored=restored,
                        candidate_replicas_kept=len(
                            self._candidate_replicas()
                        ),
                    )
                    with self._lock:
                        self._state = S_ABORTED
                        self._phase = "aborted"
                        self._reason = (
                            f"{reason}; rollback aborted: incumbent "
                            f"unavailable ({e.reason})"
                        )
                    logger.error(
                        "rollback ABORTED: incumbent artifact unavailable — "
                        "leaving %d candidate replica(s) serving",
                        len(self._candidate_replicas()),
                    )
                    return
                restored += 1
            try:
                self._drain(rep.replica_id, "rollback")
            except _Rollback:
                # a candidate replica that will not drain is eventually
                # reaped by the manager; keep going — the goal is incumbent
                # capacity, which the replacement already restored
                logger.warning(
                    "candidate replica %d did not drain in time",
                    rep.replica_id,
                )
            else:
                drained += 1
        self.telemetry.event(
            PROMOTION_ROLLBACK_EVENT,
            phase=phase,
            reason=reason,
            status=S_ROLLED_BACK,
            restored=restored,
            drained=drained,
        )
        with self._lock:
            self._state = S_ROLLED_BACK
            self._phase = "rolled_back"
            self._reason = reason

    # -- plumbing ------------------------------------------------------------

    def _state_is_terminal(self) -> bool:
        with self._lock:
            return self._state in (S_REFUSED, S_COMPLETE)

    def _model_matches(self, rep) -> bool:
        """Model-scoped promotions only ever see their own tenant's
        replicas; unscoped (legacy) promotions see everything."""
        return (
            self._model is None
            or getattr(rep, "model", None) == self._model
        )

    def _live_replicas(self, exclude: Optional[int] = None) -> List:
        return [
            r
            for r in self.manager.replicas()
            if r.state == _R_LIVE
            and r.replica_id != exclude
            and self._model_matches(r)
        ]

    def _rep_artifact_dir(self, rep) -> str:
        return rep.artifact_dir or self._incumbent_dir

    def _incumbent_replicas(self) -> List:
        return [
            r
            for r in self._live_replicas()
            if self._rep_artifact_dir(r) != self._candidate_dir
        ]

    def _candidate_replicas(self) -> List:
        return [
            r
            for r in self.manager.replicas()
            if r.artifact_dir == self._candidate_dir
            and r.state != _R_ABANDONED
            and self._model_matches(r)
        ]

    def _find(self, rid: int):
        for r in self.manager.replicas():
            if r.replica_id == rid:
                return r
        return None

    def _watch_crash_loop(self, phase: str) -> None:
        threshold = self._config.crash_loop_threshold
        for rep in self._candidate_replicas():
            if rep.restarts >= threshold or rep.state == _R_ABANDONED:
                raise _Rollback(
                    f"candidate replica {rep.replica_id} crash-looping "
                    f"({rep.restarts} restart(s), state {rep.state})",
                    phase,
                )

    def _wait_ready(self, rid: int, phase: str) -> None:
        """Block until the replica reports ready; bail early on abandonment
        or a spawn that dies before EVER becoming ready (a missing artifact
        fails in seconds — no point burning the full timeout)."""
        cfg = self._config
        deadline = time.monotonic() + cfg.ready_timeout_s
        while time.monotonic() < deadline:
            rep = self._find(rid)
            if rep is None:
                raise _Rollback(f"replica {rid} vanished during spawn", phase)
            if rep.state == _R_ABANDONED:
                raise _Rollback(
                    f"replica {rid} abandoned during spawn "
                    f"({rep.restarts} failed launch(es))",
                    phase,
                )
            if rep.ready.is_set() and rep.state == _R_LIVE:
                return
            if (
                rep.url is None
                and rep.restarts >= self._config.crash_loop_threshold
            ):
                # died repeatedly before EVER becoming ready: the spawn
                # itself is broken (missing artifact, bad export). One
                # death stays a tolerated blip — the monitor's backoff
                # relaunch gets its chance before we give the spawn up
                raise _Rollback(
                    f"replica {rid} died {rep.restarts} time(s) before "
                    f"becoming ready (rc={rep.exit_code})",
                    phase,
                )
            if self._abort.wait(cfg.poll_interval_s):
                raise _Rollback("operator abort", phase)
        raise _Rollback(
            f"replica {rid} not ready after {cfg.ready_timeout_s:.0f}s",
            phase,
        )

    def _verify_identity(
        self, rid: int, identity: Optional[Dict], phase: str
    ) -> None:
        """The router's polled /healthz identity for ``rid`` must BE the
        candidate — trust what the replica answers, not what was launched.
        Candidates without a fingerprint (legacy manifests) skip the check."""
        if not identity or not identity.get("source_fingerprint"):
            logger.info(
                "candidate carries no source fingerprint — identity "
                "verification skipped"
            )
            return
        cfg = self._config
        want = identity["source_fingerprint"]
        deadline = time.monotonic() + cfg.identity_timeout_s
        while time.monotonic() < deadline:
            try:
                self.router.poll_once()
            except Exception:  # noqa: BLE001
                pass
            seen = self.router.replica_artifacts().get(rid)
            if seen and seen.get("source_fingerprint") == want:
                return
            if seen and seen.get("source_fingerprint") not in (None, want):
                raise _Rollback(
                    f"replica {rid} serves fingerprint "
                    f"{seen['source_fingerprint'][:8]}…, expected "
                    f"{want[:8]}…",
                    phase,
                )
            if self._abort.wait(cfg.poll_interval_s):
                raise _Rollback("operator abort", phase)
        raise _Rollback(
            f"replica {rid} identity unverified after "
            f"{cfg.identity_timeout_s:.0f}s",
            phase,
        )

    def _drain(self, rid: int, phase: str) -> None:
        cfg = self._config
        if self.manager.scale_down(rid) is None:
            # already gone (reaped, abandoned): the goal state holds
            return
        deadline = time.monotonic() + cfg.drain_timeout_s
        while time.monotonic() < deadline:
            if self._find(rid) is None:
                return
            time.sleep(cfg.poll_interval_s)
        raise _Rollback(
            f"replica {rid} did not drain within {cfg.drain_timeout_s:.0f}s",
            phase,
        )

    def _fleet_p99(self) -> Optional[float]:
        try:
            return self.router.fleet_snapshot().get("worst_p99_ms")
        except Exception:  # noqa: BLE001
            return None
