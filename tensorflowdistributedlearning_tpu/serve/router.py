"""Fleet router: one HTTP front end load-balancing N serving replicas.

One replica (server.py) is one engine behind one listener; millions-of-users
traffic needs N of them — and something that knows, request by request, which
replica to hand work to. This is that something: a stdlib HTTP server that
forwards ``/v1/predict`` to the least-loaded live replica and aggregates the
fleet's health and metrics behind one endpoint.

Routing policy (the signals PRs 7-8 built, finally consumed):

- a background poller GETs every replica's ``/metrics`` each
  ``poll_interval_s``: live queue depth (``serve/queue_depth`` gauge), the
  window's request p99 (``serve/request`` histogram summary), and the
  ``status`` field the SLO tracker maintains (``ok|degraded|draining``);
- each request goes to the routable replica with the lowest score —
  ``queue_depth + in-flight`` (the router's own un-acked forwards bridge the
  gap between polls), windowed p99 as the tiebreak — so load follows actual
  backlog, not round-robin position;
- ``draining`` and ``dead`` replicas are routed AROUND; ``degraded`` (SLO
  budget blown but still answering) replicas are used only when no ``ok``
  replica exists — traffic sheds toward healthy capacity first;
- a replica that refuses connections is marked dead after
  ``dead_after_failures`` consecutive failures and the request is RETRIED on
  a survivor — an accepted request is never lost to a replica death, it is
  re-dispatched (inference is idempotent, so a duplicate forward is safe);
  the poller re-admits the replica the moment its ``/metrics`` answers again
  (the fleet manager restarts dead replicas; the router just converges);
- when EVERY routable replica answers 429, the router sheds with its own
  429 and a ``Retry-After`` header — the smallest backoff any replica
  advertised — so saturation is explicit backpressure end to end, never
  unbounded queueing; no replicas at all is 503 ``no_replicas``.

Artifact identity: every poll also captures the replica's ``/healthz``
artifact identity (quantization dtype + source fingerprint), so a MIXED
fleet — replicas answering from different artifacts — is first-class state:
the aggregate ``/healthz`` and every ``router_window`` event report the
fleet's artifact mix, and ``telemetry-report`` warns when a fleet is mixed
OUTSIDE an active promotion (``promotion_active`` is stamped by the
promotion controller while a rollout is legitimately mixed).

Shadow traffic (the promotion controller's canary probe): while a shadow
target is armed (``start_shadow``), the router duplicates a configurable
slice of ACCEPTED ``/v1/predict`` traffic to that replica off the request
path — bounded queue, drop-on-full — compares the canary's outputs against
the answer the client actually received (mask IoU / disagreement / |delta|,
``serve.quant_check.output_delta``) and its latency against the serving
replica's, and NEVER answers a client from the shadow target (it is excluded
from routing candidates entirely). The accumulated stats drain through
``shadow_snapshot`` into the controller's ``shadow_window`` ledger events.

``/healthz`` aggregates fleet state (``ok`` while at least one replica is
healthy; ``degraded``/``draining``/``down`` otherwise, with per-replica
detail); ``/metrics`` returns the router's counters plus every replica's last
polled snapshot. Periodic ``router_window`` ledger events carry the same
counters, rendered by ``telemetry-report``. ``/admin/promotion`` (GET state,
POST start/abort) delegates to the promotion controller the owning
``ServeFleet`` registers as ``router.promoter`` — the remote-control surface
the ``promote`` CLI drives a live fleet through.
"""

from __future__ import annotations

import collections
import http.client
import json
import logging
import queue
import socket
import threading
import time
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional, Sequence, Tuple, Union

from tensorflowdistributedlearning_tpu.obs.telemetry import NULL_TELEMETRY
from tensorflowdistributedlearning_tpu.serve.registry import DEFAULT_MODEL

logger = logging.getLogger(__name__)

ROUTER_WINDOW_EVENT = "router_window"

# replica states the router tracks; "routable" = ok or degraded (degraded is
# last-resort capacity, see _candidates)
STATUS_STARTING = "starting"
STATUS_OK = "ok"
STATUS_DEGRADED = "degraded"
STATUS_DRAINING = "draining"
STATUS_DEAD = "dead"

_COUNTERS = (
    "requests",        # client requests that reached the predict handler
    "routed",          # forwards attempted (includes retries)
    "retries",         # re-dispatches after a replica failure/drain/429
    "shed",            # answered 429: every routable replica saturated
    "fair_shed",       # answered 429 by the weighted fair-share policy
    "no_replica",      # answered 503: no routable replica at all
    "replica_failures",  # network-level forward failures observed
    "tee_dropped",     # shadow-tee samples lost (queue full / canary 429)
    #                    — cumulative across shadow sessions, so capture
    #                    loss survives the per-window ShadowStats drain
    #                    instead of vanishing with it (PR-13 gap)
)

# per-model traffic counters the router tracks (fleet_snapshot / prometheus)
_MODEL_COUNTERS = ("requests", "routed", "shed", "fair_shed")


def artifact_key(artifact: Optional[Dict]) -> str:
    """One short label for an artifact identity — ``dtype:fingerprint8`` —
    used everywhere the fleet's artifact mix is aggregated (healthz,
    router_window, the report's mixed-fleet warning). Unknown identities
    (raw-closure engines, pre-identity replicas) all fold into "unknown"."""
    if not artifact:
        return "unknown"
    fp = artifact.get("source_fingerprint") or ""
    # fingerprints are "sha256:<hex>" (train/quantize.py): strip the hash
    # name so the 8 chars that remain actually discriminate artifacts
    fp = fp.split(":", 1)[-1]
    return f"{artifact.get('dtype') or '?'}:{fp[:8] or '?'}"


class ReplicaState:
    """The router's live view of one replica (updated by polls + forwards)."""

    def __init__(self, replica_id: int, url: str):
        self.replica_id = int(replica_id)
        self.url = url.rstrip("/")
        parsed = urllib.parse.urlsplit(self.url)
        self.host = parsed.hostname or "127.0.0.1"
        self.port = parsed.port or 80
        self.status = STATUS_STARTING
        self.queue_depth = 0.0
        self.p99_ms: Optional[float] = None
        self.inflight = 0  # router-side forwards not yet answered
        self.routed = 0  # requests this replica answered for the router
        self.failures = 0  # consecutive poll/forward network failures
        self.last_poll_t: Optional[float] = None
        # capacity/cost series from the replica's /metrics (obs/capacity.py):
        # HBM headroom (None on statless backends) and the last window's
        # per-chip request rate + cumulative chip-seconds
        self.headroom_frac: Optional[float] = None
        self.rps_per_chip: Optional[float] = None
        self.chip_seconds_total: float = 0.0
        self.n_chips: int = 1
        # the replica's /healthz artifact identity (quantization dtype +
        # source fingerprint), captured on every poll: mixed-fleet state is
        # first-class — the promotion controller verifies a canary actually
        # serves the candidate through this, and the aggregate healthz /
        # router_window report the fleet's artifact mix from it
        self.artifact: Optional[Dict] = None
        # per-model rows from the replica's /metrics "models" view
        # (server.models_snapshot): which tenants this replica answers for,
        # each with version/backlog/p99. None until the first poll of a
        # models-aware replica; legacy replicas stay None forever and are
        # treated as serving only the default model.
        self.models: Optional[Dict[str, Dict]] = None

    def serves(self, model: str) -> bool:
        if self.models is None:
            return model == DEFAULT_MODEL
        return model in self.models

    @property
    def routable(self) -> bool:
        return self.status in (STATUS_OK, STATUS_DEGRADED)

    def score(self) -> Tuple[float, float]:
        """Lower routes first: backlog (polled queue depth + the router's own
        in-flight forwards since that poll), then windowed p99."""
        return (self.queue_depth + self.inflight, self.p99_ms or 0.0)

    def snapshot(self) -> Dict:
        out = {
            "replica": self.replica_id,
            "url": self.url,
            "status": self.status,
            "queue_depth": self.queue_depth,
            "p99_ms": self.p99_ms,
            "inflight": self.inflight,
            "routed": self.routed,
        }
        if self.headroom_frac is not None:
            out["headroom_frac"] = self.headroom_frac
        if self.rps_per_chip is not None:
            out["rps_per_chip"] = self.rps_per_chip
        if self.chip_seconds_total:
            out["chip_seconds_total"] = self.chip_seconds_total
        if self.artifact is not None:
            out["artifact"] = self.artifact
        if self.models is not None:
            out["models"] = {
                name: {
                    k: row.get(k)
                    for k in ("version", "status", "queue_depth", "p99_ms")
                    if row.get(k) is not None
                }
                for name, row in self.models.items()
            }
        return out


EndpointsLike = Union[
    Callable[[], Sequence[Tuple[int, str]]], Sequence[Tuple[int, str]]
]


class ShadowStats:
    """Accumulated shadow-compare results for one shadow window.

    Filled by the router's shadow worker (off the request path), drained by
    the promotion controller into ``shadow_window`` ledger events. Every
    aggregate is defined for the EMPTY window (no divide-by-zero anywhere):
    a window with ``compared == 0`` simply reports counts of zero and None
    deltas — the controller holds the phase instead of advancing on it."""

    def __init__(self):
        self.lock = threading.Lock()
        self.selected = 0        # accepted requests picked for duplication
        self.dropped = 0         # shadow queue full: sample skipped
        self.compared = 0        # canary answered and outputs were compared
        self.canary_errors = 0   # canary answered non-200
        self.send_failures = 0   # network failure talking to the canary
        self.max_abs_delta = 0.0
        self.sum_mean_abs_delta = 0.0
        self.min_iou: Optional[float] = None
        self.sum_disagree = 0.0
        self.disagree_n = 0      # compares that produced a disagree/IoU row
        self.primary_s: List[float] = []
        self.canary_s: List[float] = []

    def note_outputs(self, deltas: Dict[str, Dict]) -> None:
        """Fold one request's per-output delta records (quant_check math)."""
        with self.lock:
            self.compared += 1
            for rec in deltas.values():
                if "max_abs_delta" in rec:
                    self.max_abs_delta = max(
                        self.max_abs_delta, rec["max_abs_delta"]
                    )
                    self.sum_mean_abs_delta += rec["mean_abs_delta"]
                if "iou" in rec:
                    self.min_iou = (
                        rec["iou"]
                        if self.min_iou is None
                        else min(self.min_iou, rec["iou"])
                    )
                if "disagree" in rec:
                    self.sum_disagree += rec["disagree"]
                    self.disagree_n += 1

    def note_latency(self, primary_s: float, canary_s: float) -> None:
        with self.lock:
            # bounded: shadow windows are short; 4096 samples is plenty for
            # a p99 and keeps a runaway window from growing host memory
            if len(self.primary_s) < 4096:
                self.primary_s.append(primary_s)
                self.canary_s.append(canary_s)

    def snapshot(self) -> Dict:
        """One window record; all ratios None when nothing was compared."""
        with self.lock:
            out: Dict = {
                "selected": self.selected,
                "compared": self.compared,
                "dropped": self.dropped,
                "canary_errors": self.canary_errors,
                "send_failures": self.send_failures,
            }
            if self.compared:
                out["max_abs_delta"] = round(self.max_abs_delta, 6)
                out["mean_abs_delta"] = round(
                    self.sum_mean_abs_delta / self.compared, 6
                )
            if self.min_iou is not None:
                out["min_iou"] = round(self.min_iou, 6)
            if self.disagree_n:
                out["mean_disagree"] = round(
                    self.sum_disagree / self.disagree_n, 6
                )
            if self.primary_s and self.canary_s:
                p = sorted(self.primary_s)
                c = sorted(self.canary_s)

                def pct(xs, q):
                    return xs[min(len(xs) - 1, int(q * len(xs)))]

                out["latency_ms"] = {
                    "primary_p50": round(pct(p, 0.50) * 1000, 3),
                    "primary_p99": round(pct(p, 0.99) * 1000, 3),
                    "canary_p50": round(pct(c, 0.50) * 1000, 3),
                    "canary_p99": round(pct(c, 0.99) * 1000, 3),
                }
                if pct(p, 0.99) > 0:
                    out["latency_ms"]["canary_p99_ratio"] = round(
                        pct(c, 0.99) / pct(p, 0.99), 3
                    )
            return out


class FairShedder:
    """Weighted fair shedding under fleet saturation — pure policy, no I/O.

    The router is work-conserving while there is capacity: every model's
    traffic is admitted. The moment the fleet saturates (a routing attempt
    ends in fleet-wide 429), fairness takes over: each model is entitled to
    ``weight_m / sum(weights of competing models)`` of the admitted window,
    and a model whose admitted share exceeds its entitlement (times a small
    ``grace``) is shed pre-forward with the same structured 429 the
    saturation path answers. The math:

    - *competing* models = models with demand in the sliding window (a lone
      tenant is never shed against itself, whatever its weight);
    - shares are measured over the last ``window`` admitted requests, so
      the policy adapts at traffic speed and needs no reset;
    - pressure decays: ``pressure_window_s`` after the last observed
      saturation signal the policy stands down and admission is
      unconditional again.

    All inputs arrive via ``note_*`` calls and ``now`` is injectable, so the
    policy is deterministic under test.
    """

    def __init__(
        self,
        weights: Optional[Dict[str, float]] = None,
        *,
        window: int = 512,
        grace: float = 1.05,
        pressure_window_s: float = 5.0,
        min_samples: int = 16,
    ):
        self.weights = dict(weights or {})
        self.grace = float(grace)
        self.pressure_window_s = float(pressure_window_s)
        self.min_samples = int(min_samples)
        self._admitted: "collections.deque" = collections.deque(
            maxlen=int(window)
        )
        self._demand: "collections.deque" = collections.deque(
            maxlen=int(window)
        )
        self._last_saturation_t: Optional[float] = None
        self._shed_counts: Dict[str, int] = {}
        self._lock = threading.Lock()

    def weight(self, model: str) -> float:
        return float(self.weights.get(model, 1.0))

    def note_demand(self, model: str) -> None:
        with self._lock:
            self._demand.append(model)

    def note_admitted(self, model: str) -> None:
        with self._lock:
            self._admitted.append(model)

    def note_saturation(self, now: Optional[float] = None) -> None:
        with self._lock:
            self._last_saturation_t = (
                now if now is not None else time.monotonic()
            )

    def pressured(self, now: Optional[float] = None) -> bool:
        with self._lock:
            t = self._last_saturation_t
        if t is None:
            return False
        now = now if now is not None else time.monotonic()
        return (now - t) <= self.pressure_window_s

    def should_shed(self, model: str, now: Optional[float] = None) -> bool:
        """Shed ``model``'s next request? Only under live saturation
        pressure, only when other models are competing for the window, and
        only when this model's admitted share exceeds its weighted fair
        share."""
        if not self.pressured(now):
            return False
        with self._lock:
            demand_counts = collections.Counter(self._demand)
            admitted_counts = collections.Counter(self._admitted)
        competing = {m for m, c in demand_counts.items() if c > 0}
        competing.add(model)
        if len(competing) < 2:
            return False
        admitted_total = sum(admitted_counts[m] for m in competing)
        if admitted_total < self.min_samples:
            return False
        total_weight = sum(self.weight(m) for m in competing)
        fair_share = self.weight(model) / total_weight
        admitted_share = admitted_counts[model] / admitted_total
        shed = admitted_share > fair_share * self.grace
        if shed:
            with self._lock:
                self._shed_counts[model] = (
                    self._shed_counts.get(model, 0) + 1
                )
        return shed

    def snapshot(self) -> Dict:
        with self._lock:
            demand_counts = collections.Counter(self._demand)
            admitted_counts = collections.Counter(self._admitted)
            shed = dict(self._shed_counts)
        admitted_total = sum(admitted_counts.values())
        out: Dict = {
            "pressured": self.pressured(),
            "weights": {
                m: self.weight(m) for m in set(demand_counts) | set(self.weights)
            },
            "demand": dict(demand_counts),
        }
        if admitted_total:
            out["admitted_shares"] = {
                m: round(c / admitted_total, 4)
                for m, c in admitted_counts.items()
            }
        if shed:
            out["fair_shed"] = shed
        return out


class FleetRouter:
    """HTTP front end over a (possibly changing) set of serving replicas.

    ``endpoints`` is either a static ``[(replica_id, url), ...]`` or a
    callable returning the current set (``FleetManager.endpoints`` — replicas
    appear as they come up and vanish when drained/abandoned). The poller
    reconciles the router's replica table against it every interval.
    """

    def __init__(
        self,
        endpoints: EndpointsLike,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        telemetry=None,
        window_secs: float = 30.0,
        poll_interval_s: float = 0.5,
        poll_timeout_s: float = 2.0,
        request_timeout_s: float = 60.0,
        dead_after_failures: int = 2,
        sock: Optional[socket.socket] = None,
        model_weights: Optional[Dict[str, float]] = None,
    ):
        self._endpoints_fn = (
            endpoints if callable(endpoints) else (lambda: list(endpoints))
        )
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self.window_secs = float(window_secs)
        self.poll_interval_s = float(poll_interval_s)
        self.poll_timeout_s = float(poll_timeout_s)
        self.request_timeout_s = float(request_timeout_s)
        self.dead_after_failures = max(1, int(dead_after_failures))
        self._replicas: Dict[int, ReplicaState] = {}
        self._lock = threading.Lock()
        self._counters: Dict[str, int] = {k: 0 for k in _COUNTERS}
        # weighted fair shedding under saturation: weights come from the
        # registry entries (serve-fleet) or the caller; unlisted models
        # default to weight 1.0
        self.shedder = FairShedder(model_weights)
        # per-model traffic counters (requests/routed/shed/fair_shed)
        self._model_stats: Dict[str, Dict[str, int]] = {}
        self._started_t = time.time()
        self._stop = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._shut_down = False
        self._conn_local = threading.local()
        # promotion surface: the owning ServeFleet registers its controller
        # here; /admin/promotion delegates to it. promotion_active is stamped
        # by the controller while the fleet is LEGITIMATELY mixed (mid-
        # rollout), so the report can warn about a silent mixed fleet without
        # false-alarming on every promotion.
        self.promoter = None
        self.promotion_active = False
        # shadow traffic state (promotion canary probe): while armed, a
        # 1-in-shadow_stride slice of accepted requests is duplicated to the
        # shadow replica off the request path; the shadow replica is never a
        # routing candidate
        self._shadow_replica: Optional[int] = None
        self._shadow_stride = 1
        self._shadow_counter = 0
        self._shadow_stats: Optional[ShadowStats] = None
        self._shadow_queue: Optional["queue.Queue"] = None
        self._shadow_thread: Optional[threading.Thread] = None
        self._shadow_stop = threading.Event()
        handler = type("RouterHandler", (_RouterHandler,), {"ctx": self})
        self._httpd = ThreadingHTTPServer(
            (host, port), handler, bind_and_activate=False
        )
        self._httpd.request_queue_size = 128
        if sock is not None:
            self._httpd.socket.close()
            self._httpd.socket = sock
            bound_host, bound_port = sock.getsockname()[:2]
            self._httpd.server_address = (bound_host, bound_port)
            self._httpd.server_name = socket.getfqdn(bound_host)
            self._httpd.server_port = bound_port
        else:
            self._httpd.allow_reuse_address = True
            self._httpd.server_bind()
        self._httpd.server_activate()
        self._httpd.daemon_threads = True
        self._serve_thread: Optional[threading.Thread] = None
        self._poll_thread: Optional[threading.Thread] = None
        self._ticker: Optional[threading.Thread] = None

    @property
    def host(self) -> str:
        return self._httpd.server_address[0]

    @property
    def port(self) -> int:
        return self._httpd.server_address[1]

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> "FleetRouter":
        # one synchronous poll before accepting traffic: the first request
        # must not race an empty replica table
        self.poll_once()
        self._serve_thread = threading.Thread(
            target=self._httpd.serve_forever, name="fleet-router-http",
            daemon=True,
        )
        self._serve_thread.start()
        self._poll_thread = threading.Thread(
            target=self._poll_loop, name="fleet-router-poll", daemon=True
        )
        self._poll_thread.start()
        if self.window_secs > 0:
            self._ticker = threading.Thread(
                target=self._tick, name="fleet-router-window", daemon=True
            )
            self._ticker.start()
        self.telemetry.event(
            "router_start",
            endpoint=self.url,
            replicas=[r.snapshot() for r in self._replica_list()],
        )
        logger.info("fleet router on %s", self.url)
        return self

    def wait(self) -> None:
        self._stop.wait()

    def shutdown(self) -> None:
        """Stop routing: final window, stop the poller, close the listener.
        Replica drain is the fleet manager's job, not the router's."""
        with self._shutdown_lock:
            if self._shut_down:
                return
            self._shut_down = True
        self._stop.set()
        self.stop_shadow()
        for t in (self._ticker, self._poll_thread):
            if t is not None:
                t.join(timeout=5)
        try:
            self.emit_window(final=True)
        except Exception:  # noqa: BLE001 — telemetry never blocks shutdown
            logger.exception("final router window emission failed")
        self.telemetry.event("router_stop", **self.counters())
        if self._serve_thread is not None:
            self._httpd.shutdown()
        self._httpd.server_close()
        if self._serve_thread is not None:
            self._serve_thread.join(timeout=5)

    # -- replica table -------------------------------------------------------

    def _replica_list(self) -> List[ReplicaState]:
        with self._lock:
            return list(self._replicas.values())

    def _reconcile(self) -> None:
        """Sync the replica table with the endpoint source: new ids appear
        (status "starting" until their first successful poll), removed ids
        (drained/abandoned replicas) drop out."""
        try:
            current = {int(i): u for i, u in self._endpoints_fn()}
        except Exception:  # noqa: BLE001 — a dying manager must not kill polls
            logger.exception("endpoint source failed; keeping current table")
            return
        with self._lock:
            for rid in list(self._replicas):
                if rid not in current:
                    del self._replicas[rid]
                elif self._replicas[rid].url != current[rid].rstrip("/"):
                    # restarted on a new port: replace the state wholesale
                    self._replicas[rid] = ReplicaState(rid, current[rid])
            for rid, url in current.items():
                if rid not in self._replicas:
                    self._replicas[rid] = ReplicaState(rid, url)

    def poll_once(self) -> None:
        """One reconcile + metrics sweep over every replica (also called
        synchronously by ``start`` and by tests)."""
        self._reconcile()
        for rep in self._replica_list():
            self._poll_replica(rep)

    def _poll_replica(self, rep: ReplicaState) -> None:
        conn = None
        try:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.poll_timeout_s
            )
            conn.request("GET", "/metrics")
            resp = conn.getresponse()
            body = json.loads(resp.read())
        except (OSError, http.client.HTTPException, ValueError):
            rep.failures += 1
            if rep.failures >= self.dead_after_failures:
                if rep.status != STATUS_DEAD:
                    logger.warning(
                        "replica %d (%s) unreachable x%d — marking dead",
                        rep.replica_id, rep.url, rep.failures,
                    )
                rep.status = STATUS_DEAD
            return
        finally:
            if conn is not None:
                conn.close()
        rep.failures = 0
        rep.last_poll_t = time.monotonic()
        rep.status = body.get("status", STATUS_OK)
        rep.queue_depth = float(body.get("queue_depth", 0) or 0)
        # the /healthz artifact identity, captured every poll. The replica's
        # /metrics body now carries it too (one request covers both); older
        # replicas without the field get a /healthz follow-up request.
        # None (raw-closure engines) stays None — the "unknown" mix bucket.
        if "artifact" in body:
            rep.artifact = body.get("artifact")
        else:
            try:
                conn = http.client.HTTPConnection(
                    rep.host, rep.port, timeout=self.poll_timeout_s
                )
                conn.request("GET", "/healthz")
                health = json.loads(conn.getresponse().read())
                rep.artifact = health.get("artifact")
            except (OSError, http.client.HTTPException, ValueError):
                pass
            finally:
                conn.close()
        hist = (body.get("registry") or {}).get("histograms") or {}
        summary = hist.get("serve/request")
        if summary and summary.get("p99_s") is not None:
            rep.p99_ms = round(summary["p99_s"] * 1000, 3)
        cost = body.get("cost") or {}
        rep.n_chips = int(cost.get("n_chips", 1) or 1)
        rep.chip_seconds_total = float(cost.get("chip_seconds_total", 0.0))
        # unconditional: an idle replica stops publishing last_window, and a
        # stale rate here would sum phantom throughput into the fleet gauges
        last_window = cost.get("last_window") or {}
        rps = last_window.get("rps_per_chip")
        rep.rps_per_chip = float(rps) if rps is not None else None
        memory = body.get("memory") or {}
        headroom = (memory.get("headroom") or {}).get("headroom_frac")
        rep.headroom_frac = (
            float(headroom) if headroom is not None else None
        )
        # per-model serving view (models-aware replicas only): what
        # model-targeted routing filters on, and what the per-model fleet
        # aggregates are built from
        models = body.get("models")
        if isinstance(models, dict):
            rep.models = models

    def _poll_loop(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception:  # noqa: BLE001 — polling must never die
                logger.exception("replica poll sweep failed")

    # -- shadow traffic --------------------------------------------------------

    def start_shadow(self, replica_id: int, fraction: float = 0.25) -> None:
        """Arm shadow mode: duplicate ~``fraction`` of accepted traffic to
        ``replica_id`` (never answering clients from it). Restartable: a new
        ``start_shadow`` resets the stats window."""
        fraction = min(1.0, max(fraction, 1e-6))
        self._shadow_stop.clear()
        with self._lock:
            self._shadow_replica = int(replica_id)
            self._shadow_stride = max(1, round(1.0 / fraction))
            self._shadow_counter = 0
            self._shadow_stats = ShadowStats()
            if self._shadow_queue is None:
                self._shadow_queue = queue.Queue(maxsize=64)
        if self._shadow_thread is None or not self._shadow_thread.is_alive():
            self._shadow_thread = threading.Thread(
                target=self._shadow_loop, name="fleet-router-shadow",
                daemon=True,
            )
            self._shadow_thread.start()

    def stop_shadow(self) -> None:
        """Disarm shadow mode: the target becomes a normal routing candidate
        again (readmission is the poller's job). The worker thread parks."""
        with self._lock:
            self._shadow_replica = None
        self._shadow_stop.set()
        if self._shadow_queue is not None:
            # unblock the worker's get()
            try:
                self._shadow_queue.put_nowait(None)
            except queue.Full:
                pass
        if self._shadow_thread is not None:
            self._shadow_thread.join(timeout=5)
            self._shadow_thread = None

    def shadow_snapshot(self, drain: bool = False) -> Optional[Dict]:
        """The current shadow window's stats; ``drain=True`` starts a fresh
        window (the controller's per-window read)."""
        with self._lock:
            stats = self._shadow_stats
            if stats is None:
                return None
            snap = stats.snapshot()
            snap["replica"] = self._shadow_replica
            if drain:
                self._shadow_stats = ShadowStats()
        return snap

    def _maybe_shadow(
        self, primary: ReplicaState, body: bytes, answer: bytes, primary_dt: float
    ) -> None:
        """Request-path hook (success answers only): pick every
        ``shadow_stride``-th accepted request and enqueue it for duplication.
        Never blocks — a full shadow queue drops the sample and counts it."""
        with self._lock:
            sid = self._shadow_replica
            stats = self._shadow_stats
            if sid is None or stats is None or primary.replica_id == sid:
                return
            self._shadow_counter += 1
            if self._shadow_counter % self._shadow_stride:
                return
            target = self._replicas.get(sid)
        if target is None:
            return
        if target.models is not None and not target.serves(
            self._parse_model(body) or DEFAULT_MODEL
        ):
            # model-scoped promotion on a multi-tenant fleet: other tenants'
            # requests must not be replayed against a canary that does not
            # serve their model (every sample would 404 and read as a canary
            # error, failing the promotion for traffic it never owned)
            return
        with stats.lock:
            stats.selected += 1
        try:
            self._shadow_queue.put_nowait(
                (target, body, answer, primary_dt, stats)
            )
        except queue.Full:
            with stats.lock:
                stats.dropped += 1
            self._count("tee_dropped")

    def _shadow_loop(self) -> None:
        """The shadow worker: replay sampled requests against the canary and
        fold output deltas + latency into the window stats. Entirely off the
        client request path; every failure is a counted stat, never an
        exception a client could see."""
        from tensorflowdistributedlearning_tpu.serve import quant_check

        # one keep-alive connection per canary endpoint: the canary's
        # measured latency must not carry a TCP connect per sample the
        # serving replicas' keep-alive path does not pay
        conns: Dict[Tuple[str, int], http.client.HTTPConnection] = {}
        while not self._shadow_stop.is_set():
            try:
                item = self._shadow_queue.get(timeout=0.5)
            except queue.Empty:
                continue
            if item is None:
                continue
            target, body, answer, primary_dt, stats = item
            key = (target.host, target.port)
            t0 = time.perf_counter()
            try:
                conn = conns.get(key)
                if conn is None:
                    conn = http.client.HTTPConnection(
                        target.host, target.port,
                        timeout=self.request_timeout_s,
                    )
                    conns[key] = conn
                conn.request(
                    "POST", "/v1/predict", body,
                    {"Content-Type": "application/json"},
                )
                resp = conn.getresponse()
                data = resp.read()
            except (OSError, http.client.HTTPException):
                stale = conns.pop(key, None)
                if stale is not None:
                    try:
                        stale.close()
                    except OSError:
                        pass
                with stats.lock:
                    stats.send_failures += 1
                continue
            canary_dt = time.perf_counter() - t0
            if resp.status == 429:
                # canary backpressure sheds the SAMPLE, it is not a wrong
                # answer — shadow load is best-effort sampling by design
                with stats.lock:
                    stats.dropped += 1
                self._count("tee_dropped")
                continue
            if resp.status != 200:
                with stats.lock:
                    stats.canary_errors += 1
                continue
            try:
                import numpy as np

                primary_out = json.loads(answer).get("predictions") or {}
                canary_out = json.loads(data).get("predictions") or {}
                deltas = {
                    name: quant_check.output_delta(
                        name,
                        np.asarray(primary_out[name]),
                        np.asarray(canary_out[name]),
                    )
                    for name in set(primary_out) & set(canary_out)
                }
            except (ValueError, TypeError):
                with stats.lock:
                    stats.canary_errors += 1
                continue
            # a canary answering with DIFFERENT output names or shapes is a
            # wrong answer, not a comparison to skip: counting it as
            # "compared" would let every accuracy gate pass vacuously (no
            # metrics to trip) and promote a behaviorally unrelated model
            if not deltas or any("error" in rec for rec in deltas.values()):
                with stats.lock:
                    stats.canary_errors += 1
                continue
            stats.note_outputs(deltas)
            stats.note_latency(primary_dt, canary_dt)
        for conn in conns.values():
            try:
                conn.close()
            except OSError:
                pass

    # -- routing -------------------------------------------------------------

    def _candidates(
        self, model: Optional[str] = None
    ) -> List[ReplicaState]:
        """Replicas to try, in order: healthy first (by score), degraded only
        after every ok replica — the SLO breach IS the drain signal. The
        shadow target (an armed canary) is NEVER a candidate: shadow mode
        must not answer clients. With ``model`` set, only replicas serving
        that model qualify — the per-model replica set."""
        with self._lock:
            shadow = self._shadow_replica
        reps = [
            r
            for r in self._replica_list()
            if r.routable
            and r.replica_id != shadow
            and (model is None or r.serves(model))
        ]
        ok = sorted(
            (r for r in reps if r.status == STATUS_OK), key=ReplicaState.score
        )
        degraded = sorted(
            (r for r in reps if r.status == STATUS_DEGRADED),
            key=ReplicaState.score,
        )
        return ok + degraded

    def _count(self, key: str, n: int = 1) -> None:
        with self._lock:
            self._counters[key] += n

    def _count_model(self, model: str, key: str, n: int = 1) -> None:
        with self._lock:
            stats = self._model_stats.setdefault(
                model, {k: 0 for k in _MODEL_COUNTERS}
            )
            stats[key] += n

    def model_stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {m: dict(s) for m, s in self._model_stats.items()}

    def counters(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._counters)

    @staticmethod
    def _parse_model(body: bytes) -> Optional[str]:
        """The ``"model"`` key of a predict payload, or None (absent /
        unparseable — the replica's own 400 stays authoritative for garbage
        bodies; the router only needs the routing hint)."""
        try:
            name = json.loads(body).get("model")
        except (ValueError, AttributeError):
            return None
        return name if isinstance(name, str) and name else None

    def artifact_mix(self) -> Dict[str, int]:
        """Replica count per served artifact identity (``dtype:fp8`` keys).
        More than one key = a mixed fleet — expected mid-promotion, a
        rendered warning otherwise."""
        mix: Dict[str, int] = {}
        for r in self._replica_list():
            key = artifact_key(r.artifact)
            mix[key] = mix.get(key, 0) + 1
        return mix

    def replica_artifacts(self) -> Dict[int, Optional[Dict]]:
        """Per-replica polled artifact identity — what the promotion
        controller verifies a relaunched replica against."""
        return {r.replica_id: r.artifact for r in self._replica_list()}

    def fleet_status(self) -> str:
        """One word for the whole fleet: ok > degraded > draining > down."""
        statuses = {r.status for r in self._replica_list()}
        if STATUS_OK in statuses:
            return STATUS_OK
        if STATUS_DEGRADED in statuses:
            return STATUS_DEGRADED
        if STATUS_DRAINING in statuses or STATUS_STARTING in statuses:
            return STATUS_DRAINING
        return "down"

    def models_snapshot(self) -> Dict[str, Dict]:
        """Per-model fleet aggregate: live replica count, summed backlog,
        worst windowed p99, version mix (one version per model except
        mid-promotion), plus the router's own per-model traffic counters and
        the model's fair-share weight. What the per-model autoscaler
        evaluates and the multitenant bench gates read."""
        out: Dict[str, Dict] = {}
        for rep in self._replica_list():
            if not rep.models or not rep.routable:
                continue
            for name, row in rep.models.items():
                agg = out.setdefault(
                    name,
                    {
                        "replicas": 0,
                        "degraded": 0,
                        "queue_depth": 0.0,
                        "worst_p99_ms": None,
                        "versions": {},
                    },
                )
                agg["replicas"] += 1
                if row.get("status") == "degraded":
                    agg["degraded"] += 1
                agg["queue_depth"] += float(row.get("queue_depth") or 0)
                p99 = row.get("p99_ms")
                if p99 is not None:
                    agg["worst_p99_ms"] = max(
                        agg["worst_p99_ms"] or 0.0, float(p99)
                    )
                version = row.get("version")
                if version is not None:
                    key = str(version)
                    agg["versions"][key] = agg["versions"].get(key, 0) + 1
        for name, stats in self.model_stats().items():
            agg = out.setdefault(
                name,
                {
                    "replicas": 0,
                    "degraded": 0,
                    "queue_depth": 0.0,
                    "worst_p99_ms": None,
                    "versions": {},
                },
            )
            agg.update(stats)
        for name, agg in out.items():
            agg["weight"] = self.shedder.weight(name)
            agg["queue_depth"] = round(agg["queue_depth"], 2)
        return out

    def fleet_snapshot(self) -> Dict:
        """The aggregate view the autoscaler evaluates (and /metrics embeds):
        per-status replica counts, total backlog, cumulative shed count."""
        reps = self._replica_list()
        by_status: Dict[str, int] = {}
        for r in reps:
            by_status[r.status] = by_status.get(r.status, 0) + 1
        queue_total = sum(
            r.queue_depth + r.inflight for r in reps if r.routable
        )
        p99s = [r.p99_ms for r in reps if r.routable and r.p99_ms is not None]
        headrooms = [
            r.headroom_frac for r in reps if r.headroom_frac is not None
        ]
        rps_chips = [
            r.rps_per_chip for r in reps if r.rps_per_chip is not None
        ]
        capacity: Dict = {}
        if headrooms:
            # the fleet is as close to OOM as its tightest replica
            capacity["min_headroom_frac"] = min(headrooms)
        if rps_chips:
            # fleet-wide serving efficiency: per-chip request rate summed
            # over replicas (replicas run one chip-set each)
            capacity["rps_per_chip_total"] = round(sum(rps_chips), 3)
        total_chip_s = sum(r.chip_seconds_total for r in reps)
        if total_chip_s:
            capacity["chip_seconds_total"] = round(total_chip_s, 3)
        snapshot = {
            **capacity,
            "replicas": len(reps),
            "live": by_status.get(STATUS_OK, 0)
            + by_status.get(STATUS_DEGRADED, 0),
            "starting": by_status.get(STATUS_STARTING, 0),
            "draining": by_status.get(STATUS_DRAINING, 0),
            "dead": by_status.get(STATUS_DEAD, 0),
            "degraded": by_status.get(STATUS_DEGRADED, 0),
            "queue_depth_total": round(queue_total, 2),
            "worst_p99_ms": max(p99s) if p99s else None,
            "shed_total": self.counters()["shed"],
            "status": self.fleet_status(),
            "artifacts": self.artifact_mix(),
            "promotion_active": self.promotion_active,
        }
        models = self.models_snapshot()
        if models:
            snapshot["models"] = models
            snapshot["fair_shed_total"] = self.counters()["fair_shed"]
        with self._lock:
            if self._shadow_replica is not None:
                snapshot["shadow_replica"] = self._shadow_replica
        return snapshot

    # -- forwarding ----------------------------------------------------------

    def _conn(self, rep: ReplicaState) -> http.client.HTTPConnection:
        """Per-(handler-thread, replica) keep-alive connection: handler
        threads are per-client-connection, so this pools exactly one upstream
        socket per client connection per replica."""
        conns = getattr(self._conn_local, "conns", None)
        if conns is None:
            conns = self._conn_local.conns = {}
        key = (rep.replica_id, rep.url)
        conn = conns.get(key)
        if conn is None:
            conn = http.client.HTTPConnection(
                rep.host, rep.port, timeout=self.request_timeout_s
            )
            conn.connect()
            conn.sock.setsockopt(
                socket.IPPROTO_TCP, socket.TCP_NODELAY, 1
            )
            conns[key] = conn
        return conn

    def profile_fleet(self, seconds: float) -> Dict:
        """Router-aggregated ``/admin/profile``: fan the capture request out
        to every non-dead replica and collect the per-replica answers. Each
        replica profiles itself (202) or reports why not (409 in-flight, 503
        no workdir); a replica that cannot be reached is reported dead-style
        rather than failing the sweep — the operator asked for whatever
        evidence the fleet can produce, not all-or-nothing."""
        results: Dict[str, Dict] = {}
        started = 0
        for rep in self._replica_list():
            if rep.status == STATUS_DEAD:
                results[str(rep.replica_id)] = {"error": "dead"}
                continue
            try:
                conn = self._conn(rep)
                conn.request("GET", f"/admin/profile?seconds={seconds:g}")
                resp = conn.getresponse()
                body = json.loads(resp.read() or b"{}")
                body["http_status"] = resp.status
                if resp.status == 202:
                    started += 1
                results[str(rep.replica_id)] = body
            except (http.client.HTTPException, OSError, ValueError) as e:
                self._drop_conn(rep)
                results[str(rep.replica_id)] = {
                    "error": f"{type(e).__name__}: {e}"
                }
        return {
            "seconds": seconds,
            "replicas": len(results),
            "started": started,
            "per_replica": results,
        }

    def _drop_conn(self, rep: ReplicaState) -> None:
        conns = getattr(self._conn_local, "conns", None)
        if conns:
            conn = conns.pop((rep.replica_id, rep.url), None)
            if conn is not None:
                try:
                    conn.close()
                except OSError:
                    pass

    def forward(
        self, rep: ReplicaState, body: bytes, request_id: str
    ) -> Tuple[int, Dict[str, str], bytes]:
        """One forward to one replica; raises ``OSError``/``HTTPException``
        on network failure (the caller retries elsewhere)."""
        conn = self._conn(rep)
        try:
            conn.request(
                "POST",
                "/v1/predict",
                body,
                {
                    "Content-Type": "application/json",
                    "x-request-id": request_id,
                },
            )
            resp = conn.getresponse()
            data = resp.read()
        except (http.client.HTTPException, OSError):
            self._drop_conn(rep)
            raise
        headers = {
            k: v
            for k, v in (
                ("x-request-id", resp.getheader("x-request-id")),
                ("Retry-After", resp.getheader("Retry-After")),
            )
            if v
        }
        return resp.status, headers, data

    def route_predict(
        self, body: bytes, request_id: str
    ) -> Tuple[int, Dict[str, str], bytes]:
        """The routing loop: parse the model hint, try that model's
        candidates best-score-first; retry on network failure / drain /
        saturation; shed structurally when the whole fleet is saturated or
        empty. Under saturation pressure the weighted fair-share policy
        (:class:`FairShedder`) sheds over-share models pre-forward so one
        tenant's burst cannot starve another's SLO."""
        self._count("requests")
        explicit_model = self._parse_model(body)
        model = explicit_model or DEFAULT_MODEL
        self._count_model(model, "requests")
        self.shedder.note_demand(model)
        if self.shedder.should_shed(model):
            self._count("shed")
            self._count("fair_shed")
            self._count_model(model, "shed")
            self._count_model(model, "fair_shed")
            return self._structured_error(
                429,
                "fleet_saturated",
                f"fleet saturated; model {model!r} is over its fair share "
                f"(weight {self.shedder.weight(model):g}) — back off",
                request_id,
                retry_after=1,
            )
        candidates = self._candidates(model)
        if not candidates and explicit_model is None:
            # legacy client on a named-model fleet: no replica claims the
            # implicit default — route over the whole fleet rather than
            # refusing traffic the replicas themselves would accept
            candidates = self._candidates()
        if not candidates:
            if explicit_model is not None and self._candidates():
                # the fleet is alive, it just doesn't serve this model:
                # caller error, not capacity — don't invite retries
                self._count("no_replica")
                return self._structured_error(
                    404,
                    "model_unknown",
                    f"no replica serves model {explicit_model!r}",
                    request_id,
                )
            self._count("no_replica")
            return self._structured_error(
                503,
                "no_replicas",
                "no live replica in the fleet (starting or recovering?)",
                request_id,
                retry_after=1,
            )
        saw_429 = False
        retry_afters: List[int] = []
        for i, rep in enumerate(candidates):
            if i:
                self._count("retries")
            self._count("routed")
            with self._lock:
                rep.inflight += 1
            t0 = time.perf_counter()
            try:
                status, headers, data = self.forward(rep, body, request_id)
            except (http.client.HTTPException, OSError):
                self._count("replica_failures")
                rep.failures += 1
                if rep.failures >= self.dead_after_failures:
                    rep.status = STATUS_DEAD
                continue
            finally:
                with self._lock:
                    rep.inflight = max(0, rep.inflight - 1)
            if status == 429:
                saw_429 = True
                ra = headers.get("Retry-After")
                if ra and ra.isdigit():
                    retry_afters.append(int(ra))
                # the poll will refresh the real depth; until then, stop
                # preferring a replica that just told us it is full
                rep.queue_depth = max(rep.queue_depth, 1.0)
                continue
            if status == 503:
                # replica-level drain: route around it from now on
                rep.status = STATUS_DRAINING
                continue
            with self._lock:
                rep.routed += 1
            if status == 200:
                self._count_model(model, "routed")
                self.shedder.note_admitted(model)
                # shadow duplication rides ONLY answered requests (the
                # canary sees what real traffic saw), enqueued off-path
                self._maybe_shadow(
                    rep, body, data, time.perf_counter() - t0
                )
            if saw_429:
                # some replica was saturated even though this one answered:
                # keep the fairness policy pressured
                self.shedder.note_saturation()
            return status, headers, data
        if saw_429:
            self._count("shed")
            self._count_model(model, "shed")
            self.shedder.note_saturation()
            # fleet-wide saturation: shed with the SMALLEST backoff any
            # replica advertised — capacity frees up as soon as the fastest
            # drain completes
            return self._structured_error(
                429,
                "fleet_saturated",
                "every replica's queue is full; back off",
                request_id,
                retry_after=min(retry_afters) if retry_afters else 1,
            )
        self._count("no_replica")
        return self._structured_error(
            503,
            "no_replicas",
            "every replica is draining or unreachable",
            request_id,
            retry_after=1,
        )

    @staticmethod
    def _structured_error(
        status: int,
        code: str,
        message: str,
        request_id: str,
        retry_after: Optional[int] = None,
    ) -> Tuple[int, Dict[str, str], bytes]:
        error: Dict = {"code": code, "message": message, "request_id": request_id}
        if retry_after is not None:
            error["retry_after_s"] = int(retry_after)
        headers = {"x-request-id": request_id}
        if retry_after is not None:
            headers["Retry-After"] = str(int(retry_after))
        return status, headers, json.dumps({"error": error}).encode()

    # -- aggregation ---------------------------------------------------------

    def healthz(self) -> Dict:
        status = self.fleet_status()
        reps = [r.snapshot() for r in self._replica_list()]
        mix = self.artifact_mix()
        return {
            "ok": status == STATUS_OK,
            "status": status,
            "role": "router",
            "live": sum(1 for r in reps if r["status"] in
                        (STATUS_OK, STATUS_DEGRADED)),
            "replicas": reps,
            # the fleet's artifact mix: which exports are answering, and a
            # first-class flag when more than one is (expected only during
            # an active promotion)
            "artifacts": mix,
            "mixed_artifacts": len(mix) > 1,
            "promotion_active": self.promotion_active,
            "uptime_s": round(time.time() - self._started_t, 3),
        }

    def metrics_snapshot(self) -> Dict:
        return {
            "role": "router",
            "uptime_s": round(time.time() - self._started_t, 3),
            "router": self.counters(),
            "fleet": self.fleet_snapshot(),
            "replicas": [r.snapshot() for r in self._replica_list()],
        }

    def prometheus_text(self) -> str:
        """Prometheus exposition for the router's ``/metrics`` (``?format=
        prometheus`` or ``Accept: text/plain``): traffic counters plus the
        fleet-aggregate capacity gauges — min replica headroom, fleet-wide
        rps-per-chip, cumulative chip-seconds — so one scrape of the router
        sees cost and OOM risk without touching individual replicas."""
        lines: List[str] = []

        def counter(name: str, value) -> None:
            lines.append(f"# TYPE tfdl_router_{name}_total counter")
            lines.append(f"tfdl_router_{name}_total {value}")

        def gauge(name: str, value) -> None:
            lines.append(f"# TYPE tfdl_router_{name} gauge")
            lines.append(f"tfdl_router_{name} {value}")

        for name, value in sorted(self.counters().items()):
            counter(name, value)
        fleet = self.fleet_snapshot()
        gauge("uptime_s", round(time.time() - self._started_t, 3))
        gauge("replicas", fleet["replicas"])
        gauge("replicas_live", fleet["live"])
        gauge("replicas_dead", fleet["dead"])
        gauge("queue_depth_total", fleet["queue_depth_total"])
        gauge("healthy", 1.0 if fleet["status"] == STATUS_OK else 0.0)
        if fleet.get("worst_p99_ms") is not None:
            gauge("worst_p99_ms", fleet["worst_p99_ms"])
        if fleet.get("min_headroom_frac") is not None:
            gauge("hbm_min_headroom_frac", fleet["min_headroom_frac"])
        if fleet.get("rps_per_chip_total") is not None:
            gauge("rps_per_chip_total", fleet["rps_per_chip_total"])
        if fleet.get("chip_seconds_total") is not None:
            gauge("chip_seconds_total", fleet["chip_seconds_total"])
        # per-model routing series, {model=} labeled so one scrape of the
        # router distinguishes tenants (versions ride on the replicas'
        # tfdl_serve_model_* series)
        models = fleet.get("models") or {}
        if models:
            for metric in _MODEL_COUNTERS:
                pname = f"tfdl_router_model_{metric}_total"
                lines.append(f"# TYPE {pname} counter")
                for name in sorted(models):
                    value = models[name].get(metric, 0)
                    lines.append(f'{pname}{{model="{name}"}} {value}')
            lines.append("# TYPE tfdl_router_model_queue_depth gauge")
            for name in sorted(models):
                lines.append(
                    f'tfdl_router_model_queue_depth{{model="{name}"}} '
                    f"{models[name]['queue_depth']}"
                )
            lines.append("# TYPE tfdl_router_model_worst_p99_ms gauge")
            for name in sorted(models):
                p99 = models[name].get("worst_p99_ms")
                if p99 is not None:
                    lines.append(
                        f'tfdl_router_model_worst_p99_ms{{model="{name}"}} '
                        f"{p99}"
                    )
        return "\n".join(lines) + "\n"

    def emit_window(self, final: bool = False) -> Dict:
        fields: Dict = {
            **self.counters(),
            "fleet": self.fleet_snapshot(),
            "per_replica_routed": {
                str(r.replica_id): r.routed for r in self._replica_list()
            },
        }
        if self._model_stats:
            # fairness evidence: admitted shares vs weights + per-model shed
            # counts — what the report's fair-shed line and the bench's
            # fairness gate read
            fields["fair_share"] = self.shedder.snapshot()
        if final:
            fields["final"] = True
        self.telemetry.event(ROUTER_WINDOW_EVENT, **fields)
        return fields

    def _tick(self) -> None:
        while not self._stop.wait(self.window_secs):
            try:
                self.emit_window()
            except Exception:  # noqa: BLE001 — telemetry never kills routing
                logger.exception("router window emission failed")


class _RouterHandler(BaseHTTPRequestHandler):
    ctx: FleetRouter
    protocol_version = "HTTP/1.1"
    disable_nagle_algorithm = True

    def log_message(self, fmt, *args):
        logger.debug("%s - %s", self.address_string(), fmt % args)

    def _respond(
        self,
        status: int,
        headers: Dict[str, str],
        body: bytes,
        content_type: str = "application/json",
    ) -> None:
        self.send_response(status)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in headers.items():
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _json(self, status: int, payload: Dict) -> None:
        self._respond(status, {}, json.dumps(payload).encode())

    def do_GET(self):  # noqa: N802
        parsed = urllib.parse.urlparse(self.path)
        if parsed.path == "/healthz":
            body = self.ctx.healthz()
            self._json(200 if body["status"] != "down" else 503, body)
        elif parsed.path == "/admin/promotion":
            promoter = self.ctx.promoter
            if promoter is None:
                self._json(
                    404,
                    {"error": {"code": "no_promoter",
                               "message": "this router has no promotion "
                               "controller (not a serve-fleet?)"}},
                )
            else:
                self._json(200, promoter.status())
        elif parsed.path == "/admin/profile":
            # fleet-wide capture sweep: ask every live replica to profile
            # itself for N seconds; the per-replica rooflines land in each
            # replica's ledger and merge through telemetry-report
            query = urllib.parse.parse_qs(parsed.query)
            try:
                seconds = float(query.get("seconds", ["1"])[0])
            except ValueError:
                self._json(
                    400,
                    {"error": {"code": "bad_request",
                               "message": "seconds must be a number"}},
                )
                return
            if not (0 < seconds <= 60):
                self._json(
                    400,
                    {"error": {"code": "bad_request",
                               "message": "seconds must be in (0, 60]"}},
                )
                return
            body = self.ctx.profile_fleet(seconds)
            self._json(202 if body["started"] else 503, body)
        elif parsed.path == "/metrics":
            query = urllib.parse.parse_qs(parsed.query)
            accept = self.headers.get("Accept", "")
            if (
                query.get("format", [""])[0] == "prometheus"
                or "text/plain" in accept
                or "openmetrics" in accept
            ):
                self._respond(
                    200,
                    {},
                    self.ctx.prometheus_text().encode(),
                    content_type="text/plain; version=0.0.4; charset=utf-8",
                )
            else:
                self._json(200, self.ctx.metrics_snapshot())
        else:
            self._json(
                404,
                {"error": {"code": "not_found",
                           "message": f"no route for GET {self.path}"}},
            )

    def do_POST(self):  # noqa: N802
        from tensorflowdistributedlearning_tpu.obs import trace as trace_lib

        if self.path == "/admin/promotion":
            self._admin_promotion()
            return
        if self.path != "/v1/predict":
            self._json(
                404,
                {"error": {"code": "not_found",
                           "message": f"no route for POST {self.path}"}},
            )
            return
        request_id = self.headers.get("x-request-id") or trace_lib.new_id()
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length) if length else b"{}"
        status, headers, data = self.ctx.route_predict(body, request_id)
        headers.setdefault("x-request-id", request_id)
        self._respond(status, headers, data)

    def _admin_promotion(self) -> None:
        """POST /admin/promotion: {"action": "start", "candidate_dir": ...}
        starts a promotion on the fleet's controller, {"action": "abort"}
        rolls an in-flight one back. The remote-control seam the `promote`
        CLI drives; structured errors, never a traceback on the wire."""
        promoter = self.ctx.promoter
        if promoter is None:
            self._json(
                404,
                {"error": {"code": "no_promoter",
                           "message": "this router has no promotion "
                           "controller (not a serve-fleet?)"}},
            )
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(length) or b"{}")
            action = payload.get("action")
        except ValueError as e:
            self._json(400, {"error": {"code": "bad_request",
                                       "message": str(e)}})
            return
        try:
            if action == "start":
                self._json(202, promoter.admin_start(payload))
            elif action == "abort":
                promoter.abort()
                self._json(202, promoter.status())
            else:
                self._json(
                    400,
                    {"error": {"code": "bad_request",
                               "message": f"unknown action {action!r} "
                               "(expected start|abort)"}},
                )
        except (ValueError, TypeError) as e:
            # TypeError covers wrongly-typed config values (a string where
            # PromoteConfig expects a number) — caller error, not a 500
            self._json(400, {"error": {"code": "bad_request",
                                       "message": str(e)}})
        except RuntimeError as e:
            # a promotion is already in flight
            self._json(409, {"error": {"code": "promotion_in_flight",
                                       "message": str(e)}})
        except Exception as e:  # noqa: BLE001 — admin must answer structurally
            logger.exception("admin promotion request failed")
            self._json(500, {"error": {"code": "internal",
                                       "message": f"{type(e).__name__}: {e}"}})
