"""quantize-check: the accuracy gate between an f32 artifact and its
quantized sibling.

A quantized serving artifact is a *candidate*: it ships only if its outputs
stay within a per-precision accuracy budget of the float32 reference it was
derived from. This module runs both artifacts over a **pinned eval batch**
(deterministic, derived from the manifest's input signature and a seed — the
same bytes every run, every machine) and fails when any output's delta
exceeds the precision's threshold. That makes it promotion-pipeline-ready
(ROADMAP item 4): the promotion controller can call ``run_quant_check`` as a
hard gate, and every verdict lands in the run ledger as a ``quant_check``
event that ``telemetry-report`` renders.

Pairing is verified before numerics: both manifests carry a source
fingerprint (sha256 over the float32 params, train/quantize.py), and a
mismatch fails the check outright — comparing artifacts from different
checkpoints produces a meaningless (and often accidentally-passing) delta.

Deltas measured per output:

- floating outputs: max/mean absolute delta (probabilities, logits);
- binary-valued outputs (segmentation masks — float {0,1}): IoU between
  the two masks;
- integer outputs (argmax class ids): disagreement fraction.
"""

from __future__ import annotations

import logging
from typing import Dict, Optional

import numpy as np

logger = logging.getLogger(__name__)

# Per-precision accuracy budgets, in output units (probabilities/masks in
# [0,1]). bf16 keeps ~3 significant digits — rounding alone cannot move a
# probability by 0.05 unless the model amplifies it, which is exactly what
# the gate exists to catch. int8 weight-quantization error is larger and
# model-dependent; the defaults are the loosest budget a production gate
# should bless. float32 candidates must be bit-exact up to run-to-run fusion
# jitter. All overridable per-run (CLI flags / thresholds=).
DEFAULT_THRESHOLDS: Dict[str, Dict[str, float]] = {
    "float32": {
        "max_abs_delta": 1e-5,
        "mean_abs_delta": 1e-6,
        "min_iou": 1.0,
        "max_disagree": 0.0,
    },
    "bfloat16": {
        "max_abs_delta": 0.05,
        "mean_abs_delta": 0.01,
        "min_iou": 0.98,
        "max_disagree": 0.02,
    },
    "int8": {
        "max_abs_delta": 0.15,
        "mean_abs_delta": 0.03,
        "min_iou": 0.95,
        "max_disagree": 0.05,
    },
    # int8-COMPUTE adds dynamic per-tensor activation quantization on top of
    # int8 weight storage: each quantized layer's inputs round to 8 bits, so
    # the error budget is wider than weight-only int8. The comparison is
    # still against the F32 REFERENCE artifact — not the dequantize-f32
    # int8-store sibling — so kernel-arithmetic drift is caught at
    # admission, on the same path that serves (the candidate's own traced
    # graph, which also stamps the drift baseline).
    "int8-compute": {
        "max_abs_delta": 0.25,
        "mean_abs_delta": 0.05,
        "min_iou": 0.92,
        "max_disagree": 0.08,
    },
}


def budget_key(quantization: Optional[Dict]) -> str:
    """Which DEFAULT_THRESHOLDS budget a manifest ``quantization`` section
    gates under: the storage dtype, except int8 storage with int8 compute
    gates under the wider ``int8-compute`` budget. The ONE place the
    (dtype, compute_dtype) pair maps to a budget name — bench_serve's gate
    table and the sentinel replay key off the same answer."""
    q = quantization or {}
    dtype = q.get("dtype", "float32")
    if dtype == "int8" and q.get("compute_dtype") == "int8":
        return "int8-compute"
    return dtype


def pinned_eval_batch(manifest: Dict, batch_size: int, seed: int = 0) -> np.ndarray:
    """The deterministic probe batch both artifacts are compared on:
    standard-normal values (the models' inputs are normalized images) shaped
    from the manifest's input signature. A fixed-batch artifact pins the
    batch dimension itself; polymorphic ones take ``batch_size``."""
    shape = list(manifest["input_shape"])
    if shape[0] is not None:
        batch_size = int(shape[0])
    rng = np.random.default_rng(seed)
    return rng.standard_normal((batch_size, *shape[1:])).astype(np.float32)


def _is_binary(a: np.ndarray) -> bool:
    return a.size > 0 and bool(np.isin(np.unique(a), (0, 1)).all())


def summarize_output_distribution(
    outputs: Dict[str, np.ndarray], *, batch: int, seed: int
) -> Dict:
    """Per-output distribution summary over the pinned eval batch — the
    canonical ``drift_baseline`` the DriftMonitor (obs/health.py) compares
    live serving outputs against. Integer outputs (argmax class ids) keep a
    normalized histogram; float outputs keep mean/std. Persisted into the
    artifact manifest at export and promotion time so drift detection never
    re-runs eval."""
    summary: Dict = {"batch": int(batch), "seed": int(seed), "outputs": {}}
    for name in sorted(outputs):
        arr = np.asarray(outputs[name])
        if np.issubdtype(arr.dtype, np.integer):
            vals, counts = np.unique(arr, return_counts=True)
            summary["outputs"][name] = {
                "kind": "integer",
                "n": int(arr.size),
                "hist": {
                    str(int(v)): round(float(c) / arr.size, 6)
                    for v, c in zip(vals, counts)
                },
            }
        else:
            a = arr.astype(np.float64)
            summary["outputs"][name] = {
                "kind": "float",
                "mean": round(float(a.mean()), 6) if a.size else 0.0,
                "std": round(float(a.std()), 6) if a.size else 0.0,
            }
    return summary


def write_drift_baseline(artifact_dir: str, baseline: Dict) -> None:
    """Install ``drift_baseline`` into an artifact's manifest atomically.
    Extra manifest keys ride along untouched (train/serving.py validates
    only what it knows), so an already-promoted artifact can be stamped
    in place."""
    import json
    import os

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    path = os.path.join(artifact_dir, serving_lib.MANIFEST_NAME)
    with open(path, encoding="utf-8") as f:
        manifest = json.load(f)
    manifest["drift_baseline"] = baseline
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def stamp_drift_baseline(
    artifact_dir: str, *, batch_size: int = 32, seed: int = 0
) -> Dict:
    """Compute and persist an artifact's own output-distribution baseline
    (export-time path — a fresh export has no quantize-check run to reuse;
    promotion reuses the check's outputs instead of calling this)."""
    import jax

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    manifest = serving_lib.read_manifest(artifact_dir)
    batch = pinned_eval_batch(manifest, batch_size, seed)
    fn = serving_lib.load_serving_artifact(artifact_dir)
    out = jax.device_get(fn(batch))
    baseline = summarize_output_distribution(
        {k: np.asarray(v) for k, v in out.items()},
        batch=batch.shape[0],
        seed=seed,
    )
    write_drift_baseline(artifact_dir, baseline)
    return baseline


def output_delta(name: str, ref: np.ndarray, cand: np.ndarray) -> Dict:
    """Delta record for one output; the applicable threshold keys depend on
    which of the three output kinds this is. Public: the promotion
    controller's shadow compare (serve/router.py) reuses exactly this math
    on live traffic — the canary's answer plays ``cand`` against the serving
    replica's ``ref``."""
    if ref.shape != cand.shape:
        return {"error": f"shape mismatch: {ref.shape} vs {cand.shape}"}
    if np.issubdtype(ref.dtype, np.integer) or np.issubdtype(
        cand.dtype, np.integer
    ):
        return {
            "kind": "integer",
            "disagree": round(float(np.mean(ref != cand)), 6),
        }
    ref64 = ref.astype(np.float64)
    cand64 = cand.astype(np.float64)
    delta = np.abs(ref64 - cand64)
    rec = {
        "kind": "float",
        "max_abs_delta": round(float(delta.max()), 6) if delta.size else 0.0,
        "mean_abs_delta": round(float(delta.mean()), 6) if delta.size else 0.0,
    }
    if _is_binary(ref64) and _is_binary(cand64):
        rec["kind"] = "binary"
        inter = float(np.sum((ref64 > 0.5) & (cand64 > 0.5)))
        union = float(np.sum((ref64 > 0.5) | (cand64 > 0.5)))
        rec["iou"] = round(inter / union, 6) if union else 1.0
    return rec


def run_quant_check(
    reference_dir: str,
    candidate_dir: str,
    *,
    batch_size: int = 16,
    seed: int = 0,
    thresholds: Optional[Dict[str, float]] = None,
    allow_fingerprint_mismatch: bool = False,
    telemetry=None,
) -> Dict:
    """Compare two exported artifacts over the pinned eval batch.

    Returns the verdict record (also ledgered as a ``quant_check`` event when
    ``telemetry`` is passed): per-output deltas, the thresholds applied, the
    failure list, and ``passed``. The candidate's precision — hence its
    budget — comes from its own manifest's ``quantization`` section via
    :func:`budget_key`: storage dtype, widened to ``int8-compute`` when the
    manifest declares int8 arithmetic (legacy manifests gate as float32).
    """
    import jax

    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    ref_manifest = serving_lib.read_manifest(reference_dir)
    cand_manifest = serving_lib.read_manifest(candidate_dir)
    dtype = budget_key(cand_manifest.get("quantization"))
    limits = dict(DEFAULT_THRESHOLDS.get(dtype, DEFAULT_THRESHOLDS["int8"]))
    if thresholds:
        limits.update({k: v for k, v in thresholds.items() if v is not None})

    failures = []
    ref_fp = (ref_manifest.get("quantization") or {}).get("source_fingerprint")
    cand_fp = (cand_manifest.get("quantization") or {}).get(
        "source_fingerprint"
    )
    if ref_fp and cand_fp and ref_fp != cand_fp:
        msg = (
            "source fingerprint mismatch — the artifacts derive from "
            "different checkpoints, the comparison is meaningless"
        )
        if allow_fingerprint_mismatch:
            logger.warning("quantize-check: %s (allowed by flag)", msg)
        else:
            failures.append(msg)

    batch = pinned_eval_batch(cand_manifest, batch_size, seed)
    outputs: Dict[str, Dict] = {}
    candidate_summary: Optional[Dict] = None
    if not failures:  # a wrong pairing makes the numerics noise; skip them
        ref_fn = serving_lib.load_serving_artifact(reference_dir)
        cand_fn = serving_lib.load_serving_artifact(candidate_dir)
        ref_out = jax.device_get(ref_fn(batch))
        cand_out = jax.device_get(cand_fn(batch))
        if set(ref_out) != set(cand_out):
            failures.append(
                f"output names differ: {sorted(ref_out)} vs {sorted(cand_out)}"
            )
        for name in sorted(set(ref_out) & set(cand_out)):
            rec = output_delta(
                name, np.asarray(ref_out[name]), np.asarray(cand_out[name])
            )
            outputs[name] = rec
            if "error" in rec:
                failures.append(f"{name}: {rec['error']}")
                continue
            if rec["kind"] == "integer":
                if rec["disagree"] > limits["max_disagree"]:
                    failures.append(
                        f"{name}: disagreement {rec['disagree']} > "
                        f"{limits['max_disagree']}"
                    )
                continue
            if rec["kind"] == "binary":
                # a binary mask's max|delta| is 1.0 the moment ANY pixel
                # flips near the decision threshold, so the float budgets
                # would fail every quantized segmentation artifact; masks
                # gate on IoU and the disagreement fraction (which IS the
                # mean |delta| of a {0,1} pair)
                if rec["mean_abs_delta"] > limits["max_disagree"]:
                    failures.append(
                        f"{name}: mask disagreement {rec['mean_abs_delta']} "
                        f"> {limits['max_disagree']}"
                    )
                if rec["iou"] < limits["min_iou"]:
                    failures.append(
                        f"{name}: IoU {rec['iou']} < {limits['min_iou']}"
                    )
                continue
            if rec["max_abs_delta"] > limits["max_abs_delta"]:
                failures.append(
                    f"{name}: max|delta| {rec['max_abs_delta']} > "
                    f"{limits['max_abs_delta']}"
                )
            if rec["mean_abs_delta"] > limits["mean_abs_delta"]:
                failures.append(
                    f"{name}: mean|delta| {rec['mean_abs_delta']} > "
                    f"{limits['mean_abs_delta']}"
                )

        # the candidate's output distribution over this same pinned batch:
        # the promotion controller persists it into the winning manifest as
        # the drift baseline — no second eval run needed
        candidate_summary = summarize_output_distribution(
            {k: np.asarray(v) for k, v in cand_out.items()},
            batch=batch.shape[0],
            seed=seed,
        )

    result = {
        "reference": reference_dir,
        "candidate": candidate_dir,
        "dtype": dtype,
        "batch": list(batch.shape),
        "seed": seed,
        "thresholds": limits,
        "outputs": outputs,
        "fingerprint_match": (
            None if not (ref_fp and cand_fp) else ref_fp == cand_fp
        ),
        "failures": failures,
        "passed": not failures,
    }
    if candidate_summary is not None:
        result["candidate_summary"] = candidate_summary
    if telemetry is not None:
        telemetry.event("quant_check", **result)
    return result
