// Native TFRecord shard reader: background-threaded file reading, masked-crc32c
// integrity checks, and a shuffle pool — the record-streaming half of the
// tf.data-class C++ input runtime (decode lives in io.cc). The reference
// inherited all of this from TensorFlow's C++ tf.data pipeline (SURVEY §2.2);
// here it is first-party.
//
// TFRecord framing (the public format):
//   uint64 length (LE) | uint32 masked_crc32c(length) | bytes data |
//   uint32 masked_crc32c(data)
// masked_crc = ((crc >> 15) | (crc << 17)) + 0xa282ead8, crc32c (Castagnoli).
//
// C API (ctypes):
//   int64 tfdl_rec_open(const char** paths, int n_paths, int shuffle_buf,
//                       uint64_t seed, int verify_crc)
//   int   tfdl_rec_next(int64 handle, const uint8_t** data, uint64_t* len)
//           -> 1 record, 0 clean end-of-stream, -1 corrupt stream
//   void  tfdl_rec_close(int64 handle)
// The pointer returned by tfdl_rec_next stays valid until the next call on the
// same handle. One producer thread per handle reads ahead into a bounded queue
// (file IO overlaps the caller's decode/augment work); the consumer side keeps
// a shuffle pool of `shuffle_buf` records and emits a uniformly random one per
// call (shard order is itself shuffled by `seed`).
//
// Offset-indexed range reads (the data-service worker read path — records at
// known byte offsets from a shard's .idx sidecar, any order):
//   int64 tfdl_ranges_open(const char* path)
//   int   tfdl_ranges_read(int64 handle, const uint64_t* offsets, int n,
//                          int verify, const uint8_t** datas, uint64_t* lens)
//           -> 0 ok (datas/lens filled), -1 corrupt, -2 io, -3 bad handle
//   void  tfdl_ranges_close(int64 handle)
// Pointers stay valid until the next read/close on the same handle; a handle
// serves ONE caller at a time (each service worker opens its own).

#include <algorithm>
#include <condition_variable>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace {

// crc32c (Castagnoli, reflected 0x82f63b78), table-driven.
uint32_t kCrcTable[256];
bool crc_table_init = [] {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0x82f63b78u ^ (c >> 1) : c >> 1;
    kCrcTable[i] = c;
  }
  return true;
}();

uint32_t Crc32c(const uint8_t* data, size_t n) {
  uint32_t c = 0xffffffffu;
  for (size_t i = 0; i < n; ++i) c = kCrcTable[(c ^ data[i]) & 0xff] ^ (c >> 8);
  return c ^ 0xffffffffu;
}

uint32_t MaskedCrc(const uint8_t* data, size_t n) {
  uint32_t crc = Crc32c(data, n);
  return ((crc >> 15) | (crc << 17)) + 0xa282ead8u;
}

struct Reader {
  std::vector<std::string> paths;
  bool verify;
  size_t queue_cap;

  std::thread producer;
  std::mutex mu;
  std::condition_variable cv_pop, cv_push;
  std::deque<std::vector<uint8_t>> queue;
  bool done = false;       // producer finished (or error)
  int error = 0;           // 0 ok, 1 crc/framing corruption, 2 file IO failure
  bool closing = false;    // consumer asked to stop

  std::vector<std::vector<uint8_t>> pool;  // shuffle pool
  std::mt19937_64 rng;
  size_t shuffle_buf;
  std::vector<uint8_t> current;  // buffer handed to the caller

  void Produce() {
    for (const auto& path : paths) {
      FILE* f = std::fopen(path.c_str(), "rb");
      if (!f) {
        SetDone(2);  // IO failure, not corruption
        return;
      }
      while (true) {
        uint8_t header[12];
        size_t got = std::fread(header, 1, 12, f);
        if (got == 0) break;  // clean end of shard
        if (got != 12) {
          std::fclose(f);
          SetDone(1);
          return;
        }
        uint64_t len;
        std::memcpy(&len, header, 8);
        // length sanity is NOT optional: a garbage 64-bit length would make the
        // vector allocation below throw in this background thread -> terminate
        if (len > (1ull << 31)) {
          std::fclose(f);
          SetDone(1);
          return;
        }
        if (verify) {
          uint32_t want;
          std::memcpy(&want, header + 8, 4);
          if (MaskedCrc(header, 8) != want) {
            std::fclose(f);
            SetDone(1);
            return;
          }
        }
        std::vector<uint8_t> rec(len);
        uint8_t footer[4];
        if (std::fread(rec.data(), 1, len, f) != len ||
            std::fread(footer, 1, 4, f) != 4) {
          std::fclose(f);
          SetDone(1);
          return;
        }
        if (verify) {
          uint32_t want;
          std::memcpy(&want, footer, 4);
          if (MaskedCrc(rec.data(), len) != want) {
            std::fclose(f);
            SetDone(1);
            return;
          }
        }
        std::unique_lock<std::mutex> lk(mu);
        cv_push.wait(lk, [&] { return queue.size() < queue_cap || closing; });
        if (closing) {
          std::fclose(f);
          return;
        }
        queue.push_back(std::move(rec));
        cv_pop.notify_one();
      }
      std::fclose(f);
    }
    SetDone(0);
  }

  void SetDone(int err) {
    std::lock_guard<std::mutex> lk(mu);
    done = true;
    error = err;
    cv_pop.notify_all();
  }

  // Pop one record from the queue; false on end-of-stream/error.
  bool Pop(std::vector<uint8_t>* out) {
    std::unique_lock<std::mutex> lk(mu);
    cv_pop.wait(lk, [&] { return !queue.empty() || done; });
    if (queue.empty()) return false;
    *out = std::move(queue.front());
    queue.pop_front();
    cv_push.notify_one();
    return true;
  }

  // 1 = record in `current`, 0 = end, -1 = corruption, -2 = file IO failure.
  int Next() {
    // top up the shuffle pool
    while (pool.size() < shuffle_buf) {
      std::vector<uint8_t> rec;
      if (!Pop(&rec)) break;
      pool.push_back(std::move(rec));
    }
    if (pool.empty()) {
      std::lock_guard<std::mutex> lk(mu);
      return error ? -error : 0;
    }
    size_t idx =
        shuffle_buf > 1 ? std::uniform_int_distribution<size_t>(0, pool.size() - 1)(rng)
                        : 0;
    current = std::move(pool[idx]);
    pool[idx] = std::move(pool.back());
    pool.pop_back();
    return 1;
  }
};

std::mutex g_mu;
std::unordered_map<int64_t, Reader*> g_readers;
int64_t g_next_handle = 1;

// One shard file opened for random-access record reads. The byte storage for
// the latest read call lives on the handle, so returned pointers stay valid
// until the next call — the same lifetime contract as tfdl_rec_next.
struct RangeReader {
  FILE* f = nullptr;
  std::vector<std::vector<uint8_t>> recs;
};

std::mutex g_range_mu;
std::unordered_map<int64_t, RangeReader*> g_range_readers;
int64_t g_next_range_handle = 1;

}  // namespace

extern "C" {

int64_t tfdl_rec_open(const char** paths, int n_paths, int shuffle_buf,
                      uint64_t seed, int verify_crc) {
  if (n_paths <= 0) return 0;
  auto* r = new Reader();
  r->paths.assign(paths, paths + n_paths);
  std::mt19937_64 order_rng(seed);
  std::shuffle(r->paths.begin(), r->paths.end(), order_rng);
  r->rng.seed(seed ^ 0x9e3779b97f4a7c15ull);
  r->shuffle_buf = shuffle_buf > 0 ? static_cast<size_t>(shuffle_buf) : 1;
  r->queue_cap = r->shuffle_buf + 1024;
  r->verify = verify_crc != 0;
  r->producer = std::thread([r] { r->Produce(); });
  std::lock_guard<std::mutex> lk(g_mu);
  int64_t h = g_next_handle++;
  g_readers[h] = r;
  return h;
}

int tfdl_rec_next(int64_t handle, const uint8_t** data, uint64_t* len) {
  Reader* r;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_readers.find(handle);
    // -3 = unknown/closed handle (a caller lifecycle bug), distinct from the
    // -1 corruption and -2 IO codes so the binding can raise the right error
    if (it == g_readers.end()) return -3;
    r = it->second;
  }
  int rc = r->Next();
  if (rc == 1) {
    *data = r->current.data();
    *len = r->current.size();
  } else {
    *data = nullptr;
    *len = 0;
  }
  return rc;
}

void tfdl_rec_close(int64_t handle) {
  Reader* r = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_mu);
    auto it = g_readers.find(handle);
    if (it == g_readers.end()) return;
    r = it->second;
    g_readers.erase(it);
  }
  {
    std::lock_guard<std::mutex> lk(r->mu);
    r->closing = true;
    r->cv_push.notify_all();
  }
  if (r->producer.joinable()) r->producer.join();
  delete r;
}

int64_t tfdl_ranges_open(const char* path) {
  FILE* f = std::fopen(path, "rb");
  if (!f) return 0;
  auto* r = new RangeReader();
  r->f = f;
  std::lock_guard<std::mutex> lk(g_range_mu);
  int64_t h = g_next_range_handle++;
  g_range_readers[h] = r;
  return h;
}

int tfdl_ranges_read(int64_t handle, const uint64_t* offsets, int n,
                     int verify, const uint8_t** datas, uint64_t* lens) {
  RangeReader* r;
  {
    std::lock_guard<std::mutex> lk(g_range_mu);
    auto it = g_range_readers.find(handle);
    if (it == g_range_readers.end()) return -3;
    r = it->second;
  }
  r->recs.clear();
  r->recs.reserve(n);
  for (int i = 0; i < n; ++i) {
    // a PRIOR call's transient error must not make this call's clean short
    // reads (real truncation) look like retryable I/O — handles are cached
    // and reused across retries
    std::clearerr(r->f);
    if (fseeko(r->f, static_cast<off_t>(offsets[i]), SEEK_SET) != 0) return -2;
    uint8_t header[12];
    if (std::fread(header, 1, 12, r->f) != 12) {
      // ferror = transient I/O (retryable -2, like the Python fallback's
      // OSError); clean short read = truncated framing / bad offset (-1)
      return std::ferror(r->f) ? -2 : -1;
    }
    uint64_t len;
    std::memcpy(&len, header, 8);
    if (len > (1ull << 31)) return -1;  // garbage length: wrong offset/corrupt
    if (verify) {
      uint32_t want;
      std::memcpy(&want, header + 8, 4);
      if (MaskedCrc(header, 8) != want) return -1;
    }
    std::vector<uint8_t> rec;
    try {
      rec.resize(len);
    } catch (const std::bad_alloc&) {
      // with verify=0 a mid-record offset's garbage length can pass the
      // 2^31 guard; an exception must not cross the extern "C" boundary
      // (std::terminate) — report it as the corruption it is
      return -1;
    }
    uint8_t footer[4];
    if (std::fread(rec.data(), 1, len, r->f) != len ||
        std::fread(footer, 1, 4, r->f) != 4) {
      return std::ferror(r->f) ? -2 : -1;
    }
    if (verify) {
      uint32_t want;
      std::memcpy(&want, footer, 4);
      if (MaskedCrc(rec.data(), len) != want) return -1;
    }
    r->recs.push_back(std::move(rec));
    datas[i] = r->recs.back().data();
    lens[i] = r->recs.back().size();
  }
  return 0;
}

void tfdl_ranges_close(int64_t handle) {
  RangeReader* r = nullptr;
  {
    std::lock_guard<std::mutex> lk(g_range_mu);
    auto it = g_range_readers.find(handle);
    if (it == g_range_readers.end()) return;
    r = it->second;
    g_range_readers.erase(it);
  }
  std::fclose(r->f);
  delete r;
}

}  // extern "C"
