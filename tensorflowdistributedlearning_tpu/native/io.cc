// Native host-side IO: multithreaded image decode into a caller-provided
// float32 arena.
//
// The reference's input pipeline leaned on TensorFlow's C++ tf.data runtime for
// its decode/shuffle/batch/prefetch hot path (reference: model.py:296-322; SURVEY
// §2.2 "tf.data C++ pipeline"). This is the TPU-native framework's equivalent:
// the host-side decode runs in native threads (off the GIL), the device-side
// augmentation stays in XLA (data/augment.py).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image):
//   tfdl_decode_png_batch(paths, n, out, h, w, channels, n_threads) -> int
//     Decodes n PNG files (which must already be h x w) into
//     out[n, h, w, channels] float32 in [0, 1]. Grayscale files fill every
//     requested channel; RGB(A) files must match channels (or be gray-converted
//     when channels == 1). Returns 0 on success, else 1 + the index of the
//     first failing file.
//   tfdl_decode_image_batch(paths, n, out, h, w, channels, n_threads) -> int
//     General form for ImageNet-class datasets: accepts PNG and JPEG (sniffed
//     by magic bytes) at ANY source size and bilinearly resizes to h x w.
//   tfdl_version() -> const char*

#include <cstddef>
#include <cstdio>

// jpeglib.h requires size_t/FILE to be declared before inclusion.
// TFDL_NO_JPEG builds (hosts without libjpeg) keep the PNG fast path and let
// the Python side fall back to PIL for JPEG files.
#ifndef TFDL_NO_JPEG
#include <jpeglib.h>
#endif
#include <png.h>

#include <atomic>
#include <cmath>
#include <csetjmp>
#include <cstdint>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Decode one 8/16-bit PNG to float32 [h, w, channels] in [0, 1].
// Returns true on success (file exists, is a PNG, and matches h x w).
bool DecodeOne(const char* path, float* out, int h, int w, int channels) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return false;

  png_byte header[8];
  if (std::fread(header, 1, 8, fp) != 8 || png_sig_cmp(header, 0, 8)) {
    std::fclose(fp);
    return false;
  }

  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) {
    std::fclose(fp);
    return false;
  }
  png_infop info = png_create_info_struct(png);
  // Declared BEFORE setjmp so a libpng longjmp unwinds through objects that are
  // already fully constructed — their destructors run on the error-path return.
  std::vector<png_byte> pixels;
  std::vector<png_bytep> rows;
  if (!info || setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, info ? &info : nullptr, nullptr);
    std::fclose(fp);
    return false;
  }

  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);

  const int img_w = png_get_image_width(png, info);
  const int img_h = png_get_image_height(png, info);
  if (img_w != w || img_h != h) {
    png_destroy_read_struct(&png, &info, nullptr);
    std::fclose(fp);
    return false;
  }

  // Normalize every input to 8-bit gray or RGB.
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  png_set_interlace_handling(png);  // de-interlace Adam7 files
  png_read_update_info(png, info);
  const int img_channels = png_get_channels(png, info);

  // Read the whole image through row pointers: png_read_image runs every
  // interlace pass, which per-row png_read_row would not.
  const size_t rowbytes = png_get_rowbytes(png, info);
  pixels.resize(rowbytes * h);
  rows.resize(h);
  for (int y = 0; y < h; ++y) rows[y] = pixels.data() + rowbytes * y;
  png_read_image(png, rows.data());

  for (int y = 0; y < h; ++y) {
    const png_byte* row = rows[y];
    float* dst = out + static_cast<int64_t>(y) * w * channels;
    if (img_channels == 1) {
      // gray: broadcast into every requested channel
      for (int x = 0; x < w; ++x) {
        const float v = row[x] / 255.0f;
        for (int c = 0; c < channels; ++c) dst[x * channels + c] = v;
      }
    } else if (img_channels == 3 && channels == 3) {
      for (int x = 0; x < w * 3; ++x) dst[x] = row[x] / 255.0f;
    } else if (img_channels == 3 && channels == 1) {
      // ITU-R BT.601 luma, what PIL's convert("L") computes
      for (int x = 0; x < w; ++x) {
        dst[x] = (0.299f * row[3 * x] + 0.587f * row[3 * x + 1] +
                  0.114f * row[3 * x + 2]) /
                 255.0f;
      }
    } else {
      png_destroy_read_struct(&png, &info, nullptr);
      std::fclose(fp);
      return false;
    }
  }

  png_destroy_read_struct(&png, &info, nullptr);
  std::fclose(fp);
  return true;
}

// ---------------------------------------------------------------------------
// General path: PNG or JPEG at any source size, bilinear-resized to h x w.
// ---------------------------------------------------------------------------

// Decode a PNG at its native size into an 8-bit gray or RGB buffer.
bool DecodePngNative(FILE* fp, std::vector<unsigned char>* pixels, int* img_h,
                     int* img_w, int* img_c) {
  png_byte header[8];
  if (std::fread(header, 1, 8, fp) != 8 || png_sig_cmp(header, 0, 8)) {
    return false;
  }
  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) return false;
  png_infop info = png_create_info_struct(png);
  std::vector<png_bytep> rows;
  if (!info || setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, info ? &info : nullptr, nullptr);
    return false;
  }
  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  png_set_interlace_handling(png);
  png_read_update_info(png, info);
  *img_h = png_get_image_height(png, info);
  *img_w = png_get_image_width(png, info);
  *img_c = png_get_channels(png, info);
  if (*img_c == 2) {  // gray+alpha survived strip_alpha ordering quirks
    png_destroy_read_struct(&png, &info, nullptr);
    return false;
  }
  const size_t rowbytes = png_get_rowbytes(png, info);
  pixels->resize(rowbytes * *img_h);
  rows.resize(*img_h);
  for (int y = 0; y < *img_h; ++y) rows[y] = pixels->data() + rowbytes * y;
  png_read_image(png, rows.data());
  png_destroy_read_struct(&png, &info, nullptr);
  return true;
}

#ifndef TFDL_NO_JPEG
struct JpegErr {
  jpeg_error_mgr mgr;
  jmp_buf jump;
};

void JpegErrorExit(j_common_ptr cinfo) {
  JpegErr* err = reinterpret_cast<JpegErr*>(cinfo->err);
  longjmp(err->jump, 1);
}

// Decode a JPEG at its native size into an 8-bit gray or RGB buffer. CMYK/YCCK
// files (a handful exist in real ImageNet) are decoded as CMYK and converted —
// libjpeg cannot convert those to RGB itself and would abort the batch.
bool DecodeJpegNative(FILE* fp, int want_channels,
                      std::vector<unsigned char>* pixels, int* img_h,
                      int* img_w, int* img_c) {
  jpeg_decompress_struct cinfo;
  JpegErr jerr;
  cinfo.err = jpeg_std_error(&jerr.mgr);
  jerr.mgr.error_exit = JpegErrorExit;
  std::vector<unsigned char> cmyk;  // constructed before setjmp
  if (setjmp(jerr.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return false;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_stdio_src(&cinfo, fp);
  jpeg_read_header(&cinfo, TRUE);
  const bool is_cmyk = cinfo.jpeg_color_space == JCS_CMYK ||
                       cinfo.jpeg_color_space == JCS_YCCK;
  if (is_cmyk) {
    cinfo.out_color_space = JCS_CMYK;
  } else {
    cinfo.out_color_space = want_channels == 1 ? JCS_GRAYSCALE : JCS_RGB;
  }
  jpeg_start_decompress(&cinfo);
  *img_h = cinfo.output_height;
  *img_w = cinfo.output_width;
  const int out_c = cinfo.output_components;
  const size_t rowbytes = static_cast<size_t>(*img_w) * out_c;
  std::vector<unsigned char>* target = is_cmyk ? &cmyk : pixels;
  target->resize(rowbytes * *img_h);
  while (cinfo.output_scanline < cinfo.output_height) {
    unsigned char* row = target->data() + rowbytes * cinfo.output_scanline;
    jpeg_read_scanlines(&cinfo, &row, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  if (!is_cmyk) {
    *img_c = out_c;
    return true;
  }
  // Adobe CMYK JPEGs store inverted values; libjpeg hands them through as-is,
  // so r = c*k/255 with the stored (inverted) samples — what PIL produces for
  // the same files via its CMYK path.
  const size_t npx = static_cast<size_t>(*img_h) * *img_w;
  pixels->resize(npx * 3);
  for (size_t i = 0; i < npx; ++i) {
    const unsigned char* p = cmyk.data() + i * 4;
    unsigned char* q = pixels->data() + i * 3;
    q[0] = static_cast<unsigned char>(p[0] * p[3] / 255);
    q[1] = static_cast<unsigned char>(p[1] * p[3] / 255);
    q[2] = static_cast<unsigned char>(p[2] * p[3] / 255);
  }
  *img_c = 3;
  return true;
}
#endif  // TFDL_NO_JPEG

// Precomputed 1-D triangle-filter resampling weights for one output axis
// (PIL-style antialiased bilinear: filter support scales with the downscale
// ratio, so minification averages instead of aliasing; half-pixel centers).
struct Taps {
  std::vector<int> start;     // first source index per output index
  std::vector<int> count;     // tap count per output index
  std::vector<int> offset;    // prefix index of each output's weights
  std::vector<float> weight;  // concatenated normalized weights
};

Taps BuildTaps(int src_n, int dst_n) {
  Taps t;
  const double scale = static_cast<double>(src_n) / dst_n;
  const double support = scale > 1.0 ? scale : 1.0;  // triangle radius
  t.start.resize(dst_n);
  t.count.resize(dst_n);
  t.offset.resize(dst_n);
  for (int i = 0; i < dst_n; ++i) {
    const double center = (i + 0.5) * scale;
    int lo = static_cast<int>(std::floor(center - support + 0.5));
    int hi = static_cast<int>(std::floor(center + support + 0.5));
    if (lo < 0) lo = 0;
    if (hi > src_n) hi = src_n;
    t.start[i] = lo;
    t.count[i] = hi - lo;
    t.offset[i] = static_cast<int>(t.weight.size());
    double total = 0.0;
    std::vector<double> ws(hi - lo);
    for (int j = lo; j < hi; ++j) {
      const double d = (j + 0.5 - center) / support;
      const double wgt = d < 0 ? 1.0 + d : 1.0 - d;  // triangle
      ws[j - lo] = wgt > 0 ? wgt : 0.0;
      total += ws[j - lo];
    }
    for (double& wgt : ws) t.weight.push_back(static_cast<float>(wgt / total));
  }
  return t;
}

// Antialiased bilinear resize of an 8-bit [src_h, src_w, src_c] buffer into
// float32 [h, w, channels] in [0, 1] (separable triangle filter, the PIL
// BILINEAR convention), with the same channel adaptation rules as the
// fixed-size path.
bool ResizeToFloat(const unsigned char* src, int src_h, int src_w, int src_c,
                   float* out, int h, int w, int channels) {
  if (!(src_c == 1 || src_c == 3)) return false;
  if (!(channels == src_c || src_c == 1 || channels == 1)) return false;
  const Taps tx = BuildTaps(src_w, w);
  const Taps ty = BuildTaps(src_h, h);

  // pass 1: horizontal, uint8 -> float32 [src_h, w, src_c]. y-outer/x-inner so
  // both the source row and the tmp row stream contiguously through cache.
  std::vector<float> tmp(static_cast<size_t>(src_h) * w * src_c);
  for (int y = 0; y < src_h; ++y) {
    const unsigned char* row = src + static_cast<size_t>(y) * src_w * src_c;
    float* trow = tmp.data() + static_cast<size_t>(y) * w * src_c;
    for (int x = 0; x < w; ++x) {
      const int lo = tx.start[x], cnt = tx.count[x];
      const float* wp = tx.weight.data() + tx.offset[x];
      float acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const unsigned char* px = row + (lo + k) * src_c;
        for (int c = 0; c < src_c; ++c) acc[c] += wp[k] * px[c];
      }
      for (int c = 0; c < src_c; ++c) trow[x * src_c + c] = acc[c];
    }
  }

  // pass 2: vertical + [0,1] scaling + channel adaptation
  for (int y = 0; y < h; ++y) {
    const int lo = ty.start[y], cnt = ty.count[y];
    const float* wp = ty.weight.data() + ty.offset[y];
    for (int x = 0; x < w; ++x) {
      float acc[3] = {0, 0, 0};
      for (int k = 0; k < cnt; ++k) {
        const float* px =
            tmp.data() + (static_cast<size_t>(lo + k) * w + x) * src_c;
        for (int c = 0; c < src_c; ++c) acc[c] += wp[k] * px[c];
      }
      for (int c = 0; c < src_c; ++c) acc[c] /= 255.0f;
      float* dst = out + (static_cast<int64_t>(y) * w + x) * channels;
      if (src_c == channels) {
        for (int c = 0; c < channels; ++c) dst[c] = acc[c];
      } else if (src_c == 1) {
        for (int c = 0; c < channels; ++c) dst[c] = acc[0];
      } else {  // RGB -> gray, BT.601 luma (PIL convert("L"))
        dst[0] = 0.299f * acc[0] + 0.587f * acc[1] + 0.114f * acc[2];
      }
    }
  }
  return true;
}

// PNG or JPEG (magic-byte sniff) at any size -> float32 [h, w, channels].
// Decode an already-open PNG/JPEG stream (file or fmemopen'd record blob).
bool DecodeImageStream(FILE* fp, float* out, int h, int w, int channels) {
  unsigned char magic[2];
  if (std::fread(magic, 1, 2, fp) != 2) return false;
  std::rewind(fp);
  std::vector<unsigned char> pixels;
  int img_h = 0, img_w = 0, img_c = 0;
  bool ok;
  if (magic[0] == 0xFF && magic[1] == 0xD8) {
#ifdef TFDL_NO_JPEG
    ok = false;  // no libjpeg on this host; Python side falls back to PIL
#else
    ok = DecodeJpegNative(fp, channels, &pixels, &img_h, &img_w, &img_c);
#endif
  } else {
    ok = DecodePngNative(fp, &pixels, &img_h, &img_w, &img_c);
  }
  if (!ok) return false;
  return ResizeToFloat(pixels.data(), img_h, img_w, img_c, out, h, w, channels);
}

bool DecodeImageOne(const char* path, float* out, int h, int w, int channels) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return false;
  bool ok = DecodeImageStream(fp, out, h, w, channels);
  std::fclose(fp);
  return ok;
}

// Shared work-stealing thread harness for both batch entry points: decode each
// file with `decode_one` and report the MINIMAL failing index.
//
// Contract relied on by the Python per-file fallback (loader.decode_image_batch):
// every index below the returned failure index HAS been decoded. Workers
// therefore process every index they claim (no early bail-out — a worker that
// returned after another thread's failure would leave its just-claimed row as
// uninitialized memory that the fallback would then trust), and failures fold
// into an atomic minimum rather than first-to-CAS.
// Generalized over any per-index decode callable (file paths, memory blobs).
template <typename DecodeIndexFn>
int DecodeBatchIndexed(DecodeIndexFn decode_index, int n, int n_threads) {
  if (n <= 0) return 0;
  if (n_threads <= 0) n_threads = 1;
  if (n_threads > n) n_threads = n;

  std::atomic<int> next(0);
  std::atomic<int> min_error(n);  // n = "no failure yet"

  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      // Skip only indices ABOVE the current minimal failure: they are beyond
      // the contract's guarantee and will be revisited by the fallback loop.
      if (i > min_error.load(std::memory_order_relaxed)) continue;
      if (!decode_index(i)) {
        int cur = min_error.load();
        while (i < cur && !min_error.compare_exchange_weak(cur, i)) {
        }
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  const int err = min_error.load();
  return err >= n ? 0 : 1 + err;
}

using DecodeFn = bool (*)(const char*, float*, int, int, int);

int DecodeBatch(DecodeFn decode_one, const char** paths, int n, float* out,
                int h, int w, int channels, int n_threads) {
  const int64_t stride = static_cast<int64_t>(h) * w * channels;
  return DecodeBatchIndexed(
      [&](int i) { return decode_one(paths[i], out + i * stride, h, w, channels); },
      n, n_threads);
}

}  // namespace

extern "C" {

int tfdl_decode_png_batch(const char** paths, int n, float* out, int h, int w,
                          int channels, int n_threads) {
  return DecodeBatch(DecodeOne, paths, n, out, h, w, channels, n_threads);
}

int tfdl_decode_image_batch(const char** paths, int n, float* out, int h, int w,
                            int channels, int n_threads) {
  return DecodeBatch(DecodeImageOne, paths, n, out, h, w, channels, n_threads);
}

// In-memory twin of tfdl_decode_image_batch for record payloads: each blob is
// wrapped with fmemopen so the stream decoders run unchanged. Same minimal-
// failing-index contract as DecodeBatch.
int tfdl_decode_image_blob_batch(const unsigned char** blobs,
                                 const unsigned long long* sizes, int n,
                                 float* out, int h, int w, int channels,
                                 int n_threads) {
  const int64_t stride = static_cast<int64_t>(h) * w * channels;
  return DecodeBatchIndexed(
      [&](int i) {
        FILE* fp = fmemopen(const_cast<unsigned char*>(blobs[i]),
                            static_cast<size_t>(sizes[i]), "rb");
        bool ok = fp != nullptr &&
                  DecodeImageStream(fp, out + i * stride, h, w, channels);
        if (fp) std::fclose(fp);
        return ok;
      },
      n, n_threads);
}

const char* tfdl_version() { return "tfdl-io 0.2.0"; }

}  // extern "C"
