// Native host-side IO: multithreaded PNG decode into a caller-provided float32
// arena.
//
// The reference's input pipeline leaned on TensorFlow's C++ tf.data runtime for
// its decode/shuffle/batch/prefetch hot path (reference: model.py:296-322; SURVEY
// §2.2 "tf.data C++ pipeline"). This is the TPU-native framework's equivalent:
// the host-side decode runs in native threads (off the GIL), the device-side
// augmentation stays in XLA (data/augment.py).
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image):
//   tfdl_decode_png_batch(paths, n, out, h, w, channels, n_threads) -> int
//     Decodes n PNG files into out[n, h, w, channels] float32 in [0, 1].
//     Grayscale files fill every requested channel; RGB(A) files must match
//     channels (or be gray-converted when channels == 1). Returns 0 on success,
//     else 1 + the index of the first failing file.
//   tfdl_version() -> const char*

#include <png.h>

#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// Decode one 8/16-bit PNG to float32 [h, w, channels] in [0, 1].
// Returns true on success (file exists, is a PNG, and matches h x w).
bool DecodeOne(const char* path, float* out, int h, int w, int channels) {
  FILE* fp = std::fopen(path, "rb");
  if (!fp) return false;

  png_byte header[8];
  if (std::fread(header, 1, 8, fp) != 8 || png_sig_cmp(header, 0, 8)) {
    std::fclose(fp);
    return false;
  }

  png_structp png =
      png_create_read_struct(PNG_LIBPNG_VER_STRING, nullptr, nullptr, nullptr);
  if (!png) {
    std::fclose(fp);
    return false;
  }
  png_infop info = png_create_info_struct(png);
  // Declared BEFORE setjmp so a libpng longjmp unwinds through objects that are
  // already fully constructed — their destructors run on the error-path return.
  std::vector<png_byte> pixels;
  std::vector<png_bytep> rows;
  if (!info || setjmp(png_jmpbuf(png))) {
    png_destroy_read_struct(&png, info ? &info : nullptr, nullptr);
    std::fclose(fp);
    return false;
  }

  png_init_io(png, fp);
  png_set_sig_bytes(png, 8);
  png_read_info(png, info);

  const int img_w = png_get_image_width(png, info);
  const int img_h = png_get_image_height(png, info);
  if (img_w != w || img_h != h) {
    png_destroy_read_struct(&png, &info, nullptr);
    std::fclose(fp);
    return false;
  }

  // Normalize every input to 8-bit gray or RGB.
  png_set_strip_16(png);
  png_set_strip_alpha(png);
  png_set_palette_to_rgb(png);
  png_set_expand_gray_1_2_4_to_8(png);
  png_set_interlace_handling(png);  // de-interlace Adam7 files
  png_read_update_info(png, info);
  const int img_channels = png_get_channels(png, info);

  // Read the whole image through row pointers: png_read_image runs every
  // interlace pass, which per-row png_read_row would not.
  const size_t rowbytes = png_get_rowbytes(png, info);
  pixels.resize(rowbytes * h);
  rows.resize(h);
  for (int y = 0; y < h; ++y) rows[y] = pixels.data() + rowbytes * y;
  png_read_image(png, rows.data());

  for (int y = 0; y < h; ++y) {
    const png_byte* row = rows[y];
    float* dst = out + static_cast<int64_t>(y) * w * channels;
    if (img_channels == 1) {
      // gray: broadcast into every requested channel
      for (int x = 0; x < w; ++x) {
        const float v = row[x] / 255.0f;
        for (int c = 0; c < channels; ++c) dst[x * channels + c] = v;
      }
    } else if (img_channels == 3 && channels == 3) {
      for (int x = 0; x < w * 3; ++x) dst[x] = row[x] / 255.0f;
    } else if (img_channels == 3 && channels == 1) {
      // ITU-R BT.601 luma, what PIL's convert("L") computes
      for (int x = 0; x < w; ++x) {
        dst[x] = (0.299f * row[3 * x] + 0.587f * row[3 * x + 1] +
                  0.114f * row[3 * x + 2]) /
                 255.0f;
      }
    } else {
      png_destroy_read_struct(&png, &info, nullptr);
      std::fclose(fp);
      return false;
    }
  }

  png_destroy_read_struct(&png, &info, nullptr);
  std::fclose(fp);
  return true;
}

}  // namespace

extern "C" {

int tfdl_decode_png_batch(const char** paths, int n, float* out, int h, int w,
                          int channels, int n_threads) {
  if (n <= 0) return 0;
  if (n_threads <= 0) n_threads = 1;
  if (n_threads > n) n_threads = n;

  std::atomic<int> next(0);
  std::atomic<int> first_error(-1);
  const int64_t stride = static_cast<int64_t>(h) * w * channels;

  auto worker = [&]() {
    for (int i = next.fetch_add(1); i < n; i = next.fetch_add(1)) {
      if (first_error.load(std::memory_order_relaxed) >= 0) return;
      if (!DecodeOne(paths[i], out + i * stride, h, w, channels)) {
        int expected = -1;
        first_error.compare_exchange_strong(expected, i);
        return;
      }
    }
  };

  std::vector<std::thread> threads;
  threads.reserve(n_threads);
  for (int t = 0; t < n_threads; ++t) threads.emplace_back(worker);
  for (auto& t : threads) t.join();

  const int err = first_error.load();
  return err < 0 ? 0 : 1 + err;
}

const char* tfdl_version() { return "tfdl-io 0.1.0"; }

}  // extern "C"
