"""Native (C++) host-side components, bound via ctypes.

The reference inherited its native IO from TensorFlow's C++ tf.data runtime
(reference: model.py:296-322; SURVEY §2.2). Here the native pieces are first-party:
``io.cc`` provides multithreaded PNG/JPEG decoding with bilinear resize that releases the GIL, compiled on
first use into ``_build/libtfdl_io.so`` and loaded with ctypes (pybind11 is not in
this image). Every native entry point has a pure-Python fallback, so the framework
works even where a C++ toolchain is absent.
"""

from tensorflowdistributedlearning_tpu.native.loader import (
    decode_image_batch,
    decode_png_batch,
    native_available,
)

__all__ = ["decode_image_batch", "decode_png_batch", "native_available"]
