"""ctypes binding + on-demand build for the native IO library (io.cc).

Build strategy: compile ``io.cc`` with the system ``g++`` into
``{package}/native/_build/libtfdl_io.so`` the first time it is needed, guarded by an
mtime check. Concurrent processes may each compile, but each writes to a
pid-unique temp file and installs with an atomic ``os.replace``, so the installed
library is never torn. Falls back to PIL decoding when no compiler or libpng is
available — same results, just slower and GIL-bound.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from typing import Optional, Sequence

import numpy as np

logger = logging.getLogger(__name__)

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "io.cc")
_BUILD_DIR = os.path.join(_HERE, "_build")
_LIB = os.path.join(_BUILD_DIR, "libtfdl_io.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build_library(
    src: str, target: str, variant_flags: Sequence[Sequence[str]]
) -> Optional[str]:
    """Compile ``src`` into ``target`` trying flag variants in order (pid-unique
    temp + atomic install — the shared build core for every native library in
    this package). Returns the install path, or None with a warning."""
    tmp = f"{target}.{os.getpid()}.tmp"  # pid-unique: parallel builders never collide
    base = ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread", src]
    last_err: Exception | None = None
    for flags in variant_flags:
        cmd = base + list(flags) + ["-o", tmp]
        try:
            os.makedirs(os.path.dirname(target), exist_ok=True)
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, target)  # atomic; concurrent winners are identical
            return target
        except (
            subprocess.CalledProcessError,
            subprocess.TimeoutExpired,
            OSError,  # includes read-only package dirs (makedirs/replace)
        ) as e:
            last_err = e
    detail = getattr(last_err, "stderr", b"")
    logger.warning(
        "native build of %s failed (%s); using Python fallback. %s",
        os.path.basename(src),
        last_err,
        detail.decode()[:500] if detail else "",
    )
    return None


def _build() -> bool:
    # Prefer full PNG+JPEG support; on hosts without libjpeg fall back to a
    # PNG-only build (TFDL_NO_JPEG) so the native PNG fast path survives —
    # decode_image_batch then PIL-decodes JPEG files one at a time.
    return (
        _build_library(
            _SRC, _LIB, [["-lpng", "-ljpeg"], ["-DTFDL_NO_JPEG", "-lpng"]]
        )
        is not None
    )


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        fresh = os.path.exists(_LIB) and os.path.getmtime(_LIB) >= os.path.getmtime(
            _SRC
        )
        if not fresh and not _build():
            return None
        try:
            lib = ctypes.CDLL(_LIB)
        except OSError as e:
            logger.warning("native IO load failed (%s); using PIL fallback", e)
            return None
        batch_sig = [
            ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int,
            ctypes.POINTER(ctypes.c_float),
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
            ctypes.c_int,
        ]
        lib.tfdl_decode_png_batch.restype = ctypes.c_int
        lib.tfdl_decode_png_batch.argtypes = batch_sig
        lib.tfdl_decode_image_batch.restype = ctypes.c_int
        lib.tfdl_decode_image_batch.argtypes = batch_sig
        lib.tfdl_version.restype = ctypes.c_char_p
        _lib = lib
        return _lib


def native_available() -> bool:
    """True when the C++ decoder built and loaded on this machine."""
    return _load() is not None


def _decode_pil(paths: Sequence[str], h: int, w: int, channels: int) -> np.ndarray:
    from PIL import Image

    out = np.empty((len(paths), h, w, channels), np.float32)
    for i, p in enumerate(paths):
        with Image.open(p) as im:
            arr = (
                np.asarray(im.convert("L" if channels == 1 else "RGB"), np.float32)
                / 255.0
            )
        if arr.shape[:2] != (h, w):
            raise ValueError(f"{p}: expected {h}x{w}, got {arr.shape[:2]}")
        out[i] = arr[:, :, None] if channels == 1 else arr
    return out


def _decode_pil_resize(
    paths: Sequence[str], h: int, w: int, channels: int
) -> np.ndarray:
    from PIL import Image

    out = np.empty((len(paths), h, w, channels), np.float32)
    for i, p in enumerate(paths):
        with Image.open(p) as im:
            im = im.convert("L" if channels == 1 else "RGB")
            if im.size != (w, h):
                im = im.resize((w, h), Image.BILINEAR)
            arr = np.asarray(im, np.float32) / 255.0
        out[i] = arr[:, :, None] if channels == 1 else arr
    return out


# default decode parallelism for IN-MEMORY BLOBS: the native decoders spawn
# fresh threads per CALL, so one-thread-per-core on a small blob batch (the
# streaming record path's batch-at-a-time shape) spends more wall time
# creating/joining threads than decoding — measured 2.4x SLOWER than a
# 4-thread decode for 64 blobs on a 24-core host, the end2end_decode
# regression RECORDS_BENCH.json recorded. Scale threads with the work
# instead: at least _MIN_ITEMS_PER_THREAD blobs each, capped by the core
# count. The PATH-based decoders keep the one-thread-per-core default: their
# per-item cost (full-size on-disk images + filesystem IO) dwarfs the spawn
# overhead this heuristic amortizes, and only the blob path was measured.
_MIN_ITEMS_PER_THREAD = 16


def _default_threads(n_items: int) -> int:
    return max(1, min(os.cpu_count() or 1, n_items // _MIN_ITEMS_PER_THREAD))


def _run_batch(fn, paths, out, h, w, channels, n_threads, what):
    c_paths = (ctypes.c_char_p * len(paths))(*[os.fsencode(p) for p in paths])
    rc = fn(
        c_paths,
        len(paths),
        out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
        h,
        w,
        channels,
        n_threads,
    )
    if rc != 0:
        raise ValueError(f"native {what} decode failed for {paths[rc - 1]!r}")
    return out


def decode_png_batch(
    paths: Sequence[str],
    h: int,
    w: int,
    channels: int = 1,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """Decode fixed-size PNGs into [N, h, w, channels] float32 in [0, 1].

    Uses the native multithreaded decoder when available (GIL-free, one thread per
    core by default), else PIL. Files must already be h x w — the TGS-salt
    contract; use ``decode_image_batch`` for variable-size/JPEG sources.
    """
    paths = list(paths)
    if not paths:
        return np.empty((0, h, w, channels), np.float32)
    lib = _load()
    if lib is None:
        return _decode_pil(paths, h, w, channels)
    if n_threads is None:
        n_threads = min(len(paths), os.cpu_count() or 1)
    out = np.empty((len(paths), h, w, channels), np.float32)
    return _run_batch(
        lib.tfdl_decode_png_batch, paths, out, h, w, channels, n_threads, "PNG"
    )


def decode_image_batch(
    paths: Sequence[str],
    h: int,
    w: int,
    channels: int = 3,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """Decode PNG/JPEG files of ANY size into [N, h, w, channels] float32 in
    [0, 1], antialias-bilinearly resized — the ImageNet-class decode path.

    Native multithreaded when available, else PIL. Files the native decoder
    cannot handle (exotic encodings; JPEGs on a PNG-only build) fall back to PIL
    ONE AT A TIME instead of failing the batch — real-world datasets always
    contain a few oddballs."""
    paths = list(paths)
    if not paths:
        return np.empty((0, h, w, channels), np.float32)
    lib = _load()
    if lib is None:
        return _decode_pil_resize(paths, h, w, channels)
    if n_threads is None:
        n_threads = min(len(paths), os.cpu_count() or 1)
    out = np.empty((len(paths), h, w, channels), np.float32)
    start = 0
    while start < len(paths):
        chunk = paths[start:]
        c_paths = (ctypes.c_char_p * len(chunk))(*[os.fsencode(p) for p in chunk])
        rc = lib.tfdl_decode_image_batch(
            c_paths,
            len(chunk),
            out[start:].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            h,
            w,
            channels,
            n_threads,
        )
        if rc == 0:
            break
        bad = start + rc - 1  # absolute index of the first failing file
        out[bad] = _decode_pil_resize([paths[bad]], h, w, channels)[0]
        start = bad + 1
    return out


def _decode_pil_blobs(
    blobs: Sequence[bytes], h: int, w: int, channels: int
) -> np.ndarray:
    import io as io_lib

    from PIL import Image

    out = np.empty((len(blobs), h, w, channels), np.float32)
    for i, blob in enumerate(blobs):
        with Image.open(io_lib.BytesIO(blob)) as im:
            im = im.convert("L" if channels == 1 else "RGB")
            if im.size != (w, h):
                im = im.resize((w, h), Image.BILINEAR)
            arr = np.asarray(im, np.float32) / 255.0
        out[i] = arr[:, :, None] if channels == 1 else arr
    return out


def decode_image_blobs(
    blobs: Sequence[bytes],
    shape,
    channels: int = 3,
    n_threads: Optional[int] = None,
) -> np.ndarray:
    """Decode in-memory PNG/JPEG byte strings (record payloads) into
    [N, h, w, channels] float32 in [0, 1], antialias-resized — the blob twin of
    ``decode_image_batch``. Native multithreaded when available (fmemopen'd
    streams, GIL-free), else PIL; native per-blob failures fall back to PIL one
    at a time under the same minimal-failing-index contract."""
    h, w = shape
    blobs = list(blobs)
    if not blobs:
        return np.empty((0, h, w, channels), np.float32)
    lib = _load()
    if lib is None or not hasattr(lib, "tfdl_decode_image_blob_batch"):
        return _decode_pil_blobs(blobs, h, w, channels)
    if n_threads is None:
        n_threads = _default_threads(len(blobs))
    out = np.empty((len(blobs), h, w, channels), np.float32)
    bufs = [np.frombuffer(b, np.uint8) for b in blobs]  # keep refs alive
    start = 0
    while start < len(blobs):
        chunk = bufs[start:]
        ptrs = (ctypes.POINTER(ctypes.c_ubyte) * len(chunk))(
            *[b.ctypes.data_as(ctypes.POINTER(ctypes.c_ubyte)) for b in chunk]
        )
        sizes = (ctypes.c_ulonglong * len(chunk))(*[b.size for b in chunk])
        rc = lib.tfdl_decode_image_blob_batch(
            ptrs,
            sizes,
            len(chunk),
            out[start:].ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            h,
            w,
            channels,
            n_threads,
        )
        if rc == 0:
            break
        bad = start + rc - 1
        out[bad] = _decode_pil_blobs([blobs[bad]], h, w, channels)[0]
        start = bad + 1
    return out


_extra_lock = threading.Lock()
_extra_libs: dict = {}


def load_extra_library(
    src_name: str, lib_name: str, *, link_png: bool = False
) -> Optional[ctypes.CDLL]:
    """Build-and-load another single-source native library from this package
    directory via the shared build core (mtime-checked, atomic install); None
    when no toolchain is available."""
    with _extra_lock:
        if src_name in _extra_libs:
            return _extra_libs[src_name]
        src = os.path.join(_HERE, src_name)
        target = os.path.join(_BUILD_DIR, lib_name)
        lib = None
        try:
            fresh = os.path.exists(target) and os.path.getmtime(
                target
            ) >= os.path.getmtime(src)
            if fresh or _build_library(
                src, target, [["-lpng"] if link_png else []]
            ):
                lib = ctypes.CDLL(target)
        except OSError as e:
            logger.warning("native %s load failed (%s); using Python fallback",
                           src_name, e)
            lib = None
        _extra_libs[src_name] = lib
        return lib
