"""Online health monitors: is this run / this replica healthy RIGHT NOW?

The ledger answers post-hoc questions; nothing in the repo could flag a run
going bad *while it is going bad* — a NaN loss quietly training on garbage
for hours, a loss spike after a bad restart, a step-time regression from a
recompile storm, a serving replica blowing its latency SLO while /healthz
still says ok. These monitors close that gap. Each one is a small host-side
state machine that consumes the telemetry stream the trainers/server already
produce and emits structured ``health_alert`` ledger events (rendered by
``telemetry-report``'s health section); the serving SLO tracker additionally
flips ``/healthz`` to a degraded state a fleet router can act on.

Monitors:

- :class:`NanGuard` — non-finite train loss; ``warn`` (alert and keep going)
  or ``abort`` (alert, then raise :class:`HealthAbortError` so the run stops
  at a recorded boundary instead of training on NaNs). Drillable via the
  fault-injection hook pattern (``--inject-fault nan-loss@N``,
  resilience/faults.py) so the recovery path is tested code;
- :class:`LossSpikeDetector` — rolling median + MAD; robust to the heavy
  right tail of loss curves where a mean/stddev z-score would either miss
  spikes or fire on warmup;
- :class:`StepTimeRegressionDetector` — median-of-first-clean-windows
  baseline, alert on sustained regression (dirty windows — compile/eval/
  checkpoint — are excluded exactly as they are from throughput);
- :class:`DataStarvedDetector` — ``data_wait`` dominating consecutive clean
  windows (the accelerator is input-bound; raise ``data_service_workers`` /
  prefetch depth — the signal the streaming data service drives to ~0);
- :class:`SloTracker` — serving p99 target expressed as a windowed error
  budget: with budget ``b``, "p99 <= target" IS "at most ``b`` of requests
  over target" (b=0.01 by default), so one fraction drives both the alert
  and the /healthz flip, and deadline-exceeded requests count as violations
  even though they never produce a latency sample;
- :class:`DriftMonitor` — serving OUTPUT-distribution shift against the
  promotion-time ``quant_check`` baseline persisted in the artifact
  manifest (``drift_baseline``): total-variation distance between the
  window's class histogram and the baseline's. Emits its own event kind —
  ``drift_alert`` — because its consumer is different in kind: the
  flywheel controller (loop/controller.py) treats an unresolved alert as a
  RETRAIN TRIGGER, not just an operator alarm.

All alerts share one event schema: ``health_alert`` with ``monitor``,
``severity`` ("warn" | "critical"), ``step`` (trainer-side), a unique
``alert_id`` (stamped at ledger time — triggered postmortem profiles
reference it), and monitor-specific numeric context; recoveries write
``resolved: true``.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import statistics
import threading
from typing import Deque, Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs import trace as trace_lib

HEALTH_ALERT_EVENT = "health_alert"
DRIFT_ALERT_EVENT = "drift_alert"

NAN_ACTIONS = ("warn", "abort", "off")


class HealthAbortError(RuntimeError):
    """Raised by the NaN guard under ``action='abort'`` AFTER the alert is
    ledgered — the run stops at a recorded boundary rather than continuing
    to train on non-finite values."""


class NanGuard:
    """Non-finite loss detector. ``action``: "warn" | "abort" | "off"."""

    def __init__(self, action: str = "warn"):
        if action not in NAN_ACTIONS:
            raise ValueError(
                f"nan_guard action must be one of {NAN_ACTIONS}, got {action!r}"
            )
        self.action = action
        self.fired = 0

    def check(self, step: int, loss: float) -> Optional[Dict]:
        if self.action == "off":
            return None
        if math.isfinite(loss):
            return None
        self.fired += 1
        return {
            "monitor": "nan_loss",
            "severity": "critical" if self.action == "abort" else "warn",
            "step": step,
            # str(), not float(): NaN/Infinity are not valid JSON numbers
            "loss": str(loss),
            "action": self.action,
        }


class LossSpikeDetector:
    """Rolling median + MAD spike detector over the (finite) loss stream.

    A loss is a spike when it exceeds ``median + threshold * scale`` where
    ``scale = max(MAD, rel_floor * |median|, abs_floor)`` — the floors keep a
    near-constant loss (MAD ~ 0) from alerting on numeric jitter. History is
    bounded (``window``) and spikes are appended too: the median is robust to
    them, and a level SHIFT (not a spike) stops alerting once the window
    rolls over, which is the behavior an operator wants."""

    def __init__(
        self,
        window: int = 32,
        min_history: int = 8,
        threshold: float = 8.0,
        rel_floor: float = 0.02,
        abs_floor: float = 1e-6,
    ):
        self.window = int(window)
        self.min_history = max(2, int(min_history))
        self.threshold = float(threshold)
        self.rel_floor = float(rel_floor)
        self.abs_floor = float(abs_floor)
        self._history: Deque[float] = collections.deque(maxlen=self.window)

    def check(self, step: int, loss: float) -> Optional[Dict]:
        if not math.isfinite(loss):
            return None  # the NaN guard owns non-finite values
        alert = None
        if len(self._history) >= self.min_history:
            med = statistics.median(self._history)
            mad = statistics.median(abs(x - med) for x in self._history)
            scale = max(mad, self.rel_floor * abs(med), self.abs_floor)
            if loss > med + self.threshold * scale:
                alert = {
                    "monitor": "loss_spike",
                    "severity": "warn",
                    "step": step,
                    "loss": round(float(loss), 6),
                    "median": round(med, 6),
                    "mad": round(mad, 6),
                    "threshold": self.threshold,
                }
        self._history.append(float(loss))
        return alert


class StepTimeRegressionDetector:
    """Step-time regression vs a baseline of the first clean windows.

    Baseline = median mean-step-time of the first ``baseline_windows`` CLEAN
    windows (dirty windows carry compile/eval/checkpoint time and are
    excluded, same as the throughput trend). Alerts on the ok→degraded
    transition when a clean window's mean exceeds ``factor`` x baseline, and
    writes a ``resolved`` event on the way back — transitions, not every
    window, so a sustained regression is one alert, not a flood."""

    def __init__(self, baseline_windows: int = 5, factor: float = 1.5):
        self.baseline_windows = max(1, int(baseline_windows))
        self.factor = float(factor)
        self._warmup: List[float] = []
        self.baseline_ms: Optional[float] = None
        self.degraded = False

    def check(
        self, step: int, mean_ms: float, dirty: bool = False
    ) -> Optional[Dict]:
        if dirty or mean_ms <= 0:
            return None
        if self.baseline_ms is None:
            self._warmup.append(float(mean_ms))
            if len(self._warmup) >= self.baseline_windows:
                self.baseline_ms = statistics.median(self._warmup)
            return None
        regressed = mean_ms > self.factor * self.baseline_ms
        if regressed and not self.degraded:
            self.degraded = True
            return {
                "monitor": "step_time",
                "severity": "warn",
                "step": step,
                "mean_ms": round(float(mean_ms), 3),
                "baseline_ms": round(self.baseline_ms, 3),
                "factor": self.factor,
            }
        if not regressed and self.degraded:
            self.degraded = False
            return {
                "monitor": "step_time",
                "severity": "warn",
                "step": step,
                "mean_ms": round(float(mean_ms), 3),
                "baseline_ms": round(self.baseline_ms, 3),
                "resolved": True,
            }
        return None


class DataStarvedDetector:
    """Input-bound training: ``data_wait`` dominates the window's host time.

    Consumes the per-window ``data_wait_frac`` the trainers already ledger
    (host blocked on the input iterator / total host busy time). Alerts on
    the ok→starved transition after ``consecutive`` CLEAN windows above
    ``threshold`` (dirty windows carry compile/eval/checkpoint time and are
    excluded, as everywhere), and writes a ``resolved`` event on recovery —
    transitions, not every window. The remedy is named in the alert: more
    ``data_service_workers`` / deeper prefetch, the knobs the data service
    exists for."""

    def __init__(self, threshold: float = 0.5, consecutive: int = 2):
        if not 0.0 < threshold < 1.0:
            raise ValueError(
                f"data_starved threshold must be in (0, 1), got {threshold}"
            )
        self.threshold = float(threshold)
        self.consecutive = max(1, int(consecutive))
        self._over = 0
        self.degraded = False

    def check(
        self, step: int, data_wait_frac: float, dirty: bool = False
    ) -> Optional[Dict]:
        if dirty:
            return None
        starved = data_wait_frac > self.threshold
        self._over = self._over + 1 if starved else 0
        fields = {
            "monitor": "data_starved",
            "severity": "warn",
            "step": step,
            "data_wait_frac": round(float(data_wait_frac), 4),
            "threshold": self.threshold,
        }
        if self._over >= self.consecutive and not self.degraded:
            self.degraded = True
            return fields
        if not starved and self.degraded:
            self.degraded = False
            fields["resolved"] = True
            return fields
        return None


class HeadroomMonitor:
    """HBM headroom: is this process about to OOM?

    Consumes the watermark stream (obs/capacity.py — ``memory_watermark``
    events carry ``peak_bytes``/``bytes_limit``) and alerts on the
    ok→degraded transition when either:

    - headroom drops below ``min_headroom_frac`` of the device limit (the
      absolute floor: past it any allocation spike — a bigger eval batch, a
      fresh compile's workspace — is an OOM); or
    - the watermark TREND projects the limit will be crossed within
      ``horizon_samples`` more watermark samples (the leak/fragmentation
      case: plenty of headroom today, none next week).

    Recovery (headroom restored — e.g. a resize or cache drop) writes a
    ``resolved`` alert, same transition discipline as the step-time monitor.
    Backends with no allocator query never feed this monitor, so it stays
    healthy on CPU builds by construction. ``degraded`` is the live state a
    ``/healthz`` endpoint folds in."""

    def __init__(
        self,
        min_headroom_frac: float = 0.05,
        horizon_samples: int = 50,
    ):
        if not 0.0 < min_headroom_frac < 1.0:
            raise ValueError(
                f"min_headroom_frac must be in (0, 1), got {min_headroom_frac}"
            )
        self.min_headroom_frac = float(min_headroom_frac)
        self.horizon_samples = max(1, int(horizon_samples))
        self.degraded = False
        self.last: Optional[Dict] = None

    def check(
        self,
        step: Optional[int],
        peak_bytes: int,
        bytes_limit: Optional[int],
        samples_to_limit: Optional[int] = None,
    ) -> Optional[Dict]:
        if not bytes_limit or peak_bytes <= 0:
            return None  # no limit reported = nothing to budget against
        headroom = max(0.0, 1.0 - peak_bytes / bytes_limit)
        low = headroom < self.min_headroom_frac
        trending_out = (
            samples_to_limit is not None
            and samples_to_limit <= self.horizon_samples
        )
        self.last = {
            "headroom_frac": round(headroom, 4),
            "peak_bytes": int(peak_bytes),
            "bytes_limit": int(bytes_limit),
        }
        at_risk = low or trending_out
        fields = {
            "monitor": "hbm_headroom",
            "severity": "critical" if low else "warn",
            "headroom_frac": round(headroom, 4),
            "min_headroom_frac": self.min_headroom_frac,
            "peak_bytes": int(peak_bytes),
            "bytes_limit": int(bytes_limit),
        }
        if step is not None:
            fields["step"] = step
        if samples_to_limit is not None:
            fields["samples_to_limit"] = int(samples_to_limit)
        if at_risk and not self.degraded:
            self.degraded = True
            fields["reason"] = "low_headroom" if low else "trend"
            return fields
        if not at_risk and self.degraded:
            self.degraded = False
            fields["severity"] = "warn"
            fields["resolved"] = True
            return fields
        return None


@dataclasses.dataclass
class SloWindow:
    """One evaluation window's SLO accounting (returned by ``evaluate``)."""

    requests: int
    violations: int
    p99_ms: Optional[float]


class SloTracker:
    """Serving SLO: p99 latency target + windowed error budget.

    ``observe(latency_s)`` per answered request; ``observe_violation()`` for
    requests that failed the latency contract without producing a sample
    (deadline-exceeded, result timeouts). ``evaluate()`` — called at each
    serve ledger window — drains the window and returns an alert dict on the
    healthy→degraded transition (and a ``resolved`` dict on recovery);
    ``healthy`` is the live state ``/healthz`` reports. Windows with fewer
    than ``min_requests`` observations are ignored (an idle replica is not
    degraded)."""

    # retained latency samples per window (p99 estimation only — the budget
    # math uses exact counters), so an unevaluated tracker (idle windows, a
    # server run with windows disabled) cannot grow host memory unboundedly
    MAX_WINDOW_SAMPLES = 4096

    def __init__(
        self,
        p99_target_ms: float,
        error_budget: float = 0.01,
        min_requests: int = 20,
    ):
        if p99_target_ms <= 0:
            raise ValueError(f"p99_target_ms must be > 0, got {p99_target_ms}")
        if not 0.0 < error_budget < 1.0:
            raise ValueError(
                f"error_budget must be in (0, 1), got {error_budget}"
            )
        self.p99_target_ms = float(p99_target_ms)
        self.error_budget = float(error_budget)
        self.min_requests = max(1, int(min_requests))
        self.healthy = True
        self.last_window: Optional[SloWindow] = None
        self._lock = threading.Lock()
        self._latencies: collections.deque = collections.deque(
            maxlen=self.MAX_WINDOW_SAMPLES
        )
        self._count = 0  # exact answered requests this window
        self._over = 0  # exact over-target (incl. violation) count

    def observe(self, latency_s: float) -> None:
        latency_s = float(latency_s)
        with self._lock:
            self._latencies.append(latency_s)
            self._count += 1
            if latency_s > self.p99_target_ms / 1000.0:
                self._over += 1

    def observe_violation(self) -> None:
        with self._lock:
            self._count += 1
            self._over += 1

    def evaluate(self) -> Optional[Dict]:
        with self._lock:
            latencies = list(self._latencies)
            n, over = self._count, self._over
            self._latencies.clear()
            self._count = 0
            self._over = 0
        p99_ms = None
        if latencies:
            s = sorted(latencies)
            p99_ms = round(s[min(len(s) - 1, int(0.99 * len(s)))] * 1000, 3)
        self.last_window = SloWindow(requests=n, violations=over, p99_ms=p99_ms)
        if n < self.min_requests:
            return None
        breached = over / n > self.error_budget
        fields = {
            "monitor": "slo",
            "severity": "critical" if breached else "warn",
            "p99_target_ms": self.p99_target_ms,
            "error_budget": self.error_budget,
            "window_requests": n,
            "window_violations": over,
            "violation_frac": round(over / n, 4),
        }
        if p99_ms is not None:
            fields["window_p99_ms"] = p99_ms
        if breached and self.healthy:
            self.healthy = False
            return fields
        if not breached and not self.healthy:
            self.healthy = True
            fields["severity"] = "warn"
            fields["resolved"] = True
            return fields
        return None

    def snapshot(self) -> Dict:
        """The live view ``/healthz`` and the serve windows embed."""
        out: Dict = {
            "p99_target_ms": self.p99_target_ms,
            "error_budget": self.error_budget,
            "healthy": self.healthy,
        }
        w = self.last_window
        if w is not None:
            out["window_requests"] = w.requests
            out["window_violations"] = w.violations
            if w.p99_ms is not None:
                out["window_p99_ms"] = w.p99_ms
        return out


class DriftMonitor:
    """Serving output-distribution drift vs the promotion-time baseline.

    The baseline is the artifact manifest's ``drift_baseline`` section —
    ``quant_check.summarize_output_distribution`` over the pinned eval
    batch, persisted at export/promotion time so no eval re-run is needed.
    The monitor tracks the first integer-valued output it names (fit's
    serving artifacts call it ``class``): ``observe`` folds each answered
    request's class ids into a histogram, ``evaluate`` (called at serve
    ledger windows) drains it and scores the shift as total-variation
    distance ``0.5 * sum|p - q|`` in [0, 1].

    Transition-disciplined like every monitor here: one ``drift_alert``
    on ok->drifted (after ``sustain_windows`` consecutive bad windows —
    one odd traffic window is not a distribution shift), one
    ``resolved: true`` on recovery. Windows under ``min_requests`` are
    ignored: an idle replica has no distribution to compare."""

    def __init__(
        self,
        baseline: Dict,
        *,
        threshold: float = 0.35,
        min_requests: int = 20,
        sustain_windows: int = 2,
    ):
        if not 0.0 < threshold <= 1.0:
            raise ValueError(f"threshold must be in (0, 1], got {threshold}")
        if sustain_windows < 1:
            raise ValueError("sustain_windows must be >= 1")
        outputs = baseline.get("outputs") or {}
        self.output_name = None
        hist = None
        for name in sorted(outputs):
            spec = outputs[name]
            if spec.get("kind") == "integer" and spec.get("hist"):
                self.output_name, hist = name, spec["hist"]
                break
        if hist is None:
            raise ValueError(
                "drift baseline has no integer output histogram — "
                "re-export the artifact (the exporter stamps drift_baseline) "
                f"or re-promote it; baseline outputs: {sorted(outputs)}"
            )
        total = sum(float(v) for v in hist.values()) or 1.0
        self.baseline_hist = {
            int(k): float(v) / total for k, v in hist.items()
        }
        self.threshold = float(threshold)
        self.min_requests = max(1, int(min_requests))
        self.sustain_windows = int(sustain_windows)
        self.healthy = True
        self.last_score: Optional[float] = None
        self._bad_streak = 0
        self._lock = threading.Lock()
        self._counts: Dict[int, int] = {}
        self._n = 0

    def observe(self, outputs: Dict) -> None:
        """Fold one answered request's outputs; cheap (a bincount over the
        batch's class ids) and silent on shape surprises — the monitor must
        never make a 200 into a 500."""
        arr = outputs.get(self.output_name)
        if arr is None:
            return
        try:
            import numpy as np

            flat = np.asarray(arr).reshape(-1)
            with self._lock:
                for cls, cnt in zip(*np.unique(flat, return_counts=True)):
                    self._counts[int(cls)] = (
                        self._counts.get(int(cls), 0) + int(cnt)
                    )
                self._n += int(flat.size)
        except (ValueError, TypeError):
            return

    def evaluate(self) -> Optional[Dict]:
        """Drain the window; alert dict on the ok->drifted transition (or
        the resolution), None otherwise — the server ledgers it as a
        ``drift_alert`` event."""
        with self._lock:
            counts, n = self._counts, self._n
            self._counts, self._n = {}, 0
        if n < self.min_requests:
            return None
        classes = set(self.baseline_hist) | set(counts)
        score = 0.5 * sum(
            abs(counts.get(c, 0) / n - self.baseline_hist.get(c, 0.0))
            for c in classes
        )
        self.last_score = round(score, 4)
        drifted = score > self.threshold
        self._bad_streak = self._bad_streak + 1 if drifted else 0
        fields = {
            "monitor": "drift",
            "output": self.output_name,
            "score": self.last_score,
            "threshold": self.threshold,
            "window_outputs": n,
            "severity": "critical" if drifted else "warn",
        }
        if drifted and self.healthy:
            if self._bad_streak < self.sustain_windows:
                return None
            self.healthy = False
            fields["sustained_windows"] = self._bad_streak
            return fields
        if not drifted and not self.healthy:
            self.healthy = True
            fields["severity"] = "warn"
            fields["resolved"] = True
            return fields
        return None

    def snapshot(self) -> Dict:
        """The live view serve windows embed (``drift`` sub-dict)."""
        out: Dict = {
            "output": self.output_name,
            "threshold": self.threshold,
            "healthy": self.healthy,
        }
        if self.last_score is not None:
            out["score"] = self.last_score
        return out


class HealthMonitor:
    """The trainer-side facade: NaN guard + loss spike + step-time regression
    over the per-window telemetry stream.

    Wired into ``Telemetry.window_event`` (the one place every trainer's
    windows flow through): checks run AFTER the ``step_window`` event is
    written, alerts append as ``health_alert`` events, and the NaN guard's
    ``abort`` action raises :class:`HealthAbortError` last — the ledger tells
    the whole story before the run dies. The loss value consults the
    fault-injection hook (``nan-loss@N``) first, so the abort path is
    drillable end to end."""

    def __init__(
        self,
        *,
        nan_action: str = "warn",
        spike: Optional[LossSpikeDetector] = None,
        step_time: Optional[StepTimeRegressionDetector] = None,
        headroom: Optional[HeadroomMonitor] = None,
        data_starved: Optional[DataStarvedDetector] = None,
    ):
        self.nan_guard = NanGuard(nan_action)
        self.spike = spike if spike is not None else LossSpikeDetector()
        self.step_time = (
            step_time if step_time is not None else StepTimeRegressionDetector()
        )
        # HBM headroom/OOM-risk (fed by Telemetry.sample_watermark — never
        # fires on backends without the allocator query)
        self.headroom = headroom if headroom is not None else HeadroomMonitor()
        # input-bound training (data_wait dominating clean windows)
        self.data_starved = (
            data_starved if data_starved is not None else DataStarvedDetector()
        )
        self.alerts: List[Dict] = []

    @classmethod
    def from_train_config(cls, tcfg) -> Optional["HealthMonitor"]:
        """The monitor a trainer runs under ``tcfg``; None when disabled."""
        if not getattr(tcfg, "health_monitors", True):
            return None
        return cls(nan_action=getattr(tcfg, "nan_guard", "warn"))

    @property
    def status(self) -> str:
        degraded = (
            self.step_time.degraded
            or self.headroom.degraded
            or self.data_starved.degraded
        )
        return "degraded" if degraded else "ok"

    def reset(self) -> None:
        """Start a fresh training phase: drop the rolling loss history and
        the step-time baseline (the K-fold trainer calls this per fold — a
        converged fold's low-loss median must not flag the next fold's
        fresh untrained loss as a spike). Accumulated ``alerts`` and the
        guard's configuration persist."""
        self.spike = LossSpikeDetector(
            window=self.spike.window,
            min_history=self.spike.min_history,
            threshold=self.spike.threshold,
            rel_floor=self.spike.rel_floor,
            abs_floor=self.spike.abs_floor,
        )
        self.step_time = StepTimeRegressionDetector(
            baseline_windows=self.step_time.baseline_windows,
            factor=self.step_time.factor,
        )
        self.data_starved = DataStarvedDetector(
            threshold=self.data_starved.threshold,
            consecutive=self.data_starved.consecutive,
        )

    def observe_memory(
        self, telemetry, step: Optional[int], watermark: Dict
    ) -> Optional[Dict]:
        """Run the headroom monitor against one ``memory_watermark`` sample
        (Telemetry.sample_watermark calls this); the alert — if any — is
        ledgered through ``telemetry`` like every other monitor's."""
        alert = self.headroom.check(
            step,
            watermark.get("peak_bytes", 0),
            watermark.get("bytes_limit"),
            samples_to_limit=watermark.get("samples_to_limit"),
        )
        if alert:
            alert.setdefault("alert_id", trace_lib.new_id())
            self.alerts.append(alert)
            telemetry.event(HEALTH_ALERT_EVENT, **alert)
        return alert

    def observe_window(
        self, telemetry, step: int, scalars: Dict, fields: Dict
    ) -> List[Dict]:
        """Run every monitor against one emitted window; write alerts through
        ``telemetry`` and return them. Raises :class:`HealthAbortError` after
        a NaN alert when the guard is set to abort."""
        alerts: List[Dict] = []
        loss = scalars.get("loss")
        if loss is not None:
            loss = float(loss)
            from tensorflowdistributedlearning_tpu.resilience import (
                faults as faults_lib,
            )

            if faults_lib.poisoned(faults_lib.SITE_LOSS, step):
                loss = float("nan")
            nan_alert = self.nan_guard.check(step, loss)
            if nan_alert:
                alerts.append(nan_alert)
            else:
                spike = self.spike.check(step, loss)
                if spike:
                    alerts.append(spike)
        mean_ms = (fields.get("step_time_ms") or {}).get("mean_ms")
        if mean_ms is not None:
            st = self.step_time.check(
                step, float(mean_ms), dirty=bool(fields.get("dirty"))
            )
            if st:
                alerts.append(st)
        frac = fields.get("data_wait_frac")
        if frac is not None:
            starved = self.data_starved.check(
                step, float(frac), dirty=bool(fields.get("dirty"))
            )
            if starved:
                alerts.append(starved)
        for alert in alerts:
            # every ledgered alert carries a unique id so downstream
            # artifacts (a triggered postmortem profile_capture, an operator
            # runbook) can reference THIS alert, not just its kind
            alert.setdefault("alert_id", trace_lib.new_id())
            self.alerts.append(alert)
            telemetry.event(HEALTH_ALERT_EVENT, **alert)
        if any(
            a["monitor"] == "nan_loss" and a.get("action") == "abort"
            for a in alerts
        ):
            raise HealthAbortError(
                f"non-finite train loss at step {step} (nan_guard='abort'; "
                "the health_alert ledger event precedes this exit)"
            )
        return alerts
