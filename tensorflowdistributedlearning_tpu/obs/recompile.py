"""Recompile detection via ``jax.monitoring`` compile-event listeners.

On TPU, a silent recompilation mid-training (a batch whose shape drifted, a
Python-level cache miss, a donation mismatch) stalls every chip for the full
compile — seconds to minutes — while throughput telemetry just shows a
mysterious slow window. pjit-era production harnesses track compilations as a
first-class signal (Yoo et al., arXiv:2204.06514 §5). This module listens on
JAX's own monitoring stream: every backend compile fires
``/jax/core/compile/backend_compile_duration`` (persistent-cache hits
included — a cached recompile still stalls the step), which we timestamp,
attribute to the telemetry span that was active when it happened, and — once
the detector is marked *warm* (steady state reached) — flag as a post-warmup
recompile.

Fallback: if this jax build has no usable ``jax.monitoring`` (the API is
public but young), ``RecompileDetector.available()`` is False and detectors
degrade to inert counters — training never depends on the listener existing.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Callable, List, Optional

logger = logging.getLogger(__name__)

try:  # the public constant lives in a private module; keep a literal fallback
    from jax._src.dispatch import BACKEND_COMPILE_EVENT as _COMPILE_EVENT
except Exception:  # noqa: BLE001
    _COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"

try:
    from jax import monitoring as _monitoring
except Exception:  # noqa: BLE001 — jax without the monitoring API
    _monitoring = None

try:  # cache-hit attribution (utils sibling); detector works without it
    from ..utils import compile_cache as _compile_cache
except Exception:  # noqa: BLE001
    _compile_cache = None

# One process-wide listener fans out to attached detectors: jax.monitoring has
# no unregister in its public API, so registering per-detector would leak a
# callback per trainer construction for the process lifetime.
_lock = threading.Lock()
_detectors: List["RecompileDetector"] = []
_listener_registered = False


def _dispatch(event: str, duration_secs: float, **kwargs) -> None:
    if event != _COMPILE_EVENT:
        return
    # JAX fires the persistent-cache hit/saved events on the compiling
    # thread BEFORE this duration event closes; consume the thread-local
    # verdict exactly once per compile so it cannot leak to the next one
    cache_hit: Optional[bool] = None
    saved_s = 0.0
    if _compile_cache is not None:
        try:
            cache_hit, saved_s = _compile_cache.consume_pending()
        except Exception:  # noqa: BLE001 — attribution is best-effort
            cache_hit, saved_s = None, 0.0
    with _lock:
        targets = list(_detectors)
    for det in targets:
        det._on_compile(duration_secs, cache_hit=cache_hit, saved_s=saved_s)


def _ensure_listener() -> bool:
    global _listener_registered
    if _monitoring is None:
        return False
    with _lock:
        if not _listener_registered:
            try:
                _monitoring.register_event_duration_secs_listener(_dispatch)
            except Exception as e:  # noqa: BLE001 — degrade, never crash
                logger.warning("recompile detector unavailable: %s", e)
                return False
            _listener_registered = True
    return True


@dataclasses.dataclass
class CompileEvent:
    t: float
    duration_s: float
    phase: str  # telemetry span active at compile time ("" when unattributed)
    post_warmup: bool
    # persistent-cache verdict: None = cache not consulted (disabled),
    # False = genuine compile (miss), True = served from cache — a cached
    # "compile" still stalls the step but costs load time, not XLA time
    cache_hit: Optional[bool] = None
    saved_s: float = 0.0  # compile time the hit saved (hit only)


class RecompileDetector:
    """Counts and timestamps backend compilations; flags the post-warmup ones.

    Usage::

        det = RecompileDetector(phase_fn=lambda: tel.current_span)
        det.attach()
        ... first steps (expected compiles) ...
        det.mark_warm()          # from here on, any compile is a recompile
        ...
        det.detach()

    Warm-up is tracked PER PHASE: a training loop's first eval legitimately
    compiles the eval step long after the train step went warm, so the
    trainers mark ``"step"`` warm after the first log window and ``"eval"``
    warm after the first eval pass. ``mark_warm()`` with no arguments marks
    every phase (the standalone usage above).

    ``phase_fn`` supplies the attribution label (the telemetry span active on
    the compiling thread); ``on_event`` is invoked for every compile with the
    ``CompileEvent`` — the Telemetry façade uses it to write ledger lines and
    log post-warmup warnings."""

    def __init__(
        self,
        *,
        phase_fn: Optional[Callable[[], str]] = None,
        on_event: Optional[Callable[[CompileEvent], None]] = None,
    ):
        self._phase_fn = phase_fn
        self._on_event = on_event
        self._warm_phases: set = set()
        self._attached = False
        self.events: List[CompileEvent] = []

    @staticmethod
    def available() -> bool:
        return _monitoring is not None

    def attach(self) -> "RecompileDetector":
        if self._attached or not _ensure_listener():
            return self
        with _lock:
            _detectors.append(self)
        self._attached = True
        return self

    def detach(self) -> None:
        if not self._attached:
            return
        with _lock:
            if self in _detectors:
                _detectors.remove(self)
        self._attached = False

    def mark_warm(self, *phases: str) -> None:
        """Declare steady state for ``phases`` (no arguments = every phase):
        compiles attributed to a warm phase are recompiles."""
        if not phases:
            self._warm_phases.add("*")
        else:
            self._warm_phases.update(phases)

    def is_warm(self, phase: str = "") -> bool:
        return "*" in self._warm_phases or phase in self._warm_phases

    @property
    def warm(self) -> bool:
        return bool(self._warm_phases)

    @property
    def compile_count(self) -> int:
        return len(self.events)

    @property
    def compile_total_s(self) -> float:
        return float(sum(e.duration_s for e in self.events))

    @property
    def post_warmup_events(self) -> List[CompileEvent]:
        return [e for e in self.events if e.post_warmup]

    @property
    def post_warmup_count(self) -> int:
        return len(self.post_warmup_events)

    @property
    def cache_hit_count(self) -> int:
        return sum(1 for e in self.events if e.cache_hit)

    @property
    def cache_miss_count(self) -> int:
        return sum(1 for e in self.events if e.cache_hit is False)

    @property
    def cache_saved_s(self) -> float:
        return float(sum(e.saved_s for e in self.events if e.cache_hit))

    # -- listener side ----------------------------------------------------

    def _on_compile(
        self,
        duration_s: float,
        *,
        cache_hit: Optional[bool] = None,
        saved_s: float = 0.0,
    ) -> None:
        phase = ""
        if self._phase_fn is not None:
            try:
                phase = self._phase_fn() or ""
            except Exception:  # noqa: BLE001 — attribution is best-effort
                phase = ""
        event = CompileEvent(
            t=time.time(),
            duration_s=float(duration_s),
            phase=phase,
            post_warmup=self.is_warm(phase),
            cache_hit=cache_hit,
            saved_s=float(saved_s),
        )
        self.events.append(event)
        if self._on_event is not None:
            try:
                self._on_event(event)
            except Exception:  # noqa: BLE001 — telemetry must not kill dispatch
                logger.exception("recompile on_event callback failed")
