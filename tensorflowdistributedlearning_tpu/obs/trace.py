"""Request/step-granular tracing: trace/span ids, ledger persistence, export.

The ledger (obs/ledger.py) records *windows* — aggregates that answer "how did
the run do" but never "where did THIS request/step spend its time". This
module adds the per-unit layer: a lightweight span API (trace_id / span_id /
parent_id, host wall clock only — a span never touches the device, so tracing
is pure host bookkeeping) that the serving stack threads through one request
(HTTP handler → batcher queue wait → engine pad/compute) and the trainers
thread through their existing span boundaries (step/eval/checkpoint/
fetch_wait). Production TPU stacks treat these per-unit timelines as
first-class signals (pjit/TPUv4 goodput methodology, arXiv:2204.06514; the
Gemma-on-TPU serving reports are full per-stage latency *distributions*).

Design rules, in descending order of importance:

- **near-zero cost when off**: a disabled tracer's ``span()`` yields ``None``
  after one attribute check; the trainers' per-step overhead with tracing ON
  is gated at <= 2% step time (``bench.py --trace-overhead``, CI);
- **sampling is per trace, decided at the root**: every span of a sampled
  trace persists, every span of an unsampled one is dropped *as a unit* —
  partial traces are worse than none. Ids still exist (and still echo as
  ``x-request-id``) whether or not the trace is sampled;
- **persistence is just ledger events**: one ``trace`` event per sampled
  span, through the same writer/failure-stance as everything else. Export to
  the Chrome/Perfetto trace-event JSON format (``chrome://tracing``,
  https://ui.perfetto.dev) is a pure read-side transform
  (``export_chrome_trace`` / ``telemetry-report --export-trace``).

Span linkage across threads (the serve path): the HTTP handler opens the
``request`` root span; the batcher worker *emits* retroactive ``queue_wait``/
``pad``/``compute`` child spans for each member request (durations measured
where they happened) carrying ``batch_span_id`` attrs that point at the batch
trace's own ``compute`` span — one batch services many requests, so the link
is an attribute, not a parent edge.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import random
import threading
import time
from typing import Any, Callable, Dict, List, Optional

# the ledger event kind sampled spans persist as
TRACE_EVENT = "trace"

# span names the built-in producers use (anything else is allowed)
SPAN_REQUEST = "request"
SPAN_QUEUE_WAIT = "queue_wait"
SPAN_BATCH = "batch"
SPAN_PAD = "pad"
SPAN_COMPUTE = "compute"


def new_id() -> str:
    """64-bit random hex id (trace and span ids share the format). PRNG, not
    ``os.urandom`` — ids need uniqueness, not unpredictability, and the span
    path runs per train step / per request, where a syscall is real money."""
    return f"{random.getrandbits(64):016x}"


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The portable identity of an open span — what crosses thread/queue
    boundaries (e.g. rides a batcher ``Request``) so another thread can emit
    retroactive child spans into the same trace with the same sampling
    verdict."""

    trace_id: str
    span_id: str
    sampled: bool


@dataclasses.dataclass
class Span:
    """One in-flight (then finished) span. ``children`` collects finished
    child spans while this span is open on the same thread — the serve
    batcher reads the engine's ``pad``/``compute`` children off its ``batch``
    span to mirror them onto member requests."""

    name: str
    trace_id: str
    span_id: str
    parent_id: Optional[str]
    start_t: float
    sampled: bool
    attrs: Dict[str, Any]
    duration_s: float = 0.0
    children: List["Span"] = dataclasses.field(default_factory=list)

    @property
    def context(self) -> TraceContext:
        return TraceContext(self.trace_id, self.span_id, self.sampled)


class Tracer:
    """Trace/span factory bound to one emit sink (the run's ledger).

    ``enabled`` is decided once at construction (a sink AND a positive sample
    rate); every path checks it first so a disabled tracer costs one
    attribute read. Thread-local span stacks give automatic parenting within
    a thread; cross-thread spans pass an explicit :class:`TraceContext`.
    """

    def __init__(
        self,
        emit: Optional[Callable[[Dict], None]] = None,
        sample_rate: float = 0.0,
    ):
        if not 0.0 <= sample_rate <= 1.0:
            raise ValueError(
                f"trace sample_rate must be in [0, 1], got {sample_rate}"
            )
        self.sample_rate = float(sample_rate)
        self.enabled = emit is not None and self.sample_rate > 0.0
        self._emit = emit
        self._tls = threading.local()

    # -- context ------------------------------------------------------------

    def _stack(self) -> List[Span]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def current(self) -> Optional[Span]:
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def current_context(self) -> Optional[TraceContext]:
        span = self.current()
        return span.context if span is not None else None

    def _sample(self) -> bool:
        return self.sample_rate >= 1.0 or random.random() < self.sample_rate

    # -- spans --------------------------------------------------------------

    @contextlib.contextmanager
    def span(
        self,
        name: str,
        *,
        trace_id: Optional[str] = None,
        parent_id: Optional[str] = None,
        sampled: Optional[bool] = None,
        attrs: Optional[Dict[str, Any]] = None,
    ):
        """Open a span. With no explicit ``trace_id`` and an enclosing span on
        this thread, the new span joins that trace as a child (inheriting the
        sampling verdict); otherwise it roots a NEW trace whose sampling is
        decided here (or forced via ``sampled``). Yields the :class:`Span`
        (mutate ``attrs`` freely while open), or ``None`` when disabled."""
        if not self.enabled:
            yield None
            return
        stack = self._stack()
        top = stack[-1] if stack else None
        if trace_id is None and top is not None:
            trace_id = top.trace_id
            parent_id = top.span_id if parent_id is None else parent_id
            sampled = top.sampled if sampled is None else sampled
        else:
            trace_id = trace_id or new_id()
            sampled = self._sample() if sampled is None else sampled
        span = Span(
            name=name,
            trace_id=trace_id,
            span_id=new_id(),
            parent_id=parent_id,
            start_t=time.time(),
            sampled=bool(sampled),
            attrs=dict(attrs or {}),
        )
        stack.append(span)
        t0 = time.perf_counter()
        try:
            yield span
        finally:
            span.duration_s = time.perf_counter() - t0
            stack.pop()
            if stack:
                stack[-1].children.append(span)
            if span.sampled:
                self._write(span)

    def emit(
        self,
        name: str,
        *,
        trace_id: str,
        parent_id: Optional[str] = None,
        start_t: float,
        duration_s: float,
        sampled: bool = True,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> str:
        """Record a retroactive span from explicit timing — the cross-thread
        path (the batcher worker emitting member-request spans after the
        batch ran). Returns the new span id (generated whether or not the
        span persists, so links stay stable)."""
        span_id = new_id()
        if self.enabled and sampled:
            self._write(
                Span(
                    name=name,
                    trace_id=trace_id,
                    span_id=span_id,
                    parent_id=parent_id,
                    start_t=start_t,
                    sampled=True,
                    attrs=dict(attrs or {}),
                    duration_s=duration_s,
                )
            )
        return span_id

    def _write(self, span: Span) -> None:
        fields: Dict[str, Any] = {
            "name": span.name,
            "trace_id": span.trace_id,
            "span_id": span.span_id,
            "start_t": round(span.start_t, 6),
            "duration_s": round(span.duration_s, 6),
        }
        if span.parent_id:
            fields["parent_id"] = span.parent_id
        if span.attrs:
            fields["attrs"] = span.attrs
        self._emit(fields)


# the shared disabled instance — hold this instead of branching on None
NULL_TRACER = Tracer(emit=None, sample_rate=0.0)


# -- Chrome/Perfetto export --------------------------------------------------


def export_chrome_trace(events: List[Dict]) -> Dict:
    """Transform ledger events into Chrome trace-event JSON (the ``{
    "traceEvents": [...] }`` object format both ``chrome://tracing`` and
    Perfetto load).

    Every sampled span becomes one complete ("X") event with the required
    fields (``name``/``ph``/``ts``/``dur``/``pid``/``tid``); trace/span/parent
    ids and attrs ride in ``args``. Traces map to tids (one track per trace)
    so a request's queue→pad→compute children nest under their root visually;
    ``batch_span_id`` links additionally become flow events ("s"/"f") from
    the batch trace's compute span to each member request's compute span."""
    spans = [e for e in events if e.get("event") == TRACE_EVENT]
    trace_events: List[Dict] = []
    if not spans:
        return {"traceEvents": trace_events, "displayTimeUnit": "ms"}
    t0 = min(e.get("start_t", 0.0) for e in spans)
    tids: Dict[str, int] = {}
    by_span_id: Dict[str, Dict] = {}
    for e in spans:
        tid = tids.setdefault(e.get("trace_id", ""), len(tids) + 1)
        if e.get("span_id"):
            by_span_id[e["span_id"]] = e
        args = {
            k: e[k]
            for k in ("trace_id", "span_id", "parent_id")
            if e.get(k) is not None
        }
        args.update(e.get("attrs") or {})
        trace_events.append(
            {
                "name": e.get("name", "span"),
                "cat": "obs",
                "ph": "X",
                "ts": round((e.get("start_t", t0) - t0) * 1e6, 3),
                "dur": round(e.get("duration_s", 0.0) * 1e6, 3),
                "pid": 1,
                "tid": tid,
                "args": args,
            }
        )
    # flow arrows for cross-trace batch links (member compute -> batch compute)
    for e in spans:
        batch_span_id = (e.get("attrs") or {}).get("batch_span_id")
        src = by_span_id.get(batch_span_id) if batch_span_id else None
        if src is None:
            continue
        flow_id = f"{batch_span_id}:{e.get('span_id')}"
        trace_events.append(
            {
                "name": "batch_link",
                "cat": "obs",
                "ph": "s",
                "id": flow_id,
                "ts": round((src.get("start_t", t0) - t0) * 1e6, 3),
                "pid": 1,
                "tid": tids[src.get("trace_id", "")],
            }
        )
        trace_events.append(
            {
                "name": "batch_link",
                "cat": "obs",
                "ph": "f",
                "bp": "e",
                "id": flow_id,
                "ts": round((e.get("start_t", t0) - t0) * 1e6, 3),
                "pid": 1,
                "tid": tids[e.get("trace_id", "")],
            }
        )
    return {"traceEvents": trace_events, "displayTimeUnit": "ms"}


def write_chrome_trace(workdir: str, out_path: str) -> int:
    """Export the LAST run's sampled spans from a workdir's ledger(s) to
    ``out_path`` as Chrome trace-event JSON; returns the number of span
    events written (flow links excluded).

    Fleet-aware: every per-process/per-replica ledger the workdir holds
    (obs/fleet.py naming contract) contributes its last run's spans, so a
    multi-host export shows all hosts' timelines — and a workdir holding
    ONLY secondary ledgers (a replica's --workdir) still exports."""
    from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib

    ledgers = fleet_lib.discover_ledgers(workdir)
    if not ledgers:
        raise FileNotFoundError(
            f"no telemetry ledger (telemetry.jsonl / telemetry-N.jsonl) "
            f"under {workdir}"
        )
    events = [e for led in ledgers for e in led.events]
    doc = export_chrome_trace(events)
    with open(out_path, "w", encoding="utf-8") as f:
        json.dump(doc, f)
    return sum(1 for e in doc["traceEvents"] if e.get("ph") == "X")
