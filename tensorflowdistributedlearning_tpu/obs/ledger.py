"""JSONL run ledger: the durable, machine-readable record of a training run.

``{workdir}/telemetry.jsonl`` is append-only, one JSON object per line, each
carrying ``event`` (the kind) and ``t`` (``time.time()``). A run writes a
``run_header`` first (mesh/config/device fingerprint), then ``step_window`` /
``eval`` / ``checkpoint`` / ``memory`` / ``compile`` events, and a ``run_end``.
Appending means a workdir accumulates every run that touched it (resumes
included) — readers anchor on the LAST ``run_header`` (``obs.report``).

Failure stance: telemetry must never take training down. An unwritable
workdir (read-only volume, deleted dir, quota) degrades to one logged warning
and every subsequent ``event()`` becomes a no-op.
"""

from __future__ import annotations

import atexit
import io
import json
import logging
import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

logger = logging.getLogger(__name__)

LEDGER_FILENAME = "telemetry.jsonl"
SCHEMA_VERSION = 1

# every open ledger, so the exit hooks can flush ALL of them: the buffered
# high-rate path (event_buffered — per-span traces, multiple per train step)
# holds lines in the stdio buffer between flushed events, and a process that
# dies between flushes used to lose that tail — exactly the final window the
# fleet kill/drain drills need to reconstruct what happened
_LIVE_LEDGERS: "weakref.WeakSet[RunLedger]" = weakref.WeakSet()
_EXIT_HOOKS_INSTALLED = False


def flush_all_ledgers(blocking: bool = True) -> None:
    """Flush every open ledger's buffered lines to disk. Signal/atexit-safe:
    per-ledger failures are swallowed (each flush already degrades
    gracefully), and a torn set during interpreter teardown is tolerated.
    ``blocking=False`` is the SIGNAL-HANDLER mode: the handler runs ON the
    main thread, so if it interrupted ``_write()`` mid-line the write lock is
    held by the very thread now asking for it — a blocking acquire would
    deadlock the exit; skipping that one ledger is the only safe choice."""
    try:
        ledgers = list(_LIVE_LEDGERS)
    except Exception:  # noqa: BLE001 — teardown-order hazards
        return
    for ledger in ledgers:
        try:
            ledger.flush(blocking=blocking)
        except Exception:  # noqa: BLE001
            pass


def _sigterm_flush(signum, frame):  # pragma: no cover — exercised in a child
    flush_all_ledgers(blocking=False)
    # restore the default action and re-raise so the exit code stays the
    # conventional 128+SIGTERM a supervisor keys restart decisions on
    import signal as signal_lib

    signal_lib.signal(signum, signal_lib.SIG_DFL)
    os.kill(os.getpid(), signum)


def _install_exit_hooks() -> None:
    """Once per process, at first ledger open: an atexit flush (covers normal
    interpreter exits that skip ``close()``), plus a SIGTERM flusher when —
    and only when — nothing else handles SIGTERM yet. Producers with their
    own SIGTERM story (the trainers' preemption handler, the serving tier's
    graceful drain) keep it: their paths flush through ``Telemetry.close``,
    and installing over them would break the preempt/drain contracts."""
    global _EXIT_HOOKS_INSTALLED
    if _EXIT_HOOKS_INSTALLED:
        return
    _EXIT_HOOKS_INSTALLED = True
    atexit.register(flush_all_ledgers)
    try:
        import signal as signal_lib

        if (
            threading.current_thread() is threading.main_thread()
            and signal_lib.getsignal(signal_lib.SIGTERM)
            == signal_lib.SIG_DFL
        ):
            signal_lib.signal(signal_lib.SIGTERM, _sigterm_flush)
    except (ValueError, OSError, RuntimeError):
        # non-main thread / exotic embedding: atexit still covers clean exits
        pass


def per_process_filename(process_index: int) -> str:
    """The fleet ledger naming contract (obs/fleet.py): process 0 keeps the
    canonical ``telemetry.jsonl`` (single-process runs and their readers are
    unchanged); every other process writes ``telemetry-{process_index}.jsonl``
    beside it, so a pod-scale run leaves one ledger per host that
    ``telemetry-report`` discovers and merges."""
    if process_index == 0:
        return LEDGER_FILENAME
    return f"telemetry-{int(process_index)}.jsonl"


class RunLedger:
    """Append-only JSONL event writer rooted at a workdir."""

    def __init__(self, workdir: str, *, filename: str = LEDGER_FILENAME):
        self.path = os.path.join(workdir, filename)
        self._f: Optional[io.TextIOBase] = None
        # the serving stack writes from several threads (handler threads'
        # trace spans, the batcher worker, the window ticker) into this one
        # TextIOWrapper, which is not thread-safe — serialize line writes so
        # concurrent events cannot garble each other's JSONL
        self._lock = threading.Lock()
        try:
            os.makedirs(workdir, exist_ok=True)
            self._f = open(self.path, "a", encoding="utf-8")
            _LIVE_LEDGERS.add(self)
            _install_exit_hooks()
        except OSError as e:
            logger.warning(
                "telemetry ledger disabled: cannot open %s (%s) — training "
                "continues without a run ledger",
                self.path,
                e,
            )

    @property
    def enabled(self) -> bool:
        return self._f is not None

    def event(self, kind: str, /, **fields) -> None:
        """Append one event and flush it to disk; a write failure disables the
        ledger with one warning (never raises into the training loop).
        ``kind`` is positional-only so producers may carry their own ``kind``
        field (the suite runner's and serving stack's headers do)."""
        self._write(kind, fields, flush=True)

    def event_buffered(self, kind: str, /, **fields) -> None:
        """Append one event WITHOUT forcing a flush — for high-rate producers
        (per-span ``trace`` events can fire multiple times per train step)
        where a syscall per line measurably steals CPU from compute. Buffered
        lines reach disk when the stdio buffer fills, at the next flushed
        ``event()`` (same file object), on ``flush()``, at ``close()``, or
        via the process-exit hooks (atexit + default-SIGTERM flush,
        ``flush_all_ledgers``) — only a hard kill (SIGKILL, OOM) can lose the
        buffered tail, never a drain or a normal exit."""
        self._write(kind, fields, flush=False)

    def _write(self, kind: str, fields: Dict, flush: bool) -> None:
        if self._f is None:
            return
        record = {"event": kind, "t": time.time(), **fields}
        line = json.dumps(record, default=_jsonable) + "\n"  # off the lock
        try:
            with self._lock:
                if self._f is None:
                    return
                self._f.write(line)
                if flush:
                    self._f.flush()
        except (OSError, ValueError) as e:  # ValueError: write to closed file
            logger.warning(
                "telemetry ledger disabled mid-run: write to %s failed (%s)",
                self.path,
                e,
            )
            self._f = None

    # signal-handler flush wait: long enough for a writer THREAD mid-_write
    # to finish its line (microseconds normally), short enough that the
    # self-deadlock case (the handler interrupted the MAIN thread inside
    # _write, so the lock can never be released) stays a bounded stall
    _SIGNAL_FLUSH_TIMEOUT_S = 0.25

    def flush(self, blocking: bool = True) -> None:
        """Push any buffered events to disk (readers of a LIVE ledger — tests,
        a tailing operator — call this through ``Telemetry.flush``).
        ``blocking=False`` (the signal-handler path, ``flush_all_ledgers``)
        bounds the lock wait instead of blocking forever: if a background
        writer holds the lock it releases within microseconds and the flush
        proceeds; if the handler interrupted THIS thread mid-``_write`` the
        lock can never be released, and only the timeout averts a deadlock
        (that one ledger's tail is the price of a clean exit)."""
        if blocking:
            self._lock.acquire()
        elif not self._lock.acquire(timeout=self._SIGNAL_FLUSH_TIMEOUT_S):
            return
        try:
            if self._f is not None:
                try:
                    self._f.flush()
                except OSError:
                    pass
        finally:
            self._lock.release()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                try:
                    self._f.close()
                except OSError:
                    pass
                self._f = None
        _LIVE_LEDGERS.discard(self)


def _jsonable(obj):
    """Best-effort JSON coercion for numpy scalars/arrays and other strays —
    a weird metric value must not kill the ledger line."""
    for attr in ("item", "tolist"):
        fn = getattr(obj, attr, None)
        if fn is not None:
            try:
                return fn()
            except Exception:  # noqa: BLE001
                pass
    return str(obj)


def read_ledger(path: str) -> List[Dict]:
    """Parse a ledger back into a list of event dicts.

    ``path`` may be the jsonl file or the workdir containing it. Tolerant of a
    truncated final line (a killed run mid-write) — that line is dropped, not
    raised (``read_ledger_with_errors`` additionally reports how many)."""
    return read_ledger_with_errors(path)[0]


def read_ledger_with_errors(path: str) -> Tuple[List[Dict], int]:
    """``read_ledger`` plus the count of undecodable lines that were skipped.

    A crashed writer's torn last line (or a corrupted middle of the file) must
    be VISIBLE, not silently absent: the report surfaces the count as
    ``ledger_parse_errors`` in its header, and a nonzero value means the
    events list understates what the run actually did."""
    if os.path.isdir(path):
        path = os.path.join(path, LEDGER_FILENAME)
    events: List[Dict] = []
    errors = 0
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                record = json.loads(line)
            except json.JSONDecodeError:
                errors += 1  # torn tail from an interrupted writer, or worse
                continue
            if isinstance(record, dict):
                events.append(record)
            else:  # valid JSON but not an event object — still not readable
                errors += 1
    return events, errors


def last_run_events(events: List[Dict]) -> List[Dict]:
    """The events of the LAST run in an (append-accumulated) ledger: the final
    ``run_header`` and everything after it. A ledger with no header (legacy or
    foreign producer) is returned whole."""
    for i in range(len(events) - 1, -1, -1):
        if events[i].get("event") == "run_header":
            return events[i:]
    return events
