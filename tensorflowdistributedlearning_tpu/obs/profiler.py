"""Continuous profiling: bounded-overhead roofline/MFU captures on a cadence.

The repo could WRITE profiles (``utils/profiling.trace``) and READ them
offline (``utils/xplane`` CLI), but a profile only existed when someone
hand-ran both after the fact — the MFU campaign the roadmap grades against
(arXiv:2204.06514 treats MFU as the first-class training metric) can't run
on a number that isn't continuously measured. This module closes that gap:

- :class:`ContinuousProfiler` captures SHORT windowed ``jax.profiler`` traces
  on a log-window cadence (``TrainConfig.profile_every_windows``), on demand
  (serve ``/admin/profile``), and at alert chokepoints (a ``step_time`` or
  SLO ``health_alert`` auto-captures ONE rate-limited postmortem, linked to
  the triggering ``alert_id``);
- each capture stops after :attr:`capture_steps` train steps (not a whole
  window) so the steady-state overhead stays inside the <=2% budget
  (``bench.py --profile-overhead``, CI-gated);
- the capture parses through ``utils/xplane`` into a per-op roofline
  classification — compute-bound (conv/matmul) vs HBM-bound (fusion, reduce,
  copy, other) vs collective, achieved FLOP/s per chip against the device
  peak table, per-phase MFU — ledgered as ``profile_capture`` +
  ``op_roofline`` events (docs/LEDGER_SCHEMA.md);
- ``planner.measured_costs_from_workdir`` reads those rooflines back so
  ``plan --measured-costs-from`` scores layouts with THIS box's measured
  rates instead of analytic constants.

MFU here is the standard analytic-FLOPs convention: the planner's
``6 * param_count * global_batch`` per-step FLOP model priced against
measured wall time and the peak bf16 FLOP/s table
(``parallel/planner.PEAK_FLOPS_BY_KIND``). On backends without a known peak
(CPU hosts) MFU is ABSENT — never a fabricated 0/0; set ``TFDL_PEAK_FLOPS``
to price against an explicit peak (the CI drill does).

Failure stance matches the rest of obs/: a profiler hiccup (unsupported
backend, torn capture, full disk) degrades to a logged warning and a
counted error — it never takes down training or serving.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.utils import xplane

logger = logging.getLogger(__name__)

PROFILE_CAPTURE_EVENT = "profile_capture"
OP_ROOFLINE_EVENT = "op_roofline"

# health_alert monitors that auto-trigger a postmortem capture: a step-time
# regression (training) or a degraded SLO (serving) is exactly the moment a
# profile answers "what changed", and both are transition-based alerts (one
# event per degradation, not one per window)
TRIGGER_MONITORS = ("step_time", "slo")

# xplane DEFAULT_GROUPS buckets → roofline class. Conv/matmul run the MXU:
# compute-bound. Collectives are the interconnect. Everything else a TPU
# spends step time on (fusions, reductions, copies, infeed) is dominated by
# HBM traffic — the standard roofline reading of an op breakdown.
_COMPUTE_BUCKETS = ("conv", "matmul")
_COLLECTIVE_BUCKETS = ("collectives",)


def resolve_peak_flops(device_kind: Optional[str] = None) -> Optional[float]:
    """Peak bf16 FLOP/s per chip for MFU accounting, or ``None`` when the
    device kind is unknown (CPU hosts) — the caller must then OMIT MFU, not
    price against a made-up peak. ``TFDL_PEAK_FLOPS`` overrides (lets CI
    drill the MFU path on CPU, and lets operators price exotic SKUs).

    Deliberately NOT ``Topology.peak_flops()``: the planner's fallback
    constant is fine for relative candidate ordering but would turn CPU MFU
    into a meaningless absolute number."""
    env = os.environ.get("TFDL_PEAK_FLOPS")
    if env:
        try:
            return float(env)
        except ValueError:
            logger.warning("ignoring unparseable TFDL_PEAK_FLOPS=%r", env)
    if device_kind is None:
        try:
            import jax

            device_kind = getattr(jax.devices()[0], "device_kind", "")
        except Exception:  # noqa: BLE001 — backend probe best-effort
            return None
    from tensorflowdistributedlearning_tpu.parallel.planner import (
        PEAK_FLOPS_BY_KIND,
    )

    kind = (device_kind or "").lower()
    for needle, flops in PEAK_FLOPS_BY_KIND.items():
        if needle in kind:
            return flops
    return None


def build_roofline(
    rows: List[xplane.OpTime],
    *,
    busy_s: Optional[float] = None,
    steps: Optional[int] = None,
    step_flops: Optional[Dict] = None,
    phase: str = "train",
    top: int = 5,
) -> Dict:
    """One ``op_roofline`` event body from an op breakdown.

    ``busy_s`` is the measured wall time of the captured ``steps`` (for
    windowed captures: the SUM of the captured step spans — the same basis as
    the ledger's ``step_time_ms``, so the roofline MFU and the report's
    goodput MFU agree on a steady-state run). ``step_flops`` is the
    telemetry's analytic pricing (:meth:`Telemetry.set_step_flops`)."""
    groups = xplane.grouped_breakdown(rows)
    total_ms = sum(groups.values())
    compute_ms = sum(groups.get(b, 0.0) for b in _COMPUTE_BUCKETS)
    collective_ms = sum(groups.get(b, 0.0) for b in _COLLECTIVE_BUCKETS)
    hbm_ms = max(0.0, total_ms - compute_ms - collective_ms)
    out: Dict = {
        "phase": phase,
        "total_ms": round(total_ms, 3),
        "buckets": groups,
        "classes": {
            "compute_frac": round(compute_ms / total_ms, 4) if total_ms else 0.0,
            "hbm_frac": round(hbm_ms / total_ms, 4) if total_ms else 0.0,
            "collective_frac": (
                round(collective_ms / total_ms, 4) if total_ms else 0.0
            ),
        },
        "top_ops": [
            {
                "name": r.name,
                "total_ms": r.total_ms,
                "fraction": r.fraction,
                "class": (
                    "compute"
                    if xplane.classify_bucket(r.name) in _COMPUTE_BUCKETS
                    else "collective"
                    if xplane.classify_bucket(r.name) in _COLLECTIVE_BUCKETS
                    else "hbm"
                ),
            }
            for r in rows[:top]
        ],
    }
    hbm_rows = [
        r for r in rows
        if xplane.classify_bucket(r.name)
        not in _COMPUTE_BUCKETS + _COLLECTIVE_BUCKETS
    ]
    if hbm_rows:
        out["top_hbm_op"] = {
            "name": hbm_rows[0].name,
            "total_ms": hbm_rows[0].total_ms,
            "fraction": hbm_rows[0].fraction,
        }
    flops_per_step = (step_flops or {}).get("flops_per_step")
    n_devices = (step_flops or {}).get("n_devices") or 1
    if flops_per_step and steps and busy_s and busy_s > 0:
        achieved = flops_per_step * steps / busy_s / n_devices
        out["analytic_flops_per_step"] = float(flops_per_step)
        out["achieved_flops_per_sec_per_chip"] = round(achieved, 3)
        peak = (step_flops or {}).get("peak_flops_per_chip")
        if peak:
            out["peak_flops_per_chip"] = float(peak)
            # per-phase MFU: every analytic FLOP of the captured steps
            # against their measured wall — the headline number
            out["mfu"] = round(achieved / peak, 4)
            if compute_ms > 0:
                # per-op-class MFU: the same FLOPs against time spent in the
                # compute-class ops ONLY — how hard the MXU runs while it
                # runs; the gap to `mfu` is what HBM + collectives cost
                out["compute_mfu"] = round(
                    flops_per_step * steps
                    / (compute_ms / 1e3)
                    / n_devices
                    / peak,
                    4,
                )
        collective_bytes = (step_flops or {}).get("collective_bytes_per_step")
        if collective_bytes and collective_ms > 0:
            # achieved per-chip collective bandwidth: the planner's priced
            # per-chip collective volume against measured collective-bucket
            # time — what measured-costs planning replaces ICI_BYTES_PER_SEC
            # with
            out["achieved_collective_bytes_per_sec"] = round(
                collective_bytes * steps / (collective_ms / 1e3), 3
            )
            out["collective_bytes_per_step"] = float(collective_bytes)
    return out


class ContinuousProfiler:
    """Windowed/timed ``jax.profiler`` captures, parsed and ledgered.

    One instance per producer (trainer or serve replica), attached to its
    :class:`~tensorflowdistributedlearning_tpu.obs.telemetry.Telemetry` via
    ``telemetry.set_profiler``. Three capture paths:

    - **cadence** (``every_windows > 0``): every N-th log window starts a
      capture that stops after :attr:`capture_steps` train steps;
    - **alert** (:meth:`on_alerts` / :meth:`trigger`): a ``step_time``/``slo``
      health alert starts ONE postmortem capture, rate-limited by
      :attr:`min_trigger_interval_s` and stamped with the alert id;
    - **admin** (:meth:`capture_timed`): an explicit N-second capture (the
      serve ``/admin/profile`` endpoint), background by default.

    With ``every_windows=0`` and nothing triggered, the profiler is
    byte-inert: no logdir, no ledger events, one pointer check per step.
    """

    def __init__(
        self,
        telemetry,
        *,
        every_windows: int = 0,
        logdir: Optional[str] = None,
        capture_steps: int = 3,
        min_trigger_interval_s: float = 300.0,
        phase: str = "train",
        plane_filter: Optional[str] = None,
        top_ops: int = 5,
    ):
        self.telemetry = telemetry
        self.every_windows = max(0, int(every_windows))
        workdir = getattr(telemetry, "workdir", None)
        self.logdir = logdir or (
            os.path.join(workdir, "profile") if workdir else None
        )
        self.capture_steps = max(1, int(capture_steps))
        self.min_trigger_interval_s = float(min_trigger_interval_s)
        self.phase = phase
        self.plane_filter = plane_filter
        self.top_ops = top_ops
        # the fast-path flag Telemetry.span checks once per train step
        self.capturing = False
        self.captures = 0
        self.rate_limited = 0
        self.errors = 0
        self._active: Optional[Dict] = None
        self._lock = threading.Lock()
        self._last_trigger: Optional[float] = None
        self._finalize_thread: Optional[threading.Thread] = None

    @property
    def enabled(self) -> bool:
        """Cadence capture armed (triggered/admin captures work regardless,
        as long as a logdir is resolvable)."""
        return self.every_windows > 0 and self.logdir is not None

    # -- capture lifecycle -------------------------------------------------

    def _begin(
        self,
        reason: str,
        *,
        step: Optional[int] = None,
        alert_id: Optional[str] = None,
        seconds: Optional[float] = None,
    ) -> Optional[Dict]:
        if self.logdir is None:
            return None
        with self._lock:
            if self._active is not None:
                return None  # capture-during-capture: the running one wins
            capture_id = trace_lib.new_id()
            capture_dir = os.path.join(self.logdir, f"capture-{capture_id}")
            try:
                import jax

                os.makedirs(capture_dir, exist_ok=True)
                jax.profiler.start_trace(capture_dir)
            except Exception as e:  # noqa: BLE001 — never kill the producer
                self.errors += 1
                logger.warning("profile capture failed to start: %s", e)
                return None
            rec: Dict = {
                "capture_id": capture_id,
                "dir": capture_dir,
                "reason": reason,
                "t0": time.perf_counter(),
                "steps": 0,
                "busy_s": 0.0,
            }
            if step is not None:
                rec["step"] = step
            if alert_id is not None:
                rec["alert_id"] = alert_id
            if seconds is not None:
                rec["seconds"] = float(seconds)
            self._active = rec
            self.capturing = True
            return rec

    def note_step(self, duration_s: float = 0.0) -> None:
        """One train step finished under an active windowed capture (called
        from ``Telemetry.span`` with the step span's wall time). Stops the
        capture once ``capture_steps`` steps are in — the bounded-overhead
        contract."""
        rec = self._active
        if rec is None or "seconds" in rec or rec.get("finalizing"):
            return  # timed captures stop on their own clock
        rec["steps"] += 1
        rec["busy_s"] += float(duration_s)
        if rec["steps"] >= self.capture_steps:
            self._finish()

    def _finish(self, wait: bool = False) -> None:
        # stop_trace serializes + writes the trace planes and the parse walks
        # them — ~1s for a multi-step window, far over the per-step budget —
        # so everything past flipping `capturing` runs off the train thread.
        # `_active` stays set until the finalize lands, which is what makes
        # back-to-back _begin calls refuse instead of double-starting TSL.
        with self._lock:
            rec = self._active
            if rec is None or rec.get("finalizing"):
                return
            rec["finalizing"] = True
            self.capturing = False
        window_s = time.perf_counter() - rec["t0"]

        def _do() -> None:
            try:
                import jax

                jax.profiler.stop_trace()
            except Exception as e:  # noqa: BLE001
                self.errors += 1
                logger.warning("profile capture failed to stop: %s", e)
            try:
                self._ledger_capture(rec, window_s)
                self.captures += 1
            except Exception as e:  # noqa: BLE001 — parse/ledger best-effort
                self.errors += 1
                logger.warning("profile capture %s not ledgered: %s",
                               rec["capture_id"], e)
            finally:
                with self._lock:
                    if self._active is rec:
                        self._active = None

        if wait:
            _do()
            return
        t = threading.Thread(target=_do, daemon=True, name="profile-finalize")
        self._finalize_thread = t
        t.start()

    def _ledger_capture(self, rec: Dict, window_s: float) -> None:
        rows: List[xplane.OpTime] = []
        skipped = 0
        try:
            rows, skipped = xplane.op_breakdown_with_errors(
                rec["dir"], plane_filter=self._plane_filter()
            )
        except FileNotFoundError:
            # backend wrote no planes (profiler unsupported): the capture
            # event still records the attempt, with ops=0
            pass
        capture: Dict = {
            "capture_id": rec["capture_id"],
            "reason": rec["reason"],
            "logdir": rec["dir"],
            "window_s": round(window_s, 6),
            "ops": len(rows),
            "skipped_plane_files": skipped,
        }
        for key in ("step", "alert_id", "seconds", "steps"):
            if key in rec and rec[key] is not None:
                capture[key] = rec[key]
        self.telemetry.event(PROFILE_CAPTURE_EVENT, **capture)
        if not rows:
            return
        steps = rec.get("steps") or None
        busy_s = rec.get("busy_s") or None
        roofline = build_roofline(
            rows,
            busy_s=busy_s,
            steps=steps,
            step_flops=getattr(self.telemetry, "step_flops", None),
            phase=self.phase,
            top=self.top_ops,
        )
        roofline["capture_id"] = rec["capture_id"]
        roofline["reason"] = rec["reason"]
        if skipped:
            roofline["skipped_plane_files"] = skipped
        for key in ("step", "alert_id"):
            if key in rec:
                roofline[key] = rec[key]
        self.telemetry.event(OP_ROOFLINE_EVENT, **roofline)

    def _plane_filter(self) -> str:
        if self.plane_filter is not None:
            return self.plane_filter
        try:
            import jax

            backend = jax.default_backend()
        except Exception:  # noqa: BLE001
            backend = ""
        if backend == "tpu":
            return "TPU"
        if backend == "gpu":
            return "GPU"
        # CPU: no device plane — the XLA:CPU op events (Eigen threadpool
        # lines) live on /host:CPU; naming it skips the event-less
        # /host:metadata plane, which is half the capture's parse bytes
        return "/host:CPU"

    # -- entry points ------------------------------------------------------

    def on_window(
        self,
        *,
        step: Optional[int] = None,
        windows: int = 0,
        alerts: Optional[List[Dict]] = None,
    ) -> None:
        """Window-boundary hook (called by ``Telemetry.window_event`` after
        the window is persisted): postmortem triggers first — an alert is a
        better reason to capture than the calendar — then the cadence."""
        for alert in alerts or ():
            if (
                alert.get("monitor") in TRIGGER_MONITORS
                and not alert.get("resolved")
            ):
                self.trigger(alert, step=step)
                break
        if (
            self.every_windows
            and windows > 0
            and windows % self.every_windows == 0
        ):
            self._begin("cadence", step=step)

    def trigger(
        self,
        alert: Dict,
        *,
        step: Optional[int] = None,
        seconds: Optional[float] = None,
    ) -> Optional[Dict]:
        """Postmortem capture for a health alert: rate-limited (at most one
        per ``min_trigger_interval_s``), stamped with the alert's id.
        ``seconds`` switches to a timed capture (serving, where no train
        steps will stop a windowed one)."""
        now = time.monotonic()
        if (
            self._last_trigger is not None
            and now - self._last_trigger < self.min_trigger_interval_s
        ):
            self.rate_limited += 1
            return None
        alert_id = alert.get("alert_id")
        if seconds is not None:
            out = self.capture_timed(
                seconds, reason="alert", alert_id=alert_id
            )
        else:
            rec = self._begin("alert", step=step, alert_id=alert_id)
            out = {"capture_id": rec["capture_id"]} if rec else None
        if out is not None:
            self._last_trigger = now
        return out

    def capture_timed(
        self,
        seconds: float = 1.0,
        *,
        reason: str = "admin",
        alert_id: Optional[str] = None,
        wait: bool = False,
    ) -> Optional[Dict]:
        """Explicit N-second capture (serve ``/admin/profile``): returns
        ``{capture_id, seconds, status}`` immediately (the capture finishes
        and ledgers on a background thread), or ``None`` when a capture is
        already in flight."""
        seconds = max(0.05, float(seconds))
        rec = self._begin(reason, alert_id=alert_id, seconds=seconds)
        if rec is None:
            return None
        def _run() -> None:
            time.sleep(seconds)
            self._finish(wait=True)  # already off the hot path

        t = threading.Thread(
            target=_run, daemon=True, name="profile-capture"
        )
        t.start()
        if wait:
            t.join()
        return {
            "capture_id": rec["capture_id"],
            "seconds": seconds,
            "status": "complete" if wait else "started",
        }

    def close(self) -> None:
        """Finish (stop + parse + ledger) any capture still in flight — the
        trainers call this from ``Telemetry.close`` so a run ending mid-
        capture still lands its events before the ledger closes."""
        self._finish(wait=True)
        t = self._finalize_thread
        if t is not None and t.is_alive():
            t.join(timeout=30.0)
