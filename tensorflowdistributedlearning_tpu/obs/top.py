"""``telemetry-top``: the live fleet console over the merged run ledgers.

``telemetry-report`` is the post-hoc story; an operator babysitting a live
run (or a serving fleet mid-incident) needs the NOW view: is the fleet making
progress, where is the backlog, which host is the straggler, how close is HBM
to the limit, what is a request costing. This module tails the same
per-process ledgers the report merges (``obs/fleet.py`` discovery — the
canonical ``telemetry.jsonl`` plus every ``telemetry-{i}.jsonl``) and renders
one compact refreshing frame:

    python -m tensorflowdistributedlearning_tpu telemetry-top WORKDIR
    python -m tensorflowdistributedlearning_tpu telemetry-top WORKDIR --once

Per process: goodput split and step time (training), requests/backlog/p99
(serving), HBM headroom and cost rates (obs/capacity.py events), health and
straggler flags. ``--once`` prints a single frame and exits 0 — the scripting
/ CI-smoke mode. Reading is report-side only (no cost on the producers), and
every degraded shape is a frame, not a crash: an empty workdir renders "no
ledgers yet", a serving-only workdir has no training rows, a training-only
workdir no serving rows.

Cost note: each REBUILD re-parses the ledgers in full (the discovery/merge
machinery is shared with ``telemetry-report``, which has no incremental
mode); the refresh loop therefore stats the files first and reuses the
previous frame when nothing changed, so an idle fleet costs one stat sweep
per interval. A very large ledger (a long run with high-rate sampled traces)
still pays a full parse per CHANGE — prefer a longer ``--interval`` there.
"""

from __future__ import annotations

import glob
import os
import sys
import time
from typing import Dict, List, Optional, Tuple

from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib
from tensorflowdistributedlearning_tpu.obs.profiler import OP_ROOFLINE_EVENT

# ANSI: clear screen + home; plain strings so tests can strip them trivially
_CLEAR = "\x1b[2J\x1b[H"


def _last(events: List[Dict], kind: str) -> Optional[Dict]:
    for e in reversed(events):
        if e.get("event") == kind:
            return e
    return None


def _fmt_bytes(n: float) -> str:
    if n >= 2**30:
        return f"{n / 2**30:.2f}GiB"
    return f"{n / 2**20:.1f}MiB"


def _fmt_age(seconds: float) -> str:
    if seconds < 120:
        return f"{seconds:.0f}s"
    if seconds < 7200:
        return f"{seconds / 60:.0f}m"
    return f"{seconds / 3600:.1f}h"


def _process_status(led: fleet_lib.ProcessLedger, now: float) -> Dict:
    """One frame row from one process ledger's last run."""
    events = led.events
    header = led.header
    row: Dict = {
        "process_index": led.process_index,
        "kind": header.get("kind") or header.get("task") or "unknown",
        "parse_errors": led.parse_errors,
    }
    if events:
        row["last_event_age_s"] = max(0.0, now - events[-1].get("t", now))
    run_end = _last(events, "run_end")
    row["live"] = run_end is None
    window = _last(events, "step_window")
    if window is not None:
        row["step"] = window.get("step")
        st = window.get("step_time_ms") or {}
        if st.get("mean_ms") is not None:
            row["step_time_mean_ms"] = st["mean_ms"]
        busy = sum(
            window.get(k, 0.0)
            for k in (
                "data_wait_s",
                "compute_s",
                "fetch_wait_s",
                "barrier_wait_s",
            )
        )
        if busy:
            row["goodput"] = {
                "compute_frac": round(window.get("compute_s", 0.0) / busy, 3),
                "data_wait_frac": round(
                    window.get("data_wait_s", 0.0) / busy, 3
                ),
            }
        if window.get("images_per_sec") is not None:
            row["images_per_sec"] = window["images_per_sec"]
        if window.get("mfu") is not None:
            row["mfu"] = window["mfu"]
        if window.get("recompiles_post_warmup"):
            row["recompiles_post_warmup"] = window["recompiles_post_warmup"]
        svc = window.get("data_service")
        if svc is not None:
            # the input service's live backpressure (data/service.py):
            # reorder-buffer depth, consumer-starved takes, worker busy
            # fraction — "is the input side keeping up", right now
            srow: Dict = {"underruns": int(svc.get("underruns", 0))}
            ready = svc.get("ready_depth") or {}
            if ready.get("mean") is not None:
                srow["ready_depth_mean"] = ready["mean"]
            if ready.get("min") is not None:
                srow["ready_depth_min"] = ready["min"]
            if svc.get("worker_util") is not None:
                srow["worker_util"] = svc["worker_util"]
            row["data_service"] = srow
    serve = _last(events, "serve_window")
    if serve is not None:
        srow: Dict = {
            "requests": serve.get("requests", 0),
            "completed": serve.get("completed", 0),
            "backlog": serve.get("queue_depth", 0),
        }
        if serve.get("replica") is not None:
            srow["replica"] = serve["replica"]
        if serve.get("model"):
            srow["model"] = serve["model"]
        elif serve.get("models"):
            # multi-tenant replica: name the mounted tenants compactly
            srow["models"] = sorted(serve["models"])
        req = (serve.get("latency_ms") or {}).get("request") or {}
        if req.get("p99_ms") is not None:
            srow["p99_ms"] = req["p99_ms"]
        slo = serve.get("slo")
        if slo is not None:
            srow["slo_healthy"] = bool(slo.get("healthy", True))
        if serve.get("tee_dropped"):
            srow["tee_dropped"] = serve["tee_dropped"]
        drift = serve.get("drift")
        if drift is not None:
            srow["drift_healthy"] = bool(drift.get("healthy", True))
        row["serve"] = srow
    cap = _last(events, "capture_window")
    if cap is not None:
        # the loop's raw-material gauge: live capture volume and loss
        row["capture"] = {
            "captured": cap.get("total_captured", 0),
            "dropped": cap.get("total_dropped", 0),
            "shards": cap.get("shards", 0),
            "bytes_on_disk": cap.get("bytes_on_disk", 0),
        }
    loop_retrain = _last(events, "loop_retrain")
    loop_trigger = _last(events, "loop_trigger")
    if loop_trigger is not None or loop_retrain is not None:
        lrow: Dict = {}
        if loop_trigger is not None:
            lrow["last_trigger"] = loop_trigger.get("reason")
        if loop_retrain is not None:
            lrow["last_retrain_rc"] = loop_retrain.get("rc")
            promoted = _last(events, "loop_promoted")
            rejected = _last(events, "loop_rejected")
            if promoted is not None or rejected is not None:
                p_t = (promoted or {}).get("t", -1.0)
                r_t = (rejected or {}).get("t", -1.0)
                lrow["last_verdict"] = (
                    "promoted" if p_t >= r_t else "rejected"
                )
        row["loop"] = lrow
    ready = _last(events, "replica_ready")
    if ready is not None and ready.get("time_to_ready_s") is not None:
        # the controller's newest replica cold-start: spawn -> readiness line
        row["last_replica_ready"] = {
            "replica": ready.get("replica"),
            "time_to_ready_s": ready["time_to_ready_s"],
        }
    router = _last(events, "router_window")
    if router is not None:
        fleet_state = router.get("fleet") or {}
        row["router"] = {
            "requests": router.get("requests", 0),
            "shed": router.get("shed", 0),
            "backlog": fleet_state.get("queue_depth_total", 0),
            "live": fleet_state.get("live", 0),
            "status": fleet_state.get("status", "?"),
        }
        models = fleet_state.get("models") or {}
        if models:
            row["router"]["models"] = {
                name: {
                    "replicas": m.get("replicas", 0),
                    "shed": m.get("shed", 0),
                    **(
                        {"worst_p99_ms": m["worst_p99_ms"]}
                        if m.get("worst_p99_ms") is not None
                        else {}
                    ),
                }
                for name, m in models.items()
            }
        artifacts = fleet_state.get("artifacts") or {}
        if artifacts:
            from tensorflowdistributedlearning_tpu.obs import (
                report as report_lib,
            )

            row["router"]["artifacts"] = artifacts
            # one definition of "silently mixed" for report AND top
            row["router"]["mixed"] = report_lib.silent_mixed_fleet(
                fleet_state
            )
    marks = capacity_lib.aggregate_watermark_events(events)
    if marks:
        mem: Dict = {"peak_bytes": marks["peak_bytes"]}
        if marks.get("headroom_frac") is not None:
            mem["headroom_frac"] = marks["headroom_frac"]
        row["memory"] = mem
    cost = capacity_lib.aggregate_cost_events(events)
    if cost:
        crow: Dict = {}
        train = cost.get("train") or {}
        if train.get("chip_seconds_per_step") is not None:
            crow["chip_seconds_per_step"] = train["chip_seconds_per_step"]
        if train.get("examples_per_chip_second") is not None:
            crow["examples_per_chip_second"] = train[
                "examples_per_chip_second"
            ]
        serve_cost = cost.get("serve") or {}
        if serve_cost.get("rps_per_chip") is not None:
            crow["rps_per_chip"] = serve_cost["rps_per_chip"]
        if serve_cost.get("chip_seconds_total") is not None:
            crow["chip_seconds_total"] = serve_cost["chip_seconds_total"]
        elif train.get("chip_seconds_total") is not None:
            crow["chip_seconds_total"] = train["chip_seconds_total"]
        if crow:
            row["cost"] = crow
    # last ledgered roofline (obs/profiler.py): the live "where do the FLOPs
    # go" row — roofline class split, top HBM-bound op, collective share.
    # Workdirs without captures simply have no "roofline" key (rendered "-").
    roofline = _last(events, OP_ROOFLINE_EVENT)
    if roofline is not None:
        cls = roofline.get("classes") or {}
        rrow: Dict = {
            "reason": roofline.get("reason"),
            "compute_frac": cls.get("compute_frac"),
            "hbm_frac": cls.get("hbm_frac"),
            "collective_frac": cls.get("collective_frac"),
        }
        if roofline.get("mfu") is not None:
            rrow["mfu"] = roofline["mfu"]
        hbm_op = roofline.get("top_hbm_op")
        if hbm_op:
            rrow["top_hbm_op"] = hbm_op.get("name")
        row["roofline"] = rrow
    alerts = [e for e in events if e.get("event") == "health_alert"]
    if alerts:
        active: Dict[str, bool] = {}
        for a in alerts:
            active[a.get("monitor", "unknown")] = not a.get("resolved")
        degraded = sorted(m for m, live in active.items() if live)
        row["health"] = {"alerts": len(alerts), "degraded": degraded}
    return row


def build_frame(workdir: str, *, now: Optional[float] = None) -> Dict:
    """One console frame as data (the ``--once``/test contract; rendering is
    presentation only). Never raises on empty/foreign workdirs — a frame with
    ``processes == 0`` means nothing is writing ledgers yet."""
    now = now if now is not None else time.time()
    try:
        ledgers = fleet_lib.discover_ledgers(workdir)
    except OSError:
        ledgers = []
    frame: Dict = {
        "workdir": workdir,
        "t": now,
        "processes": len(ledgers),
        "rows": [_process_status(led, now) for led in ledgers],
    }
    if len(ledgers) >= 2:
        straggler = fleet_lib.straggler_section(ledgers)
        if straggler:
            frame["straggler"] = {
                "max_skew": straggler["max_skew"],
                "alert_count": straggler["alert_count"],
                "worst_process": straggler["worst_process"],
            }
    if ledgers:
        # elastic session status (parallel/elastic.py): the coordinator
        # appends to the canonical (process-0) ledger, so its whole history
        # carries the elastic_start/world_resize/elastic_end brackets
        from tensorflowdistributedlearning_tpu.obs import report as report_lib

        elastic = report_lib._elastic_section(ledgers[0].all_events)
        if elastic:
            frame["elastic"] = {
                k: elastic.get(k)
                for k in (
                    "hosts", "min_hosts", "world_size", "live", "resizes",
                    "evictions", "resize_downtime_s", "aborted",
                )
            }
    return frame


def render_frame(frame: Dict) -> str:
    lines: List[str] = [
        f"telemetry-top — {frame['workdir']} — "
        f"{time.strftime('%H:%M:%S', time.localtime(frame['t']))}"
    ]
    if not frame["processes"]:
        lines.append(
            "  no ledgers yet (telemetry.jsonl / telemetry-N.jsonl absent) — "
            "is the run pointed at this workdir?"
        )
        return "\n".join(lines)
    ela = frame.get("elastic")
    if ela:
        state = "LIVE" if ela.get("live") else "ended"
        line = (
            f"elastic: world {ela['world_size']}/{ela['hosts']} [{state}] — "
            f"{ela['resizes']} resize(s), {ela['evictions']} eviction(s), "
            f"{(ela.get('resize_downtime_s') or 0.0):.1f}s resize downtime"
        )
        if ela.get("aborted"):
            line += f"  !! ABORTED ({ela['aborted']})"
        lines.append(line)
    for row in frame["rows"]:
        state = "live" if row.get("live") else "ended"
        age = row.get("last_event_age_s")
        if age is not None:
            state += f", last event {_fmt_age(age)} ago"
        lines.append(f"p{row['process_index']} [{row['kind']}] ({state})")
        if "step" in row:
            bits = [f"  step {row['step']}"]
            if row.get("step_time_mean_ms") is not None:
                bits.append(f"{row['step_time_mean_ms']:.1f}ms/step")
            gp = row.get("goodput")
            if gp:
                bits.append(
                    f"compute {gp['compute_frac']:.0%} / "
                    f"data-wait {gp['data_wait_frac']:.0%}"
                )
            if row.get("images_per_sec") is not None:
                bits.append(f"{row['images_per_sec']:.1f} img/s")
            lines.append("  ".join(bits))
        if "step" in row or row.get("roofline"):
            # the live MFU/roofline row: "-" where no pricing/capture exists
            # (CPU backend without flop counters, workdir with no captures)
            rf = row.get("roofline") or {}
            mfu = row.get("mfu", rf.get("mfu"))
            bits = [
                "  mfu "
                + (f"{mfu:.1%}" if mfu is not None else "-")
            ]
            if rf.get("compute_frac") is not None:
                bits.append(
                    f"roofline compute {rf['compute_frac']:.0%} / "
                    f"hbm {rf['hbm_frac']:.0%} / "
                    f"coll {rf['collective_frac']:.0%}"
                )
            else:
                bits.append("roofline -")
            bits.append(
                f"top-hbm {rf['top_hbm_op']}"
                if rf.get("top_hbm_op")
                else "top-hbm -"
            )
            lines.append("  ".join(bits))
        ds = row.get("data_service")
        if ds:
            bits = ["  data-svc:"]
            if ds.get("ready_depth_mean") is not None:
                bits.append(f"ready {ds['ready_depth_mean']:.1f}")
            if ds.get("worker_util") is not None:
                bits.append(f"workers {ds['worker_util']:.0%} busy")
            bits.append(f"{ds['underruns']} underrun(s)")
            if ds["underruns"]:
                bits.append("!! STARVED")
            lines.append("  ".join(bits))
        sv = row.get("serve")
        if sv:
            model_tag = ""
            if sv.get("model"):
                model_tag = f" [{sv['model']}]"
            elif sv.get("models"):
                model_tag = f" [{'+'.join(sv['models'])}]"
            bits = [
                f"  serve"
                + (f" r{sv['replica']}" if "replica" in sv else "")
                + model_tag
                + f": {sv['completed']}/{sv['requests']} ok",
                f"backlog {sv['backlog']}",
            ]
            if sv.get("p99_ms") is not None:
                bits.append(f"p99 {sv['p99_ms']:.1f}ms")
            if sv.get("slo_healthy") is False:
                bits.append("!! SLO BREACHED")
            if sv.get("tee_dropped"):
                bits.append(f"!! tee dropped {sv['tee_dropped']}")
            if sv.get("drift_healthy") is False:
                bits.append("!! DRIFTED")
            lines.append("  ".join(bits))
        cap = row.get("capture")
        if cap:
            line = (
                f"  capture: {cap['captured']} rec in {cap['shards']} "
                f"shard(s) ({_fmt_bytes(cap['bytes_on_disk'])})"
            )
            if cap.get("dropped"):
                line += f"  !! {cap['dropped']} dropped"
            lines.append(line)
        lp = row.get("loop")
        if lp:
            line = "  loop:"
            if lp.get("last_trigger"):
                line += f" trigger {lp['last_trigger']}"
            if lp.get("last_verdict"):
                line += f", last cycle {lp['last_verdict'].upper()}"
            elif lp.get("last_retrain_rc") is not None:
                line += f", retrain rc={lp['last_retrain_rc']}"
            lines.append(line)
        rt = row.get("router")
        if rt:
            line = (
                f"  router: {rt['requests']} req, {rt['shed']} shed, "
                f"backlog {rt['backlog']}, {rt['live']} live "
                f"[{rt['status']}]"
            )
            if rt.get("mixed"):
                line += "  !! MIXED ARTIFACTS (no promotion active)"
            rr = row.get("last_replica_ready")
            if rr:
                line += (
                    f", last ready r{rr.get('replica', '?')} in "
                    f"{rr['time_to_ready_s']:.1f}s"
                )
            lines.append(line)
            for name, m in sorted((rt.get("models") or {}).items()):
                mline = (
                    f"    {name}: {m['replicas']} replica(s), "
                    f"{m['shed']} shed"
                )
                if m.get("worst_p99_ms") is not None:
                    mline += f", p99 {m['worst_p99_ms']:.1f}ms"
                lines.append(mline)
        mem = row.get("memory")
        if mem:
            line = f"  hbm peak {_fmt_bytes(mem['peak_bytes'])}"
            if mem.get("headroom_frac") is not None:
                line += f", headroom {mem['headroom_frac']:.1%}"
                if mem["headroom_frac"] < 0.1:
                    line += "  !! LOW"
            lines.append(line)
        cost = row.get("cost")
        if cost:
            bits = ["  cost:"]
            if cost.get("chip_seconds_per_step") is not None:
                bits.append(
                    f"{cost['chip_seconds_per_step'] * 1000:.2f} chip-ms/step"
                )
            if cost.get("examples_per_chip_second") is not None:
                bits.append(
                    f"{cost['examples_per_chip_second']:.1f} ex/chip-s"
                )
            if cost.get("rps_per_chip") is not None:
                bits.append(f"{cost['rps_per_chip']:.1f} rps/chip")
            if cost.get("chip_seconds_total") is not None:
                bits.append(
                    f"{cost['chip_seconds_total']:.1f} chip-s total"
                )
            lines.append("  ".join(bits))
        hl = row.get("health")
        if hl:
            if hl["degraded"]:
                lines.append(
                    f"  !! health degraded: {', '.join(hl['degraded'])} "
                    f"({hl['alerts']} alert(s))"
                )
            else:
                lines.append(
                    f"  health: {hl['alerts']} alert(s), all resolved"
                )
        if row.get("recompiles_post_warmup"):
            lines.append(
                f"  !! {row['recompiles_post_warmup']} post-warmup "
                "recompile(s)"
            )
        if row.get("parse_errors"):
            lines.append(
                f"  !! {row['parse_errors']} unparseable ledger line(s)"
            )
    st = frame.get("straggler")
    if st:
        flag = (
            f" — !! {st['alert_count']} alert(s), worst p{st['worst_process']}"
            if st["alert_count"]
            else ""
        )
        lines.append(f"straggler skew: {st['max_skew']:.2f}x{flag}")
    return "\n".join(lines)


def _ledger_signature(workdir: str) -> Tuple:
    """(path, size, mtime) of every ledger file — the cheap change detector
    the refresh loop uses to skip full re-parses of an unchanged fleet."""
    sig = []
    for path in sorted(glob.glob(os.path.join(workdir, "telemetry*.jsonl"))):
        try:
            st = os.stat(path)
            sig.append((path, st.st_size, st.st_mtime_ns))
        except OSError:
            continue
    return tuple(sig)


def top(
    workdir: str,
    *,
    interval_s: float = 2.0,
    once: bool = False,
    iterations: Optional[int] = None,
    out=None,
) -> int:
    """The ``telemetry-top`` loop: render a frame every ``interval_s``
    seconds until interrupted. ``once`` prints a single frame (scripting /
    CI smoke); ``iterations`` bounds the loop for tests. Exit code 0 always —
    an empty workdir is an honest frame, not an error (a run that has not
    started yet is the normal first thing an operator watches)."""
    out = out if out is not None else sys.stdout
    count = 0
    last_sig: Optional[Tuple] = None
    frame: Dict = {}
    try:
        while True:
            sig = _ledger_signature(workdir)
            if frame and sig == last_sig:
                # nothing wrote since the last frame: refresh the clock and
                # ages only — an idle fleet costs one stat sweep per interval
                now = time.time()
                elapsed = now - frame["t"]
                frame["t"] = now
                for row in frame["rows"]:
                    if "last_event_age_s" in row:
                        row["last_event_age_s"] += elapsed
            else:
                frame = build_frame(workdir)
                last_sig = sig
            text = render_frame(frame)
            if once or iterations is not None:
                print(text, file=out, flush=True)
            else:
                print(_CLEAR + text, file=out, flush=True)
            count += 1
            if once or (iterations is not None and count >= iterations):
                return 0
            time.sleep(interval_s)
    except KeyboardInterrupt:
        return 0
