"""The ``Telemetry`` façade the trainers wire in: spans + ledger + detector.

One object per training run, constructed against the run's workdir. It owns:

- a ``MetricsRegistry`` the span API records into (``span("data_wait")`` /
  ``span("step")`` / ``span("eval")`` — each span is host wall time, also
  annotated into any active ``jax.profiler`` trace so ledger windows and
  xplane timelines line up);
- a ``RunLedger`` (``telemetry.jsonl``; only process 0 writes under
  multi-host — spans still accumulate everywhere, they are process-local);
- a ``RecompileDetector`` attributing compiles to the active span and writing
  them to the ledger; post-warmup recompiles are additionally WARNED, because
  they are the silent goodput killer the whole subsystem exists to catch.

Span accounting semantics (honest about async dispatch): ``data_wait`` is the
host blocked on the input iterator — loader-bound time. ``step`` is the rest
of the loop body; with async dispatch the device sync lands on the log
window's ``device_get``, which the trainers also run inside a ``step`` span,
so per-WINDOW totals are real wall time even though individual step samples
measure dispatch+backpressure. The window event carries both the split and
the per-step percentiles.

``NULL_TELEMETRY`` is the disabled instance (no workdir, no ledger, no
detector, spans are near-free) so trainer code never branches on None.
"""

from __future__ import annotations

import contextlib
import logging
import time
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
from tensorflowdistributedlearning_tpu.obs import trace as trace_lib
from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger
from tensorflowdistributedlearning_tpu.obs.metrics import (
    MetricsRegistry,
    time_summary,
    window_count,
    window_total_s,
)
from tensorflowdistributedlearning_tpu.obs.recompile import (
    CompileEvent,
    RecompileDetector,
)

logger = logging.getLogger(__name__)

# span names the trainers use; anything else is allowed, these are the schema
SPAN_DATA_WAIT = "data_wait"
SPAN_STEP = "step"
SPAN_EVAL = "eval"
# host blocked waiting on a device value (the async loop's bounded
# dispatch-ahead and deferred window fetch — train/async_loop.py); disjoint
# from data_wait/step like the other window spans
SPAN_FETCH_WAIT = "fetch_wait"
# checkpoint save wall time (the trainers wrap periodic/forced saves) — not a
# window span (nothing drains it; the histogram ring bounds it), but a trace
# boundary: sampled runs show checkpoint spans in the exported timeline
SPAN_CHECKPOINT = "checkpoint"
# host blocked at a cross-process sync point (parallel/multihost.py wraps its
# multihost_utils calls in `barrier_probe`): on a healthy fleet this is ~0 on
# the slowest host and largest on the fastest, so per-host barrier_wait is the
# signal that separates "slow host" from "slow network" in the fleet report
SPAN_BARRIER = "barrier_wait"

# registry histogram the input prefetcher records its ready-queue depth into
# (data/pipeline.py:device_prefetch); drained per window like the spans, so
# prefetch underruns are visible in the ledger and telemetry-report
PREFETCH_DEPTH_HISTOGRAM = "prefetch/queue_depth"

# data-service backpressure telemetry (data/service.py): reorder-buffer depth
# at each consumer take, one sample per consumer-blocked-on-workers event
# (an underrun: the device side is about to starve), per-batch worker busy
# seconds (utilization = busy / (workers x window wall)), and the live worker
# count. Drained per window like the prefetch gauge; rendered by
# telemetry-report's prefetch section and watched by the data_starved monitor
DATA_READY_HISTOGRAM = "data_service/ready_depth"
DATA_UNDERRUN_HISTOGRAM = "data_service/underruns"
DATA_WORKER_BUSY_HISTOGRAM = "data_service/worker_busy"
DATA_WORKERS_GAUGE = "data_service/workers"


def run_fingerprint() -> Dict:
    """Device/process fingerprint for the run header — enough to answer
    "what hardware produced this ledger" from the file alone."""
    import jax

    devices = jax.devices()
    return {
        "platform": devices[0].platform,
        "device_kind": getattr(devices[0], "device_kind", "unknown"),
        "n_devices": len(devices),
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "jax_version": jax.__version__,
    }


class Telemetry:
    """Per-run telemetry: span timing, JSONL ledger, recompile detection."""

    def __init__(
        self,
        workdir: Optional[str],
        *,
        run_info: Optional[Dict] = None,
        enabled: bool = True,
        memory_every_windows: int = 5,
        is_main: Optional[bool] = None,
        trace_sample_rate: float = 0.0,
        health=None,
        process_index: Optional[int] = None,
        process_count: Optional[int] = None,
        capacity_sampling: bool = True,
    ):
        self.enabled = enabled and workdir is not None
        # the run's workdir (None when disabled) — the continuous profiler
        # (obs/profiler.py) roots its capture dirs under it
        self.workdir = workdir if self.enabled else None
        # attached via set_profiler; None = no profiling (one pointer check
        # per step/window is the whole cost of the hook points)
        self.profiler = None
        # analytic per-step FLOP pricing (set_step_flops): what turns
        # measured step time into a first-class windowed `mfu` field and
        # prices the profiler's rooflines
        self.step_flops: Optional[Dict] = None
        # capacity/cost layer (obs/capacity.py): per-phase HBM watermarks and
        # chip-seconds accounting, sampled on the WINDOW cadence (never per
        # step — the <=1% overhead gate, bench.py --capacity-overhead).
        # Constructed unconditionally (cheap, no backend touch) so callers
        # never branch on None; only an enabled Telemetry emits events.
        self.capacity_sampling = bool(capacity_sampling)
        self.watermarks = capacity_lib.WatermarkTracker()
        self.cost = capacity_lib.CostMeter()
        self.registry = MetricsRegistry()
        self._span_stack: List[str] = []
        self._windows = 0
        self._memory_every_windows = max(1, memory_every_windows)
        self._closed = False
        self.ledger: Optional[RunLedger] = None
        self.detector: Optional[RecompileDetector] = None
        # online health monitors (obs/health.py) consulted at every window
        # event; None = no monitoring (the trainers pass
        # HealthMonitor.from_train_config)
        self.health = health
        # per-unit tracing (obs/trace.py): sampled spans persist as `trace`
        # ledger events through the same writer — BUFFERED (no flush per
        # span: spans can fire several times per train step, and a syscall
        # per line steals CPU from compute; buffered lines land at the next
        # flushed event / flush() / close()). Rate 0 keeps the tracer
        # disabled and span() single-branch cheap.
        self.tracer = trace_lib.Tracer(
            emit=self._trace_event if self.enabled else None,
            sample_rate=trace_sample_rate if self.enabled else 0.0,
        )
        if not self.enabled:
            return
        if process_index is None:
            # the normal trainer path: this process's slot in the
            # jax.distributed cluster decides the ledger it writes. Explicit
            # process_index is for producers whose fleet identity is NOT a
            # jax process — serve replicas sharing one workdir pass their
            # replica id so each writes its own telemetry-{i}.jsonl.
            process_index, process_count = 0, 1
            if is_main is None:
                try:
                    from tensorflowdistributedlearning_tpu.parallel import (
                        multihost,
                    )

                    info = multihost.process_info()
                    process_index = info["process_index"]
                    process_count = info["process_count"]
                except Exception:  # noqa: BLE001 — backend probe best-effort
                    pass
        process_index = int(process_index)
        if is_main is None:
            is_main = process_index == 0
        # any non-zero index writes a ledger (jax secondary process OR an
        # explicitly-identified serve replica); process 0 keeps the legacy
        # is_main gate
        if is_main or process_index > 0:
            import os

            # fleet ledger contract (obs/fleet.py): under multi-host EVERY
            # process writes its own ledger — process 0 the canonical
            # telemetry.jsonl, process i>0 telemetry-{i}.jsonl — so the merge
            # can attribute windows to hosts; single-process is unchanged
            from tensorflowdistributedlearning_tpu.obs.ledger import (
                per_process_filename,
            )

            self.ledger = RunLedger(
                workdir, filename=per_process_filename(process_index)
            )
            header = {
                "schema_version": 1,
                "process_index": process_index,
            }
            # only when actually known: an explicit process_index with no
            # count (a serve replica that cannot know the fleet size) must
            # not persist a fabricated count
            if process_count is not None:
                header["process_count"] = int(process_count)
            if os.environ.get("TFDL_SUPERVISED_CHILD"):
                # stamped by resilience/supervisor.py on its children: lets
                # obs/report tell a supervised session's relaunches apart
                # from later standalone runs in the same workdir
                header["supervised"] = True
            try:
                header["fingerprint"] = run_fingerprint()
            except Exception as e:  # noqa: BLE001 — backend probe is best-effort
                header["fingerprint"] = {"error": str(e)[:200]}
            if run_info:
                header.update(run_info)
            self.ledger.event("run_header", **header)
        self.detector = RecompileDetector(
            phase_fn=lambda: self.current_span,
            on_event=self._on_compile,
        ).attach()

    # -- spans -------------------------------------------------------------

    @property
    def current_span(self) -> str:
        return self._span_stack[-1] if self._span_stack else ""

    @contextlib.contextmanager
    def span(self, name: str):
        """Time a named host-side phase; nested spans attribute to the
        innermost name. Also opens a profiler TraceAnnotation so captured
        xplane traces carry the same phase names the ledger uses."""
        if not self.enabled:
            yield
            return
        self._span_stack.append(name)
        t0 = time.perf_counter()
        try:
            import jax

            with jax.profiler.TraceAnnotation(f"obs/{name}"):
                if self.tracer.enabled:
                    # per-unit tracing: a top-level span roots its own
                    # (sampled) trace; nested spans join the enclosing one
                    with self.tracer.span(name):
                        yield
                else:
                    yield
        finally:
            dt = time.perf_counter() - t0
            self.registry.histogram(f"span/{name}").record(dt)
            self._span_stack.pop()
            prof = self.profiler
            if prof is not None and prof.capturing and name == SPAN_STEP:
                # an active windowed capture counts train steps (and their
                # wall time — the same basis as step_time_ms) so it can stop
                # after capture_steps; the common path costs one None check
                try:
                    prof.note_step(dt)
                except Exception:  # noqa: BLE001 — profiling never kills training
                    logger.warning("profiler note_step failed", exc_info=True)

    def _span_delta(self, name: str) -> List[float]:
        """Span samples recorded since the last window boundary. Draining
        (not marking) keeps per-step span histograms bounded by one window —
        a 500k-step run would otherwise retain ~1M floats nothing reads."""
        return self.registry.histogram(f"span/{name}").drain()

    def drain_window_samples(self) -> Dict[str, List[float]]:
        """Drain the per-window samples NOW and hand them to the caller.

        Deferred-emission callers (the async host loop) snapshot at the
        window BOUNDARY and pass the result back through
        ``window_event(samples=...)`` one window later, so a late-written
        window event still describes its own interval instead of the next
        one's."""
        samples = {
            name: self._span_delta(name)
            for name in (
                SPAN_DATA_WAIT,
                SPAN_STEP,
                SPAN_FETCH_WAIT,
                SPAN_BARRIER,
            )
        }
        samples["prefetch_depth"] = self.registry.histogram(
            PREFETCH_DEPTH_HISTOGRAM
        ).drain()
        samples["data_ready_depth"] = self.registry.histogram(
            DATA_READY_HISTOGRAM
        ).drain()
        samples["data_underruns"] = self.registry.histogram(
            DATA_UNDERRUN_HISTOGRAM
        ).drain()
        samples["data_worker_busy"] = self.registry.histogram(
            DATA_WORKER_BUSY_HISTOGRAM
        ).drain()
        return samples

    # -- profiling / MFU ---------------------------------------------------

    def set_profiler(self, profiler) -> None:
        """Attach a ``ContinuousProfiler`` (obs/profiler.py). The telemetry
        drives its hook points: step spans count into active captures,
        window boundaries run the cadence + alert triggers, and close()
        finishes any capture in flight."""
        self.profiler = profiler

    def set_step_flops(
        self,
        flops_per_step: float,
        *,
        peak_flops_per_chip: Optional[float] = None,
        n_devices: Optional[int] = None,
        collective_bytes_per_step: Optional[float] = None,
    ) -> None:
        """Price this run's steps analytically so measured time becomes MFU.

        ``flops_per_step`` is the planner's dense-proxy model
        (``6 * param_count * global_batch``) for ONE optimizer step across
        the whole job; ``peak_flops_per_chip`` defaults to the device peak
        table (``obs.profiler.resolve_peak_flops``) and stays ``None`` on
        unknown kinds — every ``step_window`` then simply omits ``mfu``
        (never a fabricated 0/0). ``collective_bytes_per_step`` is the
        planner's priced per-chip collective volume, which lets rooflines
        report achieved collective bandwidth."""
        if not self.enabled:
            return
        if n_devices is None:
            try:
                import jax

                n_devices = len(jax.devices())
            except Exception:  # noqa: BLE001
                n_devices = 1
        if peak_flops_per_chip is None:
            from tensorflowdistributedlearning_tpu.obs.profiler import (
                resolve_peak_flops,
            )

            peak_flops_per_chip = resolve_peak_flops()
        self.step_flops = {
            "flops_per_step": float(flops_per_step),
            "n_devices": int(n_devices),
        }
        if peak_flops_per_chip:
            self.step_flops["peak_flops_per_chip"] = float(peak_flops_per_chip)
        if collective_bytes_per_step:
            self.step_flops["collective_bytes_per_step"] = float(
                collective_bytes_per_step
            )

    def _window_mfu(self, mean_step_s: float) -> Optional[float]:
        """Model FLOPs utilization for a window with the given mean step
        time; None unless both the analytic pricing and a real device peak
        are known."""
        sf = self.step_flops
        if not sf or not mean_step_s or mean_step_s <= 0:
            return None
        peak = sf.get("peak_flops_per_chip")
        if not peak:
            return None
        achieved = sf["flops_per_step"] / mean_step_s / sf["n_devices"]
        return round(achieved / peak, 4)

    # -- events ------------------------------------------------------------

    def _event(self, kind: str, /, **fields) -> None:
        if self.ledger is not None:
            self.ledger.event(kind, **fields)

    def _trace_event(self, fields: Dict) -> None:
        if self.ledger is not None:
            self.ledger.event_buffered(trace_lib.TRACE_EVENT, **fields)

    def flush(self) -> None:
        """Push buffered (trace) events to disk — for readers of a live
        ledger; flushed events and close() do this implicitly."""
        if self.ledger is not None:
            self.ledger.flush()

    def event(self, kind: str, /, **fields) -> None:
        """Append an arbitrary ledger event under this run's header — the
        extension point non-trainer producers (the serving stack's
        ``serve_window`` events, suite stages) write through, so every
        producer shares one schema, one writer, one failure stance."""
        self._event(kind, **fields)

    def window_event(
        self,
        step: int,
        *,
        steps: int,
        images_per_sec: Optional[float] = None,
        scalars: Optional[Dict[str, float]] = None,
        dirty: bool = False,
        samples: Optional[Dict[str, List[float]]] = None,
        examples: Optional[int] = None,
        **extra,
    ) -> None:
        """One per-log-window record: throughput, data-wait vs step-compute
        vs blocked-on-fetch split, per-step time percentiles, prefetch queue
        depth, recompiles seen this window. ``dirty`` marks windows containing
        compile/eval/checkpoint time (their throughput point is not
        steady-state). ``samples`` lets a deferred emitter pass the window's
        own boundary-snapshotted samples (``drain_window_samples``); default
        drains now."""
        if not self.enabled:
            return
        if samples is None:
            samples = self.drain_window_samples()
        wait = samples.get(SPAN_DATA_WAIT, [])
        compute = samples.get(SPAN_STEP, [])
        fetch = samples.get(SPAN_FETCH_WAIT, [])
        barrier = samples.get(SPAN_BARRIER, [])
        depth = samples.get("prefetch_depth", [])
        # exact totals even when a histogram ring capped the raw samples
        # (obs/metrics.py:SampleWindow)
        wait_s, compute_s, fetch_s, barrier_s = (
            window_total_s(wait),
            window_total_s(compute),
            window_total_s(fetch),
            window_total_s(barrier),
        )
        busy = wait_s + compute_s + fetch_s + barrier_s
        fields: Dict = {
            "step": step,
            "steps": steps,
            "data_wait_s": round(wait_s, 6),
            "compute_s": round(compute_s, 6),
            "fetch_wait_s": round(fetch_s, 6),
            "barrier_wait_s": round(barrier_s, 6),
            "data_wait_frac": round(wait_s / busy, 4) if busy else 0.0,
            "dirty": dirty,
            **extra,
        }
        if depth:
            # ready batches behind each consumer take: mean tells how full
            # the input prefetch queue ran, min 0 marks an underrun window
            fields["prefetch_queue_depth"] = {
                "mean": round(sum(depth) / len(depth), 2),
                "min": int(min(depth)),
            }
        svc_ready = samples.get("data_ready_depth", [])
        svc_under = samples.get("data_underruns", [])
        svc_busy = samples.get("data_worker_busy", [])
        if svc_ready or svc_under or svc_busy:
            # data-service backpressure for this window (data/service.py):
            # reorder-buffer depth at each take, consumer-starved events, and
            # worker utilization against the window's host wall time
            svc_fields: Dict = {"underruns": window_count(svc_under)}
            if svc_ready:
                svc_fields["ready_depth"] = {
                    "mean": round(sum(svc_ready) / len(svc_ready), 2),
                    "min": int(min(svc_ready)),
                }
            n_workers = self.registry.gauge(DATA_WORKERS_GAUGE).value
            if svc_busy and n_workers and busy > 0:
                svc_fields["worker_util"] = round(
                    min(1.0, window_total_s(svc_busy) / (n_workers * busy)), 3
                )
            fields["data_service"] = svc_fields
        if compute:
            s = time_summary(compute)
            fields["step_time_ms"] = {
                k[:-2] + "_ms": round(v * 1000, 3)
                for k, v in s.items()
                if k.endswith("_s") and k != "total_s"
            }
            # first-class MFU: analytic step FLOPs (set_step_flops) against
            # this window's mean measured step time; absent without a known
            # device peak (CPU) — never 0/0
            mfu = self._window_mfu(s.get("mean_s") or 0.0)
            if mfu is not None:
                fields["mfu"] = mfu
        if images_per_sec is not None:
            fields["images_per_sec"] = round(float(images_per_sec), 2)
        if scalars:
            fields["scalars"] = {k: float(v) for k, v in scalars.items()}
        if self.detector is not None:
            fields["recompiles_post_warmup"] = self.detector.post_warmup_count
        self._event("step_window", **fields)
        if self.capacity_sampling:
            # chip-seconds attribution for the window (obs/capacity.py):
            # compute_s is device-busy wall time on every chip (SPMD), so the
            # cost event rides the same cadence as the window itself
            cost_fields = self.cost.train_window(
                compute_s, steps, examples=examples, step=step
            )
            if cost_fields:
                self._event(capacity_lib.COST_EVENT, **cost_fields)
        self._windows += 1
        if self._windows % self._memory_every_windows == 0:
            self.memory_event(step=step)
        alerts: List[Dict] = []
        try:
            if self.health is not None:
                # AFTER the window is persisted: alerts (and a NaN-guard
                # abort) land in a ledger that already tells the window's
                # story
                alerts = (
                    self.health.observe_window(self, step, scalars or {}, fields)
                    or []
                )
        finally:
            # profiler hooks run even when a health abort is propagating —
            # the alert that ends the run is the one most worth a capture at
            # the NEXT opportunity; failures degrade to a warning
            if self.profiler is not None:
                try:
                    self.profiler.on_window(
                        step=step, windows=self._windows, alerts=alerts
                    )
                except Exception:  # noqa: BLE001 — never kill training
                    logger.warning("profiler window hook failed", exc_info=True)

    def eval_event(
        self, step: int, metrics: Dict[str, float], duration_s: float, **extra
    ) -> None:
        self._event(
            "eval",
            step=step,
            duration_s=round(duration_s, 6),
            metrics={k: float(v) for k, v in metrics.items()},
            **extra,
        )
        # eval just ran: if the pass pushed the allocator's peak past the
        # train watermark, the eval phase owns the new high-water mark
        self.sample_watermark(capacity_lib.PHASE_EVAL, step=step)

    def checkpoint_event(self, step: int, **extra) -> None:
        self._event("checkpoint", step=step, **extra)
        self.sample_watermark(capacity_lib.PHASE_CKPT, step=step)

    def memory_event(self, step: Optional[int] = None, **extra) -> None:
        """Per-device HBM snapshot (``profiling.memory_stats``) plus host RSS —
        on backends without the device query (CPU builds) the host side still
        makes the snapshot meaningful. ``extra`` fields ride along verbatim:
        the trainers attach exact per-device state accounting
        (``opt_state_bytes_per_device``/``params_bytes_per_device``, from
        ``train.state.tree_bytes_per_device``) so the weight-update-sharding
        saving is visible in the ledger even where the allocator query is
        unavailable."""
        if not self.enabled:
            return
        from tensorflowdistributedlearning_tpu.utils.profiling import (
            memory_stats,
        )

        try:
            devices = memory_stats()
        except Exception:  # noqa: BLE001 — a failed probe must not crash
            devices = {}
        fields: Dict = {"devices": devices, **extra}
        rss = _host_rss_bytes()
        if rss is not None:
            fields["host_rss_bytes"] = rss
        if step is not None:
            fields["step"] = step
        self._event("memory", **fields)
        # capacity layer (obs/capacity.py): the trainers' exact
        # tree_bytes_per_device accounting becomes the watermark tracker's
        # prediction, and every memory snapshot doubles as a watermark sample
        # attributed to the phase that was running
        predicted = (extra.get("params_bytes_per_device") or 0) + (
            extra.get("opt_state_bytes_per_device") or 0
        )
        if predicted:
            self.watermarks.set_predicted(predicted)
        # reuse the snapshot already in hand: one allocator query per window
        self.sample_watermark(self._memory_phase(), step=step, stats=devices)

    def _memory_phase(self) -> str:
        """Which lifecycle phase owns a watermark sampled NOW: the active
        eval/checkpoint span wins; otherwise "step" once the train step is
        warm, "compile" before that (the first windows' peaks are the
        compiler's workspace, not steady state)."""
        span = self.current_span
        if span == SPAN_EVAL:
            return capacity_lib.PHASE_EVAL
        if span == SPAN_CHECKPOINT:
            return capacity_lib.PHASE_CKPT
        if self.detector is not None and self.detector.is_warm(SPAN_STEP):
            return capacity_lib.PHASE_STEP
        return capacity_lib.PHASE_COMPILE

    def sample_watermark(
        self,
        phase: str,
        step: Optional[int] = None,
        stats: Optional[Dict] = None,
    ) -> Optional[Dict]:
        """Query the allocator once (or reuse the caller's ``stats``
        snapshot), attributed to ``phase``; ledger a ``memory_watermark``
        event when the peak advanced and feed the headroom health monitor.
        The monitor runs on EVERY sample — not only peak advances — so a
        trend-triggered degraded state can resolve once the peak plateaus.
        No-op (None) when telemetry or capacity sampling is off, and on
        backends without the allocator query."""
        if not (self.enabled and self.capacity_sampling):
            return None
        fields = self.watermarks.sample(phase, step=step, stats=stats)
        if fields:
            self._event(capacity_lib.WATERMARK_EVENT, **fields)
        observe = getattr(self.health, "observe_memory", None)
        if observe is not None:
            headroom = self.watermarks.headroom()
            if headroom and headroom.get("bytes_limit"):
                observe(self, step, headroom)
        return fields

    def mark_warm(self, *phases: str) -> None:
        """Steady state reached for ``phases`` (none = all): compiles
        attributed to a warm phase from now on are recompiles. The trainers
        mark the train spans warm after the first log window and ``eval``
        warm after the first eval pass."""
        if self.detector is not None:
            self.detector.mark_warm(*phases)

    # a run dispatches hundreds of trivial sub-ms executables (placement,
    # schedule evals); ledger lines are reserved for compiles that cost real
    # time — post-warmup recompiles are ALWAYS written, they are the signal
    _COMPILE_LEDGER_MIN_S = 0.01

    def _on_compile(self, event: CompileEvent) -> None:
        # cache-served compiles are fast by construction, so the min-duration
        # gate would hide exactly the events that prove the cache works —
        # any compile with a cache verdict is ledgered unconditionally
        if (
            event.post_warmup
            or event.cache_hit is not None
            or event.duration_s >= self._COMPILE_LEDGER_MIN_S
        ):
            fields = {
                "duration_s": round(event.duration_s, 6),
                "phase": event.phase,
                "post_warmup": event.post_warmup,
            }
            if event.cache_hit is not None:
                fields["cache_hit"] = event.cache_hit
                if event.cache_hit:
                    fields["saved_s"] = round(event.saved_s, 6)
            self._event("compile", **fields)
        if event.post_warmup:
            logger.warning(
                "post-warmup recompilation #%d detected (%.2fs, during %r) — "
                "on TPU this stalls every chip; check for shape drift in the "
                "input pipeline or Python-level jit cache misses",
                self.detector.post_warmup_count if self.detector else 0,
                event.duration_s,
                event.phase or "unattributed",
            )

    def close(self, **final_fields) -> None:
        """End-of-run: one ``run_end`` event (pass final metrics/step), then
        detach the compile listener and close the ledger. Idempotent — the
        trainers close with final metrics on success and ``interrupted=True``
        from their finally blocks, so an exception exit is recorded as
        interrupted rather than silently looking completed."""
        if self._closed:
            return
        self._closed = True
        if not self.enabled:
            return
        if self.profiler is not None:
            # finish any capture in flight BEFORE run_end/close so its
            # events land inside this run's ledger
            try:
                self.profiler.close()
            except Exception:  # noqa: BLE001
                logger.warning("profiler close failed", exc_info=True)
        if self.detector is not None:
            final_fields.setdefault(
                "recompiles_post_warmup", self.detector.post_warmup_count
            )
            final_fields.setdefault("compiles", self.detector.compile_count)
            final_fields.setdefault(
                "compile_total_s", round(self.detector.compile_total_s, 3)
            )
            if self.detector.cache_hit_count or self.detector.cache_miss_count:
                final_fields.setdefault(
                    "compile_cache_hits", self.detector.cache_hit_count
                )
                final_fields.setdefault(
                    "compile_cache_misses", self.detector.cache_miss_count
                )
                final_fields.setdefault(
                    "compile_saved_s", round(self.detector.cache_saved_s, 3)
                )
            self.detector.detach()
        self._event("run_end", **final_fields)
        if self.ledger is not None:
            self.ledger.close()


def _host_rss_bytes() -> Optional[int]:
    try:
        import os

        page = os.sysconf("SC_PAGE_SIZE")  # 64KiB-page kernels exist
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * page
    except (OSError, ValueError, IndexError):
        return None


# The disabled instance trainer code holds when telemetry is off — every
# method is a cheap no-op, so call sites never branch on None.
NULL_TELEMETRY = Telemetry(None, enabled=False)
