"""Metrics registry: counters, gauges, and wall-time histograms.

The registry is host-side and dependency-free (numpy only) — it instruments
the Python training loop, not the jitted step (device-side time lives in the
XPlane trace, ``utils/xplane.py``). ``TimeHistogram`` is the single
step-timing/percentile implementation in the repo: ``utils.profiling.StepTimer``
and the telemetry spans both record into it, so p50/p90/p99 mean the same
thing everywhere they are reported.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

import numpy as np


def time_summary(
    times: Sequence[float], skip_first: int = 0
) -> Dict[str, float]:
    """Summary statistics over a sequence of durations (seconds).

    ``skip_first`` drops leading samples (the compile step) — when that would
    drop everything, the full sequence is summarized instead so a 1-sample
    timer still reports. Raises on an empty sequence: a vacuous summary would
    read as a measured zero."""
    if not times:
        raise ValueError("time_summary: no samples recorded")
    ts = np.asarray(list(times[skip_first:]) or list(times), np.float64)
    return {
        "count": float(len(ts)),
        "mean_s": float(ts.mean()),
        "p50_s": float(np.percentile(ts, 50)),
        "p90_s": float(np.percentile(ts, 90)),
        "p99_s": float(np.percentile(ts, 99)),
        "max_s": float(ts.max()),
        "total_s": float(ts.sum()),
    }


class Counter:
    """Monotonic event counter."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-value-wins instantaneous measurement."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value


class TimeHistogram:
    """Accumulates durations (seconds); reports count/mean/p50/p90/p99/total.

    Samples are kept raw so consumers can slice deltas
    (``samples_since(mark)``) or hand ownership over entirely (``drain()`` —
    what the telemetry window loop uses, so per-step span histograms stay
    bounded by one window's samples instead of growing for the whole run)."""

    def __init__(self, name: str):
        self.name = name
        self._samples: List[float] = []

    def record(self, seconds: float) -> None:
        self._samples.append(float(seconds))

    def __len__(self) -> int:
        return len(self._samples)

    @property
    def total_s(self) -> float:
        return float(sum(self._samples))

    @property
    def samples(self) -> List[float]:
        return list(self._samples)

    def samples_since(self, mark: int) -> List[float]:
        return self._samples[mark:]

    def drain(self) -> List[float]:
        """Take (and clear) every recorded sample — the bounded-memory way to
        consume a histogram windowed."""
        out, self._samples = self._samples, []
        return out

    def summary(self, skip_first: int = 0) -> Dict[str, float]:
        return time_summary(self._samples, skip_first=skip_first)


class MetricsRegistry:
    """Named instrument registry (get-or-create). Thread-safe creation — the
    device-prefetch producer thread and the train loop may both touch it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, TimeHistogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(name))

    def histogram(self, name: str) -> TimeHistogram:
        with self._lock:
            return self._histograms.setdefault(name, TimeHistogram(name))

    def snapshot(self) -> Dict[str, Dict]:
        """One JSON-serializable view of every instrument (histograms as
        summaries, empty ones omitted)."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {
                    n: g.value
                    for n, g in self._gauges.items()
                    if g.value is not None
                },
                "histograms": {
                    n: h.summary()
                    for n, h in self._histograms.items()
                    if len(h)
                },
            }
