"""Metrics registry: counters, gauges, and wall-time histograms.

The registry is host-side and dependency-free (numpy only) — it instruments
the Python training loop, not the jitted step (device-side time lives in the
XPlane trace, ``utils/xplane.py``). ``TimeHistogram`` is the single
step-timing/percentile implementation in the repo: ``utils.profiling.StepTimer``
and the telemetry spans both record into it, so p50/p90/p99 mean the same
thing everywhere they are reported.

``MetricsRegistry.render_prometheus`` exposes the same instruments in the
Prometheus text exposition format (``text/plain; version=0.0.4``) so every
serving replica's ``/metrics`` is scrapeable by standard collectors: counters
as ``*_total``, gauges verbatim, time histograms as summaries
(``{quantile=...}`` over the retained samples, lifetime-exact ``_sum`` /
``_count``).
"""

from __future__ import annotations

import collections
import re
import threading
from typing import Deque, Dict, List, Optional, Sequence

import numpy as np


def time_summary(
    times: Sequence[float], skip_first: int = 0
) -> Dict[str, float]:
    """Summary statistics over a sequence of durations (seconds).

    ``skip_first`` drops leading samples (the compile step) — when that would
    drop everything, the full sequence is summarized instead so a 1-sample
    timer still reports. Raises on an empty sequence: a vacuous summary would
    read as a measured zero."""
    if not times:
        raise ValueError("time_summary: no samples recorded")
    ts = np.asarray(list(times[skip_first:]) or list(times), np.float64)
    return {
        "count": float(len(ts)),
        "mean_s": float(ts.mean()),
        "p50_s": float(np.percentile(ts, 50)),
        "p90_s": float(np.percentile(ts, 90)),
        "p99_s": float(np.percentile(ts, 99)),
        "max_s": float(ts.max()),
        "total_s": float(ts.sum()),
    }


class Counter:
    """Monotonic event counter."""

    def __init__(self, name: str):
        self.name = name
        self._value = 0

    def inc(self, n: int = 1) -> None:
        self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-value-wins instantaneous measurement."""

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value


class SampleWindow(list):
    """The list ``TimeHistogram.drain()`` returns, annotated with the EXACT
    ``count``/``total_s`` of the drained interval. When the interval recorded
    more samples than the histogram's ring retains, the list holds the most
    recent ``max_samples`` (percentiles degrade gracefully) while ``count``
    and ``total_s`` stay exact — consumers that sum a window (the telemetry
    goodput split) must read these instead of ``sum(window)``."""

    def __init__(self, samples: Sequence[float], count: int, total_s: float):
        super().__init__(samples)
        self.count = int(count)
        self.total_s = float(total_s)


def window_total_s(samples) -> float:
    """Exact wall-seconds of a drained window: ``total_s`` when the window
    carries it (:class:`SampleWindow`), else the plain sum."""
    if samples is None:
        return 0.0
    exact = getattr(samples, "total_s", None)
    return float(exact) if exact is not None else float(sum(samples))


def window_count(samples) -> int:
    """Exact sample count of a drained window (see :func:`window_total_s`)."""
    if samples is None:
        return 0
    exact = getattr(samples, "count", None)
    return int(exact) if exact is not None else len(samples)


class TimeHistogram:
    """Accumulates durations (seconds); reports count/mean/p50/p90/p99/total.

    Memory is BOUNDED: raw samples live in a ring of the most recent
    ``max_samples`` (default 8192 ≈ 64 KiB of floats), so a long-lived
    producer that nothing drains — a multi-week serving replica, a span no
    window consumes — cannot grow host memory without bound. Exactness is
    kept where it matters: ``len()``, ``total_s``, and ``drain()``'s
    ``count``/``total_s`` (:class:`SampleWindow`) count EVERY recorded
    sample; only the percentile inputs are capped (and recency-biased once
    the ring wraps). ``lifetime_count``/``lifetime_total_s`` survive drains —
    the monotonic series Prometheus scrapes.

    Consumers can slice deltas (``samples_since(mark)``) or hand ownership
    over entirely (``drain()`` — what the telemetry window loop uses)."""

    DEFAULT_MAX_SAMPLES = 8192

    def __init__(self, name: str, max_samples: int = DEFAULT_MAX_SAMPLES):
        if max_samples < 1:
            raise ValueError(f"max_samples must be >= 1, got {max_samples}")
        self.name = name
        self.max_samples = int(max_samples)
        # recorded from handler threads while the window ticker drains:
        # the multi-field record/drain sequences must be atomic or samples
        # recorded mid-drain vanish from both windows and the exact
        # counters drift
        self._lock = threading.Lock()
        self._samples: Deque[float] = collections.deque(maxlen=self.max_samples)
        self._count = 0  # since the last drain, exact
        self._total_s = 0.0  # since the last drain, exact
        self.lifetime_count = 0
        self.lifetime_total_s = 0.0

    def record(self, seconds: float) -> None:
        s = float(seconds)
        with self._lock:
            self._samples.append(s)
            self._count += 1
            self._total_s += s
            self.lifetime_count += 1
            self.lifetime_total_s += s

    def __len__(self) -> int:
        return self._count

    @property
    def total_s(self) -> float:
        return self._total_s

    @property
    def samples(self) -> List[float]:
        """The RETAINED samples (at most ``max_samples``, most recent)."""
        with self._lock:
            return list(self._samples)

    def samples_since(self, mark: int) -> List[float]:
        """Samples recorded after position ``mark`` (a previous ``len()``).
        Marks that the ring has already evicted past resolve to everything
        retained."""
        with self._lock:
            evicted = self._count - len(self._samples)
            return list(self._samples)[max(0, mark - evicted):]

    def drain(self) -> SampleWindow:
        """Take (and clear) the interval since the last drain: the retained
        samples plus the interval's exact count/total (the bounded-memory way
        to consume a histogram windowed)."""
        with self._lock:
            out = SampleWindow(self._samples, self._count, self._total_s)
            self._samples.clear()
            self._count = 0
            self._total_s = 0.0
        return out

    def summary(self, skip_first: int = 0) -> Dict[str, float]:
        with self._lock:
            retained = list(self._samples)
            count, total_s = self._count, self._total_s
        s = time_summary(retained, skip_first=skip_first)
        if skip_first == 0 and count > len(retained):
            # ring wrapped: percentiles come from the retained tail, but the
            # count/total the summary reports stay exact
            s["count"] = float(count)
            s["total_s"] = total_s
            s["mean_s"] = total_s / count
        return s


_PROM_INVALID = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str, prefix: str) -> str:
    base = _PROM_INVALID.sub("_", name)
    if base and base[0].isdigit():
        base = "_" + base
    return f"{prefix}_{base}" if prefix else base


def _prom_num(v: float) -> str:
    f = float(v)
    if f != f:
        return "NaN"
    if f in (float("inf"), float("-inf")):
        return "+Inf" if f > 0 else "-Inf"
    return format(f, ".10g")


class MetricsRegistry:
    """Named instrument registry (get-or-create). Thread-safe creation — the
    device-prefetch producer thread and the train loop may both touch it."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, TimeHistogram] = {}

    def counter(self, name: str) -> Counter:
        with self._lock:
            return self._counters.setdefault(name, Counter(name))

    def gauge(self, name: str) -> Gauge:
        with self._lock:
            return self._gauges.setdefault(name, Gauge(name))

    def histogram(self, name: str) -> TimeHistogram:
        with self._lock:
            return self._histograms.setdefault(name, TimeHistogram(name))

    def snapshot(self) -> Dict[str, Dict]:
        """One JSON-serializable view of every instrument (histograms as
        summaries, empty ones omitted)."""
        with self._lock:
            return {
                "counters": {n: c.value for n, c in self._counters.items()},
                "gauges": {
                    n: g.value
                    for n, g in self._gauges.items()
                    if g.value is not None
                },
                "histograms": {
                    n: h.summary()
                    for n, h in self._histograms.items()
                    if len(h)
                },
            }

    def render_prometheus(self, prefix: str = "tfdl") -> str:
        """Prometheus text exposition (format version 0.0.4) of the registry.

        Instrument names sanitize ``/`` (and anything else outside
        ``[a-zA-Z0-9_:]``) to ``_`` under ``prefix``; counters gain the
        conventional ``_total`` suffix, time histograms render as summaries in
        SECONDS — quantiles over the retained ring (omitted while empty),
        ``_sum``/``_count`` from the lifetime-exact monotonic totals (drains
        do not reset them, so scrape deltas are meaningful)."""
        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(
                (n, g.value) for n, g in self._gauges.items()
                if g.value is not None
            )
            hists = sorted(self._histograms.items())
        lines: List[str] = []
        for name, c in counters:
            pname = _prom_name(name, prefix) + "_total"
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {_prom_num(c.value)}")
        for name, value in gauges:
            pname = _prom_name(name, prefix)
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {_prom_num(value)}")
        for name, h in hists:
            if not h.lifetime_count:
                continue
            pname = _prom_name(name, prefix) + "_seconds"
            lines.append(f"# TYPE {pname} summary")
            retained = h.samples
            if retained:
                arr = np.asarray(retained, np.float64)
                for q in (0.5, 0.9, 0.99):
                    lines.append(
                        f'{pname}{{quantile="{q}"}} '
                        f"{_prom_num(np.percentile(arr, q * 100))}"
                    )
            lines.append(f"{pname}_sum {_prom_num(h.lifetime_total_s)}")
            lines.append(f"{pname}_count {_prom_num(h.lifetime_count)}")
        return "\n".join(lines) + "\n"
