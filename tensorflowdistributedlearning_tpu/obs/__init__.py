"""Unified telemetry: metrics registry, JSONL run ledger, trainer spans,
recompile detection, and the goodput report.

The reference harness had no profiler story at all (SURVEY §5.1) and this
repo's observability used to be three disconnected islands (``utils/profiling``
step timing, ``utils/xplane`` op breakdowns, ``utils/summary`` TB scalars) with
no durable machine-readable record of what a run did. This package is the
layer that ties them together, the way production TPU training is actually
operated (pjit/TPUv4-scale jobs run off step-time/throughput telemetry and
recompile tracking — Yoo et al., arXiv:2204.06514; TensorFlow shipped
metrics+tracing as a core subsystem, Abadi et al., arXiv:1605.08695):

- ``obs.metrics``   — counters, gauges, time-histograms (p50/p90/p99); the ONE
  step-timing implementation (``utils.profiling.StepTimer`` delegates here);
- ``obs.ledger``    — append-only ``telemetry.jsonl`` run ledger in the workdir
  (degrades to a warning when the workdir is unwritable — never crashes
  training);
- ``obs.recompile`` — ``jax.monitoring``-based compile listener that counts and
  timestamps post-warmup recompilations, the silent goodput killer on TPU;
- ``obs.telemetry`` — the ``Telemetry`` façade + span API the trainers wire in
  (data-wait vs step-compute split per log window, eval/checkpoint/memory
  events);
- ``obs.report``    — merges the ledger with ``utils.xplane.op_breakdown`` into
  one goodput report (CLI: ``telemetry-report <workdir>``);
- ``obs.trace``     — request/step-granular trace/span layer (trace_id/span_id/
  parent, host clock only) persisted as sampled ``trace`` ledger events and
  exportable as Chrome/Perfetto trace-event JSON
  (``telemetry-report --export-trace``);
- ``obs.health``    — online health monitors (NaN/Inf loss guard, loss-spike
  MAD detector, step-time regression, serving SLO error budget) emitting
  structured ``health_alert`` ledger events;
- ``obs.profiler``  — continuous profiling: bounded windowed ``jax.profiler``
  captures on a cadence, on demand, and at alert chokepoints; per-op roofline
  classification and achieved-vs-peak MFU ledgered as ``profile_capture`` /
  ``op_roofline`` events that feed the planner's measured cost model.
"""

from tensorflowdistributedlearning_tpu.obs.capacity import (
    COST_EVENT,
    WATERMARK_EVENT,
    CostMeter,
    WatermarkTracker,
)
from tensorflowdistributedlearning_tpu.obs.compare import (
    compare_workdirs,
    load_registry,
    register_run,
    run_summary,
)
from tensorflowdistributedlearning_tpu.obs.fleet import (
    STRAGGLER_ALERT_EVENT,
    discover_ledgers,
    fleet_section,
    fleet_summary,
)
from tensorflowdistributedlearning_tpu.obs.health import (
    HEALTH_ALERT_EVENT,
    HeadroomMonitor,
    HealthAbortError,
    HealthMonitor,
    SloTracker,
)
from tensorflowdistributedlearning_tpu.obs.ledger import (
    LEDGER_FILENAME,
    RunLedger,
    flush_all_ledgers,
    per_process_filename,
    read_ledger,
    read_ledger_with_errors,
)
from tensorflowdistributedlearning_tpu.obs.metrics import (
    Counter,
    Gauge,
    MetricsRegistry,
    TimeHistogram,
    time_summary,
)
from tensorflowdistributedlearning_tpu.obs.profiler import (
    OP_ROOFLINE_EVENT,
    PROFILE_CAPTURE_EVENT,
    ContinuousProfiler,
    build_roofline,
    resolve_peak_flops,
)
from tensorflowdistributedlearning_tpu.obs.recompile import RecompileDetector
from tensorflowdistributedlearning_tpu.obs.telemetry import (
    NULL_TELEMETRY,
    PREFETCH_DEPTH_HISTOGRAM,
    SPAN_BARRIER,
    SPAN_CHECKPOINT,
    SPAN_DATA_WAIT,
    SPAN_EVAL,
    SPAN_FETCH_WAIT,
    SPAN_STEP,
    Telemetry,
)
from tensorflowdistributedlearning_tpu.obs.trace import (
    NULL_TRACER,
    TRACE_EVENT,
    TraceContext,
    Tracer,
    export_chrome_trace,
    write_chrome_trace,
)

__all__ = [
    "COST_EVENT",
    "HEALTH_ALERT_EVENT",
    "PREFETCH_DEPTH_HISTOGRAM",
    "SPAN_BARRIER",
    "SPAN_CHECKPOINT",
    "SPAN_DATA_WAIT",
    "SPAN_EVAL",
    "SPAN_FETCH_WAIT",
    "SPAN_STEP",
    "STRAGGLER_ALERT_EVENT",
    "TRACE_EVENT",
    "WATERMARK_EVENT",
    "CostMeter",
    "Counter",
    "Gauge",
    "HeadroomMonitor",
    "HealthAbortError",
    "HealthMonitor",
    "LEDGER_FILENAME",
    "MetricsRegistry",
    "NULL_TELEMETRY",
    "NULL_TRACER",
    "OP_ROOFLINE_EVENT",
    "PROFILE_CAPTURE_EVENT",
    "ContinuousProfiler",
    "RecompileDetector",
    "RunLedger",
    "SloTracker",
    "Telemetry",
    "TimeHistogram",
    "TraceContext",
    "Tracer",
    "WatermarkTracker",
    "build_roofline",
    "compare_workdirs",
    "discover_ledgers",
    "export_chrome_trace",
    "fleet_section",
    "fleet_summary",
    "flush_all_ledgers",
    "load_registry",
    "per_process_filename",
    "read_ledger",
    "read_ledger_with_errors",
    "register_run",
    "resolve_peak_flops",
    "run_summary",
    "time_summary",
    "write_chrome_trace",
]
