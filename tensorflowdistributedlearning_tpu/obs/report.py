"""Goodput report: one view over the run ledger + (optionally) an xplane trace.

``build_report(workdir)`` reads ``telemetry.jsonl`` (last run in the file) and
answers the questions a TPU run is operated by: where did the wall time go
(data-wait vs step-compute vs eval vs compile), what was the throughput trend,
what were the step-time percentiles, did anything recompile after warmup, and
— when a ``jax.profiler`` trace exists under the workdir — which device ops
dominate (``utils.xplane.op_breakdown``, TensorBoard-free).

Attribution note: ``data_wait``/``compute``/``eval`` are disjoint host spans;
``compile`` time OVERLAPS whichever span it happened inside (a compile stalls
the step that triggered it), so it is reported as its own row, not added into
the split sum.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib


def _weighted(values: List[float], weights: List[float]) -> Optional[float]:
    total = sum(weights)
    if not total:
        return None
    return sum(v * w for v, w in zip(values, weights)) / total


def _trace_section(trace_dir: str, top: int) -> Optional[Dict]:
    """Top-k device ops + coarse buckets from an xplane capture; None when no
    trace exists (the common case — traces are opt-in captures)."""
    from tensorflowdistributedlearning_tpu.utils import xplane

    if not xplane.find_xplane_files(trace_dir):
        return None
    # device planes first (TPU, then any /device:); CPU-backend captures have
    # ONLY host-thread planes — still aggregated, with a note, so the report
    # names the hot host frames rather than showing nothing
    note = None
    skipped = 0
    for plane_filter in ("TPU", "/device:", ""):
        # _with_errors: a torn/partially-written plane file (profiler killed
        # mid-capture) is skipped and counted, not a mid-report crash
        rows, skipped = xplane.op_breakdown_with_errors(
            trace_dir, plane_filter=plane_filter
        )
        if rows:
            if plane_filter == "":
                note = (
                    "no device plane in this capture — host-thread timelines "
                    "aggregated instead"
                )
            break
    section = {
        "dir": trace_dir,
        "buckets_ms": xplane.grouped_breakdown(rows),
        "top_ops": [dataclasses.asdict(r) for r in rows[:top]],
    }
    if skipped:
        section["skipped_plane_files"] = skipped
    if note:
        section["note"] = note
    return section


def _serve_section(windows: List[Dict]) -> Dict:
    """Aggregate ``serve_window`` events (serve/server.py) for the report.

    Counters in a window are cumulative since server start, so totals come
    from the last window; latency summaries are per-window (the server drains
    its histograms at each boundary), merged the same approximate way as
    ``step_time_ms``: count-weighted mean/p50/p90, worst-window p99."""
    last = windows[-1]
    totals = {
        k: last.get(k, 0)
        for k in (
            "requests",
            "completed",
            "rejected_queue_full",
            "deadline_exceeded",
            "errors",
            "batches",
            "batched_examples",
        )
    }
    section: Dict = {
        "windows": len(windows),
        **totals,
        "bucket_hits": last.get("bucket_hits", {}),
        "recompiles_post_warmup": last.get("recompiles_post_warmup"),
    }
    if last.get("serving_dtype"):
        section["serving_dtype"] = last["serving_dtype"]
    if last.get("padding_waste"):
        # cumulative like the hits: fraction of compiled batch slots filled
        # with padding, per bucket that saw traffic
        section["padding_waste"] = last["padding_waste"]
    if totals["batches"]:
        section["mean_batch_fill"] = round(
            totals["batched_examples"] / totals["batches"], 2
        )
    if windows[-1].get("slo"):
        section["slo"] = windows[-1]["slo"]
    # capture-tee loss (cumulative, like the other counters): samples the
    # loop WANTED but the bounded queue dropped — visible capture loss is
    # the fix for the shadow tee's original silent-drop gap
    if last.get("tee_dropped"):
        section["tee_dropped"] = last["tee_dropped"]
    if last.get("drift"):
        section["drift"] = last["drift"]
    # multi-tenant replica: per-model counters/latency/SLO ride in the last
    # window's "models" dict (serve/server.py emit_window); a single-tenant
    # model-aware replica stamps "model"/"model_version" at top level
    if last.get("models"):
        section["models"] = last["models"]
    elif last.get("model"):
        section["model"] = last["model"]
        if last.get("model_version") is not None:
            section["model_version"] = last["model_version"]
    latency: Dict = {}
    for name in ("queue_wait", "pad", "compute", "request"):
        per_window = [
            e["latency_ms"][name]
            for e in windows
            if name in e.get("latency_ms", {})
        ]
        if not per_window:
            continue
        weights = [s.get("count", 1.0) for s in per_window]
        latency[name] = {
            "mean": round(
                _weighted([s["mean_ms"] for s in per_window], weights) or 0, 3
            ),
            "p50": round(
                _weighted([s["p50_ms"] for s in per_window], weights) or 0, 3
            ),
            "p90": round(
                _weighted([s["p90_ms"] for s in per_window], weights) or 0, 3
            ),
            "p99_worst_window": round(
                max(s["p99_ms"] for s in per_window), 3
            ),
        }
    if latency:
        section["latency_ms"] = latency
    return section


def silent_mixed_fleet(fleet_state: Optional[Dict]) -> bool:
    """The warning condition the report and ``telemetry-top`` must agree
    on: replicas answering from more than one artifact identity with no
    promotion controller in charge (``fleet_state`` is a router_window
    event's ``fleet`` payload)."""
    fleet_state = fleet_state or {}
    artifacts = fleet_state.get("artifacts") or {}
    if len(artifacts) <= 1 or fleet_state.get("promotion_active"):
        return False
    models = fleet_state.get("models") or {}
    if models:
        # multi-tenant fleet: distinct artifacts per model are the design,
        # not drift — the mix is only "silent" when a single model answers
        # from more than one registry version with no promotion in charge
        return any(
            len(row.get("versions") or {}) > 1 for row in models.values()
        )
    return True


def _serve_fleet_section(events: List[Dict]) -> Optional[Dict]:
    """Aggregate the serving-fleet controller's events (serve/fleet.py +
    serve/router.py + serve/autoscale.py): router traffic counters,
    ``fleet_scale`` autoscale decisions, and replica lifecycle churn. None
    when the run was not a fleet controller."""
    router_windows = [e for e in events if e.get("event") == "router_window"]
    scales = [e for e in events if e.get("event") == "fleet_scale"]
    lifecycle = {
        kind: sum(1 for e in events if e.get("event") == f"replica_{kind}")
        for kind in ("spawn", "ready", "exit", "restart", "drain", "abandoned")
    }
    if not (router_windows or scales or any(lifecycle.values())):
        return None
    section: Dict = {}
    if router_windows:
        last = router_windows[-1]
        section["router"] = {
            "windows": len(router_windows),
            **{
                k: last.get(k, 0)
                for k in (
                    "requests",
                    "routed",
                    "retries",
                    "shed",
                    "no_replica",
                    "replica_failures",
                    "tee_dropped",
                )
            },
            "per_replica_routed": last.get("per_replica_routed", {}),
            "fleet": last.get("fleet", {}),
        }
        # artifact mix (serve/router.py polls each replica's /healthz
        # identity): >1 distinct artifact OUTSIDE an active promotion is a
        # silent mixed fleet — rendered as a warning, not trivia
        fleet_state = last.get("fleet") or {}
        if fleet_state.get("models"):
            # multi-tenant routing: per-model replica sets, backlog, worst
            # p99, version mix, and the router's own per-model counters
            section["router"]["models"] = fleet_state["models"]
        if last.get("fair_share"):
            section["router"]["fair_share"] = last["fair_share"]
        artifacts = fleet_state.get("artifacts") or {}
        if artifacts:
            section["router"]["artifacts"] = artifacts
            section["router"]["mixed_artifacts"] = len(artifacts) > 1
            section["router"]["silent_mixed_fleet"] = silent_mixed_fleet(
                fleet_state
            )
    if scales:
        section["autoscale"] = {
            "decisions": len(scales),
            "scale_up": sum(1 for e in scales if e.get("action") == "scale_up"),
            "scale_down": sum(
                1 for e in scales if e.get("action") == "scale_down"
            ),
            "budget_deferred": sum(
                1 for e in scales if e.get("action") == "budget_deferred"
            ),
            "final_replicas": scales[-1].get("to_replicas"),
            "events": [
                {
                    k: e.get(k)
                    for k in (
                        "action",
                        "model",
                        "from_replicas",
                        "to_replicas",
                        "reason",
                        "mean_queue_depth",
                    )
                    if k != "model" or e.get("model") is not None
                }
                for e in scales[-10:]
            ],
        }
    if any(lifecycle.values()):
        section["replicas"] = dict(lifecycle)
        # spawn -> readiness-line wall time per replica_ready event: the
        # cold-start metric (interpreter boot + artifact load + ladder
        # warmup) the shipped compile cache exists to shrink
        ttrs = [
            float(e["time_to_ready_s"])
            for e in events
            if e.get("event") == "replica_ready"
            and e.get("time_to_ready_s") is not None
        ]
        if ttrs:
            section["replicas"]["time_to_ready_s"] = {
                "count": len(ttrs),
                "mean": round(sum(ttrs) / len(ttrs), 3),
                "max": round(max(ttrs), 3),
                "last": round(ttrs[-1], 3),
            }
    return section


_PROMOTION_KINDS = (
    "promotion_start",
    "phase_advance",
    "shadow_window",
    "promotion_rollback",
    "promotion_complete",
)


def _promotion_section(events: List[Dict]) -> Optional[Dict]:
    """The deployment history (serve/promote.py): every promotion the run's
    controller drove, phase by phase — starts, canary/rollout advances,
    shadow-compare windows, rollbacks (with reasons), completions. None when
    the run never promoted."""
    rows = [e for e in events if e.get("event") in _PROMOTION_KINDS]
    if not rows:
        return None
    shadows = [e for e in rows if e.get("event") == "shadow_window"]
    rollbacks = [e for e in rows if e.get("event") == "promotion_rollback"]
    section: Dict = {
        "events": len(rows),
        "starts": sum(
            1
            for e in rows
            if e.get("event") == "promotion_start" and not e.get("refused")
        ),
        "completed": sum(
            1 for e in rows if e.get("event") == "promotion_complete"
        ),
        "rolled_back": sum(
            1 for e in rollbacks if e.get("status") == "rolled_back"
        ),
        "refused": sum(
            1 for e in rollbacks if e.get("status") == "refused"
        ),
        "aborted": sum(
            1 for e in rollbacks if e.get("status") == "aborted"
        ),
        "shadow_windows": len(shadows),
        "shadow_compared": sum(e.get("compared", 0) for e in shadows),
    }
    history = []
    for e in rows:
        entry = {
            "t": e.get("t"),
            "kind": e.get("event"),
        }
        for k in (
            "phase", "candidate_dir", "dtype", "fingerprint", "replica",
            "replaced", "remaining", "reason", "status", "refused",
            "compared", "min_iou", "mean_disagree", "max_abs_delta",
            "restored", "drained", "abort_reason", "duration_s", "windows",
        ):
            if e.get(k) is not None:
                entry[k] = e[k]
        history.append(entry)
    section["history"] = history
    if rollbacks:
        section["last_rollback"] = {
            k: rollbacks[-1].get(k)
            for k in ("phase", "reason", "status", "restored", "abort_reason")
            if rollbacks[-1].get(k) is not None
        }
    return section


_LOOP_KINDS = (
    "loop_trigger",
    "loop_retrain",
    "loop_promoted",
    "loop_rejected",
)


def _loop_section(ledgers) -> Optional[Dict]:
    """The continuous-learning loop's audit trail (loop/), merged across
    EVERY process ledger in the workdir: capture_window/drift_alert events
    live in the replica ledgers (process >= 1), records_ingest and the
    loop_* cycle events in the flywheel's high-numbered ledger. None when
    nothing loop-related ever ran here."""
    merged: List[Dict] = []
    for led in ledgers:
        merged.extend(
            e
            for e in led.events
            if e.get("event")
            in _LOOP_KINDS + ("capture_window", "records_ingest", "drift_alert")
        )
    if not merged:
        return None
    merged.sort(key=lambda e: e.get("t", 0.0))
    section: Dict = {}

    captures = [e for e in merged if e.get("event") == "capture_window"]
    if captures:
        # totals are cumulative per replica — take each replica's last window
        last_per_replica: Dict = {}
        for e in captures:
            last_per_replica[e.get("replica", 0)] = e
        section["capture"] = {
            "windows": len(captures),
            "replicas": len(last_per_replica),
            "captured": sum(
                e.get("total_captured", 0)
                for e in last_per_replica.values()
            ),
            "dropped": sum(
                e.get("total_dropped", 0) for e in last_per_replica.values()
            ),
            "shards": sum(
                e.get("shards", 0) for e in last_per_replica.values()
            ),
            "evicted": sum(
                e.get("shards_evicted", 0) for e in captures
            ),
            "bytes_on_disk": sum(
                e.get("bytes_on_disk", 0)
                for e in last_per_replica.values()
            ),
        }

    ingests = [e for e in merged if e.get("event") == "records_ingest"]
    if ingests:
        last = ingests[-1]
        section["ingest"] = {
            "runs": len(ingests),
            "records_added": sum(e.get("records_added", 0) for e in ingests),
            "new_shards": sum(e.get("new_shards", 0) for e in ingests),
            "deduped": sum(e.get("deduped", 0) for e in ingests),
            "corrupt": sum(e.get("corrupt", 0) for e in ingests),
            "dataset_version": last.get("version"),
            "records_total": last.get("records_total"),
            "dataset_dir": last.get("dataset_dir"),
        }

    drift_alerts = [e for e in merged if e.get("event") == "drift_alert"]
    fired = [e for e in drift_alerts if not e.get("resolved")]
    if drift_alerts:
        section["drift"] = {
            "alerts": len(fired),
            "resolved": len(drift_alerts) - len(fired),
            "last": {
                k: drift_alerts[-1].get(k)
                for k in (
                    "replica", "score", "threshold", "output", "resolved",
                )
                if drift_alerts[-1].get(k) is not None
            },
        }

    cycles = [e for e in merged if e.get("event") in _LOOP_KINDS]
    if cycles:
        triggers = [e for e in cycles if e.get("event") == "loop_trigger"]
        promoted = [e for e in cycles if e.get("event") == "loop_promoted"]
        rejected = [e for e in cycles if e.get("event") == "loop_rejected"]
        loop: Dict = {
            "triggers": len(triggers),
            "retrains": sum(
                1 for e in cycles if e.get("event") == "loop_retrain"
            ),
            "promoted": len(promoted),
            "rejected": len(rejected),
            "history": [
                {
                    "t": e.get("t"),
                    "kind": e.get("event"),
                    **{
                        k: e.get(k)
                        for k in (
                            "reason", "records_new", "dataset_version",
                            "drift_score", "rc", "duration_s",
                            "candidate_dir", "fingerprint", "error",
                        )
                        if e.get(k) is not None
                    },
                }
                for e in cycles
            ],
        }
        # drift-trigger latency: alert fired -> loop answered
        drift_trigs = [
            e
            for e in triggers
            if e.get("reason") == "drift" and e.get("drift_alert_t")
        ]
        if drift_trigs:
            loop["drift_trigger_latency_s"] = round(
                max(
                    0.0,
                    drift_trigs[-1]["t"] - drift_trigs[-1]["drift_alert_t"],
                ),
                3,
            )
        if promoted:
            last_ok = promoted[-1]
            loop["last_promoted"] = {
                k: last_ok.get(k)
                for k in ("candidate_dir", "fingerprint", "duration_s")
                if last_ok.get(k) is not None
            }
        section["cycles"] = loop

    return section or None


def _health_section(events: List[Dict]) -> Optional[Dict]:
    """Aggregate ``health_alert`` events (obs/health.py) for the last run:
    per-monitor counts, active-vs-resolved state, and the most recent alert's
    details. None when the run never alerted."""
    alerts = [e for e in events if e.get("event") == "health_alert"]
    if not alerts:
        return None
    monitors: Dict[str, Dict] = {}
    for e in alerts:
        name = e.get("monitor", "unknown")
        m = monitors.setdefault(
            name, {"alerts": 0, "resolved": 0, "active": False}
        )
        if e.get("resolved"):
            m["resolved"] += 1
            m["active"] = False
        else:
            m["alerts"] += 1
            m["active"] = True
        m["last"] = {
            k: v for k, v in e.items() if k not in ("event", "t")
        }
    return {
        "alerts": sum(m["alerts"] for m in monitors.values()),
        "monitors": monitors,
        "degraded": sorted(
            name for name, m in monitors.items() if m["active"]
        ),
    }


def _trace_summary(events: List[Dict]) -> Optional[Dict]:
    """Span counts by name for the run's sampled ``trace`` events — enough
    for the report to say tracing was on and what `--export-trace` will
    contain. None when the run recorded no spans."""
    spans = [e for e in events if e.get("event") == "trace"]
    if not spans:
        return None
    by_name: Dict[str, int] = {}
    traces = set()
    for e in spans:
        by_name[e.get("name", "span")] = by_name.get(e.get("name", "span"), 0) + 1
        traces.add(e.get("trace_id"))
    return {"spans": len(spans), "traces": len(traces), "by_name": by_name}


def _resilience_scope(all_events: List[Dict]) -> List[Dict]:
    """The event window the resilience section describes: the last SUPERVISED
    SESSION (from ``supervisor_start``; every relaunch in it writes its own
    ``supervised``-stamped run header, so restarts by construction straddle
    run boundaries and a plain last-run scope would lose them) — unless a
    later STANDALONE run (a run header without the ``supervised`` stamp)
    started after the session, in which case that run is the story and stale
    restarts/aborts must not haunt it. Keying the takeover on the header
    stamp rather than ``supervisor_end`` means even a hard-killed supervisor
    (no end event ever written) cannot haunt later clean runs."""
    last_start = None
    last_header = None
    for i, e in enumerate(all_events):
        kind = e.get("event")
        if kind == "supervisor_start":
            last_start = i
        elif kind == "run_header":
            last_header = i
    if last_start is None:
        return all_events[last_header:] if last_header is not None else all_events
    standalone = [
        i
        for i, e in enumerate(all_events[last_start:], last_start)
        if e.get("event") == "run_header" and not e.get("supervised")
    ]
    if standalone:
        return all_events[standalone[-1]:]
    return all_events[last_start:]


def _resilience_section(all_events: List[Dict]) -> Optional[Dict]:
    """Aggregate resilience events (resilience/) over ``_resilience_scope``.
    None when that window shows a clean, never-preempted history."""
    scope = _resilience_scope(all_events)
    restarts = [e for e in scope if e.get("event") == "restart"]
    preempted = [e for e in scope if e.get("event") == "preempted"]
    resumed = [e for e in scope if e.get("event") == "resumed"]
    # only per-step events: the fresh-init SUMMARY event shares the kind but
    # has no step, and counting it would inflate skipped-checkpoint totals
    corrupt = [
        e
        for e in scope
        if e.get("event") == "checkpoint_corrupt" and "step" in e
    ]
    retries = [e for e in scope if e.get("event") == "checkpoint_retry"]
    aborts = [e for e in scope if e.get("event") == "supervisor_abort"]
    if not (restarts or preempted or resumed or corrupt or retries or aborts):
        return None
    section: Dict = {
        "restarts": len(restarts),
        # goodput lost to restarts: child-death -> relaunch wall time
        # (backoff included), as measured by the supervisor
        "restart_downtime_s": round(
            sum(e.get("downtime_s", 0.0) for e in restarts), 3
        ),
        "preemptions": len(preempted),
        "resumes": len(resumed),
        "corrupt_checkpoints_skipped": len(corrupt),
        "checkpoint_retries": len(retries),
    }
    if restarts:
        section["last_restart"] = {
            k: restarts[-1].get(k) for k in ("attempt", "rc", "reason", "step")
        }
    if resumed:
        section["last_resume_step"] = resumed[-1].get("step")
    if aborts:
        section["aborted"] = aborts[-1].get("reason")
    return section


def _elastic_section(all_events: List[Dict]) -> Optional[Dict]:
    """Aggregate the last elastic session's events (parallel/elastic.py):
    ``elastic_start`` .. ``elastic_end`` brackets with every ``world_resize``
    / ``host_evicted`` / ``data_redeal`` in between — the world-trajectory
    and goodput-lost-to-resizes story. None when the history holds no
    elastic session."""
    starts = [
        i for i, e in enumerate(all_events)
        if e.get("event") == "elastic_start"
    ]
    if not starts:
        return None
    scope = all_events[starts[-1]:]
    start = scope[0]
    resizes = [e for e in scope if e.get("event") == "world_resize"]
    evictions = [e for e in scope if e.get("event") == "host_evicted"]
    redeals = [e for e in scope if e.get("event") == "data_redeal"]
    aborts = [e for e in scope if e.get("event") == "elastic_abort"]
    end = next(
        (e for e in reversed(scope) if e.get("event") == "elastic_end"), None
    )
    hosts = start.get("hosts")
    world = (
        end.get("world_size") if end else
        (resizes[-1].get("new_world") if resizes else hosts)
    )
    section: Dict = {
        "hosts": hosts,
        "min_hosts": start.get("min_hosts"),
        "world_size": world,
        "live": end is None,
        "resizes": len(resizes),
        "evictions": len(evictions),
        "data_redeals": len(redeals),
        # goodput lost to resizes: drain start -> new world spawned, as the
        # coordinator measured it (the same accounting lens as the
        # resilience section's restart downtime)
        "resize_downtime_s": round(
            sum(e.get("downtime_s", 0.0) for e in resizes), 3
        ),
        "resize_events": [
            {
                k: e.get(k)
                for k in (
                    "old_world", "new_world", "reason", "progress_step",
                    "downtime_s", "process_index", "evicted_process",
                    "measured_margin_bytes", "plan_old", "plan_new",
                )
                if e.get(k) is not None
            }
            for e in resizes
        ],
    }
    if end is not None:
        section["ok"] = bool(end.get("ok"))
    if aborts:
        section["aborted"] = aborts[-1].get("reason")
    elif end is not None and end.get("aborted"):
        section["aborted"] = end["aborted"]
    return section


def build_report(
    workdir: str,
    *,
    trace_dir: Optional[str] = None,
    top: int = 10,
    straggler_threshold: float = fleet_lib.DEFAULT_SKEW_THRESHOLD,
) -> Dict:
    """Assemble the goodput report dict for a workdir's last run.

    Multi-host workdirs hold one ledger per process (obs/fleet.py naming
    contract); the report is anchored on process 0's ledger and gains a
    ``fleet`` section merging all of them (per-host goodput splits, straggler
    analysis past ``straggler_threshold`` skew)."""
    ledgers = fleet_lib.discover_ledgers(workdir)
    if not ledgers:
        raise FileNotFoundError(
            f"no telemetry ledger (telemetry.jsonl / telemetry-N.jsonl) "
            f"under {workdir} — pass the run's workdir (the --model-dir a "
            "trainer wrote, or a serve --workdir)"
        )
    # the primary (lowest-index) ledger, parsed once by the discovery: the
    # resilience section reads the WHOLE appended history (it scopes across
    # run boundaries), everything else the last run
    all_events = ledgers[0].all_events
    parse_errors = ledgers[0].parse_errors
    events = ledgers[0].events
    if not events:
        raise ValueError(f"empty telemetry ledger under {workdir}")
    header = events[0] if events[0].get("event") == "run_header" else None
    windows = [e for e in events if e.get("event") == "step_window"]
    clean = [e for e in windows if not e.get("dirty")]
    evals = [e for e in events if e.get("event") == "eval"]
    checkpoints = [e for e in events if e.get("event") == "checkpoint"]
    compiles = [e for e in events if e.get("event") == "compile"]
    # a cache-SERVED compile still stalls the step that triggered it, but it
    # is a load, not a rebuild: counting it as a recompile would page the
    # operator for a shared cache doing its job. The zero-post-warmup
    # contract applies to REAL compiles only.
    cached_compiles = [e for e in compiles if e.get("cache_hit")]
    recompiles = [
        e for e in compiles if e.get("post_warmup") and not e.get("cache_hit")
    ]
    cached_post_warmup = [e for e in cached_compiles if e.get("post_warmup")]
    memories = [e for e in events if e.get("event") == "memory"]
    run_end = next(
        (e for e in reversed(events) if e.get("event") == "run_end"), None
    )

    wall_s = events[-1]["t"] - events[0]["t"] if len(events) > 1 else 0.0
    data_wait_s = sum(e.get("data_wait_s", 0.0) for e in windows)
    compute_s = sum(e.get("compute_s", 0.0) for e in windows)
    fetch_wait_s = sum(e.get("fetch_wait_s", 0.0) for e in windows)
    barrier_wait_s = sum(e.get("barrier_wait_s", 0.0) for e in windows)
    eval_s = sum(e.get("duration_s", 0.0) for e in evals)
    # run_end carries the exact total from the detector (ledger compile lines
    # are thresholded to the non-trivial ones); fall back to summing those
    compile_s = (run_end or {}).get(
        "compile_total_s", sum(e.get("duration_s", 0.0) for e in compiles)
    )
    recompile_s = sum(e.get("duration_s", 0.0) for e in recompiles)

    def frac(x: float) -> Optional[float]:
        return round(x / wall_s, 4) if wall_s > 0 else None

    report: Dict = {
        "workdir": workdir,
        "header": {
            **{
                k: v
                for k, v in (header or {}).items()
                if k not in ("event", "t")
            },
            # always present, normally 0: a crashed writer's torn last line
            # (or a corrupted middle) must be visible, not silently absent
            "ledger_parse_errors": parse_errors,
        },
        "run": {
            # when the run actually happened (first event's clock): registry
            # rows key their run_id off this, so registering a week-old
            # workdir does not stamp it with today's date
            "started_t": round(events[0]["t"], 3) if "t" in events[0] else None,
            "wall_s": round(wall_s, 3),
            "last_step": windows[-1]["step"] if windows else None,
            "windows": len(windows),
            "clean_windows": len(clean),
            # the trainers' finally blocks record exception exits with
            # interrupted=True, so a bare run_end means a clean finish
            "completed": run_end is not None and not run_end.get("interrupted"),
            "final": {
                k: v
                for k, v in (run_end or {}).items()
                if k not in ("event", "t")
            },
        },
        "time_split": {
            "data_wait_s": round(data_wait_s, 3),
            "compute_s": round(compute_s, 3),
            "fetch_wait_s": round(fetch_wait_s, 3),
            "barrier_wait_s": round(barrier_wait_s, 3),
            "eval_s": round(eval_s, 3),
            "compile_s": round(compile_s, 3),
            "data_wait_frac": frac(data_wait_s),
            "compute_frac": frac(compute_s),
            "fetch_wait_frac": frac(fetch_wait_s),
            "barrier_wait_frac": frac(barrier_wait_s),
            "eval_frac": frac(eval_s),
            "compile_frac": frac(compile_s),
        },
        "recompiles": {
            "post_warmup_count": len(recompiles),
            "post_warmup_s": round(recompile_s, 3),
            # post-warmup compiles the persistent cache answered: visible
            # (they still interrupt a step) but not alarms
            "cache_served_post_warmup": len(cached_post_warmup),
            "events": [
                {
                    "t": e["t"],
                    "duration_s": e.get("duration_s"),
                    "phase": e.get("phase", ""),
                }
                for e in recompiles
            ],
        },
        "evals": {
            "count": len(evals),
            "last_metrics": evals[-1].get("metrics") if evals else None,
        },
        "checkpoints": len(checkpoints),
    }

    # persistent compile cache verdicts: run_end carries the detector's
    # exact totals; a run that died early falls back to the ledgered
    # per-compile verdicts (cache-consulted compiles are always ledgered)
    cc_hits = (run_end or {}).get("compile_cache_hits")
    cc_misses = (run_end or {}).get("compile_cache_misses")
    cc_saved = (run_end or {}).get("compile_saved_s")
    if cc_hits is None and cc_misses is None:
        verdicts = [e for e in compiles if e.get("cache_hit") is not None]
        if verdicts:
            cc_hits = sum(1 for e in verdicts if e.get("cache_hit"))
            cc_misses = len(verdicts) - cc_hits
            cc_saved = round(
                sum(e.get("saved_s", 0.0) for e in verdicts
                    if e.get("cache_hit")),
                3,
            )
    if cc_hits is not None:
        total = cc_hits + (cc_misses or 0)
        report["compile_cache"] = {
            "hits": cc_hits,
            "misses": cc_misses or 0,
            "hit_ratio": round(cc_hits / total, 4) if total else None,
            "saved_s": cc_saved,
        }

    fleet = fleet_lib.fleet_section(
        workdir, ledgers=ledgers, skew_threshold=straggler_threshold
    )
    if fleet:
        report["fleet"] = fleet

    resilience = _resilience_section(all_events)
    if resilience:
        report["resilience"] = resilience

    elastic = _elastic_section(all_events)
    if elastic:
        report["elastic"] = elastic

    health = _health_section(events)
    if health:
        report["health"] = health
    traces = _trace_summary(events)
    if traces:
        report["traces"] = traces

    serve_windows = [e for e in events if e.get("event") == "serve_window"]
    if serve_windows:
        report["serve"] = _serve_section(serve_windows)

    serve_fleet = _serve_fleet_section(events)
    if serve_fleet:
        report["serve_fleet"] = serve_fleet

    promotion = _promotion_section(events)
    if promotion:
        report["promotion"] = promotion

    loop = _loop_section(ledgers)
    if loop:
        report["loop"] = loop

    quant_checks = [e for e in events if e.get("event") == "quant_check"]
    if quant_checks:
        report["quant_checks"] = [
            {
                k: e.get(k)
                for k in (
                    "dtype",
                    "passed",
                    "candidate",
                    "outputs",
                    "failures",
                    "fingerprint_match",
                )
            }
            for e in quant_checks
        ]

    depths = [e["prefetch_queue_depth"] for e in windows if "prefetch_queue_depth" in e]
    if depths:
        report["prefetch"] = {
            "windows": len(depths),
            "mean_queue_depth": round(
                sum(d["mean"] for d in depths) / len(depths), 2
            ),
            "min_queue_depth": min(d["min"] for d in depths),
            # windows whose queue touched empty: the loader failed to stay
            # ahead of the device at least once in them
            "underrun_windows": sum(1 for d in depths if d["min"] == 0),
        }
    # dirty windows carry compile/eval/checkpoint stalls whose input-side
    # hiccups are startup noise, not the workers failing to keep pace —
    # excluded exactly as they are from the throughput trend
    svc = [
        e["data_service"]
        for e in windows
        if "data_service" in e and not e.get("dirty")
    ]
    if svc:
        # the input service's own backpressure (data/service.py): reorder-
        # buffer depth behind the prefetcher, consumer-starved takes, and
        # worker utilization — the "is the service keeping up" row
        entry = {
            "windows": len(svc),
            "underruns": sum(int(s.get("underruns", 0)) for s in svc),
        }
        ready = [s["ready_depth"] for s in svc if "ready_depth" in s]
        if ready:
            entry["mean_ready_depth"] = round(
                sum(r["mean"] for r in ready) / len(ready), 2
            )
        utils = [s["worker_util"] for s in svc if "worker_util" in s]
        if utils:
            entry["mean_worker_util"] = round(sum(utils) / len(utils), 3)
        report.setdefault("prefetch", {})["data_service"] = entry

    ips = [
        (e["step"], e["images_per_sec"])
        for e in clean
        if e.get("images_per_sec") is not None
    ]
    if ips:
        vals = [v for _, v in ips]
        report["throughput"] = {
            "unit": "images/sec",
            "first": vals[0],
            "last": vals[-1],
            "best": max(vals),
            "mean": round(sum(vals) / len(vals), 2),
            "trend": ips,
        }
    stw = [e for e in windows if "step_time_ms" in e]
    if stw:
        weights = [float(e.get("steps", 1)) for e in stw]
        report["step_time_ms"] = {
            "mean": round(
                _weighted([e["step_time_ms"]["mean_ms"] for e in stw], weights), 3
            ),
            # per-window percentiles are merged approximately: weighted p50/p90,
            # worst-window p99 (raw samples are not persisted to the ledger)
            "p50": round(
                _weighted([e["step_time_ms"]["p50_ms"] for e in stw], weights), 3
            ),
            "p90": round(
                _weighted([e["step_time_ms"]["p90_ms"] for e in stw], weights), 3
            ),
            "p99_worst_window": round(
                max(e["step_time_ms"]["p99_ms"] for e in stw), 3
            ),
        }
    # MFU: analytic FLOPs (6*params*batch, the planner's model) over measured
    # step time and the device peak — absent (never 0/0) when the backend has
    # no peak-FLOPs entry (CPU) or the trainer never priced the step. Clean
    # windows only: a compile/eval window's step time is not model FLOPs.
    mfu_windows = [e for e in clean if e.get("mfu") is not None]
    if mfu_windows:
        mfu_weights = [float(e.get("steps", 1)) for e in mfu_windows]
        mfu_vals = [float(e["mfu"]) for e in mfu_windows]
        report["mfu"] = {
            "windows": len(mfu_vals),
            "mean": round(_weighted(mfu_vals, mfu_weights) or 0.0, 4),
            "last": mfu_vals[-1],
            "best": max(mfu_vals),
        }
    # continuous profiling (obs/profiler.py): windowed/triggered jax.profiler
    # captures and their per-op roofline classification. Stable --json keys:
    # profiling.{captures,by_reason,rooflines,skipped_plane_files,
    # last_roofline}
    captures = [e for e in events if e.get("event") == "profile_capture"]
    rooflines = [e for e in events if e.get("event") == "op_roofline"]
    if captures or rooflines:
        by_reason: Dict[str, int] = {}
        for e in captures:
            reason = str(e.get("reason") or "unknown")
            by_reason[reason] = by_reason.get(reason, 0) + 1
        prof: Dict = {"captures": len(captures), "by_reason": by_reason}
        skipped_planes = sum(
            int(e.get("skipped_plane_files") or 0) for e in captures
        )
        if skipped_planes:
            prof["skipped_plane_files"] = skipped_planes
        if rooflines:
            prof["rooflines"] = len(rooflines)
            last_rf = rooflines[-1]
            prof["last_roofline"] = {
                k: last_rf.get(k)
                for k in (
                    "capture_id", "reason", "phase", "total_ms", "classes",
                    "top_hbm_op", "mfu", "compute_mfu",
                    "achieved_flops_per_sec_per_chip", "peak_flops_per_chip",
                    "achieved_collective_bytes_per_sec", "alert_id",
                )
                if last_rf.get(k) is not None
            }
        report["profiling"] = prof
    if memories:
        device_peak = 0
        for e in memories:
            for stats in (e.get("devices") or {}).values():
                device_peak = max(
                    device_peak,
                    stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)),
                )
        mem: Dict = {"snapshots": len(memories)}
        if device_peak:
            mem["device_peak_bytes"] = device_peak
        rss = [
            e["host_rss_bytes"] for e in memories if "host_rss_bytes" in e
        ]
        if rss:
            mem["host_rss_peak_bytes"] = max(rss)
        # exact per-device state accounting the trainers attach post-init:
        # under weight_update_sharding the opt-state number is ~1/dp of the
        # replicated run's — the saving the mode exists for, made visible
        for key in ("opt_state_bytes_per_device", "params_bytes_per_device"):
            vals = [e[key] for e in memories if key in e]
            if vals:
                mem[key] = vals[-1]
        wus = [
            e["weight_update_sharding"]
            for e in memories
            if "weight_update_sharding" in e
        ]
        if wus:
            mem["weight_update_sharding"] = wus[-1]
        report["memory"] = mem

    # capacity layer (obs/capacity.py): per-phase peak-HBM watermarks with
    # the measured-vs-predicted bytes/chip delta, and chip-seconds cost.
    # Stable --json keys: memory.watermarks.{events,peak_bytes,phases,
    # bytes_limit,headroom_frac,predicted_bytes_per_device,
    # measured_minus_predicted_bytes} and cost.{events,train,serve} (train:
    # n_chips/chip_seconds_total/chip_seconds_per_step/
    # examples_per_chip_second; serve: n_chips/chip_seconds_total/requests/
    # rps_per_chip/duty_cycle/chip_seconds_per_request).
    watermarks = capacity_lib.aggregate_watermark_events(events)
    if watermarks:
        report.setdefault("memory", {})["watermarks"] = watermarks
    cost = capacity_lib.aggregate_cost_events(events)
    if cost:
        report["cost"] = cost

    # parallelism plan (parallel/planner.py, riding the run header): the
    # chosen layout + predicted bytes/chip, closed against the measured
    # watermark peak when the backend ledgered one — the margin the
    # planner's activation model needs, per run. Stable --json keys:
    # plan.{source,layout,predicted,headroom_frac,measured_peak_bytes,
    # measured_minus_predicted_bytes}
    plan = (header or {}).get("plan")
    if plan:
        plan_section: Dict = dict(plan)
        predicted_total = (plan.get("predicted") or {}).get(
            "total_bytes_per_chip"
        )
        measured = (watermarks or {}).get("peak_bytes")
        if predicted_total and measured:
            plan_section["measured_peak_bytes"] = measured
            plan_section["measured_minus_predicted_bytes"] = (
                measured - predicted_total
            )
        report["plan"] = plan_section

    try:
        report["trace"] = _trace_section(trace_dir or workdir, top)
    except (FileNotFoundError, ValueError, OSError):
        report["trace"] = None
    return report


def _fmt_frac(x: Optional[float]) -> str:
    return f"{x:6.1%}" if x is not None else "   n/a"


def render_report(report: Dict) -> str:
    """Human-readable rendering of ``build_report``'s dict."""
    lines: List[str] = []
    fp = (report.get("header") or {}).get("fingerprint") or {}
    run = report["run"]
    lines.append(f"== goodput report: {report['workdir']}")
    parse_errors = (report.get("header") or {}).get("ledger_parse_errors")
    if parse_errors:
        lines.append(
            f"   !! {parse_errors} unparseable ledger line(s) dropped — a "
            "crashed writer's torn tail, or worse; the report understates "
            "the run"
        )
    if fp and "error" not in fp:
        lines.append(
            f"   {fp.get('n_devices', '?')}x {fp.get('device_kind', '?')} "
            f"({fp.get('platform', '?')}), "
            f"{fp.get('process_count', 1)} process(es), "
            f"jax {fp.get('jax_version', '?')}"
        )
    lines.append(
        f"   wall {run['wall_s']:.1f}s, last step {run['last_step']}, "
        f"{run['windows']} windows ({run['clean_windows']} clean), "
        f"run {'completed' if run['completed'] else 'IN PROGRESS / interrupted'}"
    )
    plan = report.get("plan")
    if plan:
        lay = plan.get("layout") or {}
        parts = [f"dp{lay.get('data_parallel', '?')}"]
        for key, tag in (
            ("model_parallel", "tp"),
            ("pipeline_parallel", "pp"),
            ("sequence_parallel", "sp"),
            ("expert_parallel", "ep"),
        ):
            if (lay.get(key) or 1) > 1:
                parts.append(f"{tag}{lay[key]}")
        if lay.get("weight_update_sharding"):
            parts.append("zero1")
        pred = plan.get("predicted") or {}
        line = (
            f"\nparallelism plan ({plan.get('source', '?')}): "
            + "x".join(parts)
        )
        if pred.get("total_bytes_per_chip"):
            line += (
                f" — predicted {pred['total_bytes_per_chip'] / (1 << 20):.1f}"
                " MB/chip"
            )
            detail = [
                f"{tag} {pred[key] / (1 << 20):.1f}"
                for key, tag in (
                    ("params_bytes_per_chip", "params"),
                    ("opt_state_bytes_per_chip", "opt"),
                    ("activation_bytes_per_chip", "act"),
                )
                if pred.get(key) is not None
            ]
            if detail:
                line += f" ({', '.join(detail)})"
        if plan.get("headroom_frac") is not None:
            line += f", headroom {plan['headroom_frac']:.1%}"
        lines.append(line)
        if plan.get("measured_peak_bytes"):
            delta = plan.get("measured_minus_predicted_bytes", 0)
            lines.append(
                f"   measured peak {plan['measured_peak_bytes'] / (1 << 20):.1f}"
                f" MB/chip — {'+' if delta >= 0 else ''}"
                f"{delta / (1 << 20):.1f} MB vs predicted (the margin the "
                "planner's activation model needs)"
            )
        if plan.get("cost_provenance"):
            prov = plan["cost_provenance"]
            mc = plan.get("measured_costs") or {}
            if prov == "measured" and mc.get("flops_per_sec_per_chip"):
                lines.append(
                    f"   cost model: measured "
                    f"({mc['flops_per_sec_per_chip'] / 1e12:.2f} TFLOP/s/chip "
                    f"from {mc.get('captures', 0)} roofline capture(s))"
                )
            else:
                lines.append(f"   cost model: {prov}")
        for warning in plan.get("warnings") or ():
            lines.append(f"   !! {warning}")
    tp = report.get("throughput")
    if tp:
        lines.append(
            f"\nthroughput ({tp['unit']}): first {tp['first']:.1f} -> "
            f"last {tp['last']:.1f} (best {tp['best']:.1f}, mean {tp['mean']:.1f})"
        )
    st = report.get("step_time_ms")
    if st:
        lines.append(
            f"step time (ms): mean {st['mean']:.2f}  p50 {st['p50']:.2f}  "
            f"p90 {st['p90']:.2f}  p99(worst window) {st['p99_worst_window']:.2f}"
        )
    mfu = report.get("mfu")
    if mfu:
        lines.append(
            f"MFU: mean {mfu['mean']:.1%}  best {mfu['best']:.1%}  "
            f"last {mfu['last']:.1%}  over {mfu['windows']} clean window(s) "
            "(analytic 6*params*batch FLOPs vs device peak)"
        )
    ts = report["time_split"]
    lines.append("\nwhere the wall time went:")
    lines.append(
        f"  data-wait    {_fmt_frac(ts['data_wait_frac'])}  {ts['data_wait_s']:9.2f}s"
    )
    lines.append(
        f"  step-compute {_fmt_frac(ts['compute_frac'])}  {ts['compute_s']:9.2f}s"
    )
    if ts.get("fetch_wait_s"):
        lines.append(
            f"  fetch-wait   {_fmt_frac(ts.get('fetch_wait_frac'))}  "
            f"{ts['fetch_wait_s']:9.2f}s  (host blocked on device values — "
            "dispatch-ahead backpressure)"
        )
    if ts.get("barrier_wait_s"):
        lines.append(
            f"  barrier-wait {_fmt_frac(ts.get('barrier_wait_frac'))}  "
            f"{ts['barrier_wait_s']:9.2f}s  (blocked at cross-process sync "
            "points — waiting on slower hosts)"
        )
    lines.append(
        f"  eval         {_fmt_frac(ts['eval_frac'])}  {ts['eval_s']:9.2f}s"
    )
    lines.append(
        f"  compile      {_fmt_frac(ts['compile_frac'])}  {ts['compile_s']:9.2f}s"
        "  (overlaps the span it interrupted)"
    )
    cc = report.get("compile_cache")
    if cc:
        ratio = (
            f"{cc['hit_ratio']:.0%}" if cc.get("hit_ratio") is not None
            else "n/a"
        )
        line = (
            f"compile cache: {cc['hits']} hit(s) / {cc['misses']} miss(es) "
            f"— {ratio} served from cache"
        )
        if cc.get("saved_s") is not None:
            line += f", ~{cc['saved_s']:.2f}s compile time saved"
        lines.append(line)
    rc = report["recompiles"]
    if rc["post_warmup_count"]:
        lines.append(
            f"\n!! {rc['post_warmup_count']} POST-WARMUP RECOMPILE(S) "
            f"({rc['post_warmup_s']:.2f}s lost):"
        )
        for e in rc["events"]:
            lines.append(
                f"   - {e['duration_s']:.2f}s during {e['phase'] or 'unattributed'!r}"
            )
    else:
        lines.append("\nrecompiles after warmup: none")
    if rc.get("cache_served_post_warmup"):
        lines.append(
            f"  ({rc['cache_served_post_warmup']} post-warmup compile(s) "
            "served from the persistent cache — loads, not rebuilds)"
        )
    pf = report.get("prefetch")
    if pf:
        if "mean_queue_depth" in pf:
            line = (
                f"input prefetch: mean queue depth {pf['mean_queue_depth']:.1f} "
                f"(min {pf['min_queue_depth']}) over {pf['windows']} window(s)"
            )
            if pf["underrun_windows"]:
                line += (
                    f" — !! {pf['underrun_windows']} window(s) underran (queue "
                    "hit empty; raise --prefetch-depth or speed the loader up)"
                )
            lines.append(line)
        ds = pf.get("data_service")
        if ds:
            line = f"data service: {ds['underruns']} underrun(s)"
            if "mean_ready_depth" in ds:
                line += f", mean ready depth {ds['mean_ready_depth']:.1f}"
            if "mean_worker_util" in ds:
                line += f", worker util {ds['mean_worker_util']:.0%}"
            line += f" over {ds['windows']} window(s)"
            if ds["underruns"]:
                line += (
                    " — !! consumers outran the workers; raise "
                    "--data-workers"
                )
            lines.append(line)
    ev = report["evals"]
    lines.append(
        f"evals: {ev['count']}"
        + (f", last: {ev['last_metrics']}" if ev["last_metrics"] else "")
    )
    lines.append(f"checkpoints: {report['checkpoints']}")
    fleet = report.get("fleet")
    if fleet:
        lines.extend(fleet_lib.render_fleet_section(fleet))
    res = report.get("resilience")
    if res:
        lines.append(
            f"\nresilience: {res['restarts']} restart(s), "
            f"{res['restart_downtime_s']:.2f}s goodput lost to restarts; "
            f"{res['preemptions']} preemption(s), {res['resumes']} resume(s), "
            f"{res['corrupt_checkpoints_skipped']} corrupt checkpoint(s) "
            f"skipped, {res['checkpoint_retries']} checkpoint retry(ies)"
        )
        lr = res.get("last_restart")
        if lr:
            lines.append(
                f"  last restart: attempt {lr['attempt']}, rc={lr['rc']} "
                f"({lr['reason']}) at step {lr['step']}"
            )
        if res.get("aborted"):
            explanation = {
                "crash-loop": "no step progress between restarts",
                "restart-budget": "the restart budget was exhausted",
                "signaled": "the supervisor itself was signaled to stop",
            }.get(res["aborted"], "see the supervisor_abort ledger event")
            lines.append(
                f"  !! supervisor gave this run up: {res['aborted']} — "
                f"{explanation}"
            )
    ela = report.get("elastic")
    if ela:
        state = "LIVE" if ela.get("live") else (
            "ok" if ela.get("ok") else "failed"
        )
        lines.append(
            f"\nelastic: world {ela['hosts']} -> {ela['world_size']} "
            f"[{state}] — {ela['resizes']} resize(s), "
            f"{ela['evictions']} eviction(s), "
            f"{ela['data_redeals']} data re-deal(s), "
            f"{ela['resize_downtime_s']:.2f}s goodput lost to resizes "
            f"(min_hosts {ela['min_hosts']})"
        )
        for rz in ela.get("resize_events", []):
            plan = ""
            if rz.get("plan_old") or rz.get("plan_new"):
                old_l = (rz.get("plan_old") or {}).get("layout") or {}
                new_l = (rz.get("plan_new") or {}).get("layout") or {}
                if old_l or new_l:
                    plan = (
                        f", plan dp{old_l.get('data_parallel', '?')} -> "
                        f"dp{new_l.get('data_parallel', '?')}"
                    )
            evicted = (
                f", evicted host {rz['evicted_process']}"
                if rz.get("evicted_process") is not None else ""
            )
            lines.append(
                f"   - {rz.get('old_world')} -> {rz.get('new_world')} "
                f"({rz.get('reason')}) at step "
                f"{rz.get('progress_step')}, "
                f"{rz.get('downtime_s', 0.0):.2f}s downtime"
                f"{evicted}{plan}"
            )
        if ela.get("aborted"):
            explanation = {
                "min-hosts": "a resize would have crossed --min-hosts",
                "resize-budget": "the resize budget was exhausted",
                "crash-loop": "no step progress between restarts",
                "restart-budget": "the restart budget was exhausted",
                "signaled": "the coordinator itself was signaled to stop",
            }.get(ela["aborted"], "see the elastic_abort ledger event")
            lines.append(
                f"  !! elastic session aborted: {ela['aborted']} — "
                f"{explanation}"
            )
    hl = report.get("health")
    if hl:
        lines.append(
            f"\n!! health: {hl['alerts']} alert(s)"
            + (
                f" — DEGRADED: {', '.join(hl['degraded'])}"
                if hl["degraded"]
                else " (all resolved)"
            )
        )
        for name, m in sorted(hl["monitors"].items()):
            last = m.get("last", {})
            detail = ", ".join(
                f"{k}={last[k]}"
                for k in (
                    "step", "loss", "median", "mean_ms", "baseline_ms",
                    "window_p99_ms", "p99_target_ms", "violation_frac",
                )
                if k in last
            )
            state = "ACTIVE" if m["active"] else "resolved"
            lines.append(
                f"   - {name}: {m['alerts']} alert(s) [{state}]"
                + (f" — last: {detail}" if detail else "")
            )
    tr_s = report.get("traces")
    if tr_s:
        names = ", ".join(
            f"{n}:{c}" for n, c in sorted(tr_s["by_name"].items())
        )
        lines.append(
            f"tracing: {tr_s['spans']} sampled span(s) across "
            f"{tr_s['traces']} trace(s) ({names}) — export with "
            "`telemetry-report --export-trace out.json`"
        )
    mem = report.get("memory")
    if mem:
        parts = []
        if "snapshots" in mem:
            parts.append(f"{mem['snapshots']} snapshot(s)")
        if "device_peak_bytes" in mem:
            parts.append(f"device peak {mem['device_peak_bytes'] / 2**20:.1f} MiB")
        if "host_rss_peak_bytes" in mem:
            parts.append(f"host RSS peak {mem['host_rss_peak_bytes'] / 2**20:.1f} MiB")
        if "opt_state_bytes_per_device" in mem:
            tag = " (ZeRO-1 sharded)" if mem.get("weight_update_sharding") else ""
            parts.append(
                f"opt state {mem['opt_state_bytes_per_device'] / 2**20:.1f} "
                f"MiB/device{tag}"
            )
        if parts:
            lines.append("memory: " + ", ".join(parts))
        wm = mem.get("watermarks")
        if wm:
            line = f"HBM watermarks: peak {wm['peak_bytes'] / 2**20:.1f} MiB"
            if wm.get("bytes_limit"):
                line += (
                    f" of {wm['bytes_limit'] / 2**20:.1f} MiB limit "
                    f"({wm.get('headroom_frac', 0):.1%} headroom)"
                )
            lines.append(line)
            for phase, row in sorted(wm["phases"].items()):
                at = (
                    f" @ step {row['step']}"
                    if row.get("step") is not None
                    else ""
                )
                lines.append(
                    f"  {phase:<8} {row['peak_bytes'] / 2**20:>9.1f} MiB{at}"
                )
            if wm.get("predicted_bytes_per_device") is not None:
                delta = wm.get("measured_minus_predicted_bytes", 0)
                lines.append(
                    f"  measured vs predicted bytes/chip: "
                    f"{wm['predicted_bytes_per_device'] / 2**20:.1f} MiB "
                    f"predicted (params+opt state), "
                    f"{delta / 2**20:+.1f} MiB residual "
                    "(activations/workspace the planner must margin for)"
                )
    cost = report.get("cost")
    if cost:
        ct = cost.get("train")
        if ct:
            line = (
                f"cost (train): {ct['chip_seconds_total']:.1f} chip-seconds "
                f"on {ct.get('n_chips', '?')} chip(s)"
            )
            if ct.get("chip_seconds_per_step") is not None:
                line += f", {ct['chip_seconds_per_step'] * 1000:.2f} chip-ms/step"
            if ct.get("examples_per_chip_second") is not None:
                line += (
                    f", {ct['examples_per_chip_second']:.1f} "
                    "examples/chip-second"
                )
            lines.append(line)
        cs = cost.get("serve")
        if cs:
            line = (
                f"cost (serve): {cs['chip_seconds_total']:.1f} chip-seconds "
                f"on {cs.get('n_chips', '?')} chip(s)"
            )
            if cs.get("rps_per_chip") is not None:
                line += f", {cs['rps_per_chip']:.1f} requests/sec/chip"
            if cs.get("duty_cycle") is not None:
                line += f", duty cycle {cs['duty_cycle']:.1%}"
            lines.append(line)
            pr = cs.get("chip_seconds_per_request")
            if pr:
                lines.append(
                    "  chip-ms/request: "
                    f"mean {pr['mean'] * 1000:.3f}  "
                    f"p50 {pr['p50'] * 1000:.3f}  "
                    f"p90 {pr['p90'] * 1000:.3f}  "
                    f"p99(worst window) {pr['p99_worst_window'] * 1000:.3f}"
                )
    sv = report.get("serve")
    if sv:
        dtype_tag = (
            f" [{sv['serving_dtype']}]" if sv.get("serving_dtype") else ""
        )
        if sv.get("model"):
            ver = sv.get("model_version")
            dtype_tag += f" [{sv['model']}" + (
                f" v{ver}]" if ver is not None else "]"
            )
        lines.append(
            f"\nserving{dtype_tag} ({sv['windows']} window(s)): "
            f"{sv['requests']} requests, {sv['completed']} completed, "
            f"{sv['rejected_queue_full']} rejected (queue full), "
            f"{sv['deadline_exceeded']} deadline-exceeded, "
            f"{sv['errors']} errors"
        )
        if sv.get("batches"):
            lines.append(
                f"  batches: {sv['batches']} "
                f"(mean fill {sv.get('mean_batch_fill', 0):.1f} examples)"
            )
        for name, m in sorted((sv.get("models") or {}).items()):
            p99 = (
                (m.get("latency_ms") or {}).get("request") or {}
            ).get("p99_ms")
            mline = (
                f"  model {name} v{m.get('version', '?')}: "
                f"{m.get('completed', 0)}/{m.get('requests', 0)} ok"
            )
            if p99 is not None:
                mline += f", window p99 {p99:.1f}ms"
            mslo = m.get("slo")
            if mslo:
                mline += (
                    f", SLO {mslo['p99_target_ms']:.0f}ms "
                    + ("met" if mslo.get("healthy", True) else "BREACHED")
                )
            if m.get("serving_dtype"):
                mline += f" [{m['serving_dtype']}]"
            lines.append(mline)
        if sv.get("bucket_hits"):
            hits = "  ".join(
                f"{b}:{n}" for b, n in sorted(
                    sv["bucket_hits"].items(), key=lambda kv: int(kv[0])
                )
            )
            lines.append(f"  bucket hits: {hits}")
        if sv.get("padding_waste"):
            waste = "  ".join(
                f"{b}:{w:.1%}" for b, w in sorted(
                    sv["padding_waste"].items(), key=lambda kv: int(kv[0])
                )
            )
            lines.append(f"  padding waste (slots padded/compiled): {waste}")
        for name, s in (sv.get("latency_ms") or {}).items():
            lines.append(
                f"  {name.replace('_', '-'):<12} (ms): mean {s['mean']:.2f}  "
                f"p50 {s['p50']:.2f}  p90 {s['p90']:.2f}  "
                f"p99(worst window) {s['p99_worst_window']:.2f}"
            )
        slo = sv.get("slo")
        if slo:
            state = "met" if slo.get("healthy", True) else "BREACHED"
            line = (
                f"  SLO: p99 target {slo['p99_target_ms']:.1f}ms, error "
                f"budget {slo['error_budget']:.1%} — {state}"
            )
            if slo.get("window_p99_ms") is not None:
                line += f" (last window p99 {slo['window_p99_ms']:.1f}ms)"
            lines.append(line)
        if sv.get("tee_dropped"):
            lines.append(
                f"  !! capture tee dropped {sv['tee_dropped']} sample(s) "
                "(bounded queue full) — captured data under-represents the "
                "traffic; slow the sample fraction or raise the queue"
            )
        dr = sv.get("drift")
        if dr:
            state = "ok" if dr.get("healthy", True) else "DRIFTED"
            line = (
                f"  drift monitor [{dr.get('output', '?')}]: {state} "
                f"(threshold {dr.get('threshold', 0):.2f}"
            )
            if dr.get("score") is not None:
                line += f", last score {dr['score']:.3f}"
            lines.append(line + ")")
        rc_s = sv.get("recompiles_post_warmup")
        if rc_s:
            lines.append(
                f"  !! {rc_s} POST-WARMUP RECOMPILE(S) on the request path — "
                "a shape escaped the bucket ladder"
            )
        elif rc_s == 0:
            lines.append("  post-warmup recompiles on the request path: none")
    sf = report.get("serve_fleet")
    if sf:
        rt = sf.get("router")
        if rt:
            lines.append(
                f"\nserving fleet router ({rt['windows']} window(s)): "
                f"{rt['requests']} requests, {rt['routed']} forwards "
                f"({rt['retries']} retries), {rt['shed']} shed (429), "
                f"{rt['no_replica']} no-replica (503), "
                f"{rt['replica_failures']} replica failure(s)"
            )
            if rt.get("tee_dropped"):
                lines.append(
                    f"  !! shadow tee dropped {rt['tee_dropped']} "
                    "request(s) (bounded queue full / canary 429) — the "
                    "shadow compare saw less traffic than the fraction "
                    "promised"
                )
            if rt.get("per_replica_routed"):
                routed = "  ".join(
                    f"r{rid}:{n}" for rid, n in sorted(
                        rt["per_replica_routed"].items(),
                        key=lambda kv: int(kv[0]),
                    )
                )
                lines.append(f"  routed per replica: {routed}")
            fl = rt.get("fleet") or {}
            if fl:
                lines.append(
                    f"  fleet state: {fl.get('status', '?')} — "
                    f"{fl.get('live', 0)} live, "
                    f"{fl.get('starting', 0)} starting, "
                    f"{fl.get('draining', 0)} draining, "
                    f"{fl.get('dead', 0)} dead"
                )
            for name, m in sorted((rt.get("models") or {}).items()):
                mline = (
                    f"  model {name}: {m.get('replicas', 0)} replica(s), "
                    f"{m.get('routed', 0)}/{m.get('requests', 0)} routed, "
                    f"{m.get('shed', 0)} shed "
                    f"({m.get('fair_shed', 0)} fair-shed)"
                )
                if m.get("worst_p99_ms") is not None:
                    mline += f", worst p99 {m['worst_p99_ms']:.1f}ms"
                versions = m.get("versions") or {}
                if versions:
                    mline += ", " + "/".join(
                        f"v{v}:{n}" for v, n in sorted(versions.items())
                    )
                    if len(versions) > 1:
                        mline += " (mixed — promotion in flight?)"
                lines.append(mline)
            fs = rt.get("fair_share")
            if fs and fs.get("admitted_shares"):
                weights = fs.get("weights") or {}
                total_w = sum(weights.values()) or 1.0
                bits = [
                    f"{name} {share:.0%}"
                    + (
                        f" (fair {weights[name] / total_w:.0%})"
                        if name in weights
                        else ""
                    )
                    for name, share in sorted(
                        fs["admitted_shares"].items()
                    )
                ]
                tag = " UNDER PRESSURE" if fs.get("pressured") else ""
                lines.append(
                    f"  admitted shares{tag}: " + ", ".join(bits)
                )
            if rt.get("artifacts"):
                mix = "  ".join(
                    f"{key}:{n}" for key, n in sorted(rt["artifacts"].items())
                )
                lines.append(f"  artifacts served: {mix}")
                if rt.get("silent_mixed_fleet"):
                    lines.append(
                        "  !! MIXED FLEET outside an active promotion — "
                        "replicas are answering from different artifacts "
                        "with no controller in charge; promote or drain "
                        "until the fingerprints converge"
                    )
        sc = sf.get("autoscale")
        if sc:
            counts = (
                f"({sc['scale_up']} up / {sc['scale_down']} down"
                + (
                    f" / {sc['budget_deferred']} budget-deferred"
                    if sc.get("budget_deferred")
                    else ""
                )
                + ")"
            )
            lines.append(
                f"  autoscale: {sc['decisions']} decision(s) {counts}, "
                f"final target {sc['final_replicas']} replica(s)"
            )
            for e in sc["events"][-3:]:
                model_tag = (
                    f"[{e['model']}] " if e.get("model") else ""
                )
                lines.append(
                    f"    - {model_tag}{e['action']}: "
                    f"{e['from_replicas']} -> "
                    f"{e['to_replicas']} ({e['reason']}, mean queue "
                    f"{e['mean_queue_depth']})"
                )
        rl = sf.get("replicas")
        if rl:
            line = (
                f"  replica lifecycle: {rl['spawn']} spawn(s), "
                f"{rl['exit']} unplanned exit(s), {rl['restart']} "
                f"restart(s), {rl['drain']} drain(s)"
            )
            if rl.get("abandoned"):
                line += f", !! {rl['abandoned']} ABANDONED"
            lines.append(line)
            ttr = rl.get("time_to_ready_s")
            if ttr:
                lines.append(
                    f"  replica time-to-ready: mean {ttr['mean']:.2f}s  "
                    f"max {ttr['max']:.2f}s  last {ttr['last']:.2f}s "
                    f"over {ttr['count']} readiness event(s)"
                )
    pm = report.get("promotion")
    if pm:
        verdictbits = []
        if pm["completed"]:
            verdictbits.append(f"{pm['completed']} completed")
        if pm["rolled_back"]:
            verdictbits.append(f"{pm['rolled_back']} ROLLED BACK")
        if pm["refused"]:
            verdictbits.append(f"{pm['refused']} refused at admission")
        if pm["aborted"]:
            verdictbits.append(f"{pm['aborted']} ABORTED mid-rollback")
        lines.append(
            f"\ndeployment history: {pm['starts']} promotion(s) — "
            + (", ".join(verdictbits) if verdictbits else "in progress")
            + f"; {pm['shadow_windows']} shadow window(s), "
            f"{pm['shadow_compared']} request(s) shadow-compared"
        )
        for e in pm["history"]:
            kind = e["kind"]
            if kind == "promotion_start":
                what = "refused at admission" if e.get("refused") else "start"
                lines.append(
                    f"  - {what}: {e.get('candidate_dir', '?')}"
                    + (f" [{e['dtype']}]" if e.get("dtype") else "")
                )
            elif kind == "phase_advance":
                detail = ", ".join(
                    f"{k}={e[k]}"
                    for k in ("replica", "replaced", "remaining", "windows",
                              "compared")
                    if e.get(k) is not None
                )
                lines.append(
                    f"  - phase {e.get('phase')}"
                    + (f" ({detail})" if detail else "")
                )
            elif kind == "shadow_window":
                detail = ", ".join(
                    f"{k}={e[k]}"
                    for k in ("compared", "min_iou", "mean_disagree",
                              "max_abs_delta")
                    if e.get(k) is not None
                )
                lines.append(f"  - shadow window ({detail})")
            elif kind == "promotion_rollback":
                lines.append(
                    f"  - !! {e.get('status', 'rollback').upper()} at "
                    f"{e.get('phase', '?')}: {e.get('reason', '?')}"
                    + (
                        f" — {e['abort_reason']}"
                        if e.get("abort_reason")
                        else ""
                    )
                )
            elif kind == "promotion_complete":
                lines.append(
                    f"  - complete: fleet on {e.get('candidate_dir', '?')}"
                    + (
                        f" in {e['duration_s']}s"
                        if e.get("duration_s") is not None
                        else ""
                    )
                )
    lp = report.get("loop")
    if lp:
        lines.append("\ncontinuous learning loop:")
        cap = lp.get("capture")
        if cap:
            line = (
                f"  capture: {cap['captured']} record(s) across "
                f"{cap['shards']} shard(s) from {cap['replicas']} "
                f"replica(s) ({cap['bytes_on_disk'] / 2**20:.1f} MiB on "
                "disk)"
            )
            if cap.get("evicted"):
                line += f", {cap['evicted']} shard(s) quota-evicted"
            lines.append(line)
            if cap.get("dropped"):
                lines.append(
                    f"  !! capture dropped {cap['dropped']} sample(s) — "
                    "bounded-queue loss, counted not silent"
                )
        ing = lp.get("ingest")
        if ing:
            lines.append(
                f"  ingest: {ing['runs']} pass(es) — "
                f"+{ing['records_added']} record(s) in "
                f"{ing['new_shards']} shard(s) "
                f"({ing['deduped']} duplicate, {ing['corrupt']} corrupt "
                f"skipped); dataset v{ing.get('dataset_version')} holds "
                f"{ing.get('records_total')} record(s)"
            )
        dr = lp.get("drift")
        if dr:
            last = dr.get("last") or {}
            line = f"  drift: {dr['alerts']} alert(s)"
            if dr.get("resolved"):
                line += f", {dr['resolved']} resolved"
            if last.get("score") is not None:
                line += (
                    f" — last score {last['score']:.3f} vs threshold "
                    f"{last.get('threshold', 0):.2f}"
                    f" (replica {last.get('replica', '?')})"
                )
            lines.append(line)
        cy = lp.get("cycles")
        if cy:
            lines.append(
                f"  cycles: {cy['triggers']} trigger(s), "
                f"{cy['retrains']} retrain(s) — {cy['promoted']} "
                f"promoted, {cy['rejected']} rejected"
                + (
                    f"; drift->trigger latency "
                    f"{cy['drift_trigger_latency_s']:.1f}s"
                    if cy.get("drift_trigger_latency_s") is not None
                    else ""
                )
            )
            for e in cy["history"]:
                kind = e["kind"]
                if kind == "loop_trigger":
                    detail = ", ".join(
                        f"{k}={e[k]}"
                        for k in ("records_new", "dataset_version",
                                  "drift_score")
                        if e.get(k) is not None
                    )
                    lines.append(
                        f"    - trigger [{e.get('reason', '?')}]"
                        + (f" ({detail})" if detail else "")
                    )
                elif kind == "loop_retrain":
                    lines.append(
                        f"    - retrain rc={e.get('rc')} in "
                        f"{e.get('duration_s', 0)}s"
                        + (
                            f" -> {e['candidate_dir']}"
                            if e.get("candidate_dir")
                            else ""
                        )
                    )
                elif kind == "loop_promoted":
                    lines.append(
                        "    - PROMOTED: fleet flipped to "
                        f"{e.get('candidate_dir', '?')}"
                    )
                elif kind == "loop_rejected":
                    lines.append(
                        "    - rejected"
                        + (f": {e['error']}" if e.get("error") else
                           f" (rc={e.get('rc')})")
                    )
    for qc in report.get("quant_checks", ()):
        verdict = "PASSED" if qc.get("passed") else "FAILED"
        details = []
        for name, rec in (qc.get("outputs") or {}).items():
            if "max_abs_delta" in rec:
                details.append(f"{name} max|Δ| {rec['max_abs_delta']}")
            if "iou" in rec:
                details.append(f"{name} IoU {rec['iou']}")
            if "disagree" in rec:
                details.append(f"{name} disagree {rec['disagree']}")
        line = (
            f"\nquantize-check [{qc.get('dtype')}] {verdict}"
            + (f": {', '.join(details)}" if details else "")
        )
        lines.append(line)
        for failure in qc.get("failures") or ():
            lines.append(f"  !! {failure}")
    prof = report.get("profiling")
    if prof:
        reasons = ", ".join(
            f"{n} {reason}" for reason, n in sorted(prof["by_reason"].items())
        ) or "none"
        line = (
            f"\ncontinuous profiling: {prof['captures']} capture(s) "
            f"({reasons}), {prof.get('rooflines', 0)} roofline(s)"
        )
        if prof.get("skipped_plane_files"):
            line += (
                f" — !! {prof['skipped_plane_files']} truncated plane "
                "file(s) skipped"
            )
        lines.append(line)
        rf = prof.get("last_roofline")
        if rf:
            cls = rf.get("classes") or {}
            detail = (
                f"  last roofline [{rf.get('reason', '?')}]: "
                f"compute {cls.get('compute_frac', 0):.0%} / "
                f"hbm {cls.get('hbm_frac', 0):.0%} / "
                f"collective {cls.get('collective_frac', 0):.0%}"
            )
            if rf.get("mfu") is not None:
                detail += f", mfu {rf['mfu']:.1%}"
            if rf.get("achieved_flops_per_sec_per_chip"):
                detail += (
                    f" ({rf['achieved_flops_per_sec_per_chip'] / 1e12:.2f} "
                    "TFLOP/s/chip achieved)"
                )
            lines.append(detail)
            hbm_op = rf.get("top_hbm_op")
            if hbm_op:
                lines.append(
                    f"  top HBM-bound op: {hbm_op['name']} "
                    f"({hbm_op['total_ms']:.3f} ms, {hbm_op['fraction']:.1%})"
                )
            if rf.get("alert_id"):
                lines.append(
                    f"  postmortem capture triggered by alert {rf['alert_id']}"
                )
    tr = report.get("trace")
    if tr:
        lines.append(f"\ndevice op breakdown ({tr['dir']}):")
        if tr.get("note"):
            lines.append(f"  ({tr['note']})")
        if tr.get("skipped_plane_files"):
            lines.append(
                f"  !! {tr['skipped_plane_files']} truncated/corrupt plane "
                "file(s) skipped"
            )
        for bucket, ms in tr["buckets_ms"].items():
            lines.append(f"  {bucket:<24} {ms:>10.3f} ms")
        lines.append(f"  top {len(tr['top_ops'])} ops:")
        for op in tr["top_ops"]:
            lines.append(
                f"    {op['total_ms']:>10.3f} ms  x{op['occurrences']:<6} "
                f"{op['fraction']:>6.1%}  {op['name']}"
            )
    else:
        lines.append(
            "\nno xplane trace under the workdir (capture one with "
            "utils.profiling.trace / tools/profile_step.py to get the "
            "per-op device breakdown)"
        )
    return "\n".join(lines)


def report_workdir(
    workdir: str,
    *,
    trace_dir: Optional[str] = None,
    top: int = 10,
    as_json: bool = False,
    straggler_threshold: float = fleet_lib.DEFAULT_SKEW_THRESHOLD,
) -> str:
    """The ``telemetry-report`` CLI body: build + render (or JSON-dump)."""
    import json

    if not os.path.exists(workdir):
        raise FileNotFoundError(f"workdir {workdir} does not exist")
    report = build_report(
        workdir,
        trace_dir=trace_dir,
        top=top,
        straggler_threshold=straggler_threshold,
    )
    if as_json:
        return json.dumps(report)
    return render_report(report)
