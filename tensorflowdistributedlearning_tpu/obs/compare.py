"""Cross-run registry + run-vs-run deltas: is THIS run better than the last?

A single report answers "what did this run do"; promotion decisions (ROADMAP
open item 4) and perf-trajectory tracking need runs compared as trajectories
— the serving-comparison stance of "Fine-Tuning and Serving Gemma on Cloud
TPU" (PAPERS.md): curves and deltas, not single points.

Two pieces:

- **Registry**: ``runs.jsonl`` under a registry dir — one summary ROW per
  run (:func:`run_summary`: config hash, mesh, process count, final eval
  metrics, goodput split, step-time percentiles, serving totals), appended by
  ``telemetry-report <workdir> --registry-dir D --register``. Rows are
  self-contained: comparisons keep working after the workdir is gone.
- **Compare**: :func:`compare_rows` emits STRUCTURED deltas with noise-aware
  thresholds — each metric carries a direction (lower/higher is better) and a
  relative (or absolute, for fractions) threshold below which the delta is
  ``neutral``; past it, ``regressed`` or ``improved``. Run-to-run wall-clock
  jitter on shared machines is real; a compare that calls every 2% blip a
  regression trains operators to ignore it.

``tools/regression_sentinel.py`` applies the same stance to committed
``BENCH_*.json`` baselines; this module owns ledger-derived runs.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs import report as report_lib

REGISTRY_FILENAME = "runs.jsonl"


def _normalized_layout(header: Dict) -> Optional[Dict]:
    """The run's parallelism layout, independent of whether the best-effort
    plan resolved: the plan's layout verbatim when present, else the same
    six fields reconstructed from the train config + mesh (the trainers
    ledger the POST-override config, so the two forms always agree)."""
    plan_layout = (header.get("plan") or {}).get("layout")
    if plan_layout is not None:
        return plan_layout
    tcfg = header.get("train_config") or {}
    mesh = header.get("mesh") or {}
    if not tcfg and not mesh:
        return None
    return {
        "data_parallel": mesh.get("batch"),
        "model_parallel": tcfg.get("model_parallel", 1),
        "pipeline_parallel": tcfg.get("pipeline_parallel", 1),
        "sequence_parallel": tcfg.get("sequence_parallel", 1),
        "expert_parallel": tcfg.get("expert_parallel", 1),
        "weight_update_sharding": tcfg.get("weight_update_sharding", False),
    }


def config_hash(header: Dict) -> Optional[str]:
    """Short stable hash over the run's model+train config (the run header
    carries both as dicts) — two runs compare apples-to-apples iff it
    matches. None when the header has no config (foreign/serve ledgers).

    The parallelism plan's LAYOUT is part of the identity: two runs of the
    same config whose planner chose different layouts (``--parallelism
    auto`` at different world sizes or budgets) are different executions —
    their perf deltas are expected, and must never read as config_match.
    The plan itself is attached best-effort, so when the header carries no
    ``plan`` the layout is reconstructed from the (always-present) train
    config degrees + mesh — a run with a plan and an identical run without
    one hash the same."""
    cfg = {
        k: header.get(k)
        for k in ("model_config", "train_config", "mesh")
        if header.get(k) is not None
    }
    layout = _normalized_layout(header)
    if layout is not None:
        cfg["plan_layout"] = layout
    if not cfg:
        return None
    blob = json.dumps(cfg, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:12]


def run_summary(workdir: str) -> Dict:
    """One registry row for the workdir's last run, built from the full
    report (fleet merge included)."""
    report = report_lib.build_report(workdir)
    header = report.get("header") or {}
    run = report["run"]
    # the run's own start clock, NOT registration time: re-registering the
    # same workdir reproduces the same run_id (resolve_run's duplicate-id
    # contract), and old runs keep their real date
    t = run.get("started_t") or time.time()
    row: Dict = {
        "run_id": (
            time.strftime("%Y%m%d-%H%M%S", time.localtime(t))
            + "-"
            + (os.path.basename(os.path.normpath(workdir)) or "run")
        ),
        "t": round(float(t), 3),
        "workdir": os.path.abspath(workdir),
        "kind": header.get("kind") or header.get("task") or "unknown",
        "config_hash": config_hash(header),
        "mesh": header.get("mesh"),
        "process_count": header.get("process_count")
        or (header.get("fingerprint") or {}).get("process_count", 1),
        "steps": run.get("last_step"),
        "wall_s": run.get("wall_s"),
        "completed": run.get("completed"),
        "goodput": report.get("time_split"),
        "recompiles_post_warmup": report["recompiles"]["post_warmup_count"],
        "ledger_parse_errors": header.get("ledger_parse_errors", 0),
    }
    plan = header.get("plan") or {}
    if plan.get("layout"):
        # the layout rides the row so a registry diff names WHICH mesh each
        # run trained under, not just that the hashes differ
        row["plan"] = {
            "source": plan.get("source"),
            "layout": plan["layout"],
            "predicted_total_bytes_per_chip": (
                plan.get("predicted") or {}
            ).get("total_bytes_per_chip"),
        }
    st = report.get("step_time_ms")
    if st:
        row["step_time_ms"] = st
    tp = report.get("throughput")
    if tp:
        row["throughput_mean"] = tp["mean"]
    mfu = report.get("mfu")
    if mfu:
        row["mfu_mean"] = mfu["mean"]
    metrics = report["evals"].get("last_metrics")
    if metrics:
        row["eval_metrics"] = metrics
    sv = report.get("serve")
    if sv:
        serve_row: Dict = {
            "requests": sv.get("requests"),
            "completed": sv.get("completed"),
        }
        req = (sv.get("latency_ms") or {}).get("request")
        if req:
            serve_row["request_p99_ms"] = req["p99_worst_window"]
        row["serve"] = serve_row
    fleet = report.get("fleet")
    if fleet and fleet.get("straggler"):
        row["straggler_max_skew"] = fleet["straggler"]["max_skew"]
    # capacity/cost (obs/capacity.py): the chip-seconds and watermark
    # numbers run-vs-run compares track as first-class perf trajectories
    cost = report.get("cost") or {}
    cost_row: Dict = {}
    train_cost = cost.get("train")
    if train_cost:
        if train_cost.get("chip_seconds_per_step") is not None:
            cost_row["chip_seconds_per_step"] = train_cost[
                "chip_seconds_per_step"
            ]
        if train_cost.get("examples_per_chip_second") is not None:
            cost_row["examples_per_chip_second"] = train_cost[
                "examples_per_chip_second"
            ]
    serve_cost = cost.get("serve")
    if serve_cost:
        if serve_cost.get("rps_per_chip") is not None:
            cost_row["rps_per_chip"] = serve_cost["rps_per_chip"]
        per_req = serve_cost.get("chip_seconds_per_request") or {}
        if per_req.get("p99_worst_window") is not None:
            cost_row["chip_seconds_per_request_p99"] = per_req[
                "p99_worst_window"
            ]
    if cost_row:
        row["cost"] = cost_row
    watermarks = (report.get("memory") or {}).get("watermarks") or {}
    if watermarks.get("peak_bytes"):
        mem_row: Dict = {"peak_bytes": watermarks["peak_bytes"]}
        if watermarks.get("headroom_frac") is not None:
            mem_row["headroom_frac"] = watermarks["headroom_frac"]
        row["memory"] = mem_row
    return row


# -- registry ----------------------------------------------------------------


def register_run(registry_dir: str, workdir: str) -> Dict:
    """Append the workdir's summary row to ``{registry_dir}/runs.jsonl``
    (created on first use) and return it."""
    row = run_summary(workdir)
    os.makedirs(registry_dir, exist_ok=True)
    path = os.path.join(registry_dir, REGISTRY_FILENAME)
    with open(path, "a", encoding="utf-8") as f:
        f.write(json.dumps(row, default=str) + "\n")
    return row


def load_registry(registry_dir: str) -> List[Dict]:
    """Every registered row, file order (= registration order). Missing
    registry reads as empty — a first ``--register`` starts the history."""
    path = os.path.join(registry_dir, REGISTRY_FILENAME)
    if not os.path.isfile(path):
        return []
    rows: List[Dict] = []
    with open(path, encoding="utf-8") as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue  # torn tail — same stance as the run ledger
    return rows


def resolve_run(ref: str, registry_dir: Optional[str] = None) -> Dict:
    """A compare operand: a workdir path (summarized fresh) or — with a
    registry — a registered ``run_id`` (most recent row wins on duplicate
    ids, e.g. the same workdir registered twice)."""
    if os.path.isdir(ref):
        return run_summary(ref)
    if registry_dir:
        rows = [r for r in load_registry(registry_dir) if r.get("run_id") == ref]
        if rows:
            return rows[-1]
    raise FileNotFoundError(
        f"run {ref!r} is neither a workdir nor a registered run id"
        + (f" in {registry_dir}" if registry_dir else " (no --registry-dir)")
    )


# -- deltas ------------------------------------------------------------------

# serving p99 noise band (rel): shared with the promotion controller's
# canary-latency gate so "regressed" means the same thing in a run compare
# and in a rollout decision
SERVE_P99_BAND = 0.15

# (metric label, extractor, direction, threshold, threshold kind)
# - "rel": |b-a|/|a| must exceed it to leave neutral
# - "abs": |b-a| must exceed it (fractions and accuracy-like metrics, where
#   a relative threshold on a near-zero baseline is meaningless)
_METRICS = (
    ("step_time_mean_ms", lambda r: (r.get("step_time_ms") or {}).get("mean"),
     "lower", 0.10, "rel"),
    ("step_time_p99_ms",
     lambda r: (r.get("step_time_ms") or {}).get("p99_worst_window"),
     "lower", 0.25, "rel"),
    ("data_wait_frac", lambda r: (r.get("goodput") or {}).get("data_wait_frac"),
     "lower", 0.05, "abs"),
    ("fetch_wait_frac",
     lambda r: (r.get("goodput") or {}).get("fetch_wait_frac"),
     "lower", 0.05, "abs"),
    ("throughput_mean", lambda r: r.get("throughput_mean"),
     "higher", 0.10, "rel"),
    # MFU derives from the same step-time samples as throughput (the FLOP
    # numerator is deterministic for a fixed config) → same 10% noise band
    ("mfu_mean", lambda r: r.get("mfu_mean"), "higher", 0.10, "rel"),
    ("wall_s", lambda r: r.get("wall_s"), "lower", 0.25, "rel"),
    ("recompiles_post_warmup", lambda r: r.get("recompiles_post_warmup"),
     "lower", 0.0, "abs"),
    ("serve_request_p99_ms",
     lambda r: (r.get("serve") or {}).get("request_p99_ms"),
     "lower", SERVE_P99_BAND, "rel"),
    # capacity/cost trajectories (obs/capacity.py): chip-seconds numbers
    # derive from span wall time (same jitter as step time → same 10% band);
    # the per-request p99 inherits the tail-noise band; device peak bytes is
    # near-deterministic for a fixed config, so a 5% move is a real change
    ("chip_seconds_per_step",
     lambda r: (r.get("cost") or {}).get("chip_seconds_per_step"),
     "lower", 0.10, "rel"),
    ("rps_per_chip",
     lambda r: (r.get("cost") or {}).get("rps_per_chip"),
     "higher", 0.10, "rel"),
    ("chip_seconds_per_request_p99",
     lambda r: (r.get("cost") or {}).get("chip_seconds_per_request_p99"),
     "lower", 0.25, "rel"),
    ("hbm_peak_bytes",
     lambda r: (r.get("memory") or {}).get("peak_bytes"),
     "lower", 0.05, "rel"),
)


def _eval_metric_spec(name: str):
    """Direction + threshold for a task eval metric by naming convention:
    loss-like metrics regress UP (rel 5%), accuracy-like metrics (top1, iou,
    ...) regress DOWN (abs 0.005 — half a point)."""
    if "loss" in name:
        return "lower", 0.05, "rel"
    return "higher", 0.005, "abs"


def verdict(a, b, direction: str, threshold: float, kind: str) -> str:
    """Noise-banded A→B verdict: ``neutral`` inside the band, else
    ``regressed``/``improved`` by ``direction``. Public: the promotion
    controller (serve/promote.py) gates canary latency deltas through the
    same bands the run-vs-run compare uses."""
    delta = b - a
    magnitude = abs(delta) if kind == "abs" else (
        abs(delta) / abs(a) if a else float("inf") if delta else 0.0
    )
    if magnitude <= threshold:
        return "neutral"
    worse = delta > 0 if direction == "lower" else delta < 0
    return "regressed" if worse else "improved"


_verdict = verdict  # original private name, kept for callers/tests


def compare_rows(row_a: Dict, row_b: Dict) -> Dict:
    """Structured A→B deltas over every metric both rows carry."""
    deltas: List[Dict] = []

    def add(metric, a, b, direction, threshold, kind):
        if a is None or b is None:
            return
        a, b = float(a), float(b)
        entry = {
            "metric": metric,
            "a": round(a, 4),
            "b": round(b, 4),
            "delta": round(b - a, 4),
            "ratio": round(b / a, 4) if a else None,
            "direction": direction,
            "threshold": threshold,
            "threshold_kind": kind,
            "verdict": _verdict(a, b, direction, threshold, kind),
        }
        deltas.append(entry)

    for metric, extract, direction, threshold, kind in _METRICS:
        add(metric, extract(row_a), extract(row_b), direction, threshold, kind)
    metrics_a = row_a.get("eval_metrics") or {}
    metrics_b = row_b.get("eval_metrics") or {}
    for name in sorted(set(metrics_a) & set(metrics_b)):
        direction, threshold, kind = _eval_metric_spec(name)
        add(f"eval:{name}", metrics_a[name], metrics_b[name],
            direction, threshold, kind)
    return {
        "a": {k: row_a.get(k) for k in ("run_id", "workdir", "kind", "steps")},
        "b": {k: row_b.get(k) for k in ("run_id", "workdir", "kind", "steps")},
        # apples-to-apples flag: perf deltas between different configs/meshes
        # are expected, not regressions
        "config_match": (
            row_a.get("config_hash") is not None
            and row_a.get("config_hash") == row_b.get("config_hash")
        ),
        "deltas": deltas,
        "regressions": sum(
            1 for d in deltas if d["verdict"] == "regressed"
        ),
        "improvements": sum(
            1 for d in deltas if d["verdict"] == "improved"
        ),
    }


def compare_workdirs(
    ref_a: str, ref_b: str, *, registry_dir: Optional[str] = None
) -> Dict:
    """``telemetry-report --compare A B``: each ref a workdir or (with a
    registry) a registered run id."""
    return compare_rows(
        resolve_run(ref_a, registry_dir), resolve_run(ref_b, registry_dir)
    )


def render_compare(result: Dict) -> str:
    """Human-readable rendering of :func:`compare_rows`."""
    a, b = result["a"], result["b"]
    lines = [
        f"== run compare: {a.get('run_id') or a.get('workdir')} -> "
        f"{b.get('run_id') or b.get('workdir')}",
        "   configs "
        + ("match" if result["config_match"]
           else "DIFFER (deltas are cross-config)"),
    ]
    marks = {"regressed": "!!", "improved": "++", "neutral": "  "}
    for d in result["deltas"]:
        arrow = "<=" if d["direction"] == "lower" else ">="
        ratio = f" ({d['ratio']:.3f}x)" if d["ratio"] is not None else ""
        noise = (
            f"{d['threshold']:.0%}"
            if d["threshold_kind"] == "rel"
            else f"{d['threshold']:g}"
        )
        lines.append(
            f" {marks[d['verdict']]} {d['metric']:<24} "
            f"{d['a']:>10.3f} -> {d['b']:>10.3f}{ratio}  "
            f"[{d['verdict']}; good is {arrow}, noise band {noise}]"
        )
    lines.append(
        f"   {result['regressions']} regression(s), "
        f"{result['improvements']} improvement(s), "
        f"{len(result['deltas']) - result['regressions'] - result['improvements']} neutral"
    )
    return "\n".join(lines)
