"""Capacity & cost accounting: HBM watermarks and chip-seconds meters.

Two questions the ledger could not answer before this module existed:

- **"How close is this run to OOM?"** The periodic ``memory`` snapshot
  recorded whatever the allocator said at sampling time, but nothing tracked
  the PEAK per phase (compile vs steady-state step vs eval vs checkpoint vs
  inference), nothing compared the measured bytes/chip against the
  ``tree_bytes_per_device`` prediction the parallelism modes budget with
  (the pjit/TPUv4 methodology, arXiv:2204.06514, plans placements off exactly
  that number), and nothing estimated whether the trend crosses the device
  limit. :class:`WatermarkTracker` does all three, emitting a
  ``memory_watermark`` ledger event whenever the fleet-wide peak advances.

- **"What does one prediction cost in chip-seconds?"** Throughput tells you
  images/sec; a capacity planner needs device-time-per-unit-of-work — the
  cost-per-qps lens of the Gemma-on-TPU serving comparison (arXiv:2605.25645).
  :class:`CostMeter` attributes device time to training windows
  (``chip_seconds_per_step``, ``images_per_chip_second``) and, via
  batch-share, to individual serving requests (``chip_seconds_per_request``
  percentiles, ``rps_per_chip``), emitting ``cost`` ledger events.

Both meters are HOST-side bookkeeping on the existing window cadence — one
allocator query and a handful of float ops per ledger window, never per step
— so their overhead hides under real device work (gated <= 1% step time by
``bench.py --capacity-overhead``, the same discipline as the tracing gate).

Failure stance matches the rest of ``obs/``: backends without the allocator
query (CPU builds — ``memory_stats`` returns nothing there) degrade to
None-samples, never to a crash, and the cost meter works everywhere (wall
time x chip count needs no backend support).
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

from tensorflowdistributedlearning_tpu.obs.metrics import (
    TimeHistogram,
    window_count,
    window_total_s,
)

# ledger event kinds this module owns (see docs/LEDGER_SCHEMA.md)
WATERMARK_EVENT = "memory_watermark"
COST_EVENT = "cost"

# the phases a watermark is attributed to — the coarse lifecycle of a run
PHASE_COMPILE = "compile"
PHASE_STEP = "step"
PHASE_EVAL = "eval"
PHASE_CKPT = "ckpt"
PHASE_INFER = "infer"
PHASES = (PHASE_COMPILE, PHASE_STEP, PHASE_EVAL, PHASE_CKPT, PHASE_INFER)


def _trend_bytes_per_sample(history: Sequence[Tuple[float, int]]) -> Optional[float]:
    """Least-squares slope of peak_bytes over the retained samples: a
    steadily climbing peak (a leak, a growing cache, a fragmenting allocator)
    shows up as bytes/sample long before the limit. None under 3 samples."""
    if len(history) < 3:
        return None
    n = len(history)
    ys = [p for _, p in history]
    mean_x = (n - 1) / 2.0
    mean_y = sum(ys) / n
    denom = sum((x - mean_x) ** 2 for x in range(n))
    if not denom:
        return 0.0
    return sum(
        (x - mean_x) * (y - mean_y) for x, y in zip(range(n), ys)
    ) / denom


def _default_stats() -> Dict[str, Dict[str, int]]:
    from tensorflowdistributedlearning_tpu.utils.profiling import memory_stats

    return memory_stats()


def peak_bytes_across_devices(
    stats: Optional[Dict[str, Dict[str, int]]] = None,
) -> int:
    """Max ``peak_bytes_in_use`` (falling back to ``bytes_in_use``) across
    local devices — THE peak-extraction rule, shared by the watermark
    tracker and the bench fields so the sentinel's ``peak_hbm_bytes`` gate
    can never diverge from the ledger's watermark numbers. 0 when the
    backend reports nothing (CPU builds) or the probe fails."""
    if stats is None:
        try:
            stats = _default_stats() or {}
        except Exception:  # noqa: BLE001 — a failed probe must not crash
            return 0
    return max(
        (
            int(s.get("peak_bytes_in_use", s.get("bytes_in_use", 0)))
            for s in stats.values()
        ),
        default=0,
    )


def device_count() -> int:
    """Local chip count for cost accounting; 1 when the backend probe fails
    (cost then degrades to plain wall-seconds, still monotonic and
    comparable run-to-run on the same shape)."""
    try:
        import jax

        return max(1, len(jax.local_devices()))
    except Exception:  # noqa: BLE001 — a down backend must not kill telemetry
        return 1


class WatermarkTracker:
    """Per-phase peak-HBM watermarks with measured-vs-predicted accounting.

    ``sample(phase)`` queries the allocator (``profiling.memory_stats``) and,
    when the fleet-wide ``peak_bytes_in_use`` advanced past the recorded
    high-water mark, returns the fields of a ``memory_watermark`` ledger
    event attributing the new peak to ``phase`` — the phase that was running
    when the watermark moved is the phase that owns the memory. A phase's
    FIRST sample under an existing peak is also recorded (``advanced:
    false``, ``delta_bytes: 0`` — an observation, not an allocation), so the
    per-phase table stays complete while steady-state steps under a
    compile-time peak remain the healthy, delta-free case.

    ``predicted_bytes_per_device`` is the exact ``tree_bytes_per_device``
    accounting the trainers attach (params + optimizer state); every
    watermark event carries ``measured_minus_predicted_bytes`` so the
    activations/workspace residual — the number a placement planner must
    margin for — is ledgered per run.

    ``headroom()`` is the live OOM-risk view: current headroom fraction
    against ``bytes_limit`` plus a linear trend over the recent samples and
    the projected samples-to-limit. Backends without the allocator query
    yield ``sample() -> None`` and ``headroom() -> None``; nothing crashes.
    """

    # recent (t, peak_bytes) pairs the trend is fit over
    TREND_SAMPLES = 16

    def __init__(
        self,
        predicted_bytes_per_device: Optional[int] = None,
        *,
        stats_fn: Callable[[], Dict[str, Dict[str, int]]] = _default_stats,
    ):
        self.predicted_bytes_per_device = predicted_bytes_per_device
        self._stats_fn = stats_fn
        self._lock = threading.Lock()
        self.peak_bytes = 0  # fleet-wide high-water mark seen so far
        self.bytes_limit: Optional[int] = None
        self.phase_peaks: Dict[str, Dict] = {}
        self._history: Deque[Tuple[float, int]] = collections.deque(
            maxlen=self.TREND_SAMPLES
        )
        self.samples = 0  # queries that returned device stats

    def set_predicted(self, bytes_per_device: Optional[int]) -> None:
        if bytes_per_device:
            self.predicted_bytes_per_device = int(bytes_per_device)

    def _query(
        self, stats: Optional[Dict[str, Dict[str, int]]] = None
    ) -> Tuple[int, Optional[int], int]:
        """(max peak, max limit, live bytes) across local devices; zeros when
        the backend reports nothing (CPU builds). ``stats`` lets a caller that
        already holds a snapshot (Telemetry.memory_event) avoid a second
        allocator round trip — one query per window is the contract. An
        EMPTY snapshot falls through to ``stats_fn``: real backends with the
        query never produce one, and it keeps an injected stats_fn (tests)
        authoritative over a statless caller's probe."""
        if not stats:
            try:
                stats = self._stats_fn() or {}
            except Exception:  # noqa: BLE001 — a failed probe must not crash
                return 0, None, 0
        peak = peak_bytes_across_devices(stats)
        live = 0
        limit: Optional[int] = None
        for s in stats.values():
            live = max(live, int(s.get("bytes_in_use", 0)))
            if s.get("bytes_limit"):
                limit = max(limit or 0, int(s["bytes_limit"]))
        return peak, limit, live

    def sample(
        self,
        phase: str,
        step: Optional[int] = None,
        stats: Optional[Dict[str, Dict[str, int]]] = None,
    ) -> Optional[Dict]:
        """One allocator query attributed to ``phase`` (or zero queries when
        the caller passes its already-fetched ``stats``). Returns the
        ``memory_watermark`` event fields when the global peak advanced (or
        this phase records its first peak), None otherwise — including on
        backends with no allocator query at all."""
        peak, limit, live = self._query(stats)
        if peak <= 0:
            return None
        with self._lock:
            self.samples += 1
            if limit is not None:
                self.bytes_limit = limit
            self._history.append((time.monotonic(), peak))
            prev_global = self.peak_bytes
            advanced = peak > prev_global
            first_for_phase = phase not in self.phase_peaks
            if advanced:
                self.peak_bytes = peak
            if not (advanced or first_for_phase):
                return None
            self.phase_peaks[phase] = {
                "peak_bytes": peak,
                "step": step,
            }
            fields: Dict = {
                "phase": phase,
                "peak_bytes": peak,
                # only an ADVANCE owns new memory: a phase's first sample
                # under an existing (e.g. compile-time) peak records the
                # observation with delta 0 rather than claiming bytes some
                # earlier phase actually allocated
                "delta_bytes": peak - prev_global if advanced else 0,
                "advanced": advanced,
                "bytes_in_use": live,
            }
            if step is not None:
                fields["step"] = step
            if self.bytes_limit:
                fields["bytes_limit"] = self.bytes_limit
                fields["headroom_frac"] = round(
                    max(0.0, 1.0 - peak / self.bytes_limit), 4
                )
                slope = _trend_bytes_per_sample(list(self._history))
                if slope is not None and slope > 0:
                    fields["samples_to_limit"] = int(
                        (self.bytes_limit - peak) / slope
                    )
            if self.predicted_bytes_per_device:
                fields["predicted_bytes_per_device"] = (
                    self.predicted_bytes_per_device
                )
                fields["measured_minus_predicted_bytes"] = (
                    peak - self.predicted_bytes_per_device
                )
            return fields

    def headroom(self) -> Optional[Dict]:
        """Live headroom + trend: how much HBM is left and how fast the peak
        is moving. None until a device sample exists."""
        with self._lock:
            if not self.peak_bytes:
                return None
            out: Dict = {"peak_bytes": self.peak_bytes}
            if self.bytes_limit:
                out["bytes_limit"] = self.bytes_limit
                out["headroom_frac"] = round(
                    max(0.0, 1.0 - self.peak_bytes / self.bytes_limit), 4
                )
            history = list(self._history)
        slope = _trend_bytes_per_sample(history)
        if slope is not None:
            out["trend_bytes_per_sample"] = int(slope)
            if self.bytes_limit and slope > 0:
                out["samples_to_limit"] = int(
                    (self.bytes_limit - self.peak_bytes) / slope
                )
        return out

    def snapshot(self) -> Dict:
        """The /metrics view: per-phase peaks + the headroom estimate."""
        with self._lock:
            out: Dict = {
                "peak_bytes": self.peak_bytes,
                "phases": {
                    p: dict(v) for p, v in self.phase_peaks.items()
                },
            }
            if self.bytes_limit:
                out["bytes_limit"] = self.bytes_limit
            if self.predicted_bytes_per_device:
                out["predicted_bytes_per_device"] = (
                    self.predicted_bytes_per_device
                )
        hr = self.headroom()
        if hr:
            out["headroom"] = hr
        return out


class CostMeter:
    """Chip-seconds accounting for training windows and serving requests.

    One chip-second = one device busy for one second; a window's device time
    times the local chip count. Training: the window's ``compute_s`` span
    total IS the device-busy wall time (SPMD steps run every chip in
    lockstep), so ``chip_seconds = compute_s * n_chips``. Serving: each
    dispatched batch's engine time is split across its member requests by
    batch-share (a request with ``n_i`` of the batch's ``N`` examples owns
    ``n_i/N`` of the batch's chip-seconds) — padding waste is deliberately
    charged to the requests that rode the bucket, because the padded slots
    were burned on their behalf.
    """

    def __init__(self, n_chips: Optional[int] = None):
        # lazy: resolving the chip count touches the jax backend, which must
        # not happen at module import (NULL_TELEMETRY) or before the caller's
        # platform selection ran
        self._n_chips = n_chips
        self._lock = threading.Lock()
        self.chip_seconds_total = 0.0
        self.train_steps = 0
        self.train_examples = 0.0
        # per-request chip-second samples, drained per serving window
        self._request_hist = TimeHistogram("cost/chip_seconds_per_request")
        self._completed_requests = 0
        self._window_started_t = time.monotonic()
        self._window_chip_seconds = 0.0
        self._window_completed = 0

    @property
    def n_chips(self) -> int:
        if self._n_chips is None:
            self._n_chips = device_count()
        return self._n_chips

    # -- training ----------------------------------------------------------

    def train_window(
        self,
        compute_s: float,
        steps: int,
        *,
        examples: Optional[float] = None,
        step: Optional[int] = None,
    ) -> Optional[Dict]:
        """Account one training log window; returns the ``cost`` ledger event
        fields (None for an empty window)."""
        if compute_s <= 0 or steps <= 0:
            return None
        chip_s = compute_s * self.n_chips
        with self._lock:
            self.chip_seconds_total += chip_s
            self.train_steps += steps
            if examples:
                self.train_examples += examples
            total = self.chip_seconds_total
        fields: Dict = {
            "scope": "train",
            "n_chips": self.n_chips,
            "chip_seconds": round(chip_s, 6),
            "chip_seconds_total": round(total, 6),
            "chip_seconds_per_step": round(chip_s / steps, 6),
        }
        if step is not None:
            fields["step"] = step
        if examples:
            fields["examples"] = int(examples)
            fields["examples_per_chip_second"] = round(examples / chip_s, 2)
        return fields

    # -- serving -----------------------------------------------------------

    def add_batch(
        self, compute_s: float, request_examples: Sequence[int]
    ) -> None:
        """Attribute one dispatched batch's device time to its member
        requests by batch-share. Called from the batcher worker — one
        histogram record per request, no allocation beyond that."""
        total = sum(request_examples)
        if compute_s <= 0 or total <= 0:
            return
        chip_s = compute_s * self.n_chips
        with self._lock:
            self.chip_seconds_total += chip_s
            self._window_chip_seconds += chip_s
            self._window_completed += len(request_examples)
            self._completed_requests += len(request_examples)
        for n in request_examples:
            self._request_hist.record(chip_s * n / total)

    def serve_window(self) -> Optional[Dict]:
        """Drain one serving window: the ``cost`` ledger event fields —
        window + cumulative chip-seconds, ``rps_per_chip``, per-request
        chip-second percentiles, and the duty cycle (fraction of the fleet's
        chip capacity the window actually used). None for an idle window."""
        samples = self._request_hist.drain()
        with self._lock:
            now = time.monotonic()
            window_s = max(now - self._window_started_t, 1e-9)
            chip_s = self._window_chip_seconds
            completed = self._window_completed
            total = self.chip_seconds_total
            self._window_started_t = now
            self._window_chip_seconds = 0.0
            self._window_completed = 0
        if not completed:
            return None
        fields: Dict = {
            "scope": "serve",
            "n_chips": self.n_chips,
            "window_s": round(window_s, 3),
            "chip_seconds": round(chip_s, 6),
            "chip_seconds_total": round(total, 6),
            "requests": completed,
            "rps_per_chip": round(completed / window_s / self.n_chips, 3),
            # chip-seconds the window burned / chip-seconds it had: <1 means
            # idle capacity, the autoscale-down signal of the cost view
            "duty_cycle": round(chip_s / (window_s * self.n_chips), 4),
        }
        if samples:
            import numpy as np

            arr = np.asarray(list(samples), np.float64)
            count = window_count(samples)
            total_s = window_total_s(samples)
            fields["chip_seconds_per_request"] = {
                "mean": round(total_s / max(count, 1), 9),
                "p50": round(float(np.percentile(arr, 50)), 9),
                "p90": round(float(np.percentile(arr, 90)), 9),
                "p99": round(float(np.percentile(arr, 99)), 9),
            }
        return fields

    def snapshot(self) -> Dict:
        """The /metrics view (cumulative; rates belong to windows)."""
        with self._lock:
            out = {
                "n_chips": self.n_chips,
                "chip_seconds_total": round(self.chip_seconds_total, 6),
            }
            if self.train_steps:
                out["train_steps"] = self.train_steps
                out["chip_seconds_per_step"] = round(
                    self.chip_seconds_total / self.train_steps, 6
                )
            if self._completed_requests:
                out["completed_requests"] = self._completed_requests
        return out


def aggregate_cost_events(events: List[Dict]) -> Optional[Dict]:
    """Report-side aggregation of a ledger's ``cost`` events: one dict with
    ``train`` / ``serve`` sub-sections (stable keys — the ``telemetry-report
    --json`` schema). None when the run ledgered no cost."""
    cost = [e for e in events if e.get("event") == COST_EVENT]
    if not cost:
        return None
    out: Dict = {"events": len(cost)}
    train = [e for e in cost if e.get("scope") == "train"]
    serve = [e for e in cost if e.get("scope") == "serve"]
    if train:
        last = train[-1]
        total_chip_s = last.get("chip_seconds_total", 0.0)
        steps = sum(
            e.get("chip_seconds", 0.0) / e["chip_seconds_per_step"]
            for e in train
            if e.get("chip_seconds_per_step")
        )
        section: Dict = {
            "n_chips": last.get("n_chips"),
            "chip_seconds_total": round(total_chip_s, 3),
        }
        if steps:
            section["chip_seconds_per_step"] = round(
                sum(e.get("chip_seconds", 0.0) for e in train) / steps, 6
            )
        examples = sum(e.get("examples", 0) for e in train)
        window_chip_s = sum(e.get("chip_seconds", 0.0) for e in train)
        if examples and window_chip_s:
            section["examples_per_chip_second"] = round(
                examples / window_chip_s, 2
            )
        out["train"] = section
    if serve:
        last = serve[-1]
        window_s = sum(e.get("window_s", 0.0) for e in serve)
        requests = sum(e.get("requests", 0) for e in serve)
        n_chips = last.get("n_chips") or 1
        section = {
            "n_chips": n_chips,
            "chip_seconds_total": round(
                last.get("chip_seconds_total", 0.0), 3
            ),
            "requests": requests,
        }
        if window_s:
            section["rps_per_chip"] = round(
                requests / window_s / n_chips, 3
            )
            section["duty_cycle"] = round(
                sum(e.get("chip_seconds", 0.0) for e in serve)
                / (window_s * n_chips),
                4,
            )
        per_req = [
            e["chip_seconds_per_request"]
            for e in serve
            if "chip_seconds_per_request" in e
        ]
        if per_req:
            weights = [e.get("requests", 1) for e in serve if "chip_seconds_per_request" in e]
            total_w = sum(weights) or 1

            def merged(key: str) -> float:
                return sum(
                    s[key] * w for s, w in zip(per_req, weights)
                ) / total_w

            section["chip_seconds_per_request"] = {
                "mean": round(merged("mean"), 9),
                "p50": round(merged("p50"), 9),
                "p90": round(merged("p90"), 9),
                # percentile merging across windows is approximate everywhere
                # else in the report (step_time_ms) — worst window for p99
                "p99_worst_window": round(max(s["p99"] for s in per_req), 9),
            }
        out["serve"] = section
    return out


def aggregate_watermark_events(events: List[Dict]) -> Optional[Dict]:
    """Report-side aggregation of ``memory_watermark`` events: per-phase
    final peaks, the global peak, and the last measured-vs-predicted delta.
    None when the run ledgered no watermarks (CPU backends)."""
    marks = [e for e in events if e.get("event") == WATERMARK_EVENT]
    if not marks:
        return None
    phases: Dict[str, Dict] = {}
    for e in marks:
        phase = e.get("phase", "unknown")
        row = {"peak_bytes": e.get("peak_bytes", 0)}
        if e.get("step") is not None:
            row["step"] = e["step"]
        phases[phase] = row  # last write wins: the phase's final watermark
    last = marks[-1]
    out: Dict = {
        "events": len(marks),
        "peak_bytes": max(e.get("peak_bytes", 0) for e in marks),
        "phases": phases,
    }
    for key in (
        "bytes_limit",
        "headroom_frac",
        "predicted_bytes_per_device",
        "measured_minus_predicted_bytes",
    ):
        if last.get(key) is not None:
            out[key] = last[key]
    return out
