"""Fleet aggregation: merge per-process run ledgers into one cross-host view.

One process = one ledger (``obs.ledger.per_process_filename``: process 0 keeps
the canonical ``telemetry.jsonl``, process i>0 writes ``telemetry-{i}.jsonl``
beside it). A pod-scale run therefore leaves N files in one workdir — and the
pjit scaling methodology this repo follows (arXiv:2204.06514) operates on the
SLICE, not a process: per-host step-time skew is the straggler signal, and
per-host barrier wait is how "slow host" is told apart from "slow network".

This module is read-side only (report time, no training-path cost):

- :func:`discover_ledgers` — find + parse every per-process ledger under a
  workdir (each scoped to its last run, parse errors counted, sorted by
  process index);
- :func:`straggler_section` — per-window max/median step-time skew across
  hosts, worst-host attribution, and ``straggler_alert`` entries for windows
  past a configurable skew threshold (the same shape as ``health_alert``
  events, so downstream tooling treats them uniformly);
- :func:`fleet_section` — the merged report section: per-host goodput splits
  (data-wait / compute / fetch-wait / barrier-wait), per-host serving totals
  (keyed by the replica id ``serve_window`` events carry), the straggler
  analysis, and the slow-host-vs-slow-network hint;
- :func:`fleet_summary` — standalone merge for non-report callers
  (``tools/run_suite.py --aggregate``).

``obs.report.build_report`` calls into here automatically: a workdir with one
ledger renders exactly as before; a workdir with several gains a ``fleet``
section.
"""

from __future__ import annotations

import dataclasses
import glob
import os
import re
import statistics
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.obs.ledger import (
    LEDGER_FILENAME,
    last_run_events,
    read_ledger_with_errors,
)

# windows needing at least this much skew before a straggler_alert fires;
# 1.25 = the slowest host runs 25% over the fleet median, which on a
# synchronous SPMD step is 25% of every chip's time burned waiting
DEFAULT_SKEW_THRESHOLD = 1.25

_SECONDARY_LEDGER_RE = re.compile(r"telemetry-(\d+)\.jsonl$")

STRAGGLER_ALERT_EVENT = "straggler_alert"


@dataclasses.dataclass
class ProcessLedger:
    """One process's parsed ledger. ``events`` is scoped to the LAST run
    (what every fleet aggregation reads); ``all_events`` keeps the whole
    appended history for readers with cross-run scope (the report's
    resilience section) — same parsed objects, no second file read."""

    process_index: int
    path: str
    events: List[Dict]
    all_events: List[Dict]
    parse_errors: int

    @property
    def header(self) -> Dict:
        if self.events and self.events[0].get("event") == "run_header":
            return self.events[0]
        return {}


def discover_ledgers(workdir: str) -> List[ProcessLedger]:
    """Every per-process ledger under ``workdir``, sorted by process index.

    ``telemetry.jsonl`` is process 0 (headers that carry an explicit
    ``process_index`` win over the filename); ``telemetry-{i}.jsonl`` is
    process i. Unreadable files are skipped (a dead NFS mount on one host
    must not take down the whole fleet's report); an empty list means the
    workdir holds no ledger at all."""
    ledgers: List[ProcessLedger] = []
    candidates = []
    canonical = os.path.join(workdir, LEDGER_FILENAME)
    if os.path.isfile(canonical):
        candidates.append((0, canonical))
    for path in sorted(glob.glob(os.path.join(workdir, "telemetry-*.jsonl"))):
        m = _SECONDARY_LEDGER_RE.search(os.path.basename(path))
        if m:
            candidates.append((int(m.group(1)), path))
    for index, path in candidates:
        try:
            all_events, errors = read_ledger_with_errors(path)
        except OSError:
            continue
        events = last_run_events(all_events)
        header = (
            events[0]
            if events and events[0].get("event") == "run_header"
            else {}
        )
        ledgers.append(
            ProcessLedger(
                process_index=int(header.get("process_index", index)),
                path=path,
                events=events,
                all_events=all_events,
                parse_errors=errors,
            )
        )
    ledgers.sort(key=lambda led: led.process_index)
    return ledgers


def _windows(ledger: ProcessLedger) -> List[Dict]:
    return [e for e in ledger.events if e.get("event") == "step_window"]


def _weighted_mean_ms(windows: List[Dict]) -> Optional[float]:
    pairs = [
        (e["step_time_ms"]["mean_ms"], float(e.get("steps", 1)))
        for e in windows
        if "step_time_ms" in e
    ]
    total = sum(w for _, w in pairs)
    if not total:
        return None
    return sum(v * w for v, w in pairs) / total


def straggler_section(
    ledgers: List[ProcessLedger],
    *,
    skew_threshold: float = DEFAULT_SKEW_THRESHOLD,
    max_alerts: int = 20,
) -> Optional[Dict]:
    """Cross-host step-time skew, window by window.

    Windows are aligned by their ``step`` field (every host logs the same
    boundaries — the loop structure is SPMD); for each step present on >= 2
    hosts, skew = max(mean step time) / median(mean step time) over hosts.
    Past ``skew_threshold`` the window contributes a ``straggler_alert``
    naming the worst host. None when fewer than two hosts have comparable
    windows."""
    per_host: Dict[int, Dict[int, float]] = {}
    for led in ledgers:
        by_step = {
            int(e["step"]): e["step_time_ms"]["mean_ms"]
            for e in _windows(led)
            if "step_time_ms" in e and "step" in e
        }
        if by_step:
            per_host[led.process_index] = by_step
    if len(per_host) < 2:
        return None
    shared_steps = sorted(
        set.intersection(*(set(m) for m in per_host.values()))
    )
    if not shared_steps:
        return None
    alerts: List[Dict] = []
    skews: List[float] = []
    worst_counts: Dict[int, int] = {}
    for step in shared_steps:
        values = {proc: per_host[proc][step] for proc in per_host}
        med = statistics.median(values.values())
        if med <= 0:
            continue
        worst_proc = max(values, key=lambda p: values[p])
        skew = values[worst_proc] / med
        skews.append(skew)
        worst_counts[worst_proc] = worst_counts.get(worst_proc, 0) + 1
        if skew > skew_threshold:
            alerts.append(
                {
                    "event": STRAGGLER_ALERT_EVENT,
                    "severity": "warn",
                    "step": step,
                    "skew": round(skew, 3),
                    "worst_process": worst_proc,
                    "worst_ms": round(values[worst_proc], 3),
                    "median_ms": round(med, 3),
                }
            )
    if not skews:
        return None
    # the host named by the section: most-often-slowest among ALERTED windows
    # when any fired (that is the straggler); most-often-slowest overall
    # otherwise (informational — nobody crossed the threshold)
    if alerts:
        attributed: Dict[int, int] = {}
        for a in alerts:
            attributed[a["worst_process"]] = (
                attributed.get(a["worst_process"], 0) + 1
            )
        worst_process = max(attributed, key=lambda p: attributed[p])
    else:
        worst_process = max(worst_counts, key=lambda p: worst_counts[p])
    return {
        "windows_compared": len(skews),
        "skew_threshold": skew_threshold,
        "max_skew": round(max(skews), 3),
        "median_skew": round(statistics.median(skews), 3),
        "worst_process": worst_process,
        "worst_window_counts": {
            str(p): n for p, n in sorted(worst_counts.items())
        },
        "alert_count": len(alerts),
        "alerts": alerts[:max_alerts],
    }


def _process_row(led: ProcessLedger) -> Dict:
    """One per-host summary row of the fleet section."""
    windows = _windows(led)
    header = led.header
    serve_windows = [
        e for e in led.events if e.get("event") == "serve_window"
    ]
    row: Dict = {
        "process_index": led.process_index,
        "ledger": os.path.basename(led.path),
        "parse_errors": led.parse_errors,
        "kind": header.get("kind") or header.get("task") or "unknown",
        "windows": len(windows),
        "last_step": windows[-1].get("step") if windows else None,
        "data_wait_s": round(
            sum(e.get("data_wait_s", 0.0) for e in windows), 3
        ),
        "compute_s": round(sum(e.get("compute_s", 0.0) for e in windows), 3),
        "fetch_wait_s": round(
            sum(e.get("fetch_wait_s", 0.0) for e in windows), 3
        ),
        "barrier_wait_s": round(
            sum(e.get("barrier_wait_s", 0.0) for e in windows), 3
        ),
    }
    mean_ms = _weighted_mean_ms(windows)
    if mean_ms is not None:
        row["step_time_mean_ms"] = round(mean_ms, 3)
    # per-host MFU (steps-weighted over clean windows): a host whose MFU sits
    # below the fleet's is burning its FLOPs somewhere — the roofline capture
    # says where. Absent when the backend has no peak-FLOPs entry (CPU).
    mfu_pairs = [
        (float(e["mfu"]), float(e.get("steps", 1)))
        for e in windows
        if e.get("mfu") is not None and not e.get("dirty")
    ]
    if mfu_pairs:
        total_w = sum(w for _, w in mfu_pairs)
        if total_w:
            row["mfu"] = round(
                sum(v * w for v, w in mfu_pairs) / total_w, 4
            )
    fp = header.get("fingerprint") or {}
    if fp and "error" not in fp:
        row["device_kind"] = fp.get("device_kind")
    # capacity/cost accounting per process (obs/capacity.py): cumulative
    # chip-seconds, per-chip request rate, and the HBM watermark — the
    # per-host halves of the fleet-wide cost/headroom aggregates
    from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib

    cost = capacity_lib.aggregate_cost_events(led.events)
    if cost:
        cost_row: Dict = {}
        for scope in ("train", "serve"):
            section = cost.get(scope)
            if not section:
                continue
            cost_row["n_chips"] = section.get("n_chips")
            cost_row["chip_seconds_total"] = section.get("chip_seconds_total")
            if scope == "serve" and section.get("rps_per_chip") is not None:
                cost_row["rps_per_chip"] = section["rps_per_chip"]
            if scope == "serve" and section.get("chip_seconds_per_request"):
                cost_row["chip_seconds_per_request"] = section[
                    "chip_seconds_per_request"
                ]
                cost_row["requests"] = section.get("requests")
            if scope == "train" and section.get("chip_seconds_per_step") is not None:
                cost_row["chip_seconds_per_step"] = section[
                    "chip_seconds_per_step"
                ]
        if cost_row:
            row["cost"] = cost_row
    marks = capacity_lib.aggregate_watermark_events(led.events)
    if marks:
        mem_row: Dict = {"peak_bytes": marks["peak_bytes"]}
        if marks.get("headroom_frac") is not None:
            mem_row["headroom_frac"] = marks["headroom_frac"]
        row["memory"] = mem_row
    if serve_windows:
        last = serve_windows[-1]
        serve: Dict = {
            "windows": len(serve_windows),
            "requests": last.get("requests", 0),
            "completed": last.get("completed", 0),
            "rejected_queue_full": last.get("rejected_queue_full", 0),
        }
        if last.get("replica") is not None:
            serve["replica"] = last["replica"]
        # multi-tenant attribution: a model-bound replica stamps its model
        # (and registry version) on every window; a replica mounting several
        # models carries a per-model sub-dict instead
        if last.get("model") is not None:
            serve["model"] = last["model"]
            if last.get("model_version") is not None:
                serve["model_version"] = last["model_version"]
        models = last.get("models")
        if isinstance(models, dict):
            serve["models"] = {
                name: {
                    "version": mrow.get("version"),
                    "requests": mrow.get("requests", 0),
                    "completed": mrow.get("completed", 0),
                    "p99_ms": (
                        (mrow.get("latency_ms") or {}).get("request") or {}
                    ).get("p99_ms"),
                }
                for name, mrow in models.items()
            }
        p99s = [
            e["latency_ms"]["request"]["p99_ms"]
            for e in serve_windows
            if "request" in e.get("latency_ms", {})
        ]
        if p99s:
            serve["request_p99_worst_window_ms"] = round(max(p99s), 3)
        row["serve"] = serve
    return row


def _attribution_hint(
    rows: List[Dict], straggler: Optional[Dict]
) -> Optional[str]:
    """Slow host or slow network? On a synchronous fleet the straggler
    arrives at barriers LAST and so waits least; if the named worst host also
    has the minimum barrier wait, the skew is that host's own step time (slow
    host). Roughly equal barrier waits with high collective time in the
    xplane buckets point at the interconnect instead."""
    if not straggler or not straggler["alert_count"]:
        return None
    waits = {
        r["process_index"]: r["barrier_wait_s"]
        for r in rows
        if r.get("windows")
    }
    if len(waits) < 2 or not any(waits.values()):
        return None
    worst = straggler["worst_process"]
    if worst in waits and waits[worst] == min(waits.values()):
        return (
            f"process {worst} waits least at barriers while running the "
            "slowest steps — a slow HOST, not a slow network"
        )
    return (
        "barrier waits do not single out the slow host — check the trace "
        "section's collectives bucket for network time"
    )


def fleet_section(
    workdir: str,
    *,
    ledgers: Optional[List[ProcessLedger]] = None,
    skew_threshold: float = DEFAULT_SKEW_THRESHOLD,
) -> Optional[Dict]:
    """The merged report's ``fleet`` section; None for single-ledger
    workdirs (the overwhelmingly common case costs one glob)."""
    if ledgers is None:
        ledgers = discover_ledgers(workdir)
    if len(ledgers) < 2:
        return None
    rows = [_process_row(led) for led in ledgers]
    section: Dict = {
        "processes": len(ledgers),
        "ledger_parse_errors": sum(led.parse_errors for led in ledgers),
        "per_process": rows,
    }
    # fleet-wide cost/capacity rollup: total chip-seconds across every
    # process, summed per-chip request rate (the Gemma-on-TPU cost-per-qps
    # lens at fleet scale), and the tightest replica's headroom
    chip_s = [r["cost"]["chip_seconds_total"] for r in rows if r.get("cost")]
    rps = [
        r["cost"]["rps_per_chip"]
        for r in rows
        if r.get("cost", {}).get("rps_per_chip") is not None
    ]
    headrooms = [
        r["memory"]["headroom_frac"]
        for r in rows
        if r.get("memory", {}).get("headroom_frac") is not None
    ]
    if chip_s or rps or headrooms:
        rollup: Dict = {}
        if chip_s:
            rollup["chip_seconds_total"] = round(sum(chip_s), 3)
        if rps:
            rollup["rps_per_chip_total"] = round(sum(rps), 3)
        if headrooms:
            rollup["min_headroom_frac"] = min(headrooms)
        # fleet-wide chip-seconds/request: request-count-weighted merge of
        # the replicas' percentiles (worst replica for p99 — the same
        # approximate merge every other cross-window percentile uses)
        per_req = [
            (r["cost"]["chip_seconds_per_request"], r["cost"].get("requests") or 1)
            for r in rows
            if r.get("cost", {}).get("chip_seconds_per_request")
        ]
        if per_req:
            total_w = sum(w for _, w in per_req)
            rollup["chip_seconds_per_request"] = {
                key: round(
                    sum(s[key] * w for s, w in per_req) / total_w, 9
                )
                for key in ("mean", "p50", "p90")
            }
            rollup["chip_seconds_per_request"]["p99_worst_replica"] = round(
                max(
                    s.get("p99_worst_window", s.get("p99", 0.0))
                    for s, _ in per_req
                ),
                9,
            )
        section["capacity"] = rollup
    # fleet MFU rollup: min + median across hosts. A host whose MFU trails
    # the fleet median is a straggler signal ORTHOGONAL to step-time skew —
    # on a synchronous fleet steps finish together, so a slow host shows up
    # as everyone's lower MFU, but a host burning time off the device (input
    # stalls, host-side work) shows a LOWER OWN MFU at the same step time.
    mfus = sorted(
        (r["process_index"], r["mfu"]) for r in rows if r.get("mfu") is not None
    )
    if mfus:
        vals = sorted(v for _, v in mfus)
        mid = len(vals) // 2
        median = (
            vals[mid]
            if len(vals) % 2
            else (vals[mid - 1] + vals[mid]) / 2.0
        )
        worst = min(mfus, key=lambda pair: pair[1])
        section["mfu"] = {
            "hosts": len(mfus),
            "min": round(min(vals), 4),
            "median": round(median, 4),
            "min_process": worst[0],
        }
    # per-model serving rollup across the fleet: replica count, completed
    # totals, worst replica p99 per tenant (both attribution shapes merge —
    # single-model replicas' top-level stamp and multi-mount sub-dicts)
    model_totals: Dict[str, Dict] = {}
    for r in rows:
        sv = r.get("serve")
        if not sv:
            continue
        per = sv.get("models")
        if not per and sv.get("model"):
            per = {
                sv["model"]: {
                    "version": sv.get("model_version"),
                    "requests": sv.get("requests", 0),
                    "completed": sv.get("completed", 0),
                    "p99_ms": sv.get("request_p99_worst_window_ms"),
                }
            }
        if not per:
            continue
        for name, mrow in per.items():
            agg = model_totals.setdefault(
                name,
                {
                    "replicas": 0,
                    "requests": 0,
                    "completed": 0,
                    "worst_p99_ms": None,
                    "versions": {},
                },
            )
            agg["replicas"] += 1
            agg["requests"] += int(mrow.get("requests") or 0)
            agg["completed"] += int(mrow.get("completed") or 0)
            p99 = mrow.get("p99_ms")
            if p99 is not None:
                agg["worst_p99_ms"] = max(
                    agg["worst_p99_ms"] or 0.0, float(p99)
                )
            if mrow.get("version") is not None:
                key = str(mrow["version"])
                agg["versions"][key] = agg["versions"].get(key, 0) + 1
    if model_totals:
        section["models"] = model_totals
    straggler = straggler_section(ledgers, skew_threshold=skew_threshold)
    if straggler:
        section["straggler"] = straggler
        hint = _attribution_hint(rows, straggler)
        if hint:
            section["attribution_hint"] = hint
    return section


def fleet_summary(workdir: str, **kwargs) -> Dict:
    """Standalone merge (``run_suite --aggregate``, ad-hoc tooling): like
    :func:`fleet_section` but meaningful for ANY ledger count — a dict with
    ``processes`` 0 (nothing found), 1, or the full merged section."""
    ledgers = discover_ledgers(workdir)
    if not ledgers:
        return {"processes": 0, "per_process": [], "ledger_parse_errors": 0}
    section = fleet_section(workdir, ledgers=ledgers, **kwargs)
    if section is None:
        section = {
            "processes": 1,
            "ledger_parse_errors": ledgers[0].parse_errors,
            "per_process": [_process_row(ledgers[0])],
        }
    return section


def render_fleet_section(section: Dict) -> List[str]:
    """Text lines for ``obs.report.render_report``."""
    lines = [f"\nfleet: {section['processes']} process ledgers merged"]
    if section.get("ledger_parse_errors"):
        lines.append(
            f"  !! {section['ledger_parse_errors']} unparseable ledger "
            "line(s) dropped across the fleet (torn writes?)"
        )
    for row in section["per_process"]:
        parts = [
            f"  p{row['process_index']} [{row['kind']}]",
            f"{row['windows']} window(s)",
        ]
        if row.get("step_time_mean_ms") is not None:
            parts.append(f"step {row['step_time_mean_ms']:.2f}ms")
        if row.get("mfu") is not None:
            parts.append(f"mfu {row['mfu']:.1%}")
        parts.append(
            f"wait/compute/fetch/barrier "
            f"{row['data_wait_s']:.2f}/{row['compute_s']:.2f}/"
            f"{row['fetch_wait_s']:.2f}/{row['barrier_wait_s']:.2f}s"
        )
        if row.get("serve"):
            sv = row["serve"]
            replica = (
                f" replica {sv['replica']}" if "replica" in sv else ""
            )
            model = f"[{sv['model']}]" if sv.get("model") else ""
            parts.append(
                f"serve{model}{replica}: {sv['completed']}/{sv['requests']} ok"
            )
        if row.get("cost", {}).get("rps_per_chip") is not None:
            parts.append(f"{row['cost']['rps_per_chip']:.1f} rps/chip")
        if row.get("memory", {}).get("headroom_frac") is not None:
            parts.append(
                f"headroom {row['memory']['headroom_frac']:.1%}"
            )
        if row.get("parse_errors"):
            parts.append(f"!! {row['parse_errors']} parse error(s)")
        lines.append("  ".join(parts))
    cap = section.get("capacity")
    if cap:
        parts = []
        if cap.get("chip_seconds_total") is not None:
            parts.append(f"{cap['chip_seconds_total']:.1f} chip-seconds total")
        if cap.get("rps_per_chip_total") is not None:
            parts.append(
                f"{cap['rps_per_chip_total']:.1f} rps/chip fleet-wide"
            )
        if cap.get("min_headroom_frac") is not None:
            parts.append(
                f"min HBM headroom {cap['min_headroom_frac']:.1%}"
            )
        lines.append("  capacity: " + ", ".join(parts))
        pr = cap.get("chip_seconds_per_request")
        if pr:
            lines.append(
                "    chip-ms/request: "
                f"mean {pr['mean'] * 1000:.3f}  p50 {pr['p50'] * 1000:.3f}  "
                f"p90 {pr['p90'] * 1000:.3f}  "
                f"p99(worst replica) {pr['p99_worst_replica'] * 1000:.3f}"
            )
    models = section.get("models")
    if models:
        lines.append("  models:")
        for name, m in models.items():
            line = (
                f"    {name}: {m['replicas']} replica(s), "
                f"{m['completed']}/{m['requests']} ok"
            )
            if m.get("worst_p99_ms") is not None:
                line += f", worst p99 {m['worst_p99_ms']:.1f}ms"
            if m.get("versions"):
                vers = "/".join(sorted(m["versions"]))
                line += f", v{vers}"
                if len(m["versions"]) > 1:
                    line += " (mixed — promotion in flight?)"
            lines.append(line)
    fleet_mfu = section.get("mfu")
    if fleet_mfu:
        line = (
            f"  mfu: min {fleet_mfu['min']:.1%} "
            f"(p{fleet_mfu['min_process']}), "
            f"median {fleet_mfu['median']:.1%} over {fleet_mfu['hosts']} "
            "host(s)"
        )
        if fleet_mfu["min"] < 0.8 * fleet_mfu["median"]:
            line += (
                f" — !! p{fleet_mfu['min_process']} trails the fleet (host-"
                "side stall? capture a roofline with --profile-every-windows)"
            )
        lines.append(line)
    st = section.get("straggler")
    if st:
        lines.append(
            f"  straggler: max skew {st['max_skew']:.2f}x over "
            f"{st['windows_compared']} comparable window(s) "
            f"(threshold {st['skew_threshold']:.2f}x)"
        )
        if st["alert_count"]:
            lines.append(
                f"  !! {st['alert_count']} straggler_alert(s) — worst host: "
                f"process {st['worst_process']}"
            )
            for a in st["alerts"][:3]:
                lines.append(
                    f"     - step {a['step']}: p{a['worst_process']} at "
                    f"{a['worst_ms']:.1f}ms vs median {a['median_ms']:.1f}ms "
                    f"({a['skew']:.2f}x)"
                )
        else:
            lines.append("  no straggler alerts (skew within threshold)")
    if section.get("attribution_hint"):
        lines.append(f"  hint: {section['attribution_hint']}")
    return lines
