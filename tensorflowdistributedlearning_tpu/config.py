"""Typed configuration for the framework.

The reference exposed its knobs as module constants plus ad-hoc ``**kwargs`` plumbing in
``Model.__init__`` (reference: model.py:13-24, 63-106). Here the same knob set is a pair of
frozen dataclasses so configs are explicit, hashable (usable as jit static args), and
serializable. The reference's ``batch_norm_decay`` copy-paste bug (it read
``kwargs["weight_decay"]``, reference: model.py:69) is intentionally NOT reproduced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters.

    Defaults mirror the reference's module constants (reference: model.py:13-24) and
    ``Model.__init__`` fallbacks (reference: model.py:63-106).
    """

    backbone: str = "resnet"  # "resnet" | "xception"
    # l2 regularisation (reference: model.py:14 WEIGHT_DECAY = 0.001)
    weight_decay: float = 0.001
    # batch norm (reference: model.py:16-18)
    batch_norm_decay: float = 0.99
    batch_norm_epsilon: float = 0.001
    batch_norm_scale: bool = True
    # atrous output stride (reference: model.py:20 OUTPUT_STRIDE = 8)
    output_stride: int = 8
    # spatial input shape, channels excluded (reference: model.py:22 INPUT_SHAPE)
    input_shape: Tuple[int, int] = (101, 101)
    # input channels: image + Laplacian channel (reference: preprocessing.py:243)
    input_channels: int = 2
    # deepest residual stage width (reference: model.py:24 BASE_DEPTH = 256)
    base_depth: int = 256
    # residual units per stage before the atrous stage (reference: model.py:101-103)
    n_blocks: Tuple[int, ...] = (3, 4, 6)
    # residual-stage width family (backbone="resnet"): "reference" keeps the
    # reference's doubled stage widths — bottleneck 128/256/512 plus the
    # 1024-wide atrous multi-grid stage (reference: core/resnet.py:330-344),
    # ~3x the FLOPs of the standard model; "classic" is the standard
    # ResNet-50/101/152 ladder (bottleneck 64/128/256/512, four plain stages,
    # stride-32, no atrous stage) — the apples-to-apples architecture for
    # ImageNet benchmarks quoted against published ResNet-50 numbers. With
    # "classic", n_blocks has length 4 (e.g. (3, 4, 6, 3) = ResNet-50).
    block_layout: str = "reference"
    # "bottleneck" | "basic_block" (reference: model.py:104-106)
    block_type: str = "bottleneck"
    # Classification-path knobs (reference: core/resnet.py:246-256 kept a num_classes /
    # global_pool path alongside segmentation); None means segmentation head.
    num_classes: Optional[int] = None
    # compute dtype: params stay float32, activations/matmuls run in this dtype. TPU MXU
    # natively prefers bfloat16 — this is a TPU-first knob the reference had no analogue of.
    dtype: str = "float32"
    # route the ASPP's atrous depthwise convs through the Pallas VMEM kernel
    # (ops/pallas_kernels.py) instead of XLA's grouped conv; parameter trees are
    # identical between the two paths, so this is a pure execution-path switch.
    # Default OFF on STEP-LEVEL evidence (2026-08-01 v5e A/B, bf16 flagship,
    # best-of-3 40-step windows): pure XLA 37.95 ms/step vs 41.03 (Pallas at
    # rates >= 4, the old gate) vs 41.36 (all rates). The standalone kernel
    # genuinely beats XLA's grouped conv 1.46-1.61x per kernel
    # (bench_kernels.py, device-dominated protocol) — but inside the real
    # step XLA fuses depthwise+BN+ReLU chains, and the custom call forces
    # materialization that costs more than the kernel saves. The flag stays
    # for non-fused contexts; the dispatch remains rate- and platform-aware
    # (models/layers.py:DepthwiseConv2D).
    use_pallas_depthwise: bool = False
    # rematerialize residual units on the backward pass (jax.checkpoint): trades
    # recompute FLOPs for activation HBM — enables large per-chip batches.
    remat: bool = False
    # execute the root 3x3 stride-2 conv as a 2x2 conv on the
    # space-to-depth(2) input transform (models/layers.py:SpaceToDepthConv) —
    # numerically identical, but the MXU contracts over 4x the input channels
    # (12 vs 3 for RGB), the standard TPU stem trick. resnet/xception only;
    # requires even input dims; checkpoint-compatible with the plain stem
    # (the canonical 3x3 kernel is the stored parameter either way).
    stem_space_to_depth: bool = False
    # uniform channel-width scale for every backbone stage (root convs, residual
    # stages, Xception flows, ViT embed dim). 1.0 keeps the reference widths
    # (core/resnet.py:333-344, core/xception.py:405-465); fractional values give
    # width-scaled variants (Wide-ResNet-style scaling, and the knob that makes
    # tiny CI models actually tiny — the stage widths are otherwise fixed
    # constants).
    width_multiplier: float = 1.0
    # ViT family knobs (backbone="vit" — beyond-parity: the transformer
    # classifier that consumes parallel/ring_attention.py under sequence
    # parallelism; defaults are ViT-S/16).
    patch_size: int = 16
    embed_dim: int = 384
    vit_layers: int = 12
    num_heads: int = 6
    mlp_ratio: float = 4.0
    # route ViT attention through the fused Pallas block-attention kernel
    # (ops/flash_attention.py) instead of the XLA einsum path; parameter trees
    # are identical, so this is a pure execution-path switch. Ignored (with a
    # warning) under sequence_parallel>1, where the ring formulation owns the
    # attention math.
    use_fused_attention: bool = False
    # Switch-style mixture-of-experts (arXiv:2101.03961): every OTHER ViT
    # block's FFN becomes a top-1-routed MoE with this many experts (0 = dense;
    # backbone="vit" only). Trains with the load-balancing auxiliary loss on
    # any mesh (all experts local); TrainConfig.expert_parallel places one
    # expert per shard with all-to-all dispatch (parallel/expert.py).
    moe_experts: int = 0
    # per-expert capacity = ceil(tokens/E * factor); beyond-capacity tokens
    # pass through the residual (the standard fixed-shape trade)
    moe_capacity_factor: float = 1.25
    # weight of the sown load-balancing loss in the training objective (the
    # Switch paper's alpha = 0.01)
    moe_aux_weight: float = 0.01

    def __post_init__(self):
        if self.backbone not in ("resnet", "xception", "vit"):
            raise ValueError(f"Unknown backbone {self.backbone!r}")
        if self.block_type not in ("bottleneck", "basic_block"):
            raise ValueError(f"Unknown block type {self.block_type!r}")
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError(f"Unknown dtype {self.dtype!r}")
        if self.block_layout not in ("reference", "classic"):
            raise ValueError(f"Unknown block_layout {self.block_layout!r}")
        if self.block_layout == "classic":
            if self.backbone != "resnet":
                raise ValueError("block_layout='classic' applies to backbone='resnet' only")
            if len(self.n_blocks) != 4:
                raise ValueError(
                    "block_layout='classic' expects n_blocks of length 4, "
                    f"e.g. (3, 4, 6, 3) for ResNet-50; got {self.n_blocks}"
                )
        if self.width_multiplier <= 0:
            raise ValueError("width_multiplier must be positive")
        if self.stem_space_to_depth:
            if self.backbone == "vit":
                raise ValueError(
                    "stem_space_to_depth applies to conv stems "
                    "(backbone='resnet'/'xception'); ViT patchification already "
                    "folds pixels into the contraction"
                )
            if self.input_shape[0] % 2 or self.input_shape[1] % 2:
                raise ValueError(
                    "stem_space_to_depth needs even input dims, got "
                    f"{self.input_shape}"
                )
        if self.moe_experts < 0:
            raise ValueError(f"moe_experts must be >= 0, got {self.moe_experts}")
        if self.moe_experts:
            if self.backbone != "vit":
                raise ValueError(
                    "moe_experts requires backbone='vit' (the MoE FFN replaces "
                    "transformer-block MLPs)"
                )
            if self.vit_layers < 2:
                raise ValueError(
                    "moe_experts needs vit_layers >= 2 (every OTHER block is "
                    "MoE; a 1-layer stack would have none)"
                )


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training-loop hyperparameters.

    Defaults mirror the reference's ``Model.__init__`` signature (reference:
    model.py:29-37) and its train-step constants: Adam with exponential decay — half the
    lr every 10 000 steps (reference: model.py:457-462), checkpoints every 500 steps
    (reference: model.py:118), eval throttled to >= 300 s (reference: model.py:214).
    """

    # "NHWC" | "NCHW" accepted at the API boundary for parity (reference: model.py:58-61).
    # NCHW is a SERVING/PREDICT boundary layout: serving_fn/export_serving take and
    # return [B, C, H, W] and predict() returns NCHW outputs. Training REJECTS it
    # (validate_training_data_format): the input pipelines feed NHWC by construction,
    # and on TPU the compute-layout motivation behind the reference's NCHW mode
    # ("about 10% faster" on GPU, model.py:45-46, transposed at model_fn top,
    # model.py:344-351) does not exist — XLA owns the internal layout.
    data_format: str = "NHWC"
    # "adam" reproduces the reference (tf.contrib AdamOptimizer, model.py:462);
    # "sgd" is Nesterov momentum — the standard ImageNet recipe behind the
    # 76%-top-1 north star (BASELINE.md); "lars" is layer-wise adaptive rate
    # scaling for large-batch training (You et al., arXiv:1708.03888 — the
    # published stabilizer for the 8k-batch preset).
    optimizer: str = "adam"
    sgd_momentum: float = 0.9
    # decoupled-from-the-loss weight decay applied inside the optimizer chain,
    # masked to conv/dense kernels only (BN scale/bias and biases stay
    # undecayed — the standard recipe, arXiv:1706.02677). For sgd it enters
    # before momentum+lr scaling, i.e. exactly the classic l2-SGD form; for
    # adam it switches the chain to AdamW; for lars it rides the trust-ratio
    # update. 0.0 reproduces the reference's EFFECTIVE objective (it declared
    # an l2 regularizer but never minimized it — reference: model.py:462-467,
    # core/resnet.py:357-376); the ImageNet presets set 1e-4 per their cited
    # recipe (configs.py).
    weight_decay: float = 0.0
    # exponential moving average of the parameters, tracked inside the
    # optimizer chain (train/step.py:ema_tracker) and used automatically for
    # eval and best-export when > 0 (train/step.py:with_ema_params). 0.0
    # disables (the reference's behavior: TF1/slim with no weight averaging);
    # ~0.9999 is the modern recipe value at ImageNet scale. Costs one extra
    # params-sized buffer in opt_state.
    ema_decay: float = 0.0
    # clip gradients to this global l2 norm before the optimizer update
    # (optax.clip_by_global_norm at the head of the chain, so decay/momentum
    # see the clipped gradient). 0.0 disables (the reference never clipped);
    # 1.0 is the standard ViT/large-LR stabilizer. Applies to every execution
    # strategy because it rides TrainState.tx.
    grad_clip_norm: float = 0.0
    # accumulate gradients over this many sequential microbatches inside each
    # train step (lax.scan), then apply ONE optimizer update on their mean —
    # effective batch = grad_accum_steps x fed batch at one microbatch's
    # activation memory. The optimizer step count (and therefore the lr
    # schedule) advances once per UPDATE, matching the semantics of feeding
    # the large batch directly. BN batch statistics are computed per
    # microbatch sequentially (the same per-shard locality the reference's
    # per-tower BN had). Standard data-parallel/spatial step only (the GSPMD
    # tensor-parallel and pipeline strategies define their own batch math).
    grad_accum_steps: int = 1
    # classification train-loss label smoothing (0.1 in the standard ImageNet
    # recipe, arXiv:1512.00567); eval metrics stay plain CE
    label_smoothing: float = 0.0
    # fit()'s on-device train augmentation policy: "flip_crop" (random mirror +
    # reflect-padded random crop — the ImageNet/CIFAR recipe and the default),
    # "crop" (no mirror — for chirality-sensitive classes: digits, text,
    # signage), "none" (stream batches untouched), "mixup" (flip_crop then
    # Beta(0.2)-convex image/label mixing, arXiv:1710.09412), or "cutmix"
    # (flip_crop then area-weighted box pasting, arXiv:1905.04899). The mixing
    # policies train against per-example paired CE (no soft-label buffers) and
    # require the standard data-parallel/tensor-parallel step (not
    # sequence/pipeline parallel). Eval is never augmented.
    augmentation: str = "flip_crop"
    lr: float = 0.001
    # "exponential" reproduces the reference's continuous decay (model.py:457-459);
    # "cosine" is the standard ImageNet recipe (linear warmup to `lr` over
    # `lr_warmup_steps`, cosine decay to ~0 over `lr_decay_steps`)
    lr_schedule: str = "exponential"
    # exponential: lr halves every `lr_decay_steps` (reference: model.py:457-459);
    # cosine: total decay horizon in steps
    lr_decay_steps: int = 10_000
    lr_decay_rate: float = 0.5
    lr_warmup_steps: int = 0
    # number of devices to use; None = all (reference: n_gpus, model.py:33)
    n_devices: Optional[int] = None
    # layout selection mode (parallel/planner.py): "explicit" runs the
    # degrees below verbatim (validated through the planner so indivisible
    # specs fail at parse time with a named constraint); "auto" derives the
    # whole (dp, tp, pp, spatial, zero1) layout from the model's exact
    # param/opt-state accounting, the per-chip HBM budget, and the device
    # topology — any degree explicitly set above its default stays PINNED
    # (explicit flags win) and the planner fills the rest. The chosen plan
    # rides the run-header ledger event either way.
    parallelism: str = "explicit"
    # per-chip HBM budget in GiB for the planner's feasibility gate; None
    # reads the backend's bytes_limit (CPU builds report none — the budget
    # gate then only fires when this is set)
    hbm_budget_gb: Optional[float] = None
    # sequence (spatial) parallel degree: shard the image H dimension over this
    # many devices per data-parallel replica (halo-exchange convs,
    # parallel/spatial.py). 1 = pure data parallelism (the reference's only mode).
    # A TPU-first capability for feature maps too large for one chip's HBM.
    sequence_parallel: int = 1
    # tensor (model) parallel degree: shard parameters/optimizer state over the
    # mesh's model axis via GSPMD annotations (parallel/tensor.py) — per-chip
    # param+optimizer memory drops by this factor; XLA places the collectives.
    # fit() only; mutually exclusive with sequence_parallel>1 (the GSPMD step
    # and the shard_map spatial step are different execution strategies).
    model_parallel: int = 1
    # pipeline parallel degree: run the ViT block stack as a K-stage GPipe
    # pipeline over the mesh's model axis (parallel/pipeline.py;
    # train/pipeline_step.py), each stage holding vit_layers/K consecutive
    # blocks, microbatches flowing stage-to-stage over one ppermute ICI hop
    # per tick. fit() + backbone="vit" only; mutually exclusive with
    # model_parallel>1 and sequence_parallel>1 (different execution
    # strategies over the same axes).
    pipeline_parallel: int = 1
    # microbatches per local batch for the GPipe schedule (bubble fraction
    # (K-1)/(M+K-1): set M >> K in production). None = pipeline_parallel
    # (correctness default).
    pipeline_microbatches: Optional[int] = None
    # expert parallel degree: place the MoE blocks' experts one-per-shard on
    # the mesh's model axis with all-to-all dispatch (parallel/expert.py).
    # Requires ModelConfig.moe_experts == expert_parallel and backbone="vit";
    # 1 computes every expert locally (dense dispatch, any mesh). Mutually
    # exclusive with the other model-axis strategies.
    expert_parallel: int = 1
    # ZeRO-1 cross-replica weight-update sharding (arXiv:2004.13336,
    # parallel/zero.py): optimizer state (Adam moments, LARS/SGD momentum,
    # the EMA tracker) shards over the data-parallel mesh axis — each leaf
    # partitioned on its largest dp-divisible dimension, tiny/indivisible
    # leaves replicated — and the weight update runs on each chip's 1/dp
    # shard under GSPMD constraints, with the parameter all-gather placed by
    # the partitioner. Per-chip optimizer memory drops by ~the data-parallel
    # degree (Adam slots are ~2x params; +1x more with ema_decay) at
    # neutral step time; numerics match the replicated update
    # (tests/test_zero1.py pins step-for-step equivalence). Composes with
    # grad_accum_steps, sequence_parallel, sync_batch_norm, the multi-step
    # scan, and model_parallel (slots shard over (model, batch) jointly);
    # mutually exclusive with pipeline_parallel, whose stage runner owns its
    # own update placement.
    weight_update_sharding: bool = False
    # synchronized cross-shard BatchNorm: compute BN statistics over the
    # GLOBAL batch (lax.pmean over the batch mesh axis inside flax BN)
    # instead of per shard. Default False preserves the reference's
    # per-tower MirroredStrategy BN semantics; True is the cross-replica BN
    # standard on TPU pods when the per-shard batch gets small. Semantics
    # pinned against a full-batch single-device oracle
    # (tests/test_train_step.py::test_sync_batch_norm_matches_global_batch_oracle)
    # and measured worth +7.8 points of real accuracy at digits scale where
    # the per-shard batch is 8 (DIGITS_RUN.json 'xception_adam_syncbn':
    # 93.9% vs 86.1% per-shard; the chip's native full-batch BN scores
    # 96.4%). Composes with sequence_parallel
    # (stats span batch AND sequence shards); mutually exclusive with
    # pipeline_parallel, whose GPipe schedule owns BN microbatch-wise.
    sync_batch_norm: bool = False
    n_folds: int = 5
    seed: int = 42
    # best-model exports to keep (reference: model.py:37, 196-202)
    save_best: int = 5
    checkpoint_every_steps: int = 500
    eval_throttle_secs: int = 300
    # eval cadence in steps, decoupled from checkpointing and EXEMPT from
    # eval_throttle_secs (an explicit cadence is explicit user intent; same
    # semantics in Trainer and fit()). None preserves the reference's
    # train_and_evaluate shape: eval considered when a periodic checkpoint
    # lands AND the time throttle passed (reference: model.py:214)
    eval_every_steps: Optional[int] = None
    # train summaries every N steps / eval summaries every step (reference: model.py:470-481)
    train_log_every_steps: int = 20
    # write the JSONL run ledger ({workdir}/telemetry.jsonl, obs/ledger.py):
    # run header, per-window step events with the data-wait/compute split,
    # eval/checkpoint/memory snapshots, and post-warmup recompile flags —
    # the machine-readable record `telemetry-report` renders. Ledger writes
    # degrade to a warning on an unwritable workdir; disabling also skips the
    # span bookkeeping and the jax.monitoring compile listener.
    telemetry: bool = True
    # persistent XLA compile cache directory (utils/compile_cache.py): point
    # repeated runs at the same dir and a second same-shape run loads its
    # executables instead of recompiling (keys hash the StableHLO module +
    # jaxlib version + XLA flags + device kinds — NOT process topology, so
    # the elastic AOT standby and serve replicas share entries). None (the
    # default) leaves the cache off; an unwritable dir degrades to a warning
    # and an uncached run. CLI: --compile-cache-dir on train/fit/serve.
    compile_cache_dir: Optional[str] = None
    # memory snapshot cadence, counted in LOG WINDOWS (every N-th window event
    # also records per-device HBM + host RSS); the trainers additionally
    # snapshot once after state init
    telemetry_memory_every_windows: int = 5
    # per-unit tracing (obs/trace.py): fraction of traces (one per top-level
    # span — each train step, eval pass, checkpoint save) persisted as
    # `trace` ledger events, exportable via `telemetry-report --export-trace`
    # as Chrome/Perfetto trace-event JSON. 0.0 disables tracing entirely
    # (zero per-step cost); 1.0 keeps every span. Sampling is decided per
    # TRACE at its root, so sampled traces are always complete. Overhead
    # with tracing fully on is gated <= 2% step time (bench.py
    # --trace-overhead, CI).
    trace_sample_rate: float = 0.0
    # continuous profiling cadence (obs/profiler.py), counted in LOG WINDOWS:
    # every N-th window boundary captures a short windowed jax.profiler trace
    # (a few steps, stopped early), parses it through utils/xplane.py into a
    # per-op roofline classification, and ledgers `profile_capture` +
    # `op_roofline` events the planner's measured-costs loop and the live
    # console read. 0 (default) disables cadence capture entirely; triggered
    # captures (health alerts, serve /admin/profile) are independent of it.
    # Overhead with the cadence on is gated <= 2% step time (bench.py
    # --profile-overhead, CI).
    profile_every_windows: int = 0
    # online health monitors (obs/health.py) over the per-window telemetry:
    # NaN/Inf loss guard, rolling median+MAD loss-spike detector, step-time
    # regression vs the first clean windows. Alerts land as structured
    # `health_alert` ledger events and render in telemetry-report's health
    # section.
    health_monitors: bool = True
    # NaN/Inf loss guard action: "warn" alerts and keeps training, "abort"
    # alerts then raises HealthAbortError (stop at a recorded boundary
    # instead of training on garbage), "off" disables just this guard.
    # Drill it with --inject-fault nan-loss@N (resilience/faults.py).
    nan_guard: str = "warn"
    # overlap periodic Orbax saves with subsequent train steps (background
    # serialization); best exports and resume points still synchronize
    async_checkpointing: bool = False
    # host→device input prefetch depth (data/pipeline.py:device_prefetch):
    # the producer thread stays this many PLACED batches ahead of the train
    # loop so HBM copies overlap the previous step's compute — the
    # generalized form of the reference's prefetch(2×n_gpus)
    # (reference: model.py:319-320). Per-window queue-depth telemetry makes
    # underruns visible in telemetry-report; raise this when they show.
    prefetch_depth: int = 2
    # host–device overlap budget (train/async_loop.py): the host may run at
    # most this many dispatched-but-unretired train steps ahead of the
    # device, and log windows defer their metric fetch one window
    # (copy_to_host_async at the boundary, fetched while the next window is
    # already dispatching) — the device queue never drains on a log line.
    # The blocked-past-budget time is ledgered as the fetch_wait span.
    # 0 = the synchronous legacy loop (blocking device_get per log window);
    # numerics are bit-identical either way (tests/test_async_loop.py,
    # BENCH_ASYNC.json).
    dispatch_ahead_steps: int = 2
    # parallel input-service workers (data/service.py): N background
    # read+decode workers execute the index-keyed global-shuffle batch plan
    # and hand batches back in order — record-sharded training streams scale
    # past the single reader thread, and the K-fold trainer's in-memory fold
    # streams assemble off the host loop. Batch CONTENT is worker-count
    # invariant (the plan is a pure function of the seed), so this knob is
    # pure throughput. 0 = the legacy in-line streams (records.py batches /
    # pipeline.train_batches) with their seed-folded resume.
    data_service_workers: int = 2
    # fit() with record shards and NO val split: hold out this fraction of the
    # train record shards (at least one) as the eval split, so best-checkpoint
    # selection runs on data the model never trains on. 0.0 keeps every shard
    # in training and falls back to evaluating one pass over the train records
    # (with a loud warning — train-set top-1 as the selection signal silently
    # overfits).
    eval_holdout_fraction: float = 0.0

    def __post_init__(self):
        if self.data_format not in ("NCHW", "NHWC"):
            raise ValueError(
                f"Unknown data format {self.data_format}. Has to be either NCHW or NHWC"
            )
        if self.parallelism not in ("explicit", "auto"):
            raise ValueError(
                "parallelism must be 'explicit' or 'auto', got "
                f"{self.parallelism!r}"
            )
        if self.hbm_budget_gb is not None and self.hbm_budget_gb <= 0:
            raise ValueError(
                f"hbm_budget_gb must be positive, got {self.hbm_budget_gb}"
            )
        if self.sequence_parallel < 1:
            raise ValueError(
                f"sequence_parallel must be >= 1, got {self.sequence_parallel}"
            )
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel must be >= 1, got {self.model_parallel}"
            )
        if self.model_parallel > 1 and self.sequence_parallel > 1:
            raise ValueError(
                "model_parallel and sequence_parallel cannot both exceed 1: "
                "the GSPMD tensor-parallel step and the shard_map spatial step "
                "are different execution strategies"
            )
        if self.pipeline_parallel < 1:
            raise ValueError(
                f"pipeline_parallel must be >= 1, got {self.pipeline_parallel}"
            )
        if self.pipeline_parallel > 1 and (
            self.model_parallel > 1 or self.sequence_parallel > 1
        ):
            raise ValueError(
                "pipeline_parallel cannot combine with model_parallel or "
                "sequence_parallel: the GPipe stage runner, the GSPMD "
                "tensor-parallel step, and the shard_map spatial step are "
                "different execution strategies over the same mesh axes"
            )
        if self.pipeline_microbatches is not None and (
            self.pipeline_microbatches < self.pipeline_parallel
            or self.pipeline_parallel == 1
        ):
            raise ValueError(
                "pipeline_microbatches requires pipeline_parallel > 1 and at "
                "least one microbatch per stage "
                f"(got microbatches={self.pipeline_microbatches}, "
                f"stages={self.pipeline_parallel})"
            )
        if self.weight_update_sharding and self.pipeline_parallel > 1:
            raise ValueError(
                "weight_update_sharding cannot combine with pipeline_parallel: "
                "the GPipe stage runner applies its own update placement "
                "(train/pipeline_step.py); ZeRO-1 shards the data axis the "
                "standard and GSPMD steps own"
            )
        if self.sync_batch_norm and self.pipeline_parallel > 1:
            raise ValueError(
                "sync_batch_norm cannot combine with pipeline_parallel: the "
                "GPipe schedule computes BN statistics microbatch-wise per "
                "stage (train/pipeline_step.py)"
            )
        if self.expert_parallel < 1:
            raise ValueError(
                f"expert_parallel must be >= 1, got {self.expert_parallel}"
            )
        if self.expert_parallel > 1 and (
            self.model_parallel > 1
            or self.sequence_parallel > 1
            or self.pipeline_parallel > 1
        ):
            raise ValueError(
                "expert_parallel cannot combine with model_parallel, "
                "sequence_parallel, or pipeline_parallel: each owns the "
                "model/sequence mesh axes as a different execution strategy"
            )
        if self.augmentation not in ("flip_crop", "crop", "none", "mixup", "cutmix"):
            raise ValueError(f"Unknown augmentation {self.augmentation!r}")
        if self.augmentation in ("mixup", "cutmix") and (
            self.sequence_parallel > 1 or self.pipeline_parallel > 1
        ):
            raise ValueError(
                f"augmentation={self.augmentation!r} pairs examples through "
                "extra per-example batch fields (labels_b/lam), which the "
                "sequence-parallel and pipeline execution strategies do not "
                "thread; use the data/tensor-parallel step"
            )
        if self.lr_schedule not in ("exponential", "cosine"):
            raise ValueError(f"Unknown lr_schedule {self.lr_schedule!r}")
        if self.optimizer not in ("adam", "sgd", "lars"):
            raise ValueError(f"Unknown optimizer {self.optimizer!r}")
        if self.weight_decay < 0:
            raise ValueError(f"weight_decay must be >= 0, got {self.weight_decay}")
        if not 0.0 <= self.ema_decay < 1.0:
            raise ValueError(
                f"ema_decay must be in [0, 1), got {self.ema_decay}"
            )
        if self.grad_clip_norm < 0:
            raise ValueError(
                f"grad_clip_norm must be >= 0, got {self.grad_clip_norm}"
            )
        if self.grad_accum_steps < 1:
            raise ValueError(
                f"grad_accum_steps must be >= 1, got {self.grad_accum_steps}"
            )
        if self.grad_accum_steps > 1 and (
            self.model_parallel > 1 or self.pipeline_parallel > 1
        ):
            raise ValueError(
                "grad_accum_steps > 1 runs inside the shard_map "
                "data/spatial-parallel step; the GSPMD tensor-parallel and "
                "pipeline strategies define their own batch math"
            )
        # cadence knobs are modulus divisors in the train loops
        # (`step_no % knob`): a zero would surface as a ZeroDivisionError
        # mid-run, hours in — reject it at construction instead
        if self.train_log_every_steps < 1:
            raise ValueError(
                "train_log_every_steps must be >= 1, got "
                f"{self.train_log_every_steps}"
            )
        if self.checkpoint_every_steps < 1:
            raise ValueError(
                "checkpoint_every_steps must be >= 1, got "
                f"{self.checkpoint_every_steps}"
            )
        if self.eval_every_steps is not None and self.eval_every_steps < 1:
            raise ValueError(
                "eval_every_steps must be >= 1 (or None for the "
                f"checkpoint-coupled default), got {self.eval_every_steps}"
            )
        if self.eval_throttle_secs < 0:
            raise ValueError(
                f"eval_throttle_secs must be >= 0, got {self.eval_throttle_secs}"
            )
        if self.prefetch_depth < 1:
            raise ValueError(
                f"prefetch_depth must be >= 1, got {self.prefetch_depth} "
                "(1 = single-buffered; there is no unprefetched mode)"
            )
        if self.data_service_workers < 0:
            raise ValueError(
                "data_service_workers must be >= 0 (0 = the legacy in-line "
                f"input streams), got {self.data_service_workers}"
            )
        if self.dispatch_ahead_steps < 0:
            raise ValueError(
                "dispatch_ahead_steps must be >= 0 (0 = the synchronous "
                f"host loop), got {self.dispatch_ahead_steps}"
            )
        if self.telemetry_memory_every_windows < 1:
            raise ValueError(
                "telemetry_memory_every_windows must be >= 1, got "
                f"{self.telemetry_memory_every_windows}"
            )
        if not 0.0 <= self.trace_sample_rate <= 1.0:
            raise ValueError(
                "trace_sample_rate must be in [0, 1] (0 disables tracing), "
                f"got {self.trace_sample_rate}"
            )
        if self.profile_every_windows < 0:
            raise ValueError(
                "profile_every_windows must be >= 0 (0 disables cadence "
                f"profiling), got {self.profile_every_windows}"
            )
        if self.nan_guard not in ("warn", "abort", "off"):
            raise ValueError(
                "nan_guard must be one of ('warn', 'abort', 'off'), got "
                f"{self.nan_guard!r}"
            )
        if not 0.0 <= self.eval_holdout_fraction < 1.0:
            raise ValueError(
                "eval_holdout_fraction must be in [0, 1), got "
                f"{self.eval_holdout_fraction}"
            )


def validate_training_data_format(cfg: TrainConfig) -> None:
    """Reject NCHW at the TRAINING boundary (serving/predict honor it).

    The reference trained in NCHW because it was ~10% faster on its GPUs
    (reference: model.py:45-46, 344-351). On TPU that motivation does not
    exist — XLA chooses the internal layout — and the framework's input
    pipelines feed NHWC by construction, so accepting NCHW for training would
    be a silently-ignored knob. Train NHWC; NCHW remains fully honored where
    user-facing arrays actually cross the boundary: ``serving_fn``,
    ``export_serving``, and ``predict`` outputs."""
    if cfg.data_format == "NCHW":
        raise ValueError(
            "data_format='NCHW' applies to the serving/predict boundary only; "
            "training input is NHWC by construction (on TPU, XLA owns the "
            "compute layout — the reference's NCHW-for-speed mode, "
            "model.py:45-46, has no TPU analogue). Train with NHWC, then "
            "construct a Trainer with data_format='NCHW' over the same "
            "model_dir for NCHW serving/prediction."
        )
