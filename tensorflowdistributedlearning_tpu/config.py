"""Typed configuration for the framework.

The reference exposed its knobs as module constants plus ad-hoc ``**kwargs`` plumbing in
``Model.__init__`` (reference: model.py:13-24, 63-106). Here the same knob set is a pair of
frozen dataclasses so configs are explicit, hashable (usable as jit static args), and
serializable. The reference's ``batch_norm_decay`` copy-paste bug (it read
``kwargs["weight_decay"]``, reference: model.py:69) is intentionally NOT reproduced.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters.

    Defaults mirror the reference's module constants (reference: model.py:13-24) and
    ``Model.__init__`` fallbacks (reference: model.py:63-106).
    """

    backbone: str = "resnet"  # "resnet" | "xception"
    # l2 regularisation (reference: model.py:14 WEIGHT_DECAY = 0.001)
    weight_decay: float = 0.001
    # batch norm (reference: model.py:16-18)
    batch_norm_decay: float = 0.99
    batch_norm_epsilon: float = 0.001
    batch_norm_scale: bool = True
    # atrous output stride (reference: model.py:20 OUTPUT_STRIDE = 8)
    output_stride: int = 8
    # spatial input shape, channels excluded (reference: model.py:22 INPUT_SHAPE)
    input_shape: Tuple[int, int] = (101, 101)
    # input channels: image + Laplacian channel (reference: preprocessing.py:243)
    input_channels: int = 2
    # deepest residual stage width (reference: model.py:24 BASE_DEPTH = 256)
    base_depth: int = 256
    # residual units per stage before the atrous stage (reference: model.py:101-103)
    n_blocks: Tuple[int, ...] = (3, 4, 6)
    # "bottleneck" | "basic_block" (reference: model.py:104-106)
    block_type: str = "bottleneck"
    # Classification-path knobs (reference: core/resnet.py:246-256 kept a num_classes /
    # global_pool path alongside segmentation); None means segmentation head.
    num_classes: Optional[int] = None
    # compute dtype: params stay float32, activations/matmuls run in this dtype. TPU MXU
    # natively prefers bfloat16 — this is a TPU-first knob the reference had no analogue of.
    dtype: str = "float32"
    # route the ASPP's atrous depthwise convs through the Pallas VMEM kernel
    # (ops/pallas_kernels.py) instead of XLA's grouped conv; parameter trees are
    # identical between the two paths, so this is a pure execution-path switch.
    use_pallas_depthwise: bool = False
    # rematerialize residual units on the backward pass (jax.checkpoint): trades
    # recompute FLOPs for activation HBM — enables large per-chip batches.
    remat: bool = False
    # uniform channel-width scale for every backbone stage (root convs, residual
    # stages, Xception flows, ViT embed dim). 1.0 keeps the reference widths
    # (core/resnet.py:333-344, core/xception.py:405-465); fractional values give
    # width-scaled variants (Wide-ResNet-style scaling, and the knob that makes
    # tiny CI models actually tiny — the stage widths are otherwise fixed
    # constants).
    width_multiplier: float = 1.0
    # ViT family knobs (backbone="vit" — beyond-parity: the transformer
    # classifier that consumes parallel/ring_attention.py under sequence
    # parallelism; defaults are ViT-S/16).
    patch_size: int = 16
    embed_dim: int = 384
    vit_layers: int = 12
    num_heads: int = 6
    mlp_ratio: float = 4.0
    # route ViT attention through the fused Pallas block-attention kernel
    # (ops/flash_attention.py) instead of the XLA einsum path; parameter trees
    # are identical, so this is a pure execution-path switch. Ignored (with a
    # warning) under sequence_parallel>1, where the ring formulation owns the
    # attention math.
    use_fused_attention: bool = False

    def __post_init__(self):
        if self.backbone not in ("resnet", "xception", "vit"):
            raise ValueError(f"Unknown backbone {self.backbone!r}")
        if self.block_type not in ("bottleneck", "basic_block"):
            raise ValueError(f"Unknown block type {self.block_type!r}")
        if self.dtype not in ("float32", "bfloat16"):
            raise ValueError(f"Unknown dtype {self.dtype!r}")
        if self.width_multiplier <= 0:
            raise ValueError("width_multiplier must be positive")


@dataclasses.dataclass(frozen=True)
class TrainConfig:
    """Training-loop hyperparameters.

    Defaults mirror the reference's ``Model.__init__`` signature (reference:
    model.py:29-37) and its train-step constants: Adam with exponential decay — half the
    lr every 10 000 steps (reference: model.py:457-462), checkpoints every 500 steps
    (reference: model.py:118), eval throttled to >= 300 s (reference: model.py:214).
    """

    # "NHWC" | "NCHW" accepted at the API boundary for parity (reference: model.py:58-61);
    # compute is always NHWC internally — on TPU, XLA picks layouts and the NCHW-vs-NHWC
    # distinction the reference hand-managed (model.py:344-351) does not exist.
    data_format: str = "NHWC"
    # "adam" reproduces the reference (tf.contrib AdamOptimizer, model.py:462);
    # "sgd" is Nesterov momentum — the standard ImageNet recipe behind the
    # 76%-top-1 north star (BASELINE.md).
    optimizer: str = "adam"
    sgd_momentum: float = 0.9
    # classification train-loss label smoothing (0.1 in the standard ImageNet
    # recipe, arXiv:1512.00567); eval metrics stay plain CE
    label_smoothing: float = 0.0
    lr: float = 0.001
    # "exponential" reproduces the reference's continuous decay (model.py:457-459);
    # "cosine" is the standard ImageNet recipe (linear warmup to `lr` over
    # `lr_warmup_steps`, cosine decay to ~0 over `lr_decay_steps`)
    lr_schedule: str = "exponential"
    # exponential: lr halves every `lr_decay_steps` (reference: model.py:457-459);
    # cosine: total decay horizon in steps
    lr_decay_steps: int = 10_000
    lr_decay_rate: float = 0.5
    lr_warmup_steps: int = 0
    # number of devices to use; None = all (reference: n_gpus, model.py:33)
    n_devices: Optional[int] = None
    # sequence (spatial) parallel degree: shard the image H dimension over this
    # many devices per data-parallel replica (halo-exchange convs,
    # parallel/spatial.py). 1 = pure data parallelism (the reference's only mode).
    # A TPU-first capability for feature maps too large for one chip's HBM.
    sequence_parallel: int = 1
    # tensor (model) parallel degree: shard parameters/optimizer state over the
    # mesh's model axis via GSPMD annotations (parallel/tensor.py) — per-chip
    # param+optimizer memory drops by this factor; XLA places the collectives.
    # fit() only; mutually exclusive with sequence_parallel>1 (the GSPMD step
    # and the shard_map spatial step are different execution strategies).
    model_parallel: int = 1
    n_folds: int = 5
    seed: int = 42
    # best-model exports to keep (reference: model.py:37, 196-202)
    save_best: int = 5
    checkpoint_every_steps: int = 500
    eval_throttle_secs: int = 300
    # eval cadence in steps, decoupled from checkpointing and EXEMPT from
    # eval_throttle_secs (an explicit cadence is explicit user intent; same
    # semantics in Trainer and fit()). None preserves the reference's
    # train_and_evaluate shape: eval considered when a periodic checkpoint
    # lands AND the time throttle passed (reference: model.py:214)
    eval_every_steps: Optional[int] = None
    # train summaries every N steps / eval summaries every step (reference: model.py:470-481)
    train_log_every_steps: int = 20
    # overlap periodic Orbax saves with subsequent train steps (background
    # serialization); best exports and resume points still synchronize
    async_checkpointing: bool = False

    def __post_init__(self):
        if self.data_format not in ("NCHW", "NHWC"):
            raise ValueError(
                f"Unknown data format {self.data_format}. Has to be either NCHW or NHWC"
            )
        if self.sequence_parallel < 1:
            raise ValueError(
                f"sequence_parallel must be >= 1, got {self.sequence_parallel}"
            )
        if self.model_parallel < 1:
            raise ValueError(
                f"model_parallel must be >= 1, got {self.model_parallel}"
            )
        if self.model_parallel > 1 and self.sequence_parallel > 1:
            raise ValueError(
                "model_parallel and sequence_parallel cannot both exceed 1: "
                "the GSPMD tensor-parallel step and the shard_map spatial step "
                "are different execution strategies"
            )
        if self.lr_schedule not in ("exponential", "cosine"):
            raise ValueError(f"Unknown lr_schedule {self.lr_schedule!r}")
        if self.optimizer not in ("adam", "sgd"):
            raise ValueError(f"Unknown optimizer {self.optimizer!r}")
