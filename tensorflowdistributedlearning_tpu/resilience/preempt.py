"""Preemption handling: turn SIGTERM into a checkpoint, not a lost run.

TPU preemptions arrive as SIGTERM with a short grace window; the Estimator
stack survived them only through its implicit resume-from-latest (reference:
model.py:117-121, 164-167) — anything since the last periodic checkpoint was
retrained. This module closes that gap: a signal handler (plus a file-based
"preemption notice" for environments that cannot deliver signals into the
training process) raises a flag the trainers poll at step boundaries; they
write a final checkpoint at the *current* step, ledger a ``preempted`` event,
and exit with ``EXIT_PREEMPTED`` so the supervisor (and any job scheduler)
can tell a routine preemption from a crash.

Semantics:

- first SIGTERM/SIGINT: graceful — finish the in-flight step, checkpoint,
  flush the ledger, exit ``EXIT_PREEMPTED`` (75, ``EX_TEMPFAIL``: "transient,
  retry me");
- second signal while already draining: escalate — the previous disposition
  is restored and the signal re-raised (a wedged run stays killable);
- notice file: ``requested()`` also answers True once ``notice_file`` exists
  (stat throttled to ``NOTICE_CHECK_INTERVAL_S`` so per-step polling is free).

Process-global like the fault injector: the CLI installs it for ``train`` and
``fit``; library code only ever calls ``requested()``, which is False when
nothing is installed.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from typing import Dict, Optional, Tuple

logger = logging.getLogger(__name__)

# EX_TEMPFAIL — distinct from crash exits so supervisors/schedulers can treat
# preemption as the routine, retryable outcome it is
EXIT_PREEMPTED = 75

NOTICE_CHECK_INTERVAL_S = 0.2


class PreemptedError(RuntimeError):
    """Raised by the trainers after the preemption checkpoint landed; the CLI
    converts it to ``EXIT_PREEMPTED``. Carries the step the run stopped at."""

    def __init__(self, step: int):
        super().__init__(f"preempted at step {step} (checkpoint written)")
        self.step = step


class PreemptionHandler:
    """One process's preemption state: signal flag + optional notice file."""

    def __init__(self, notice_file: Optional[str] = None):
        self.notice_file = notice_file
        self._flag = threading.Event()
        self._reason: Optional[str] = None
        self._prev: Dict[int, object] = {}
        self._last_notice_check = 0.0

    # -- signals -----------------------------------------------------------

    def install_signals(
        self, signals: Tuple[int, ...] = (signal.SIGTERM, signal.SIGINT)
    ) -> "PreemptionHandler":
        for sig in signals:
            try:
                self._prev[sig] = signal.signal(sig, self._on_signal)
            except ValueError:
                # off the main thread (embedding callers): CPython refuses
                # signal registration — degrade to notice-file/request()
                # preemption instead of refusing to train at all
                logger.warning(
                    "cannot install a %s handler off the main thread — "
                    "signal-based preemption disabled (the notice file and "
                    "request() still work)",
                    signal.Signals(sig).name,
                )
        return self

    def uninstall_signals(self) -> None:
        for sig, prev in self._prev.items():
            try:
                signal.signal(sig, prev)
            except (ValueError, TypeError):  # non-main thread / exotic prev
                pass
        self._prev = {}

    def _on_signal(self, signum, frame) -> None:
        if self._flag.is_set():
            # second signal: the graceful path is apparently stuck — restore
            # the previous disposition and let the signal do its normal thing
            prev = self._prev.pop(signum, signal.SIG_DFL)
            signal.signal(signum, prev)
            os.kill(os.getpid(), signum)
            return
        self._reason = f"signal:{signal.Signals(signum).name}"
        self._flag.set()
        logger.warning(
            "%s received — requesting a final checkpoint at the next step "
            "boundary (second signal kills immediately)",
            self._reason,
        )

    # -- state -------------------------------------------------------------

    def request(self, reason: str = "manual") -> None:
        """Programmatic preemption request (tests, embedding frameworks)."""
        self._reason = reason
        self._flag.set()

    def requested(self) -> bool:
        if self._flag.is_set():
            return True
        if self.notice_file:
            now = time.monotonic()
            if now - self._last_notice_check >= NOTICE_CHECK_INTERVAL_S:
                self._last_notice_check = now
                if os.path.exists(self.notice_file):
                    self._reason = f"notice-file:{self.notice_file}"
                    self._flag.set()
                    return True
        return False

    def reason(self) -> str:
        return self._reason or "unknown"


_HANDLER: Optional[PreemptionHandler] = None


def install(
    notice_file: Optional[str] = None,
    signals: Optional[Tuple[int, ...]] = (signal.SIGTERM, signal.SIGINT),
) -> PreemptionHandler:
    """Install the process-global handler (replacing any previous one, whose
    signal dispositions are restored first). ``signals=None`` skips signal
    registration (notice-file-only mode, usable off the main thread)."""
    global _HANDLER
    if _HANDLER is not None:
        _HANDLER.uninstall_signals()
    _HANDLER = PreemptionHandler(notice_file=notice_file)
    if signals:
        _HANDLER.install_signals(signals)
    return _HANDLER


def uninstall() -> None:
    global _HANDLER
    if _HANDLER is not None:
        _HANDLER.uninstall_signals()
    _HANDLER = None


def handler() -> Optional[PreemptionHandler]:
    return _HANDLER


def requested() -> bool:
    """The per-step poll the trainers run; False when nothing is installed."""
    return _HANDLER is not None and _HANDLER.requested()


def reason() -> str:
    return _HANDLER.reason() if _HANDLER is not None else "unknown"
