"""Retry with exponential backoff + jitter for transient-failure-prone paths.

Applied where production runs actually see transient faults: checkpoint
save/restore (train/checkpoint.py) and record/file reads (data/records.py,
data/kaggle.py). Every retry is counted in an ``obs.metrics`` registry under
``retry/{name}``, so the clean path is *observably* clean (zero retries) and a
flaky filesystem shows up in telemetry instead of only in latency.

Exhaustion raises ``RetryExhaustedError`` — deliberately NOT an ``OSError``
(an outer retry must not re-retry an inner exhaustion) and NOT a
``RuntimeError`` (the checkpoint layer reserves that family for structure
mismatches it must re-raise) — with ``name``/``attempts``/``last`` attached
and ``__cause__`` chained to the final failure.
"""

from __future__ import annotations

import random
import time
from typing import Callable, Optional, Tuple, Type

from tensorflowdistributedlearning_tpu.obs.metrics import MetricsRegistry

# the default sink for retry counters; tests and /metrics-style snapshots read
# it via ``retries()`` — per-call ``registry=`` overrides for scoped counting
RETRY_REGISTRY = MetricsRegistry()

# OSError subclasses that are deterministic, not transient: backing off on a
# missing file or a permission wall wastes the whole backoff schedule and then
# re-types the error — callers keep seeing the original FileNotFoundError etc.
NON_TRANSIENT = (
    FileNotFoundError,
    PermissionError,
    IsADirectoryError,
    NotADirectoryError,
)


class RetryExhaustedError(Exception):
    """All attempts failed; ``__cause__`` is the last underlying exception."""

    def __init__(self, name: str, attempts: int, last: BaseException):
        super().__init__(
            f"{name}: failed after {attempts} attempt(s); last error: "
            f"{type(last).__name__}: {last}"
        )
        self.name = name
        self.attempts = attempts
        self.last = last


def retries(name: Optional[str] = None) -> int:
    """Total retries recorded in the default registry (optionally for one
    ``retry/{name}`` counter)."""
    snapshot = RETRY_REGISTRY.snapshot()["counters"]
    if name is not None:
        return snapshot.get(f"retry/{name}", 0)
    return sum(v for k, v in snapshot.items() if k.startswith("retry/"))


def reset_registry() -> None:
    """Fresh default registry (test isolation)."""
    global RETRY_REGISTRY
    RETRY_REGISTRY = MetricsRegistry()


def backoff_delay(
    attempt: int,
    *,
    base_delay_s: float,
    max_delay_s: float,
    jitter_frac: float,
    rng: random.Random,
) -> float:
    """The one exponential-backoff-with-symmetric-jitter formula (shared by
    the retry loop and the restart supervisor): doubles from ``base_delay_s``,
    caps at ``max_delay_s``, jitters +-``jitter_frac``."""
    delay = min(base_delay_s * 2 ** (attempt - 1), max_delay_s)
    return max(0.0, delay * (1.0 + jitter_frac * (2.0 * rng.random() - 1.0)))


def call_with_retry(
    fn: Callable,
    *,
    name: str,
    exceptions: Tuple[Type[BaseException], ...] = (OSError,),
    attempts: int = 3,
    base_delay_s: float = 0.05,
    max_delay_s: float = 2.0,
    jitter_frac: float = 0.25,
    seed: int = 0,
    registry: Optional[MetricsRegistry] = None,
    on_retry: Optional[Callable[[int, BaseException], None]] = None,
    sleep: Callable[[float], None] = time.sleep,
    give_up: Tuple[Type[BaseException], ...] = NON_TRANSIENT,
):
    """Call ``fn()`` retrying ``exceptions`` up to ``attempts`` total tries.

    Backoff doubles from ``base_delay_s`` (capped at ``max_delay_s``) with
    seeded symmetric jitter (+-``jitter_frac``) — deterministic for a given
    seed, so tests can pin schedules. ``on_retry(attempt, error)`` runs before
    each sleep (the checkpoint layer ledgers through it). ``give_up``
    exceptions re-raise immediately and unwrapped even when ``exceptions``
    covers them — deterministic failures (missing file, permissions) must
    keep their type and cost no backoff."""
    if attempts < 1:
        raise ValueError(f"attempts must be >= 1, got {attempts}")
    reg = registry if registry is not None else RETRY_REGISTRY
    rng = random.Random(seed)
    for attempt in range(1, attempts + 1):
        try:
            return fn()
        except exceptions as e:  # noqa: PERF203 — retry loop
            if isinstance(e, give_up):
                raise
            if attempt == attempts:
                raise RetryExhaustedError(name, attempts, e) from e
            reg.counter(f"retry/{name}").inc()
            if on_retry is not None:
                on_retry(attempt, e)
            sleep(
                backoff_delay(
                    attempt,
                    base_delay_s=base_delay_s,
                    max_delay_s=max_delay_s,
                    jitter_frac=jitter_frac,
                    rng=rng,
                )
            )


def retry(**opts):
    """Decorator form of ``call_with_retry`` (same kwargs; ``name`` defaults
    to the wrapped function's name)."""
    import functools

    def deco(fn):
        opts.setdefault("name", fn.__name__)

        @functools.wraps(fn)
        def wrapped(*args, **kwargs):
            return call_with_retry(lambda: fn(*args, **kwargs), **opts)

        return wrapped

    return deco
