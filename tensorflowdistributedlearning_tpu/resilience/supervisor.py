"""Restart supervisor: relaunch a training command until it finishes or is
provably stuck.

The reference stack's recovery story ended at Estimator resume-from-latest —
*something else* had to notice the dead process and relaunch it. This is that
something: a small, dependency-free loop that re-runs a fold's command with
exponential backoff + seeded jitter, a max-restart budget, and crash-loop
detection (no step progress between consecutive restarts ⇒ abort — a run that
re-dies at the same step forever must page a human, not burn the budget).

Every restart writes a ``restart`` event into the workdir's run ledger
(``telemetry.jsonl``) with the observed exit code, the step progress, and the
downtime — so ``telemetry-report`` can render a goodput-lost-to-restarts line
next to the usual time split. A final ``supervisor_abort`` event records why a
run was given up on.

Exit-code contract: ``0`` done; ``preempt.EXIT_PREEMPTED`` (75) is a routine
preemption (restart after backoff); anything else is a crash (also restarted,
but the crash-loop detector watches it). Progress is read from the ledger by
default (the last event carrying a ``step``), so the supervisor needs no
protocol with its child beyond the workdir.

This class supervises ONE child at a fixed shape. The multi-process
generalization — N host-slot children, where one death triggers a
checkpoint-coordinated WORLD RESIZE instead of a same-shape restart — is
``parallel/elastic.py``'s :class:`ElasticCoordinator`, which composes this
module's progress/backoff/crash-loop machinery (``ledger_progress``,
``retry.backoff_delay``, the same restart-budget semantics for
non-membership crashes).
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal as signal_lib
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence

from tensorflowdistributedlearning_tpu.resilience.preempt import EXIT_PREEMPTED

logger = logging.getLogger(__name__)

ABORT_CRASH_LOOP = "crash-loop"
ABORT_RESTART_BUDGET = "restart-budget"
ABORT_SIGNALED = "signaled"


def ledger_progress(workdir: str) -> Optional[int]:
    """Step progress of the run under ``workdir``: the last ledger event that
    carries a ``step`` (checkpoints, step windows, preemption). ``None`` when
    there is no ledger or no stepped event yet — i.e. no observable progress."""
    import os

    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    try:
        events = read_ledger(workdir)
    except (OSError, ValueError):
        return None
    for event in reversed(events):
        step = event.get("step")
        if isinstance(step, (int, float)):
            return int(step)
    return None


@dataclasses.dataclass
class SupervisorResult:
    ok: bool
    exit_code: int
    restarts: int
    aborted: Optional[str] = None  # ABORT_* or None
    final_step: Optional[int] = None
    downtime_s: float = 0.0


class Supervisor:
    """Run ``argv`` under restart supervision rooted at ``workdir``.

    ``launch`` is injectable for tests (a callable returning an exit code);
    the default runs ``argv`` as a subprocess inheriting stdio. ``sleep`` is
    injectable so backoff schedules are testable without wall time."""

    def __init__(
        self,
        argv: Sequence[str],
        *,
        workdir: Optional[str] = None,
        max_restarts: int = 3,
        backoff_base_s: float = 1.0,
        backoff_max_s: float = 30.0,
        jitter_frac: float = 0.25,
        seed: int = 0,
        crash_loop_tolerance: int = 2,
        progress_fn: Optional[Callable[[], Optional[int]]] = None,
        env: Optional[Dict[str, str]] = None,
        launch: Optional[Callable[[], int]] = None,
        sleep: Callable[[float], None] = time.sleep,
    ):
        if max_restarts < 0:
            raise ValueError(f"max_restarts must be >= 0, got {max_restarts}")
        if crash_loop_tolerance < 1:
            raise ValueError(
                f"crash_loop_tolerance must be >= 1, got {crash_loop_tolerance}"
            )
        self.argv = list(argv)
        self.workdir = workdir
        self.max_restarts = max_restarts
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self.jitter_frac = jitter_frac
        self.crash_loop_tolerance = crash_loop_tolerance
        self._rng = random.Random(seed)
        self._progress = progress_fn or (
            (lambda: ledger_progress(self.workdir)) if workdir else (lambda: None)
        )
        self._env = env
        self._launch = launch or self._launch_subprocess
        self._sleep = sleep
        self._child: Optional[subprocess.Popen] = None
        self._stop_signal: Optional[int] = None
        self.restart_events: List[Dict] = []

    def _launch_subprocess(self) -> int:
        env = dict(self._env if self._env is not None else os.environ)
        # children know they are supervised (the CLI uses this to make
        # supervisor recursion impossible; the run-header stamp lets
        # obs/report tell a session's children from later standalone runs)
        env["TFDL_SUPERVISED_CHILD"] = "1"
        self._child = subprocess.Popen(self.argv, env=env)
        try:
            if self._stop_signal is not None:
                # the signal landed while Popen was setting up (self._child
                # still None in the handler): forward it now so the fresh
                # child drains instead of running the whole job unsignaled
                try:
                    self._child.send_signal(self._stop_signal)
                except (ProcessLookupError, OSError):
                    pass
            return self._child.wait()
        finally:
            self._child = None

    # -- signal passthrough ------------------------------------------------
    # The supervisor is the pid a scheduler signals; the preemption contract
    # (first SIGTERM = checkpoint + exit 75) lives in the CHILD. Forward the
    # signal and stop relaunching — a preempted job must drain, not restart.

    def _on_signal(self, signum, frame) -> None:
        self._stop_signal = signum
        child = self._child
        if child is not None and child.poll() is None:
            logger.warning(
                "supervisor got %s — forwarding to child pid %d and stopping "
                "the restart loop",
                signal_lib.Signals(signum).name, child.pid,
            )
            try:
                child.send_signal(signum)
            except (ProcessLookupError, OSError):
                pass

    def _install_signals(self) -> Dict[int, object]:
        prev: Dict[int, object] = {}
        for sig in (signal_lib.SIGTERM, signal_lib.SIGINT):
            try:
                prev[sig] = signal_lib.signal(sig, self._on_signal)
            except ValueError:  # non-main thread: no passthrough, still works
                pass
        return prev

    @staticmethod
    def _restore_signals(prev: Dict[int, object]) -> None:
        for sig, disposition in prev.items():
            try:
                signal_lib.signal(sig, disposition)
            except (ValueError, TypeError):
                pass

    def _ledger(self):
        if self.workdir is None:
            return None
        from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

        # a second appender on the same telemetry.jsonl: the supervisor only
        # writes between child lifetimes, and readers key on the event kind
        return RunLedger(self.workdir)

    def _backoff(self, attempt: int) -> float:
        from tensorflowdistributedlearning_tpu.resilience.retry import (
            backoff_delay,
        )

        return backoff_delay(
            attempt,
            base_delay_s=self.backoff_base_s,
            max_delay_s=self.backoff_max_s,
            jitter_frac=self.jitter_frac,
            rng=self._rng,
        )

    def _stop_result(
        self, ledger, rc: int, restarts: int, step, downtime_s: float
    ) -> SupervisorResult:
        """The supervisor itself was told to stop: the child's exit (75 after
        its preemption checkpoint, ideally) is final — relaunching a job the
        scheduler is tearing down would fight the preemption. A child that
        finished CLEANLY (rc 0) under the incoming signal is a completed run,
        not an aborted one — no abort event for it."""
        if ledger is not None and rc != 0:
            ledger.event(
                "supervisor_abort",
                reason=ABORT_SIGNALED,
                signal=int(self._stop_signal),
                rc=rc,
                restarts=restarts,
                step=step,
            )
        return SupervisorResult(
            ok=rc == 0,
            exit_code=rc,
            restarts=restarts,
            aborted=None if rc == 0 else ABORT_SIGNALED,
            final_step=step,
            downtime_s=round(downtime_s, 3),
        )

    def run(self) -> SupervisorResult:
        ledger = self._ledger()
        prev_handlers = self._install_signals()
        restarts = 0
        no_progress = 0
        downtime_s = 0.0
        prev_step = self._progress()
        result: Optional[SupervisorResult] = None
        if ledger is not None:
            # session marker: obs/report scopes its resilience section to the
            # last supervised session (supervisor_start .. supervisor_end), so
            # stale restarts/aborts do not haunt later clean runs
            ledger.event(
                "supervisor_start",
                max_restarts=self.max_restarts,
                step=prev_step,
            )
        try:
            while True:
                rc = self._launch()
                died_t = time.time()
                step = self._progress()
                if self._stop_signal is not None:
                    result = self._stop_result(
                        ledger, rc, restarts, step, downtime_s
                    )
                    return result
                if rc == 0:
                    result = SupervisorResult(
                        ok=True,
                        exit_code=0,
                        restarts=restarts,
                        final_step=step,
                        downtime_s=round(downtime_s, 3),
                    )
                    return result
                reason = "preempted" if rc == EXIT_PREEMPTED else "crash"
                progressed = step is not None and (
                    prev_step is None or step > prev_step
                )
                no_progress = 0 if progressed else no_progress + 1
                abort = None
                if no_progress >= self.crash_loop_tolerance:
                    abort = ABORT_CRASH_LOOP
                elif restarts >= self.max_restarts:
                    abort = ABORT_RESTART_BUDGET
                if abort:
                    logger.error(
                        "supervisor giving up (%s) after %d restart(s): rc=%d, "
                        "step=%s",
                        abort, restarts, rc, step,
                    )
                    if ledger is not None:
                        ledger.event(
                            "supervisor_abort",
                            reason=abort,
                            rc=rc,
                            restarts=restarts,
                            step=step,
                        )
                    result = SupervisorResult(
                        ok=False,
                        exit_code=rc,
                        restarts=restarts,
                        aborted=abort,
                        final_step=step,
                        downtime_s=round(downtime_s, 3),
                    )
                    return result
                restarts += 1
                backoff = self._backoff(restarts)
                logger.warning(
                    "child exited rc=%d (%s) at step %s — restart %d/%d in "
                    "%.2fs",
                    rc, reason, step, restarts, self.max_restarts, backoff,
                )
                self._sleep(backoff)
                if self._stop_signal is not None:
                    # a signal landing between child lifetimes (typically mid
                    # backoff sleep) must not launch a fresh child the
                    # scheduler would have to kill again
                    result = self._stop_result(
                        ledger, rc, restarts - 1, step, downtime_s
                    )
                    return result
                restart_downtime = time.time() - died_t
                downtime_s += restart_downtime
                event = {
                    "attempt": restarts,
                    "rc": rc,
                    "reason": reason,
                    "step": step,
                    "prev_step": prev_step,
                    "backoff_s": round(backoff, 3),
                    "downtime_s": round(restart_downtime, 3),
                }
                self.restart_events.append(event)
                if ledger is not None:
                    ledger.event("restart", **event)
                prev_step = step
        finally:
            self._restore_signals(prev_handlers)
            if ledger is not None:
                if result is not None:
                    ledger.event(
                        "supervisor_end",
                        ok=result.ok,
                        restarts=result.restarts,
                        aborted=result.aborted,
                        step=result.final_step,
                        downtime_s=result.downtime_s,
                    )
                ledger.close()


def run_supervised(argv: Sequence[str], **kwargs) -> SupervisorResult:
    """One-shot convenience: ``Supervisor(argv, **kwargs).run()``."""
    return Supervisor(argv, **kwargs).run()


def shell_rc(rc: int) -> int:
    """A Popen returncode as the conventional shell exit status: signal
    deaths (``-N``) fold to ``128+N`` instead of a negative value the shell
    would wrap mod 256 — shared by the supervised and elastic CLI paths."""
    return 128 - rc if rc < 0 else rc
