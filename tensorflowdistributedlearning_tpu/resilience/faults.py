"""Deterministic fault injection: the testable half of failure handling.

Production TPU jobs treat preemptions and transient faults as routine
(arXiv:2204.06514 measures goodput by how fast a run recovers from one), but a
recovery path that only ever runs during real outages is untested code. This
module makes faults a *scheduled, seeded input*: a single spec string names
what fails, where, and when — and the trainers, the data path, and the
checkpoint layer carry cheap ``fire()`` hooks at the failure-prone sites.

Spec grammar (``--inject-fault``)::

    KIND@AT[xCOUNT]

    raise@12        raise InjectedFault after train step 12
    sigterm@12      SIGTERM this process after train step 12 (the preemption
                    drill: resilience/preempt.py turns it into a final
                    checkpoint + EXIT_PREEMPTED)
    sigterm@5-20    seeded schedule: the step is drawn uniformly from [5, 20]
                    by ``install(seed=...)`` — deterministic per seed, the
                    "kill at a random step" e2e
    io-data@3       transient IOError on the 3rd emitted record batch
    io-data@3x2     ... failing the 3rd AND 4th attempt (retry-exhaustion
                    shapes need consecutive failures)
    io-read@2       transient IOError on the 2nd tracked file open
                    (record shards, kaggle CSVs)
    io-ckpt@1       transient IOError on the 1st checkpoint save attempt
    sigkill@30      SIGKILL this process after the 30th answered serve
                    request (serve/server.py fires SITE_REQUEST per
                    response) — the un-drainable replica death the fleet
                    router/supervisor must converge through; unlike sigterm
                    there is no graceful path, the process just vanishes
    sigkill-step@6  SIGKILL this process after train step 6 — the host-death
                    drill (parallel/elastic.py): one host of a multi-process
                    run vanishes without draining, and the elastic
                    coordinator must detect it, drain the survivors, and
                    resize the world
    nan-loss@2      poison the 2nd OBSERVED loss (log window) with NaN — the
                    health-monitor drill (obs/health.py): the NaN guard must
                    alert, and warn-vs-abort must behave as configured.
                    Consumed via the non-raising ``poisoned()`` query, not
                    ``fire()`` (the site transforms a value rather than
                    failing)

Transient faults raise ``TransientInjectedIOError`` (an ``OSError``), exactly
what ``resilience.retry`` retries — the clean path through the same code
observes zero fires and zero retries. Step faults fire at most ``COUNT``
times per process (default 1), so a supervised restart that resumes *past*
the step recovers, while one that resumes *before* it re-dies deterministically
(the crash-loop the supervisor must detect).

Process-global by design: one injector per process, installed by the CLI flag
or by tests, consulted via module-level ``fire(site, index)`` that is a no-op
when nothing is installed.
"""

from __future__ import annotations

import dataclasses
import os
import random
import re
import signal
import threading
from typing import Optional

# injection sites the codebase carries hooks at
SITE_STEP = "step"  # trainers, after each completed train step (index = step)
SITE_DATA = "data"  # data/records.py, per emitted record batch
SITE_IO = "io"  # tracked file opens (record shards, kaggle CSVs)
SITE_CHECKPOINT = "checkpoint"  # CheckpointManager, per save attempt
SITE_LOSS = "loss"  # obs/health.py, per observed loss window (poisoned())
SITE_REQUEST = "request"  # serve/server.py, per answered /v1/predict

_KIND_SITE = {
    "raise": SITE_STEP,
    "sigterm": SITE_STEP,
    "sigkill": SITE_REQUEST,
    "sigkill-step": SITE_STEP,
    "io-data": SITE_DATA,
    "io-read": SITE_IO,
    "io-ckpt": SITE_CHECKPOINT,
    "nan-loss": SITE_LOSS,
}

_SPEC_RE = re.compile(
    r"^(?P<kind>raise|sigterm|sigkill-step|sigkill|io-data|io-read|io-ckpt"
    r"|nan-loss)"
    r"@(?P<lo>\d+)(?:-(?P<hi>\d+))?"
    r"(?:x(?P<count>\d+))?$"
)


class InjectedFault(RuntimeError):
    """The non-transient injected failure (``raise@STEP``) — nothing retries
    it; it models a crash the supervisor must restart through."""


class TransientInjectedIOError(OSError):
    """Injected transient I/O failure — the retry decorator's exception set
    covers it, so the recovery path is the production one."""


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One resolved fault: ``kind`` (grammar above), ``at`` (step for step
    kinds; 1-based occurrence for io kinds), ``count`` fires."""

    kind: str
    at: int
    count: int = 1

    @property
    def site(self) -> str:
        return _KIND_SITE[self.kind]


def parse_fault_spec(spec: str, seed: int = 0) -> FaultSpec:
    """Parse ``KIND@AT[xCOUNT]``; an ``AT`` range ``LO-HI`` resolves to one
    seeded-uniform draw (inclusive), so "kill at a random step" is
    reproducible from the seed alone."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad fault spec {spec!r}; expected KIND@AT[xCOUNT] with KIND in "
            f"{sorted(_KIND_SITE)} (e.g. 'sigterm@12', 'io-data@3x2', "
            "'raise@5-20' for a seeded random step)"
        )
    lo = int(m.group("lo"))
    hi = int(m.group("hi")) if m.group("hi") else lo
    if hi < lo:
        raise ValueError(f"bad fault spec {spec!r}: range {lo}-{hi} is empty")
    at = lo if hi == lo else random.Random(seed).randint(lo, hi)
    count = int(m.group("count")) if m.group("count") else 1
    if count < 1:
        raise ValueError(f"bad fault spec {spec!r}: count must be >= 1")
    return FaultSpec(kind=m.group("kind"), at=at, count=count)


class FaultInjector:
    """Executes one ``FaultSpec`` against the ``fire()`` hook stream.

    Occurrence counters are per-site and per-process; a supervised restart
    starts a fresh process with fresh counters (which is the point: whether
    the fault re-fires after resume is decided by the *spec*, not by state
    smuggled across the restart)."""

    def __init__(self, spec: FaultSpec):
        self.spec = spec
        self._lock = threading.Lock()
        self._occurrences = 0
        self.fired = 0

    def poisoned(self, site: str, index: Optional[int] = None) -> bool:
        """Non-raising twin of ``fire`` for value-transforming sites: does an
        installed value fault (``nan-loss``) fire at this occurrence? The
        1-based occurrence window [at, at + count) matches the io kinds —
        ``index`` (the step) is informational; the AT in the spec counts
        *observations* (log windows), which stay meaningful whatever the
        window cadence is."""
        spec = self.spec
        if site != spec.site or spec.kind != "nan-loss":
            return False
        with self._lock:
            self._occurrences += 1
            if not spec.at <= self._occurrences < spec.at + spec.count:
                return False
            self.fired += 1
        return True

    def fire(self, site: str, index: Optional[int] = None) -> None:
        spec = self.spec
        if site != spec.site or spec.kind == "nan-loss":
            return
        with self._lock:
            if site == SITE_STEP:
                if index != spec.at or self.fired >= spec.count:
                    return
            else:
                # io sites: 1-based occurrence window [at, at + count)
                self._occurrences += 1
                if not spec.at <= self._occurrences < spec.at + spec.count:
                    return
            self.fired += 1
        if spec.kind == "raise":
            raise InjectedFault(f"injected fault: raise at step {spec.at}")
        if spec.kind == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if spec.kind in ("sigkill", "sigkill-step"):
            # uncatchable by design: the replica/host-death drills must model
            # a process that VANISHES (OOM kill, node loss), not one that
            # drains
            os.kill(os.getpid(), signal.SIGKILL)
            return
        raise TransientInjectedIOError(
            f"injected transient I/O error ({spec.kind} occurrence "
            f"{self._occurrences})"
        )


_INJECTOR: Optional[FaultInjector] = None


def install(spec: Optional[str], seed: int = 0) -> Optional[FaultInjector]:
    """Install the process-global injector from a spec string (``None``/empty
    uninstalls). Returns the injector."""
    global _INJECTOR
    _INJECTOR = FaultInjector(parse_fault_spec(spec, seed)) if spec else None
    return _INJECTOR


def uninstall() -> None:
    global _INJECTOR
    _INJECTOR = None


def installed() -> Optional[FaultInjector]:
    return _INJECTOR


def fire(site: str, index: Optional[int] = None) -> None:
    """The hook the instrumented sites call; free when nothing is installed."""
    if _INJECTOR is not None:
        _INJECTOR.fire(site, index)


def poisoned(site: str, index: Optional[int] = None) -> bool:
    """Value-fault query (``nan-loss``): should the caller corrupt the value
    it is about to observe? Free when nothing is installed."""
    return _INJECTOR is not None and _INJECTOR.poisoned(site, index)
