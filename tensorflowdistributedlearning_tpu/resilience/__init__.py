"""Resilience: fault injection, preemption-safe checkpointing, auto-resume.

Production TPU jobs treat preemptions and transient faults as routine —
goodput is defined by how fast a run is back to useful steps after one
(Yoo et al., arXiv:2204.06514), and TF's own design carried checkpoint/
recovery machinery as a first-class subsystem (Abadi et al.,
arXiv:1605.08695). The reference harness had neither: Estimator's implicit
resume-from-latest (reference: model.py:117-121) and death on everything
else. This package makes runs survivable — and *testably* so:

- ``resilience.faults``     — deterministic, seeded fault injection
  (raise-at-step, SIGTERM-at-step, transient I/O on the Nth batch/open/
  checkpoint write) driven by one spec string from tests, the CLI
  (``train --inject-fault``), and ``tools/run_suite.py --resilience-smoke``;
- ``resilience.preempt``    — SIGTERM/SIGINT handler + file-based preemption
  notice; the trainers checkpoint at the next step boundary, ledger a
  ``preempted`` event, and exit ``EXIT_PREEMPTED`` (75);
- ``resilience.supervisor`` — restart loop with exponential backoff + seeded
  jitter, a max-restart budget, and crash-loop detection (no step progress
  between restarts ⇒ abort), writing ``restart`` ledger events that
  ``telemetry-report`` renders as goodput-lost-to-restarts;
- ``resilience.retry``      — backoff retry for the transient-failure-prone
  paths (checkpoint save/restore, record/CSV reads), every retry counted in
  an ``obs.metrics`` registry so the clean path is observably clean.

The contract the whole package is tested against: a run killed at a random
step and restarted by the supervisor reaches the same final step with params
bit-for-bit identical to an uninterrupted run
(tests/test_resilience.py::test_kill_and_resume_e2e).
"""

from tensorflowdistributedlearning_tpu.resilience.faults import (
    SITE_CHECKPOINT,
    SITE_DATA,
    SITE_IO,
    SITE_STEP,
    FaultInjector,
    FaultSpec,
    InjectedFault,
    TransientInjectedIOError,
    parse_fault_spec,
)
from tensorflowdistributedlearning_tpu.resilience.preempt import (
    EXIT_PREEMPTED,
    PreemptedError,
    PreemptionHandler,
)
# NOTE: the ``retry`` decorator is deliberately NOT re-exported here — the
# name would shadow the ``resilience.retry`` submodule attribute and break
# ``import ...resilience.retry as retry_lib`` consumers; use
# ``resilience.retry.retry`` directly.
from tensorflowdistributedlearning_tpu.resilience.retry import (
    RetryExhaustedError,
    call_with_retry,
)
from tensorflowdistributedlearning_tpu.resilience.supervisor import (
    ABORT_CRASH_LOOP,
    ABORT_RESTART_BUDGET,
    ABORT_SIGNALED,
    Supervisor,
    SupervisorResult,
    ledger_progress,
    run_supervised,
)

__all__ = [
    "ABORT_CRASH_LOOP",
    "ABORT_RESTART_BUDGET",
    "ABORT_SIGNALED",
    "EXIT_PREEMPTED",
    "FaultInjector",
    "FaultSpec",
    "InjectedFault",
    "PreemptedError",
    "PreemptionHandler",
    "RetryExhaustedError",
    "SITE_CHECKPOINT",
    "SITE_DATA",
    "SITE_IO",
    "SITE_STEP",
    "Supervisor",
    "SupervisorResult",
    "TransientInjectedIOError",
    "call_with_retry",
    "ledger_progress",
    "parse_fault_spec",
    "run_supervised",
]
