"""Expert parallelism: top-1-gated mixture-of-experts with all-to-all dispatch.

The reference had no experts (SURVEY §2.3: data parallelism only), so — like the
tensor, sequence, and pipeline axes — this is a beyond-parity primitive that
completes the framework's strategy set (dp / tp / pp / sp / ep). It is built the
TPU way: experts live one-per-shard on a mesh axis, and tokens move to their
expert and back via ``lax.all_to_all`` — the single collective XLA lowers to the
ICI all-to-all that makes MoE practical on pods.

Design (the Switch-style top-1 regime, fixed shapes throughout):

- ``gate``: a linear router produces per-token expert logits; top-1 assignment
  with a per-expert capacity ``C = ceil(tokens/E * capacity_factor)``;
- tokens are bucketed into a dense [E, C, D] dispatch buffer per shard (dropped
  beyond capacity — the standard fixed-shape trade), sent with all-to-all so
  each shard holds every shard's tokens for ITS expert, processed by the local
  expert, and returned by the inverse all-to-all;
- combine scales by the gate probability; dropped tokens fall back to a zero
  update (residual-style callers add the input back).

Everything is shape-static and jit/shard_map-compatible; autodiff flows through
both all-to-alls (their transpose is the reverse all-to-all).
"""

from __future__ import annotations

from typing import Any, Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS


def top1_dispatch(
    gate_logits: jax.Array, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Greedy top-1 routing with per-expert capacity.

    ``gate_logits``: [T, E]. Returns ``(expert, slot, keep, prob)`` each [T]:
    the chosen expert, the token's slot within that expert's capacity buffer,
    whether it fit (slot < capacity), and the softmax gate probability.
    """
    probs = jax.nn.softmax(gate_logits, axis=-1)
    expert = jnp.argmax(gate_logits, axis=-1)
    prob = jnp.take_along_axis(probs, expert[:, None], axis=1)[:, 0]
    # position of each token within its expert's arrival order
    one_hot = jax.nn.one_hot(expert, gate_logits.shape[-1], dtype=jnp.int32)
    slot = jnp.cumsum(one_hot, axis=0) * one_hot  # [T, E], 1-based where chosen
    slot = jnp.sum(slot, axis=-1) - 1  # [T], 0-based
    keep = slot < capacity
    return expert, slot, keep, prob


def _dispatch_buffers(
    gate_logits: jax.Array, x: jax.Array, n_experts: int, capacity_factor: float
):
    """Shared routing + dispatch-buffer build for BOTH execution strategies
    (one source of truth — the dense/EP numerical parity the tests assert
    depends on these staying in lockstep).

    Returns ``(buffer [E, C, D], flat_idx, keep, prob)``: the dense per-expert
    capacity buffer, each token's slot index, its keep mask, and its gate
    probability. Capacity is the documented ``C = ceil(tokens/E * factor)``."""
    import math

    t, d = x.shape
    capacity = max(1, math.ceil(t * capacity_factor / n_experts))
    expert, slot, keep, prob = top1_dispatch(gate_logits, capacity)
    # dense dispatch buffer [E, C, D]: token -> (its expert, its slot)
    flat_idx = expert * capacity + jnp.minimum(slot, capacity - 1)
    buffer = jnp.zeros((n_experts * capacity, d), x.dtype)
    buffer = buffer.at[flat_idx].add(jnp.where(keep[:, None], x, 0.0))
    return buffer.reshape(n_experts, capacity, d), flat_idx, keep, prob


def _combine(
    returned: jax.Array, flat_idx: jax.Array, keep: jax.Array, prob: jax.Array
) -> jax.Array:
    """Gather expert outputs back to token order, scale by the gate
    probability, zero the capacity-dropped tokens (shared by both paths)."""
    out = returned[flat_idx]
    return jnp.where(keep[:, None], out * prob[:, None].astype(out.dtype), 0.0)


def moe_apply(
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    my_expert_params: Any,
    gate_kernel: jax.Array,
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    axis_name: str = MODEL_AXIS,
    gate_logits: jax.Array = None,
) -> jax.Array:
    """Expert-parallel MoE layer inside ``shard_map``.

    ``x``: this shard's tokens [T, D] (e.g. a data-parallel shard's flattened
    activations); ``my_expert_params``: THIS shard's expert parameters (one
    expert per shard on ``axis_name``); ``gate_kernel``: [D, E] router weights,
    replicated. Returns [T, D]: each token processed by its chosen expert and
    scaled by the gate probability (zero where dropped by capacity).

    ``gate_logits`` ([T, E], optional) supplies precomputed router logits —
    e.g. a caller's float32 routing that must agree exactly with its
    load-balancing statistics; default recomputes ``x @ gate_kernel``.
    """
    n_experts = lax.axis_size(axis_name)
    if gate_kernel.shape[-1] != n_experts:
        raise ValueError(
            f"gate_kernel routes over {gate_kernel.shape[-1]} experts but the "
            f"{axis_name!r} mesh axis has {n_experts} shards (one expert each); "
            "an over-wide router would dispatch out of the capacity buffer"
        )
    if gate_logits is None:
        gate_logits = x @ gate_kernel  # [T, E]
    buffer, flat_idx, keep, prob = _dispatch_buffers(
        gate_logits, x, n_experts, capacity_factor
    )
    capacity = buffer.shape[1]
    d = buffer.shape[-1]

    # all-to-all: shard e receives every shard's bucket for expert e ->
    # [n_shards, C, D] worth of tokens for MY expert
    incoming = lax.all_to_all(buffer, axis_name, split_axis=0, concat_axis=0)
    processed = expert_fn(
        my_expert_params, incoming.reshape(n_experts * capacity, d)
    ).reshape(n_experts, capacity, d)
    # inverse all-to-all returns each shard its own tokens, expert-processed
    returned = lax.all_to_all(processed, axis_name, split_axis=0, concat_axis=0)
    return _combine(returned.reshape(n_experts * capacity, d), flat_idx, keep, prob)


def dense_moe_apply(
    expert_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_expert_params: Any,
    gate_kernel: jax.Array,
    x: jax.Array,
    *,
    capacity_factor: float = 1.25,
    gate_logits: jax.Array = None,
) -> jax.Array:
    """The all-experts-local twin of ``moe_apply``: identical routing, capacity,
    and combine semantics (shared helpers above), with every expert computed
    on-device (vmap over the stacked [E, ...] param tree) instead of
    one-expert-per-shard all-to-alls.

    This is what makes MoE *trainable on any mesh* (pure data parallelism, the
    CPU test mesh, a single chip) with numerics identical to the
    expert-parallel execution — the strategies differ only in where the expert
    FLOPs run."""
    n_experts = gate_kernel.shape[-1]
    if gate_logits is None:
        gate_logits = x @ gate_kernel
    buffer, flat_idx, keep, prob = _dispatch_buffers(
        gate_logits, x, n_experts, capacity_factor
    )
    capacity = buffer.shape[1]
    d = buffer.shape[-1]
    processed = jax.vmap(expert_fn)(stacked_expert_params, buffer)  # [E, C, D]
    return _combine(processed.reshape(n_experts * capacity, d), flat_idx, keep, prob)


def load_balance_loss(gate_logits: jax.Array) -> jax.Array:
    """Switch Transformer load-balancing auxiliary loss (arXiv:2101.03961 eq. 4):
    ``E * sum_e f_e * P_e`` where ``f_e`` is the fraction of tokens whose top-1
    choice is expert ``e`` and ``P_e`` the mean router probability for ``e``.
    Minimized (value 1) at a uniform distribution; without it, top-1 routing
    with capacity drops collapses onto few experts."""
    n_experts = gate_logits.shape[-1]
    probs = jax.nn.softmax(gate_logits.astype(jnp.float32), axis=-1)
    chosen = jnp.argmax(gate_logits, axis=-1)
    f = jnp.mean(jax.nn.one_hot(chosen, n_experts, dtype=jnp.float32), axis=0)
    p = jnp.mean(probs, axis=0)
    return n_experts * jnp.sum(f * p)
