"""Cross-replica (ZeRO-1) sharding of the weight update and optimizer state.

In plain data parallelism the optimizer state — Adam's two moments, LARS/SGD
momentum, the EMA tracker — is fully replicated: every chip stores ~2-3x the
parameter bytes in slots and runs the identical weight update N times. The fix
is the one "Automatic Cross-Replica Sharding of Weight Update in Data-Parallel
Training" (arXiv:2004.13336) built into XLA and the pjit/TPUv4 scaling report
(arXiv:2204.06514) runs in production: shard the optimizer state (and the
update computing it) across the DATA axis, so each replica stores and updates
1/dp of the slots, then gather the freshly-updated parameters.

This module is the spec/placement/update machinery behind
``TrainConfig.weight_update_sharding``:

- ``weight_update_specs`` — PartitionSpec pytree partitioning every leaf along
  the ``batch`` mesh axis on its LARGEST dp-divisible dimension (replicated
  fallback for scalars and indivisible leaves). With ``tensor_parallel=True``
  the batch-axis shard composes on top of the model-axis channel sharding
  (``parallel/tensor.py``): the batch shard lands on a dimension the model
  axis does not already occupy, or stacks onto the channel dimension when
  that is the only one that divides.
- ``shard_state_weight_update`` — TrainState placement: params/batch_stats in
  their canonical layout (replicated, or channel-sharded under TP),
  ``opt_state`` under the weight-update specs. Multi-host capable via
  ``tensor.place_full_value``.
- ``apply_gradients_sharded`` — the update itself, run inside jit under GSPMD
  sharding constraints: replicated gradients are constrained to the opt-state
  sharding (a local slice — the cross-replica reduce already happened inside
  the step), ``tx.update`` then computes each slot shard at 1/dp cost, and
  the parameter gather falls out of constraining the updated params back to
  their canonical spec. Numerics are those of the replicated update (the same
  elementwise math over the same global gradient), which the equivalence
  tests pin step-for-step.

The shard_map train step (train/step.py) composes with this by returning
(grads, batch_stats, metrics) from the manual region and applying the update
OUTSIDE it, where GSPMD owns placement; the GSPMD tensor-parallel step
(parallel/tensor.py:make_train_step_gspmd) applies it inline.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
    largest_divisible_dim,
)


def weight_update_spec(
    shape: Tuple[int, ...], mesh: Mesh, *, tensor_parallel: bool = False
) -> P:
    """The ZeRO-1 PartitionSpec for one optimizer-state (or gradient) leaf.

    The ``batch`` axis partitions the largest dimension divisible by the
    data-parallel degree; scalars and leaves with no divisible dimension stay
    replicated (they are the cheap tail — BN scale/offset vectors, schedule
    counters). Under ``tensor_parallel`` the leaf keeps the channel sharding
    its mirrored parameter has (``tensor._spec_for_leaf`` is shape-driven, so
    applying it to an Adam moment reproduces the param's spec exactly), and
    the batch axis takes the largest dimension the model axis left unsharded —
    or stacks onto the channel dimension when nothing else divides."""
    return weight_update_spec_for_degrees(
        shape,
        dp=mesh.shape[BATCH_AXIS],
        tp=mesh.shape[MODEL_AXIS] if tensor_parallel else 1,
    )


def weight_update_spec_for_degrees(
    shape: Tuple[int, ...], *, dp: int, tp: int = 1
) -> P:
    """:func:`weight_update_spec` queryable by plain degrees — no mesh (and
    no devices) needed, so the parallelism planner can predict a candidate
    layout's exact per-chip optimizer bytes with the SAME rule placement
    uses (the rules cannot drift apart: the mesh form delegates here)."""
    from tensorflowdistributedlearning_tpu.parallel.tensor import _spec_for_leaf

    base = (
        _spec_for_leaf(jax.ShapeDtypeStruct(shape, jnp.float32), ((MODEL_AXIS, tp),))
        if tp > 1
        else P()
    )
    if dp <= 1:
        return base
    taken = {i for i, names in enumerate(base) if names is not None}
    dim = largest_divisible_dim(shape, dp, taken=taken)
    if dim is None:
        # every free dimension resists dp: try stacking batch onto the
        # model-sharded channel dimension (per-shard extent must still divide)
        if taken and shape[-1] % (tp * dp) == 0:
            spec = list(base)
            spec[-1] = (MODEL_AXIS, BATCH_AXIS)
            return P(*spec)
        return base
    spec = [base[i] if i < len(base) else None for i in range(len(shape))]
    spec[dim] = BATCH_AXIS
    return P(*spec)


def weight_update_specs(
    tree: Any, mesh: Mesh, *, tensor_parallel: bool = False
) -> Any:
    """``weight_update_spec`` mapped over a pytree (opt_state, params, grads).

    Purely shape-driven, so the one function serves the optimizer state, the
    gradients, and the updates — leaves of equal shape land on equal specs,
    which is what lets the sharded ``tx.update`` run without any resharding
    between its operands."""
    return jax.tree.map(
        lambda leaf: weight_update_spec(
            tuple(jnp.shape(leaf)), mesh, tensor_parallel=tensor_parallel
        ),
        tree,
    )


def param_placement_specs(
    params: Any, mesh: Mesh, *, tensor_parallel: bool = False
) -> Any:
    """The canonical (non-ZeRO) placement of the parameters themselves:
    replicated in plain data parallelism, channel-sharded over the model axis
    under tensor parallelism. ZeRO-1 deliberately keeps params here — only
    the OPTIMIZER state shards over data (ZeRO-2/3 territory starts where
    gradients and params shard too)."""
    if tensor_parallel:
        from tensorflowdistributedlearning_tpu.parallel.tensor import (
            tensor_parallel_specs,
        )

        return tensor_parallel_specs(params, mesh)
    return jax.tree.map(lambda _: P(), params)


def _constrain(tree: Any, mesh: Mesh, specs: Any) -> Any:
    return jax.tree.map(
        lambda x, s: jax.lax.with_sharding_constraint(x, NamedSharding(mesh, s)),
        tree,
        specs,
    )


def shard_state_weight_update(state, mesh: Mesh, *, tensor_parallel: bool = False):
    """Place a TrainState for ZeRO-1 training: params/batch_stats in their
    canonical layout, ``opt_state`` sharded over the data axis under
    ``weight_update_specs``. Works multi-host (every process holds the same
    seeded init and contributes its addressable shards)."""
    from tensorflowdistributedlearning_tpu.parallel.tensor import _place_full_value

    def place(tree, specs):
        return jax.tree.map(
            lambda x, s: _place_full_value(x, NamedSharding(mesh, s)), tree, specs
        )

    return state.replace(
        step=_place_full_value(state.step, NamedSharding(mesh, P())),
        params=place(
            state.params,
            param_placement_specs(state.params, mesh, tensor_parallel=tensor_parallel),
        ),
        batch_stats=place(
            state.batch_stats,
            param_placement_specs(
                state.batch_stats, mesh, tensor_parallel=tensor_parallel
            ),
        ),
        opt_state=place(
            state.opt_state,
            weight_update_specs(
                state.opt_state, mesh, tensor_parallel=tensor_parallel
            ),
        ),
    )


def apply_gradients_sharded(
    state, grads: Any, new_batch_stats: Any, mesh: Mesh, *,
    tensor_parallel: bool = False,
):
    """One ZeRO-1 optimizer update under GSPMD sharding constraints (call
    inside jit, on gradients that are already the cross-replica global mean).

    Constraining the replicated gradients to the opt-state sharding is a free
    local slice; ``tx.update`` then runs every slot update at 1/dp per-chip
    cost (Adam moment math, LARS trust ratios, the EMA tracker all ride
    along, since their state leaves mirror param shapes and therefore specs);
    constraining the updated params back to their canonical placement is the
    all-gather that completes the round trip. The input opt_state is also
    constrained so a caller whose placement drifted (e.g. a checkpoint
    restored without shardings) converges back to the declared layout instead
    of letting GSPMD propagate an accidental one."""
    grad_specs = weight_update_specs(grads, mesh, tensor_parallel=tensor_parallel)
    opt_specs = weight_update_specs(
        state.opt_state, mesh, tensor_parallel=tensor_parallel
    )
    grads = _constrain(grads, mesh, grad_specs)
    opt_state = _constrain(state.opt_state, mesh, opt_specs)
    updates, new_opt_state = state.tx.update(grads, opt_state, state.params)
    updates = _constrain(updates, mesh, grad_specs)
    new_opt_state = _constrain(new_opt_state, mesh, opt_specs)
    new_params = optax.apply_updates(state.params, updates)
    new_params = _constrain(
        new_params,
        mesh,
        param_placement_specs(state.params, mesh, tensor_parallel=tensor_parallel),
    )
    return state.replace(
        step=state.step + 1,
        params=new_params,
        batch_stats=new_batch_stats,
        opt_state=new_opt_state,
    )
