"""Unified parallelism planner: one mesh spec from model + HBM budget + topology.

The repo grew five composable parallelism modes (``parallel/``: zero.py,
tensor.py, pipeline.py, spatial.py, expert.py) but every run still hand-picked
``--model-parallel/--pipeline-parallel/--expert-parallel/--sequence-parallel/
--weight-update-sharding`` per invocation — exactly the manual-layout problem
the GSPMD/pjit scaling methodology (arXiv:2204.06514) automates, priced by the
chips-for-qps lens of the Gemma-on-TPU report (arXiv:2605.25645): the wrong
layout wastes chips. This module derives the layout instead:

- **Enumerate** candidate ``(dp, tp, pp, spatial, expert, zero1)`` layouts over
  the device topology (local devices plus the ``multihost.process_info`` pod
  shape — a batch shard must never span processes);
- **Reject** the indivisible ones with a NAMED constraint (the same
  divisibility rules the execution strategies enforce at trace time, surfaced
  at plan time) and the over-budget ones with exact predicted bytes/chip — the
  params/opt-state accounting reuses the REAL spec rules
  (``tensor.tensor_parallel_spec_for_shape``,
  ``zero.weight_update_spec_for_degrees``) over an abstract ``eval_shape`` of
  the actual TrainState, so the prediction matches
  ``train.state.tree_bytes_per_device`` of the placed state EXACTLY;
- **Score** the survivors with a simple comms-vs-compute cost model (per-chip
  all-reduce volume per step against per-chip FLOPs — constants documented on
  the functions; only the RELATIVE ordering matters) and emit a
  :class:`ParallelPlan` — the single object both trainers consume.

Entry points:

- :func:`plan` — the engine: pin any subset of the layout fields (explicit
  flags always win), plan the rest. ``pinned={}`` is ``--parallelism auto``;
  pinning everything is the explicit-flags validator (indivisible degrees fail
  fast with the named constraint instead of deep inside pjit; an over-budget
  EXPLICIT spec is a warning on the plan, not an error — the activation term
  is an estimate and the operator said what they wanted).
- :func:`plan_for_config` / :func:`validate_config` — the trainer-facing
  wrappers over a ``(ModelConfig, TrainConfig, global_batch)`` triple.
- :func:`render_plan_table` — the ``plan`` CLI's candidate table: chosen
  layout, predicted params/opt/activation bytes per chip, headroom against
  the budget, and why each rejected candidate lost.

The chosen plan rides the run-header ledger event (``plan`` field, rendered by
``telemetry-report``), and the capacity layer's ``memory_watermark`` events
carry measured-vs-predicted deltas against the same accounting — the feedback
loop that tells you how much margin this cost model needs.
"""

from __future__ import annotations

import dataclasses
import functools
import math
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "PlanError",
    "Layout",
    "MeasuredCosts",
    "Topology",
    "ModelProfile",
    "Candidate",
    "ParallelPlan",
    "detect_topology",
    "profile_model",
    "measured_costs_from_workdir",
    "measured_margin_from_workdir",
    "plan",
    "plan_for_config",
    "validate_config",
    "render_plan_table",
]


class PlanError(ValueError):
    """A layout (requested or required) cannot run: the message carries the
    NAMED constraint (e.g. ``model_axis_indivisible``) so failures are
    actionable at parse time, not mid-compile."""


# -- cost-model constants ----------------------------------------------------

# peak bf16 matmul FLOP/s per chip by device_kind substring (public figures;
# the same table bench.py prices MFU with). Unknown kinds (CPU hosts) fall
# back to DEFAULT_PEAK_FLOPS — on a homogeneous mesh only the compute/comms
# RATIO matters for candidate ordering, not the absolute scale.
PEAK_FLOPS_BY_KIND = {
    "v6e": 918e12,
    "v6": 918e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v5": 197e12,
    "v4": 275e12,
    "v3": 123e12,
    "v2": 45e12,
}
DEFAULT_PEAK_FLOPS = 100e12
# per-chip interconnect bandwidth the comm terms divide by (order-of-magnitude
# ICI figure; DCN-crossing layouts are already excluded by the
# spans-processes rule, so one constant suffices)
ICI_BYTES_PER_SEC = 4.5e10
# backward-pass factor on live activations (forward intermediates kept for
# grad); remat trades them back for recompute
ACTIVATION_BWD_FACTOR = 2.0
# fixed launch/sync latency per collective op: data-parallel pays it once
# (the bucketed gradient all-reduce), tensor/expert parallel pay it per
# LAYER — the term that keeps TP from winning on small models where its
# lower all-reduce volume would otherwise look free. Hosts without a real
# interconnect (CPU meshes — tests, laptops) pay an order of magnitude more
# per op, which correctly biases CPU plans toward plain DP/ZeRO-1.
COLLECTIVE_LATENCY_S = 1e-5
COLLECTIVE_LATENCY_CPU_S = 1e-4
# spatial halo exchange: fraction of the per-chip activation bytes that
# crosses the sequence axis per step (boundary rows only)
SPATIAL_HALO_FRAC = 0.1

# reject-reason names (stable strings — tests and the CLI table key on them)
REJECT_MODEL_AXIS = "model_axis_indivisible"
REJECT_SPANS_PROCESSES = "batch_shard_spans_processes"
REJECT_BATCH = "batch_indivisible"
REJECT_PROCESS_BATCH = "process_batch_indivisible"
REJECT_GRAD_ACCUM = "grad_accum_indivisible"
REJECT_MICROBATCH = "microbatch_indivisible"
REJECT_PIPELINE = "pipeline_unsupported"
REJECT_SPATIAL = "spatial_stride_indivisible"
REJECT_EXPERT = "expert_mismatch"
REJECT_CONFLICT = "strategy_conflict"
REJECT_BUDGET = "over_budget"
# the SOFT reject set: a pinned/explicit layout failing only these comes back
# with a warning instead of raising (the activation term is an estimate, and
# the operator asked for that layout); everything else is a hard constraint
# no execution strategy can run, which raises with the named reason
_SOFT_REJECTS = frozenset({REJECT_BUDGET})


@dataclasses.dataclass(frozen=True)
class Layout:
    """One concrete assignment of every parallelism knob (the fields mirror
    ``TrainConfig``; ``data_parallel`` is derived, carried for display)."""

    data_parallel: int
    model_parallel: int = 1
    pipeline_parallel: int = 1
    sequence_parallel: int = 1
    expert_parallel: int = 1
    weight_update_sharding: bool = False

    @property
    def model_axis(self) -> int:
        """The mesh's model-axis degree: tp, pp and ep are mutually exclusive
        riders on the same axis (parallel/mesh.py contract)."""
        return max(
            self.model_parallel, self.pipeline_parallel, self.expert_parallel
        )

    @property
    def denom(self) -> int:
        return self.model_axis * self.sequence_parallel

    def describe(self) -> str:
        parts = [f"dp{self.data_parallel}"]
        if self.model_parallel > 1:
            parts.append(f"tp{self.model_parallel}")
        if self.pipeline_parallel > 1:
            parts.append(f"pp{self.pipeline_parallel}")
        if self.sequence_parallel > 1:
            parts.append(f"sp{self.sequence_parallel}")
        if self.expert_parallel > 1:
            parts.append(f"ep{self.expert_parallel}")
        if self.weight_update_sharding:
            parts.append("zero1")
        return "x".join(parts)

    def to_json(self) -> Dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass(frozen=True)
class Topology:
    """The device fabric a plan targets. Constructed from the live backend by
    :func:`detect_topology`, or by hand for what-if planning (a pod layout
    planned from a laptop, the fake-``process_info`` tests)."""

    n_devices: int
    local_device_count: int
    process_count: int = 1
    hbm_bytes_per_device: Optional[int] = None
    device_kind: str = "cpu"

    def peak_flops(self) -> float:
        kind = self.device_kind.lower()
        for key, flops in PEAK_FLOPS_BY_KIND.items():
            if key in kind:
                return flops
        return DEFAULT_PEAK_FLOPS

    def collective_latency_s(self) -> float:
        kind = self.device_kind.lower()
        if any(key in kind for key in PEAK_FLOPS_BY_KIND):
            return COLLECTIVE_LATENCY_S
        return COLLECTIVE_LATENCY_CPU_S


def detect_topology(
    n_devices: Optional[int] = None,
    hbm_bytes_per_device: Optional[int] = None,
) -> Topology:
    """Topology of the live backend (the trainers' path): device count from
    ``jax.devices()`` (truncated to ``n_devices`` exactly like ``make_mesh``),
    pod shape from ``multihost.process_info``, per-chip HBM from the
    allocator's ``bytes_limit`` when the backend reports one (CPU builds
    report nothing — the budget gate then only fires on an explicit budget)."""
    import jax

    from tensorflowdistributedlearning_tpu.parallel import multihost

    devices = jax.devices()
    n = len(devices) if n_devices is None else n_devices
    if n > len(devices):
        raise PlanError(
            f"requested {n} devices but only {len(devices)} are visible"
        )
    info = multihost.process_info()
    if hbm_bytes_per_device is None:
        from tensorflowdistributedlearning_tpu.utils.profiling import (
            memory_stats,
        )

        try:
            stats = memory_stats() or {}
        except Exception:  # noqa: BLE001 — a down allocator probe is not fatal
            stats = {}
        limits = [
            int(s["bytes_limit"]) for s in stats.values() if s.get("bytes_limit")
        ]
        hbm_bytes_per_device = min(limits) if limits else None
    return Topology(
        n_devices=n,
        local_device_count=min(n, info["local_device_count"]),
        process_count=info["process_count"],
        hbm_bytes_per_device=hbm_bytes_per_device,
        device_kind=getattr(devices[0], "device_kind", devices[0].platform),
    )


@dataclasses.dataclass
class ModelProfile:
    """Abstract (ShapeDtypeStruct) view of one training state + an activation
    estimate — everything candidate evaluation needs, no device memory
    touched. Tests construct these by hand for synthetic scoring cases."""

    params: Any
    batch_stats: Any
    opt_state: Any
    activation_bytes_per_example: int
    param_count: int
    # layer-ish count (matrix/conv param leaves) for the per-collective
    # latency term; synthetic test profiles may set it directly
    n_layers: int = 1

    @property
    def params_bytes(self) -> int:
        return _tree_bytes(self.params, lambda s: ())

    @property
    def opt_state_bytes(self) -> int:
        return _tree_bytes(self.opt_state, lambda s: ())


def _leaf_bytes(leaf) -> int:
    shape = getattr(leaf, "shape", None)
    if shape is None:
        return 0
    return int(np.prod(shape, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize


def profile_model(model_config, train_config) -> ModelProfile:
    """Abstract profile of the training state ``(model_config, train_config)``
    would build: the EXACT params/opt-state pytree (``jax.eval_shape`` over
    ``create_train_state`` — the optimizer chain included, so Adam moments,
    LARS slots and the EMA tracker all count), plus an activation estimate
    from a captured-intermediates abstract forward (every module's output
    summed; coarse by design — the watermark events' measured-vs-predicted
    delta is where its error is ledgered).

    Memoized on ``(model_config, tx)``: ``make_optimizer`` already returns
    one object per equivalent optimizer config, so repeated plans over the
    same architecture (K-fold loops, every fit() in a test suite) skip the
    two abstract traces entirely."""
    from tensorflowdistributedlearning_tpu.train import step as step_lib

    return _profile_model_cached(
        model_config, step_lib.make_optimizer(train_config)
    )


@functools.lru_cache(maxsize=64)
def _profile_model_cached(model_config, tx) -> ModelProfile:
    import jax

    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.train.state import create_train_state
    from tensorflowdistributedlearning_tpu.utils.params import count_params

    model = build_model(model_config)
    h, w = model_config.input_shape
    sample = jax.ShapeDtypeStruct(
        (1, h, w, model_config.input_channels), np.float32
    )
    state = jax.eval_shape(
        lambda rng, x: create_train_state(model, tx, rng, x),
        jax.ShapeDtypeStruct((2,), np.uint32),
        sample,
    )
    act_bytes = _activation_bytes_per_example(
        model, state.params, state.batch_stats, sample
    )
    n_layers = sum(
        1
        for leaf in jax.tree_util.tree_leaves(state.params)
        if getattr(leaf, "ndim", 0) >= 2
    )
    return ModelProfile(
        params=state.params,
        batch_stats=state.batch_stats,
        opt_state=state.opt_state,
        activation_bytes_per_example=act_bytes,
        param_count=count_params(state.params),
        n_layers=max(n_layers, 1),
    )


def _activation_bytes_per_example(model, params, batch_stats, sample) -> int:
    """Sum of every module's output bytes for one example (abstract
    captured-intermediates forward) — the forward activation footprint a
    non-remat backward keeps live. Falls back to a coarse multiple of the
    input when the abstract forward cannot run (a model that insists on
    collectives outside shard_map)."""
    import jax

    input_bytes = _leaf_bytes(sample)
    variables = {"params": params}
    if jax.tree_util.tree_leaves(batch_stats):
        variables["batch_stats"] = batch_stats

    def fwd(v, x):
        return model.apply(
            v, x, train=False, capture_intermediates=True,
            mutable=["intermediates"],
        )

    try:
        _, inter = jax.eval_shape(fwd, variables, sample)
        total = input_bytes + sum(
            _leaf_bytes(leaf) for leaf in jax.tree_util.tree_leaves(inter)
        )
        return int(total)
    except Exception:  # noqa: BLE001 — estimate, not a gate
        return int(input_bytes * 64)


# -- exact shard accounting --------------------------------------------------


def _tree_bytes(tree, spec_for_shape, sizes: Optional[Dict[str, int]] = None) -> int:
    """Per-chip bytes of an abstract pytree under a spec rule: each dimension
    named in the leaf's PartitionSpec divides by the product of its axis
    degrees — integer-exact, because the spec rules only ever shard divisible
    dimensions, which is precisely ``NamedSharding.shard_shape``'s contract.
    This is what makes the planner's prediction match
    ``tree_bytes_per_device`` of the placed state bit-for-bit."""
    import jax

    sizes = sizes or {}
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            continue
        dims = list(shape)
        for i, names in enumerate(spec_for_shape(tuple(shape))):
            if names is None:
                continue
            for name in names if isinstance(names, tuple) else (names,):
                dims[i] //= sizes.get(name, 1)
        total += int(np.prod(dims, dtype=np.int64)) * np.dtype(leaf.dtype).itemsize
    return total


def _layout_bytes(
    profile: ModelProfile,
    layout: Layout,
    *,
    per_chip_examples: float,
    remat: bool,
) -> Dict[str, int]:
    """Predicted bytes/chip per component under ``layout``'s REAL spec rules
    (replicated / tensor / ZeRO-1 — the same functions placement uses)."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import (
        BATCH_AXIS,
        MODEL_AXIS,
        SEQUENCE_AXIS,
    )
    from tensorflowdistributedlearning_tpu.parallel.tensor import (
        tensor_parallel_spec_for_shape,
    )
    from tensorflowdistributedlearning_tpu.parallel.zero import (
        weight_update_spec_for_degrees,
    )

    tp = layout.model_parallel
    sizes = {
        BATCH_AXIS: layout.data_parallel,
        MODEL_AXIS: layout.model_axis,
        SEQUENCE_AXIS: layout.sequence_parallel,
    }
    replicated = lambda shape: ()  # noqa: E731 — the trivial spec rule
    param_rule = (
        (lambda s: tensor_parallel_spec_for_shape(s, tp)) if tp > 1 else replicated
    )
    if layout.weight_update_sharding:
        opt_rule = lambda s: weight_update_spec_for_degrees(  # noqa: E731
            s, dp=layout.data_parallel, tp=tp
        )
    else:
        opt_rule = param_rule
    params_bytes = _tree_bytes(profile.params, param_rule, sizes)
    stats_bytes = _tree_bytes(profile.batch_stats, param_rule, sizes)
    opt_bytes = _tree_bytes(profile.opt_state, opt_rule, sizes)
    act = profile.activation_bytes_per_example * per_chip_examples
    act *= 1.0 if remat else ACTIVATION_BWD_FACTOR
    act /= max(layout.sequence_parallel, 1)
    return {
        "params_bytes_per_chip": params_bytes,
        "batch_stats_bytes_per_chip": stats_bytes,
        "opt_state_bytes_per_chip": opt_bytes,
        "activation_bytes_per_chip": int(act),
        "total_bytes_per_chip": params_bytes + stats_bytes + opt_bytes + int(act),
    }


# -- candidate evaluation ----------------------------------------------------


@dataclasses.dataclass
class Candidate:
    layout: Layout
    feasible: bool = False
    reject_reason: Optional[str] = None
    reject_detail: Optional[str] = None
    bytes: Optional[Dict[str, int]] = None
    headroom_frac: Optional[float] = None
    compute_s: Optional[float] = None
    comm_s: Optional[float] = None
    score: Optional[float] = None
    # the analytic-constants score, kept alongside when `score` was priced
    # with measured rates — the plan table's measured-vs-analytic columns
    score_analytic: Optional[float] = None

    def to_json(self) -> Dict:
        out: Dict = {
            "layout": self.layout.to_json(),
            "feasible": self.feasible,
        }
        if self.reject_reason:
            out["reject_reason"] = self.reject_reason
            if self.reject_detail:
                out["reject_detail"] = self.reject_detail
        if self.bytes:
            out["predicted"] = dict(self.bytes)
        if self.headroom_frac is not None:
            out["headroom_frac"] = self.headroom_frac
        if self.score is not None:
            out["score"] = self.score
        if self.score_analytic is not None:
            out["score_analytic"] = self.score_analytic
        return out


def _check_conflicts(layout: Layout, train_config) -> Optional[Tuple[str, str]]:
    """The strategy mutual-exclusivity matrix (mirroring
    ``TrainConfig.__post_init__`` and the trainers): tp/pp/ep each own the
    model axis exclusively, sequence parallelism is its own execution
    strategy, the GPipe runner owns its own update placement (no ZeRO-1) and
    batch math (no grad accumulation), and the mixing augmentations thread
    extra batch fields only the data/tensor-parallel step carries. Enumerated
    layouts never combine riders, so this primarily guards PINNED combos —
    and keeps auto from choosing a layout the config would then reject."""
    riders = [
        d for d in (
            layout.model_parallel, layout.pipeline_parallel,
            layout.expert_parallel,
        ) if d > 1
    ]
    if len(riders) > 1 or (riders and layout.sequence_parallel > 1):
        return REJECT_CONFLICT, (
            f"{layout.describe()}: tensor/pipeline/expert/sequence "
            "parallelism are mutually exclusive execution strategies over "
            "the same mesh axes (one rider at a time)"
        )
    if layout.pipeline_parallel > 1 and layout.weight_update_sharding:
        return REJECT_CONFLICT, (
            "weight_update_sharding cannot combine with pipeline_parallel: "
            "the GPipe stage runner owns its own update placement"
        )
    accum = getattr(train_config, "grad_accum_steps", 1)
    if accum > 1 and (
        layout.model_parallel > 1 or layout.pipeline_parallel > 1
    ):
        return REJECT_CONFLICT, (
            f"grad_accum_steps={accum} runs inside the shard_map "
            "data/spatial step; the GSPMD tensor-parallel and pipeline "
            "strategies define their own batch math"
        )
    augmentation = getattr(train_config, "augmentation", "flip_crop")
    if augmentation in ("mixup", "cutmix") and (
        layout.sequence_parallel > 1 or layout.pipeline_parallel > 1
    ):
        return REJECT_CONFLICT, (
            f"augmentation={augmentation!r} threads paired-example batch "
            "fields the sequence-parallel and pipeline strategies do not "
            "carry"
        )
    if layout.pipeline_parallel > 1 and getattr(
        train_config, "sync_batch_norm", False
    ):
        return REJECT_CONFLICT, (
            "sync_batch_norm cannot combine with pipeline_parallel: the "
            "GPipe schedule computes BN statistics microbatch-wise"
        )
    return None


def _check_divisibility(
    layout: Layout,
    model_config,
    topo: Topology,
    global_batch: int,
    grad_accum: int,
    microbatches: Optional[int],
) -> Optional[Tuple[str, str]]:
    """First failed (reason, detail) pair, None when the layout divides. The
    rules mirror the execution strategies' own trace-time checks — pipeline
    and spatial delegate to the REAL validators so the constraints can never
    drift apart."""
    n, denom = topo.n_devices, layout.denom
    if n % denom:
        return REJECT_MODEL_AXIS, (
            f"{n} devices not divisible by model_axis*sequence = {denom}"
        )
    if topo.process_count > 1 and topo.local_device_count % denom:
        return REJECT_SPANS_PROCESSES, (
            f"model_axis*sequence = {denom} does not divide the "
            f"{topo.local_device_count} devices local to each process — a "
            "data-parallel shard would span processes"
        )
    # process divisibility first: every valid dp is a multiple of the
    # process count (a batch shard never spans processes), so checking dp
    # first would mask this with the less actionable per-dp message
    if global_batch % topo.process_count:
        return REJECT_PROCESS_BATCH, (
            f"global batch {global_batch} not divisible by process count "
            f"{topo.process_count}"
        )
    dp = layout.data_parallel
    if global_batch % dp:
        return REJECT_BATCH, (
            f"global batch {global_batch} not divisible by data-parallel "
            f"degree {dp}"
        )
    local_bs = global_batch // dp
    if local_bs % grad_accum:
        return REJECT_GRAD_ACCUM, (
            f"per-shard batch {local_bs} not divisible by "
            f"grad_accum_steps={grad_accum}"
        )
    if layout.pipeline_parallel > 1:
        from tensorflowdistributedlearning_tpu.train.pipeline_step import (
            validate_pipeline_config,
        )

        micro = microbatches or layout.pipeline_parallel
        try:
            validate_pipeline_config(
                model_config, layout.pipeline_parallel, micro
            )
        except ValueError as e:
            return REJECT_PIPELINE, str(e)
        if local_bs % micro:
            return REJECT_MICROBATCH, (
                f"per-replica batch {local_bs} not divisible into "
                f"{micro} pipeline microbatches"
            )
    if layout.sequence_parallel > 1:
        from tensorflowdistributedlearning_tpu.parallel.spatial import (
            validate_spatial_config,
        )

        try:
            validate_spatial_config(model_config, layout.sequence_parallel)
        except ValueError as e:
            return REJECT_SPATIAL, str(e)
    if layout.expert_parallel > 1:
        experts = getattr(model_config, "moe_experts", 0)
        if layout.expert_parallel != experts:
            return REJECT_EXPERT, (
                f"expert_parallel={layout.expert_parallel} requires "
                f"moe_experts={layout.expert_parallel} (one expert per "
                f"shard); the model has {experts}"
            )
    return None


@dataclasses.dataclass(frozen=True)
class MeasuredCosts:
    """Measured rates that replace the cost model's analytic constants —
    this box's numbers instead of the public peak table. Read back from the
    continuous profiler's ledgered ``op_roofline`` events
    (:func:`measured_costs_from_workdir`).

    ``flops_per_sec_per_chip`` is the achieved END-TO-END rate (analytic
    step FLOPs over measured step wall) — deliberately not the MXU-only
    rate: it folds in the HBM-bound reality the analytic peak ignores, so
    measured scores are absolute step-time estimates where analytic scores
    are only a relative ordering. ``collective_bytes_per_sec`` is the
    achieved per-chip collective bandwidth from the xplane ``collectives``
    bucket; ``None`` falls back to ``ICI_BYTES_PER_SEC`` (CPU runs, or
    captures whose layout priced no collective volume)."""

    flops_per_sec_per_chip: float
    collective_bytes_per_sec: Optional[float] = None
    captures: int = 0
    source: Optional[str] = None  # the workdir the rooflines came from

    def to_json(self) -> Dict:
        out: Dict = {
            "flops_per_sec_per_chip": self.flops_per_sec_per_chip,
            "captures": self.captures,
        }
        if self.collective_bytes_per_sec is not None:
            out["collective_bytes_per_sec"] = self.collective_bytes_per_sec
        if self.source:
            out["source"] = self.source
        return out


def _cost(
    profile: ModelProfile,
    layout: Layout,
    topo: Topology,
    bytes_per_chip: Dict[str, int],
    global_batch: int,
    microbatches: Optional[int],
    measured: Optional[MeasuredCosts] = None,
) -> Tuple[float, float]:
    """(compute_s, comm_s) for one step under the simple cost model.

    Compute: a dense-proxy ``6 * params * examples`` FLOP count split over
    the chips, inflated by the GPipe bubble ``(K-1)/M`` for pipeline layouts.
    Comms, per chip per step (ring-collective volumes over ICI):

    - data-parallel gradient all-reduce: ``2 * P_chip * (dp-1)/dp`` where
      ``P_chip`` is the per-chip gradient bytes (full params, /tp under TP);
    - ZeRO-1 adds the parameter all-gather ``P_chip * (dp-1)/dp`` (its win is
      memory and 1/dp update compute, which the budget gate prices — at
      equal feasibility plain DP therefore scores no worse, the intended
      tie-break);
    - tensor parallel adds per-layer activation all-reduces, approximated by
      the summed intermediate activations ``2 * A * (tp-1)/tp``;
    - pipeline adds stage-boundary activations ``2 * A / pp``;
    - spatial adds the halo exchange ``SPATIAL_HALO_FRAC * A``;
    - expert parallel adds the token all-to-all ``2 * A * (ep-1)/ep``.

    Every collective additionally pays ``COLLECTIVE_LATENCY_S`` per op:
    data parallel launches ONE bucketed all-reduce, tensor/expert parallel
    launch ~2 per layer — the fixed cost that keeps TP from winning on small
    models where its lower all-reduce volume would otherwise look free.

    With ``measured`` (:class:`MeasuredCosts`, from a prior run's ledgered
    rooflines) the achieved FLOP/s replaces the peak table and the achieved
    collective bandwidth replaces ``ICI_BYTES_PER_SEC`` — same model, this
    box's rates.
    """
    dp = layout.data_parallel
    tp = layout.model_parallel
    act = float(bytes_per_chip["activation_bytes_per_chip"])
    grad_bytes = float(bytes_per_chip["params_bytes_per_chip"])

    flops_per_chip_rate = (
        measured.flops_per_sec_per_chip if measured else topo.peak_flops()
    )
    ici_bytes_per_sec = (
        measured.collective_bytes_per_sec
        if measured and measured.collective_bytes_per_sec
        else ICI_BYTES_PER_SEC
    )
    flops = 6.0 * profile.param_count * global_batch
    compute = flops / topo.n_devices / flops_per_chip_rate
    if layout.pipeline_parallel > 1:
        micro = microbatches or layout.pipeline_parallel
        compute *= 1.0 + (layout.pipeline_parallel - 1) / micro

    comm = 0.0
    latency_ops = 0
    if dp > 1:
        comm += 2.0 * grad_bytes * (dp - 1) / dp
        latency_ops += 1
        if layout.weight_update_sharding:
            comm += grad_bytes * (dp - 1) / dp
            latency_ops += 1
    if tp > 1:
        comm += 2.0 * act * (tp - 1) / tp
        latency_ops += 2 * profile.n_layers
    if layout.pipeline_parallel > 1:
        comm += 2.0 * act / layout.pipeline_parallel
        latency_ops += 2 * (microbatches or layout.pipeline_parallel)
    if layout.sequence_parallel > 1:
        comm += SPATIAL_HALO_FRAC * act
        latency_ops += profile.n_layers
    if layout.expert_parallel > 1:
        ep = layout.expert_parallel
        comm += 2.0 * act * (ep - 1) / ep
        latency_ops += 2 * profile.n_layers
    return (
        compute,
        comm / ici_bytes_per_sec
        + latency_ops * topo.collective_latency_s(),
    )


def _evaluate(
    profile: ModelProfile,
    layout: Layout,
    model_config,
    train_config,
    topo: Topology,
    global_batch: int,
    grad_accum: int,
    microbatches: Optional[int],
    budget_bytes: Optional[int],
    measured_margin_bytes: int = 0,
    measured_costs: Optional[MeasuredCosts] = None,
) -> Candidate:
    cand = Candidate(layout=layout)
    failed = _check_conflicts(layout, train_config) or _check_divisibility(
        layout, model_config, topo, global_batch, grad_accum, microbatches
    )
    if failed:
        cand.reject_reason, cand.reject_detail = failed
        return cand
    local_bs = global_batch // layout.data_parallel
    per_chip_examples = local_bs / max(grad_accum, 1)
    if layout.pipeline_parallel > 1:
        per_chip_examples = local_bs / (
            microbatches or layout.pipeline_parallel
        )
    cand.bytes = _layout_bytes(
        profile,
        layout,
        per_chip_examples=per_chip_examples,
        remat=bool(getattr(model_config, "remat", False)),
    )
    if measured_margin_bytes > 0:
        # the ledgered measured-vs-predicted watermark residual of a PRIOR
        # run (obs/capacity.py): activations/workspace the abstract estimate
        # missed. A separate field (never folded into the per-component
        # predictions — those stay tree_bytes_per_device-exact) that the
        # budget gate adds on top.
        cand.bytes["measured_margin_bytes"] = int(measured_margin_bytes)
        cand.bytes["total_bytes_per_chip"] += int(measured_margin_bytes)
    if budget_bytes:
        cand.headroom_frac = round(
            1.0 - cand.bytes["total_bytes_per_chip"] / budget_bytes, 4
        )
        if cand.bytes["total_bytes_per_chip"] > budget_bytes:
            cand.reject_reason = REJECT_BUDGET
            cand.reject_detail = (
                f"predicted {cand.bytes['total_bytes_per_chip']} bytes/chip "
                f"> budget {budget_bytes}"
                + (
                    f" (incl. {measured_margin_bytes} measured margin)"
                    if measured_margin_bytes > 0 else ""
                )
            )
            return cand
    cand.feasible = True
    compute, comm = _cost(
        profile, layout, topo, cand.bytes, global_batch, microbatches,
        measured=measured_costs,
    )
    cand.compute_s, cand.comm_s = compute, comm
    cand.score = compute + comm
    if measured_costs is not None:
        # keep the analytic score alongside so the plan table can show
        # measured-vs-analytic per candidate (and a re-score is auditable)
        a_compute, a_comm = _cost(
            profile, layout, topo, cand.bytes, global_batch, microbatches
        )
        cand.score_analytic = a_compute + a_comm
    return cand


def _enumerate_layouts(model_config, topo: Topology) -> List[Layout]:
    """Every layout shape the execution strategies can run on ``topo``:
    pure DP, one model-axis rider (tp | pp | ep) OR spatial at each divisor
    of the device count, each with and without ZeRO-1 where it composes
    (dp > 1, not pipeline — the GPipe runner owns its own update placement)."""
    n = topo.n_devices
    divisors = [d for d in range(2, n + 1) if n % d == 0]
    shapes: List[Dict] = [{}]
    shapes += [{"model_parallel": d} for d in divisors]
    if getattr(model_config, "backbone", None) in ("vit", "xception"):
        shapes += [{"pipeline_parallel": d} for d in divisors]
    shapes += [{"sequence_parallel": d} for d in divisors]
    experts = getattr(model_config, "moe_experts", 0)
    if experts and n % experts == 0 and experts > 1:
        shapes.append({"expert_parallel": experts})
    layouts: List[Layout] = []
    for shape in shapes:
        base = Layout(data_parallel=1, **shape)
        # every enumerated shape's denom divides n (divisor-driven); pinned
        # combinations that do not are appended by plan() and rejected with
        # the named constraint
        layout = dataclasses.replace(base, data_parallel=max(n // base.denom, 1))
        layouts.append(layout)
        if layout.data_parallel > 1 and layout.pipeline_parallel == 1:
            layouts.append(
                dataclasses.replace(layout, weight_update_sharding=True)
            )
    return layouts


def _matches_pinned(layout: Layout, pinned: Dict) -> bool:
    return all(getattr(layout, k) == v for k, v in pinned.items())


def _layout_from_pinned(pinned: Dict, topo: Topology) -> Layout:
    base = Layout(data_parallel=1, **pinned)
    denom = base.denom
    dp = topo.n_devices // denom if topo.n_devices % denom == 0 else 1
    return dataclasses.replace(base, data_parallel=max(dp, 1))


def _complexity(layout: Layout) -> Tuple:
    """Deterministic tie-break: at equal score prefer the simpler layout —
    pure DP beats any model-axis rider, no-ZeRO beats ZeRO (nothing to gain
    when memory already fits), lower degrees beat higher."""
    return (
        layout.denom,
        int(layout.weight_update_sharding),
        layout.model_parallel,
        layout.pipeline_parallel,
        layout.sequence_parallel,
        layout.expert_parallel,
    )


@dataclasses.dataclass
class ParallelPlan:
    """The planner's verdict: the chosen layout plus the whole candidate
    table. ``source`` records how it was reached (``auto`` — scored — vs
    ``explicit`` — requested degrees validated through the same machinery)."""

    chosen: Candidate
    candidates: List[Candidate]
    source: str
    global_batch: int
    topology: Topology
    hbm_bytes_per_device: Optional[int]
    warnings: List[str] = dataclasses.field(default_factory=list)
    # the measured rates the scores were priced with (None = analytic
    # constants); `cost_provenance` is the run-header stamp
    measured_costs: Optional[MeasuredCosts] = None

    @property
    def cost_provenance(self) -> str:
        """``"measured"`` when candidate scores were priced with a prior
        run's ledgered roofline rates, ``"analytic"`` for the constants."""
        return "measured" if self.measured_costs is not None else "analytic"

    @property
    def layout(self) -> Layout:
        return self.chosen.layout

    def overrides(self) -> Dict:
        """``dataclasses.replace(TrainConfig, **overrides)`` kwargs applying
        this plan's layout (the single consumption point both trainers use)."""
        lay = self.layout
        return {
            "model_parallel": lay.model_parallel,
            "pipeline_parallel": lay.pipeline_parallel,
            "sequence_parallel": lay.sequence_parallel,
            "expert_parallel": lay.expert_parallel,
            "weight_update_sharding": lay.weight_update_sharding,
        }

    def header(self) -> Dict:
        """The run-header ledger field (``plan`` — see docs/LEDGER_SCHEMA.md):
        layout + predicted bytes/chip + verdict, JSON-clean."""
        out: Dict = {
            "source": self.source,
            "layout": self.layout.to_json(),
            "predicted": dict(self.chosen.bytes or {}),
            "feasible": self.chosen.feasible,
            "candidates_considered": len(self.candidates),
            "candidates_feasible": sum(
                1 for c in self.candidates if c.feasible
            ),
        }
        if self.hbm_bytes_per_device:
            out["hbm_bytes_per_device"] = self.hbm_bytes_per_device
            if self.chosen.headroom_frac is not None:
                out["headroom_frac"] = self.chosen.headroom_frac
        if self.chosen.score is not None:
            out["score"] = round(self.chosen.score, 9)
        out["cost_provenance"] = self.cost_provenance
        if self.measured_costs is not None:
            out["measured_costs"] = self.measured_costs.to_json()
            if self.chosen.score_analytic is not None:
                out["score_analytic"] = round(self.chosen.score_analytic, 9)
        if self.chosen.reject_reason:
            out["reject_reason"] = self.chosen.reject_reason
        if self.warnings:
            out["warnings"] = list(self.warnings)
        return out

    def to_json(self) -> Dict:
        return {
            **self.header(),
            "global_batch": self.global_batch,
            "topology": dataclasses.asdict(self.topology),
            "candidates": [c.to_json() for c in self.candidates],
        }


def plan(
    model_config,
    train_config,
    global_batch: int,
    *,
    topology: Optional[Topology] = None,
    profile: Optional[ModelProfile] = None,
    pinned: Optional[Dict] = None,
    hbm_bytes_per_device: Optional[int] = None,
    source: Optional[str] = None,
    measured_margin_bytes: Optional[int] = None,
    measured_costs: Optional[MeasuredCosts] = None,
) -> ParallelPlan:
    """The engine. ``pinned`` holds the layout fields explicit flags fixed
    (explicit flags always win); the planner fills the rest by score. With
    every field pinned this is the hand-spec validator: a layout failing a
    HARD (divisibility) constraint raises :class:`PlanError` with the named
    reason; an over-budget pinned layout comes back with a warning instead
    (the activation estimate must not veto an explicit request).

    ``measured_margin_bytes`` closes the activation-estimate feedback loop:
    pass a prior run's ledgered measured-vs-predicted watermark residual
    (:func:`measured_margin_from_workdir`) and every candidate's budget check
    adds it on top of the abstract estimate — the elastic coordinator's
    re-plan (parallel/elastic.py) sources it from the workdir it is about to
    resume.

    ``measured_costs`` closes the COST-model loop the same way
    (:func:`measured_costs_from_workdir`): candidate scores are priced with
    a prior run's achieved FLOP/s and collective bandwidth instead of the
    analytic constants, and the plan's ``cost_provenance`` header stamp
    flips to ``"measured"``."""
    pinned = dict(pinned or {})
    if topology is None:
        topology = detect_topology(getattr(train_config, "n_devices", None))
    budget = hbm_bytes_per_device
    if budget is None:
        gb = getattr(train_config, "hbm_budget_gb", None)
        if gb:
            budget = int(gb * (1 << 30))
    if budget is None:
        budget = topology.hbm_bytes_per_device
    if profile is None:
        profile = profile_model(model_config, train_config)
    grad_accum = getattr(train_config, "grad_accum_steps", 1)
    microbatches = getattr(train_config, "pipeline_microbatches", None)

    layouts = _enumerate_layouts(model_config, topology)
    if pinned and not any(_matches_pinned(l, pinned) for l in layouts):
        # a pinned combination outside the enumerated shapes (e.g. an
        # indivisible model-axis degree) still gets evaluated so the
        # rejection carries the named constraint
        layouts.append(_layout_from_pinned(pinned, topology))
    seen = set()
    candidates: List[Candidate] = []
    for layout in layouts:
        if layout in seen:
            continue
        seen.add(layout)
        candidates.append(
            _evaluate(
                profile, layout, model_config, train_config, topology,
                global_batch, grad_accum, microbatches, budget,
                measured_margin_bytes=int(measured_margin_bytes or 0),
                measured_costs=measured_costs,
            )
        )
    matching = [c for c in candidates if _matches_pinned(c.layout, pinned)]
    feasible = [c for c in matching if c.feasible]
    fully_pinned = set(pinned) >= {
        "model_parallel", "pipeline_parallel", "sequence_parallel",
        "expert_parallel", "weight_update_sharding",
    }
    warnings: List[str] = []
    if not feasible:
        rejected = matching or candidates
        soft = [
            c for c in rejected
            if c.reject_reason in _SOFT_REJECTS
        ]
        if fully_pinned and soft:
            # explicit spec over budget: warn, do not veto
            chosen = soft[0]
            warnings.append(
                f"requested layout {chosen.layout.describe()} predicted over "
                f"the HBM budget: {chosen.reject_detail}"
            )
        else:
            reasons = "; ".join(
                f"{c.layout.describe()}: {c.reject_reason}"
                + (f" ({c.reject_detail})" if c.reject_detail else "")
                for c in rejected[:8]
            )
            raise PlanError(
                ("no feasible parallelism layout" if not pinned else
                 "requested parallelism layout is not feasible")
                + f" for {topology.n_devices} device(s), global batch "
                f"{global_batch}: {reasons}"
            )
    else:
        chosen = min(
            feasible, key=lambda c: (c.score, _complexity(c.layout))
        )
    return ParallelPlan(
        chosen=chosen,
        candidates=candidates,
        source=source or ("explicit" if fully_pinned else "auto"),
        global_batch=global_batch,
        topology=topology,
        hbm_bytes_per_device=budget,
        warnings=warnings,
        measured_costs=measured_costs,
    )


def measured_margin_from_workdir(workdir: str) -> Optional[int]:
    """The activation/workspace residual a prior run under ``workdir``
    actually measured: the last ``memory_watermark`` event's
    ``measured_minus_predicted_bytes`` across every per-process ledger (the
    fleet-wide worst — a plan must fit the hungriest host). None when no run
    ledgered watermarks (CPU backends) or the workdir has no ledger; negative
    residuals (the estimate over-shot) clamp to 0 — the margin only ever adds
    safety, never spends it."""
    from tensorflowdistributedlearning_tpu.obs import capacity as capacity_lib
    from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib

    deltas = []
    try:
        ledgers = fleet_lib.discover_ledgers(workdir)
    except OSError:
        return None
    for led in ledgers:
        marks = capacity_lib.aggregate_watermark_events(led.events)
        if marks and marks.get("measured_minus_predicted_bytes") is not None:
            deltas.append(int(marks["measured_minus_predicted_bytes"]))
    if not deltas:
        return None
    return max(0, max(deltas))


def measured_costs_from_workdir(workdir: str) -> Optional[MeasuredCosts]:
    """Measured cost-model rates from the ``op_roofline`` events a prior run
    under ``workdir`` ledgered (obs/profiler.py): the achieved FLOP/s per
    chip and — when any capture priced a collective volume — the achieved
    per-chip collective bandwidth. Per ledger the LAST roofline wins (the
    most recent steady state); across the fleet the MINIMUM wins (a plan
    must price for the slowest host, the same stance as
    :func:`measured_margin_from_workdir`). None when the workdir has no
    ledger or no roofline carries an achieved rate (profiling never ran, or
    ran without analytic FLOP pricing)."""
    from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib
    from tensorflowdistributedlearning_tpu.obs.profiler import (
        OP_ROOFLINE_EVENT,
    )

    try:
        ledgers = fleet_lib.discover_ledgers(workdir)
    except OSError:
        return None
    flops_rates: List[float] = []
    coll_rates: List[float] = []
    captures = 0
    for led in ledgers:
        last_flops = None
        last_coll = None
        for e in led.events:
            if e.get("event") != OP_ROOFLINE_EVENT:
                continue
            captures += 1
            if e.get("achieved_flops_per_sec_per_chip"):
                last_flops = float(e["achieved_flops_per_sec_per_chip"])
            if e.get("achieved_collective_bytes_per_sec"):
                last_coll = float(e["achieved_collective_bytes_per_sec"])
        if last_flops is not None:
            flops_rates.append(last_flops)
        if last_coll is not None:
            coll_rates.append(last_coll)
    if not flops_rates:
        return None
    return MeasuredCosts(
        flops_per_sec_per_chip=min(flops_rates),
        collective_bytes_per_sec=min(coll_rates) if coll_rates else None,
        captures=captures,
        source=workdir,
    )


def _pinned_from_config(train_config) -> Dict:
    return {
        "model_parallel": train_config.model_parallel,
        "pipeline_parallel": train_config.pipeline_parallel,
        "sequence_parallel": train_config.sequence_parallel,
        "expert_parallel": train_config.expert_parallel,
        "weight_update_sharding": train_config.weight_update_sharding,
    }


def plan_for_config(
    model_config,
    train_config,
    global_batch: int,
    *,
    topology: Optional[Topology] = None,
    profile: Optional[ModelProfile] = None,
    workdir: Optional[str] = None,
) -> ParallelPlan:
    """The trainer-facing entry: ``parallelism='auto'`` plans freely with any
    non-default degree pinned (explicit flags win); ``'explicit'`` validates
    the requested layout through the same machinery.

    ``workdir`` (the run's model dir) closes the measured-costs loop on the
    auto path: when a PRIOR run in the same workdir ledgered rooflines
    (``profile_every_windows``), auto candidates are re-scored with that
    box's achieved rates and the run header's ``cost_provenance`` flips to
    ``"measured"`` — profile once, plan better forever after."""
    if getattr(train_config, "parallelism", "explicit") == "auto":
        pinned = {}
        for k, v in _pinned_from_config(train_config).items():
            # NB: a `v not in (1, False)` filter would drop a pinned ZeRO
            # flag, because True == 1 in Python — compare per-field defaults
            default = False if k == "weight_update_sharding" else 1
            if v != default:
                pinned[k] = v
        measured = None
        if workdir:
            try:
                measured = measured_costs_from_workdir(workdir)
            except Exception:  # noqa: BLE001 — a torn ledger must not block
                measured = None
        return plan(
            model_config, train_config, global_batch,
            topology=topology, profile=profile, pinned=pinned, source="auto",
            measured_costs=measured,
        )
    return validate_config(
        model_config, train_config, global_batch,
        topology=topology, profile=profile,
    )


def validate_config(
    model_config,
    train_config,
    global_batch: int,
    *,
    topology: Optional[Topology] = None,
    profile: Optional[ModelProfile] = None,
) -> ParallelPlan:
    """Route a hand spec (or a preset's hardcoded flags) through the planner:
    indivisible degrees fail at parse time with the NAMED constraint; the
    returned plan carries the exact predicted bytes/chip for the run header."""
    return plan(
        model_config, train_config, global_batch,
        topology=topology, profile=profile,
        pinned=_pinned_from_config(train_config), source="explicit",
    )


# -- rendering ---------------------------------------------------------------


def _mb(x: Optional[int]) -> str:
    return f"{x / (1 << 20):9.1f}" if x is not None else "      n/a"


def render_plan_table(p: ParallelPlan) -> str:
    """The ``plan`` CLI's human view: one row per candidate — layout,
    predicted params/opt/activation/total MB per chip, headroom against the
    budget, score — with the chosen row marked and every rejection named."""
    topo = p.topology
    lines = [
        f"== parallelism plan ({p.source}): {topo.n_devices} device(s) "
        f"[{topo.device_kind}], {topo.process_count} process(es), "
        f"global batch {p.global_batch}",
    ]
    if p.hbm_bytes_per_device:
        lines.append(
            f"   HBM budget: {p.hbm_bytes_per_device / (1 << 30):.2f} GiB/chip"
        )
    else:
        lines.append(
            "   HBM budget: none (divisibility-only feasibility; pass "
            "--hbm-gb or run on a backend that reports bytes_limit)"
        )
    measured = p.measured_costs is not None
    if measured:
        mc = p.measured_costs
        rate = f"{mc.flops_per_sec_per_chip / 1e12:.2f} TFLOP/s/chip"
        coll = (
            f", {mc.collective_bytes_per_sec / 1e9:.1f} GB/s collective"
            if mc.collective_bytes_per_sec
            else ""
        )
        lines.append(
            f"   cost provenance: measured ({rate}{coll}; "
            f"{mc.captures} roofline capture(s) from {mc.source})"
        )
    else:
        lines.append(
            "   cost provenance: analytic (peak-FLOPs table + ICI constant; "
            "pass --measured-costs-from WORKDIR to price with ledgered "
            "roofline rates)"
        )
    score_cols = (
        f"{'measured':>12}  {'analytic':>12}" if measured else f"{'score':>12}"
    )
    lines.append(
        f"   {'layout':<22} {'params':>9} {'opt':>9} {'act':>9} "
        f"{'total':>9}  {'headroom':>8}  {score_cols}  verdict"
    )
    order = sorted(
        p.candidates,
        key=lambda c: (
            not c.feasible,
            c.score if c.score is not None else math.inf,
            _complexity(c.layout),
        ),
    )
    for c in order:
        mark = "->" if c.layout == p.layout else "  "
        b = c.bytes or {}
        headroom = (
            f"{c.headroom_frac:8.1%}" if c.headroom_frac is not None else "     n/a"
        )
        score = f"{c.score:12.6f}" if c.score is not None else "         n/a"
        if measured:
            analytic = (
                f"{c.score_analytic:12.6f}"
                if c.score_analytic is not None
                else "         n/a"
            )
            score = f"{score}  {analytic}"
        verdict = (
            "chosen" if c.layout == p.layout else
            ("ok" if c.feasible else
             f"rejected: {c.reject_reason}")
        )
        lines.append(
            f" {mark} {c.layout.describe():<22} "
            f"{_mb(b.get('params_bytes_per_chip'))} "
            f"{_mb(b.get('opt_state_bytes_per_chip'))} "
            f"{_mb(b.get('activation_bytes_per_chip'))} "
            f"{_mb(b.get('total_bytes_per_chip'))}  "
            f"{headroom}  {score}  {verdict}"
        )
        if not c.feasible and c.reject_detail:
            lines.append(f"      {c.reject_detail}")
    for w in p.warnings:
        lines.append(f"   WARNING: {w}")
    lines.append(
        f"   chosen: {p.layout.describe()} "
        f"(MB/chip are per-chip predictions under the real placement specs; "
        f"params+opt match tree_bytes_per_device exactly)"
    )
    return "\n".join(lines)
