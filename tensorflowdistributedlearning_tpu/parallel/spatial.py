"""Spatial (sequence/context) parallelism: halo exchange + sharded convolution.

The reference had no sequence dimension at all — it "scaled context" within one
device via atrous convolution (SURVEY §5.7; reference: core/resnet.py:244, 340-344).
This module is the TPU-native generalization the mesh API reserves a ``sequence``
axis for: inputs sharded along a spatial dimension across devices, with boundary
("halo") rows exchanged over ICI neighbor links via ``lax.ppermute`` — the same
ring-neighbor communication pattern ring attention uses for sequence parallelism,
applied to the convolutional setting this framework's models live in. Everything
here runs inside ``shard_map`` and composes with the batch-parallel train step.

Use cases: images/feature maps too large for one chip's HBM (the CNN analogue of
long-context), and halving activation memory per chip at fixed batch.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, SEQUENCE_AXIS


def _neighbor_perm(n: int, forward: bool):
    """Ring permutation (i -> i+1) or (i -> i-1) over n devices."""
    if forward:
        return [(i, (i + 1) % n) for i in range(n)]
    return [(i, (i - 1) % n) for i in range(n)]


def _line_perm(n: int, forward: bool):
    """Open-chain permutation: like the ring but without the wrap-around pair.
    Devices that receive nothing get zeros from ppermute — exactly the boundary
    condition a zero-padded convolution needs, with no wasted wrap transfer."""
    if forward:
        return [(i, i + 1) for i in range(n - 1)]
    return [(i + 1, i) for i in range(n - 1)]


def halo_exchange(
    x: jax.Array,
    halo: int,
    *,
    axis_name: str = SEQUENCE_AXIS,
    spatial_axis: int = 1,
) -> jax.Array:
    """Pad a sharded block with ``halo`` boundary rows from each ring neighbor.

    ``x`` is this device's shard with the sharded spatial dimension at
    ``spatial_axis`` (default 1 = H of an NHWC tensor). Returns the shard extended
    by ``halo`` rows on each side: interior shards receive their neighbors' edge
    rows (one ``ppermute`` hop over ICI per direction), the outermost shards
    receive zeros — matching XLA's zero-padded SAME convolution so a sharded conv
    reproduces the unsharded result exactly.
    """
    if halo <= 0:
        return x
    local = x.shape[spatial_axis]
    if halo > local:
        raise ValueError(
            f"halo {halo} exceeds the local shard extent {local} along axis "
            f"{spatial_axis}; a single-hop exchange cannot reach beyond the "
            "adjacent shard — use fewer devices on the sequence axis or a "
            "smaller kernel"
        )
    n = lax.axis_size(axis_name)

    def take(arr, start, size):
        return lax.slice_in_dim(arr, start, start + size, axis=spatial_axis)

    # my last rows become my successor's top halo; my first rows the predecessor's
    # bottom halo. The open-chain permutation leaves the outermost shards' missing
    # neighbors as ppermute-provided zeros (the zero-padded-SAME boundary).
    from_prev = lax.ppermute(
        take(x, local - halo, halo), axis_name, _line_perm(n, True)
    )
    from_next = lax.ppermute(take(x, 0, halo), axis_name, _line_perm(n, False))
    return jnp.concatenate([from_prev, x, from_next], axis=spatial_axis)


def spatial_conv2d(
    x: jax.Array,
    kernel: jax.Array,
    *,
    stride: int = 1,
    rate: int = 1,
    axis_name: str = SEQUENCE_AXIS,
    feature_group_count: int = 1,
    phase: str = "same",
) -> jax.Array:
    """2-D (optionally atrous, optionally grouped) convolution of an H-sharded
    NHWC batch, exact vs the unsharded op.

    ``x``: local shard [B, H_local, W, C_in]; ``kernel``: [kh, kw, C_in/groups,
    C_out] (odd kh). H is sharded over ``axis_name``; W is whole on every device.
    The op halo-exchanges ``rate*(kh-1)/2`` rows, then convolves VALID along H
    with the padding phase of the reference op:

    - ``phase='same'``: XLA's SAME — total pad ``max(ek - stride, 0)``,
      floor-split low/high;
    - ``phase='fixed'``: slim's explicit ``fixed_padding`` + VALID (the Xception
      strided separable convs, reference: core/xception.py:18-36) — total pad
      ``ek - 1``, ``(ek-1)//2`` low.

    With ``stride`` > 1, every shard's H_local must be divisible by the stride so
    shard boundaries stay aligned with the global stride phase. When the halo
    exceeds the local extent (deep atrous stages on small maps), it falls back to
    an all-gather of H — exact, costlier in ICI bandwidth, and only hit where the
    maps are smallest.
    """
    if phase not in ("same", "fixed"):
        raise ValueError(f"Unknown padding phase {phase!r}")
    kh, kw = kernel.shape[0], kernel.shape[1]
    if kh % 2 != 1:
        raise ValueError(f"spatial_conv2d requires odd kernel height, got {kh}")
    h_local = x.shape[1]
    if h_local % stride != 0:
        raise ValueError(
            f"H_local {h_local} must be divisible by stride {stride} to keep "
            "shard boundaries stride-aligned"
        )
    # effective (dilated) kernel extents
    ekh = kh + (kh - 1) * (rate - 1)
    ekw = kw + (kw - 1) * (rate - 1)
    halo = (ekh - 1) // 2

    # padding phase along H (sharded) and W (whole)
    if phase == "same":
        total_h = max(ekh - stride, 0)
        total_w_pad = None  # computed from out_cols below
    else:
        total_h = ekh - 1
        total_w_pad = ekw - 1
    pad_lo = total_h // 2

    w = x.shape[2]
    if total_w_pad is None:
        out_cols = -(-w // stride)
        total_w = max((out_cols - 1) * stride + ekw - w, 0)
    else:
        total_w = total_w_pad
    pw_lo = total_w // 2
    pw_hi = total_w - pw_lo

    # rows of global output owned by this shard; identical for both phases when
    # H_local is stride-aligned (out rows = H_local / stride)
    out_rows = h_local // stride

    conv_kwargs = dict(
        window_strides=(stride, stride),
        rhs_dilation=(rate, rate),
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        feature_group_count=feature_group_count,
    )

    if halo > h_local:
        # single-hop halo cannot reach beyond the adjacent shard: gather H whole,
        # run the global conv, keep this shard's output rows
        idx = lax.axis_index(axis_name)
        full = lax.all_gather(x, axis_name, axis=1, tiled=True)
        hg = full.shape[1]
        total_hg = total_h if phase == "fixed" else max(
            (-(-hg // stride) - 1) * stride + ekh - hg, 0
        )
        out = lax.conv_general_dilated(
            full,
            kernel,
            padding=[(total_hg // 2, total_hg - total_hg // 2), (pw_lo, pw_hi)],
            **conv_kwargs,
        )
        return lax.dynamic_slice_in_dim(out, idx * out_rows, out_rows, axis=1)

    padded = halo_exchange(x, halo, axis_name=axis_name, spatial_axis=1)
    # The first tap of this shard's first output row sits `pad_lo` rows above the
    # shard start, i.e. at offset (halo - pad_lo) inside the halo-extended block;
    # VALID conv from there with the same stride reproduces the global output
    # rows owned by this shard.
    offset = halo - pad_lo
    window = (out_rows - 1) * stride + ekh
    sliced = lax.slice_in_dim(padded, offset, offset + window, axis=1)
    return lax.conv_general_dilated(
        sliced,
        kernel,
        padding=[(0, 0), (pw_lo, pw_hi)],
        **conv_kwargs,
    )


def spatial_max_pool(
    x: jax.Array,
    window: int = 3,
    stride: int = 2,
    *,
    axis_name: str = SEQUENCE_AXIS,
) -> jax.Array:
    """SAME max pool of an H-sharded NHWC batch, exact vs ``nn.max_pool``.

    Same halo/phase scheme as ``spatial_conv2d``; halo rows that lie beyond the
    global image boundary (the outermost shards' missing neighbors, which
    ``halo_exchange`` fills with zeros) are reset to -inf so they never win the
    max — matching reduce_window's SAME padding identity.
    """
    h_local = x.shape[1]
    if h_local % stride != 0:
        raise ValueError(
            f"H_local {h_local} must be divisible by stride {stride} to keep "
            "shard boundaries stride-aligned"
        )
    halo = (window - 1) // 2
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    # a PYTHON scalar, not a traced array: reduce_window's reverse-mode autodiff
    # rule only recognizes the max-pool pattern with a static -inf init value
    neg = (
        float("-inf")
        if jnp.issubdtype(x.dtype, jnp.floating)
        else int(jnp.iinfo(x.dtype).min)
    )
    padded = halo_exchange(x, halo, axis_name=axis_name, spatial_axis=1)
    if halo > 0:
        rows = jnp.arange(padded.shape[1])
        beyond_top = (rows < halo) & (idx == 0)
        beyond_bot = (rows >= padded.shape[1] - halo) & (idx == n - 1)
        mask = (beyond_top | beyond_bot)[None, :, None, None]
        padded = jnp.where(mask, neg, padded)
    total_pad = max(window - stride, 0)
    pad_lo = total_pad // 2
    out_rows = h_local // stride
    offset = halo - pad_lo
    span = (out_rows - 1) * stride + window
    sliced = lax.slice_in_dim(padded, offset, offset + span, axis=1)
    w = x.shape[2]
    out_cols = -(-w // stride)
    total_w = max((out_cols - 1) * stride + window - w, 0)
    pw_lo = total_w // 2
    pw_hi = total_w - pw_lo
    return lax.reduce_window(
        sliced,
        neg,
        lax.max,
        (1, window, window, 1),
        (1, stride, stride, 1),
        [(0, 0), (0, 0), (pw_lo, pw_hi), (0, 0)],
    )


def spatial_global_mean(
    x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, keepdims: bool = False
) -> jax.Array:
    """Global spatial mean over (H, W) of an H-sharded NHWC batch: local mean then
    ``pmean`` across equal shards (the ASPP image-pool branch / classifier
    global-pool under spatial parallelism)."""
    local = jnp.mean(x, axis=(1, 2), keepdims=keepdims)
    return lax.pmean(local, axis_name)


def spatial_gather(x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, axis: int = 1) -> jax.Array:
    """Reassemble the full tensor from H-shards on every device (one all-gather
    over the sequence axis) — used where a computation genuinely needs the whole
    extent (the decoder's bilinear upsampling, the per-image loss)."""
    return lax.all_gather(x, axis_name, axis=axis, tiled=True)


def ring_all_gather(
    x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, axis: int = 0
) -> jax.Array:
    """All-gather along a mesh axis implemented as n-1 ``ppermute`` ring hops —
    the bandwidth-optimal neighbor-only pattern that rides ICI links (what XLA
    emits for ``lax.all_gather`` on TPU, written out explicitly here so the
    framework owns a ring primitive for sequence-parallel algorithms).

    Returns the concatenation of every device's shard in device order, on every
    device.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = _neighbor_perm(n, True)

    def body(i, carry):
        block, out = carry
        block = lax.ppermute(block, axis_name, perm)
        # the block received at hop i originated at device (idx - 1 - i) mod n
        src = jnp.mod(idx - 1 - i, n)
        out = lax.dynamic_update_index_in_dim(out, block, src, 0)
        return block, out

    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, idx, 0)
    _, out = lax.fori_loop(0, n - 1, body, (x, out))
    return jnp.moveaxis(out, 0, axis).reshape(
        x.shape[:axis] + (n * x.shape[axis],) + x.shape[axis + 1 :]
    )


def reduce_scatter(
    x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, axis: int = 0
) -> jax.Array:
    """Sum across the mesh axis, leaving each device its own 1/n slice
    (``lax.psum_scatter``, the gradient-sharding half of distributed data/optim
    sharding). ``x.shape[axis]`` must divide by the axis size."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


# ---------------------------------------------------------------------------
# Host-side helpers to run a spatially-sharded computation end to end.
# ---------------------------------------------------------------------------


def shard_spatial(x: np.ndarray, mesh: Mesh, *, spatial_axis: int = 1):
    """Place a host array on the mesh with axis 0 on the ``batch`` mesh axis and
    ``spatial_axis`` on the ``sequence`` mesh axis."""
    if spatial_axis == 0:
        raise ValueError(
            "spatial_axis 0 is the batch dimension; pick a spatial dimension >= 1"
        )
    spec = [None] * x.ndim
    spec[0] = BATCH_AXIS
    spec[spatial_axis] = SEQUENCE_AXIS
    from jax.sharding import NamedSharding

    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def sequence_parallel_degree(mesh: Mesh) -> int:
    return mesh.shape[SEQUENCE_AXIS]


def validate_spatial_config(model_config, sequence_parallel: int) -> None:
    """Fail fast when a model/input combination cannot run H-sharded.

    Every strided stage needs its per-shard H divisible by the stride (shard
    boundaries must stay aligned with the global stride phase), which holds for
    the whole network iff the input height is divisible by
    ``overall_stride * sequence_parallel`` (overall stride = ``output_stride``
    for the atrous configs, else the full stride-32 trunk). Catching it here
    gives a clear config-time error instead of a trace-time failure deep inside
    ``spatial_conv2d`` — e.g. 224x224 classification at sequence_parallel=2
    reaches H_local=7 at the last strided stage and cannot shard; 256x256 can.
    """
    if sequence_parallel <= 1:
        return
    if getattr(model_config, "moe_experts", 0):
        raise ValueError(
            "sequence_parallel and moe_experts cannot combine: per-shard MoE "
            "routing under H-sharded tokens is unvalidated (capacity and the "
            "load-balancing loss would be computed per sequence shard)"
        )
    if getattr(model_config, "backbone", None) == "vit":
        # ViT: each shard patch-embeds its own rows, so the only constraint is
        # whole patches per shard (attention itself is the ring — degree-free)
        overall = model_config.patch_size
    else:
        overall = model_config.output_stride or 32
    required = overall * sequence_parallel
    h = model_config.input_shape[0]
    if h % required != 0:
        raise ValueError(
            f"sequence_parallel={sequence_parallel} requires the input height "
            f"to be divisible by stride*sequence_parallel = "
            f"{overall}*{sequence_parallel} = {required}, got {h}. Pad/resize "
            f"the input (e.g. {-(-h // required) * required}) or lower the "
            "sequence-parallel degree."
        )
