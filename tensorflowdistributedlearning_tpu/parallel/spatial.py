"""Spatial (sequence/context) parallelism: halo exchange + sharded convolution.

The reference had no sequence dimension at all — it "scaled context" within one
device via atrous convolution (SURVEY §5.7; reference: core/resnet.py:244, 340-344).
This module is the TPU-native generalization the mesh API reserves a ``sequence``
axis for: inputs sharded along a spatial dimension across devices, with boundary
("halo") rows exchanged over ICI neighbor links via ``lax.ppermute`` — the same
ring-neighbor communication pattern ring attention uses for sequence parallelism,
applied to the convolutional setting this framework's models live in. Everything
here runs inside ``shard_map`` and composes with the batch-parallel train step.

Use cases: images/feature maps too large for one chip's HBM (the CNN analogue of
long-context), and halving activation memory per chip at fixed batch.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, SEQUENCE_AXIS


def _neighbor_perm(n: int, forward: bool):
    """Ring permutation (i -> i+1) or (i -> i-1) over n devices."""
    if forward:
        return [(i, (i + 1) % n) for i in range(n)]
    return [(i, (i - 1) % n) for i in range(n)]


def _line_perm(n: int, forward: bool):
    """Open-chain permutation: like the ring but without the wrap-around pair.
    Devices that receive nothing get zeros from ppermute — exactly the boundary
    condition a zero-padded convolution needs, with no wasted wrap transfer."""
    if forward:
        return [(i, i + 1) for i in range(n - 1)]
    return [(i + 1, i) for i in range(n - 1)]


def halo_exchange(
    x: jax.Array,
    halo: int,
    *,
    axis_name: str = SEQUENCE_AXIS,
    spatial_axis: int = 1,
) -> jax.Array:
    """Pad a sharded block with ``halo`` boundary rows from each ring neighbor.

    ``x`` is this device's shard with the sharded spatial dimension at
    ``spatial_axis`` (default 1 = H of an NHWC tensor). Returns the shard extended
    by ``halo`` rows on each side: interior shards receive their neighbors' edge
    rows (one ``ppermute`` hop over ICI per direction), the outermost shards
    receive zeros — matching XLA's zero-padded SAME convolution so a sharded conv
    reproduces the unsharded result exactly.
    """
    if halo <= 0:
        return x
    local = x.shape[spatial_axis]
    if halo > local:
        raise ValueError(
            f"halo {halo} exceeds the local shard extent {local} along axis "
            f"{spatial_axis}; a single-hop exchange cannot reach beyond the "
            "adjacent shard — use fewer devices on the sequence axis or a "
            "smaller kernel"
        )
    n = lax.axis_size(axis_name)

    def take(arr, start, size):
        return lax.slice_in_dim(arr, start, start + size, axis=spatial_axis)

    # my last rows become my successor's top halo; my first rows the predecessor's
    # bottom halo. The open-chain permutation leaves the outermost shards' missing
    # neighbors as ppermute-provided zeros (the zero-padded-SAME boundary).
    from_prev = lax.ppermute(
        take(x, local - halo, halo), axis_name, _line_perm(n, True)
    )
    from_next = lax.ppermute(take(x, 0, halo), axis_name, _line_perm(n, False))
    return jnp.concatenate([from_prev, x, from_next], axis=spatial_axis)


def spatial_conv2d(
    x: jax.Array,
    kernel: jax.Array,
    *,
    stride: int = 1,
    axis_name: str = SEQUENCE_AXIS,
) -> jax.Array:
    """2-D convolution of an H-sharded NHWC batch, exact vs the unsharded op.

    ``x``: local shard [B, H_local, W, C_in]; ``kernel``: [kh, kw, C_in, C_out]
    (odd kh). H is sharded over ``axis_name``; W is whole on every device. The op
    halo-exchanges (kh-1)/2 rows, then convolves VALID along H / SAME along W.
    With ``stride`` > 1, every shard's H_local must be divisible by the stride so
    shard boundaries stay aligned with the global stride phase.
    """
    kh, kw = kernel.shape[0], kernel.shape[1]
    if kh % 2 != 1:
        raise ValueError(f"spatial_conv2d requires odd kernel height, got {kh}")
    h_local = x.shape[1]
    if h_local % stride != 0:
        raise ValueError(
            f"H_local {h_local} must be divisible by stride {stride} to keep "
            "shard boundaries stride-aligned"
        )
    halo = (kh - 1) // 2
    padded = halo_exchange(x, halo, axis_name=axis_name, spatial_axis=1)
    # Reproduce XLA's SAME padding phase exactly: with global H divisible by the
    # stride, SAME pads a total of max(kh - stride, 0) rows, floor-split low/high —
    # NOT (kh-1)/2 each side when stride > 1. The first tap of this shard's first
    # output row therefore sits `pad_lo` rows above the shard start, i.e. at offset
    # (halo - pad_lo) inside the halo-extended block; VALID conv from there with
    # the same stride reproduces the global output rows owned by this shard.
    total_pad = max(kh - stride, 0)
    pad_lo = total_pad // 2
    out_rows = h_local // stride
    offset = halo - pad_lo
    window = (out_rows - 1) * stride + kh
    sliced = lax.slice_in_dim(padded, offset, offset + window, axis=1)
    # W is unsharded: apply XLA's actual SAME split there too (low gets the floor)
    w = x.shape[2]
    out_cols = -(-w // stride)
    total_w = max((out_cols - 1) * stride + kw - w, 0)
    pw_lo = total_w // 2
    pw_hi = total_w - pw_lo
    return lax.conv_general_dilated(
        sliced,
        kernel,
        window_strides=(stride, stride),
        padding=[(0, 0), (pw_lo, pw_hi)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
    )


def ring_all_gather(
    x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, axis: int = 0
) -> jax.Array:
    """All-gather along a mesh axis implemented as n-1 ``ppermute`` ring hops —
    the bandwidth-optimal neighbor-only pattern that rides ICI links (what XLA
    emits for ``lax.all_gather`` on TPU, written out explicitly here so the
    framework owns a ring primitive for sequence-parallel algorithms).

    Returns the concatenation of every device's shard in device order, on every
    device.
    """
    n = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    perm = _neighbor_perm(n, True)

    def body(i, carry):
        block, out = carry
        block = lax.ppermute(block, axis_name, perm)
        # the block received at hop i originated at device (idx - 1 - i) mod n
        src = jnp.mod(idx - 1 - i, n)
        out = lax.dynamic_update_index_in_dim(out, block, src, 0)
        return block, out

    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, idx, 0)
    _, out = lax.fori_loop(0, n - 1, body, (x, out))
    return jnp.moveaxis(out, 0, axis).reshape(
        x.shape[:axis] + (n * x.shape[axis],) + x.shape[axis + 1 :]
    )


def reduce_scatter(
    x: jax.Array, *, axis_name: str = SEQUENCE_AXIS, axis: int = 0
) -> jax.Array:
    """Sum across the mesh axis, leaving each device its own 1/n slice
    (``lax.psum_scatter``, the gradient-sharding half of distributed data/optim
    sharding). ``x.shape[axis]`` must divide by the axis size."""
    return lax.psum_scatter(x, axis_name, scatter_dimension=axis, tiled=True)


# ---------------------------------------------------------------------------
# Host-side helpers to run a spatially-sharded computation end to end.
# ---------------------------------------------------------------------------


def shard_spatial(x: np.ndarray, mesh: Mesh, *, spatial_axis: int = 1):
    """Place a host array on the mesh with axis 0 on the ``batch`` mesh axis and
    ``spatial_axis`` on the ``sequence`` mesh axis."""
    if spatial_axis == 0:
        raise ValueError(
            "spatial_axis 0 is the batch dimension; pick a spatial dimension >= 1"
        )
    spec = [None] * x.ndim
    spec[0] = BATCH_AXIS
    spec[spatial_axis] = SEQUENCE_AXIS
    from jax.sharding import NamedSharding

    return jax.device_put(x, NamedSharding(mesh, P(*spec)))


def sequence_parallel_degree(mesh: Mesh) -> int:
    return mesh.shape[SEQUENCE_AXIS]
