"""Elastic pod-scale training: checkpoint-coordinated world resize.

Preemptible/spot capacity is how large TPU runs get cheap, but a fixed world
size turns one lost host into a dead run: the restart supervisor
(resilience/supervisor.py) can only relaunch the SAME shape, which no longer
exists. This module makes world size dynamic by composing pieces the repo
already has:

- **membership change detection**: the :class:`ElasticCoordinator` owns one
  child process per host slot and notices a host death (child SIGKILL/OOM —
  ``rc`` 137 — or a heartbeat stall), and the :class:`EvictionPolicy` turns
  the fleet ledgers' live straggler attribution (``obs/fleet.py``, PR 8) into
  a deliberate eviction — sustained skew past a threshold, never below
  ``min_hosts``, cooldown against flapping;
- **coordinated drain**: survivors ride the existing preemption seam
  (resilience/preempt.py — SIGTERM ⇒ final checkpoint + data-state sidecar +
  exit 75). A host DEATH leaves the survivors' collectives pointed at a dead
  peer, so the drain is bounded: children that cannot complete their
  preemption checkpoint within ``drain_timeout_s`` are killed and the resume
  falls back to the last COMPLETE checkpoint (``restore_latest`` already
  skips the torn one). An EVICTION drains everyone cooperatively — all hosts
  still live — so it loses zero steps;
- **re-plan**: the new world's mesh comes from ``parallel/planner.plan()`` at
  the new :class:`~tensorflowdistributedlearning_tpu.parallel.planner.Topology`
  (the planner takes a plain Topology, so the what-if plan runs in the
  coordinator, off-device), fed the prior run's ledgered
  measured-vs-predicted watermark residual
  (``planner.measured_margin_from_workdir``) as activation margin;
- **resize-aware resume**: children restart at the new world size and restore
  through the layout-independent checkpoint path — the abstract template
  carries the NEW placement's shardings, so ZeRO-1 optimizer state lands
  resharded to the new dp degree (the cross-mode restore contract of
  arXiv:2004.13336, pinned by tests/test_zero1.py) — while
  ``data/service.py`` re-deals the per-epoch shard assignment at the new
  ``process_count`` (batch ``i`` stays a pure function of
  ``(seed, i, process_index, process_count)``, so an elastic resume is
  bit-identical to a clean same-world run from the same checkpoint);
- **ledgered accounting**: every resize writes a ``world_resize`` event
  (old/new world, reason, measured downtime, plan delta) and every eviction a
  ``host_evicted`` event into the workdir ledger, bracketed by
  ``elastic_start``/``elastic_end`` — rendered by ``telemetry-report``'s
  elastic section and ``telemetry-top``'s world row, with resize downtime
  counted against goodput.

The coordinator's child launcher is a single-machine pod harness (one
subprocess per simulated host, explicit ``jax.distributed`` coordinator over
gloo CPU collectives — the same shape tests/test_multiprocess.py proves), and
every seam (``spawn``, ``child_argv_fn``, ``straggler_probe``, ``plan_fn``,
``sleep``/``clock``) is injectable: on a real pod the same state machine runs
with a scheduler-backed spawn. CLI: ``fit --elastic N --min-hosts M``.

Resize state machine (one generation = one spawned world)::

    spawn(W) ──all rc 0──────────────────────────────▶ done
       │ child rc 137 / heartbeat stall with a dead peer
       │        ──▶ drain survivors ─▶ resize(W-1)  [world_resize: host_death]
       │ sustained straggler (EvictionPolicy)
       │        ──▶ drain ALL (cooperative) ─▶ resize(W-1)
       │                               [host_evicted + world_resize]
       │ child crash (nonzero rc, host still fine)
       │        ──▶ drain ─▶ respawn(W)  [same-shape restart, budgeted,
       │                                  crash-loop detected via ledger]
       └ resize below min_hosts / budgets exhausted ─▶ elastic_abort
"""

from __future__ import annotations

import dataclasses
import logging
import os
import random
import signal as signal_lib
import socket
import subprocess
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tensorflowdistributedlearning_tpu.resilience.preempt import EXIT_PREEMPTED

logger = logging.getLogger(__name__)

ABORT_MIN_HOSTS = "min-hosts"
ABORT_RESIZE_BUDGET = "resize-budget"
ABORT_RESTART_BUDGET = "restart-budget"
ABORT_CRASH_LOOP = "crash-loop"
ABORT_SIGNALED = "signaled"

RESIZE_HOST_DEATH = "host_death"
RESIZE_EVICTION = "straggler_evicted"

# a child killed by SIGKILL reports rc -9 from Popen (137 once shell-folded):
# the signature of a host that VANISHED (OOM kill, node loss) rather than
# crashed — the distinction that turns a same-shape restart into a resize
_SIGKILL_RCS = (-signal_lib.SIGKILL, 128 + signal_lib.SIGKILL)


def free_port() -> int:
    """An ephemeral localhost port for one generation's jax.distributed
    coordinator (each generation binds a FRESH one — the dying world's
    coordinator socket may linger in TIME_WAIT)."""
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclasses.dataclass
class ElasticConfig:
    """Knobs of one elastic session. ``hosts`` is the initial world size;
    the world only ever shrinks (hosts joining mid-run would need a rendezvous
    jax.distributed does not offer — a re-launch at the larger size resumes
    through the same re-deal path)."""

    hosts: int
    min_hosts: int = 1
    devices_per_host: Optional[int] = None  # CPU harness: forced device count
    drain_timeout_s: float = 45.0
    poll_interval_s: float = 0.2
    straggler_poll_s: float = 2.0
    straggler_threshold: float = 1.25
    straggler_sustained: int = 3
    eviction_cooldown_s: float = 60.0
    # no ledger step progress for this long while every child is alive = a
    # wedged collective (e.g. a silently-lost peer): drain and restart. Must
    # comfortably exceed compile time; 0 disables.
    heartbeat_timeout_s: float = 600.0
    max_restarts: int = 3  # same-shape restarts (crashes), like Supervisor
    max_resizes: int = 8
    # AOT warm standby: once a generation settles (first fresh ledger step),
    # background-compile the NEXT world's (world-1) step function into the
    # shared persistent compile cache (utils/compile_cache.py), so a resize's
    # respawn LOADS its executables instead of rebuilding them and the
    # downtime left is checkpoint I/O. Cache keys hash the serialized
    # backend topology, which is PROCESS-LOCAL (total device count plus
    # which devices are this rank's) — so the standby is a real
    # (world-1)-process mini-world on a scratch workdir, rank-for-rank
    # identical to the pod a resize would spawn; a solo process emulating
    # the device count would write entries nobody ever reads. Needs a
    # standby_argv_fn (the CLI injects one) and a configured
    # --compile-cache-dir to be useful.
    aot_standby: bool = False
    crash_loop_tolerance: int = 2
    backoff_base_s: float = 1.0
    backoff_max_s: float = 30.0
    jitter_frac: float = 0.25
    seed: int = 0

    def __post_init__(self) -> None:
        if self.hosts < 1:
            raise ValueError(f"hosts must be >= 1, got {self.hosts}")
        if not 1 <= self.min_hosts <= self.hosts:
            raise ValueError(
                f"min_hosts must be in [1, hosts={self.hosts}], got "
                f"{self.min_hosts}"
            )
        if self.straggler_sustained < 1:
            raise ValueError(
                f"straggler_sustained must be >= 1, got "
                f"{self.straggler_sustained}"
            )


class EvictionPolicy:
    """The straggler-eviction state machine — pure and clock-injected, so the
    policy contract (tests/test_elastic.py) is pinned without processes.

    Feed it one observation per straggler poll (:meth:`observe`): the newest
    cross-host-compared window step and, when that window crossed the skew
    threshold, the alert naming the worst host. An eviction fires only after
    ``sustained`` CONSECUTIVE fresh alerted windows naming the SAME host — a
    clean fresh window resets the streak, so a transiently-slow (flapping)
    host never oscillates the world. Evictions never take the world below
    ``min_hosts``, and after any resize (:meth:`notify_resize`) a cooldown
    blocks further evictions while the resized fleet restabilizes."""

    def __init__(
        self,
        *,
        threshold: float = 1.25,
        sustained: int = 3,
        cooldown_s: float = 60.0,
        min_hosts: int = 1,
    ):
        self.threshold = float(threshold)
        self.sustained = int(sustained)
        self.cooldown_s = float(cooldown_s)
        self.min_hosts = int(min_hosts)
        self._last_step: Optional[int] = None
        self._candidate: Optional[int] = None
        self._streak = 0
        self._cooldown_until = 0.0

    def observe(
        self,
        now: float,
        world_size: int,
        step: Optional[int],
        alert: Optional[Dict],
    ) -> Optional[int]:
        """One poll: ``step`` is the newest step compared across >= 2 hosts
        (None: nothing comparable yet), ``alert`` the straggler alert AT that
        step ({"worst_process", "skew"}) or None when that window was clean.
        Returns the process index to evict, or None."""
        if step is None or (
            self._last_step is not None and step <= self._last_step
        ):
            return None  # no fresh window since the last poll
        self._last_step = step
        if not alert or float(alert.get("skew", 0.0)) <= self.threshold:
            self._candidate = None
            self._streak = 0
            return None
        worst = int(alert["worst_process"])
        if worst == self._candidate:
            self._streak += 1
        else:
            self._candidate = worst
            self._streak = 1
        if self._streak < self.sustained:
            return None
        if now < self._cooldown_until:
            return None
        if world_size - 1 < self.min_hosts:
            return None  # shedding the straggler would kill the run
        return self._candidate

    def notify_resize(self, now: float) -> None:
        """Any resize (eviction OR death) restarts the clock: the resized
        fleet re-warms (compile, cache refill), which looks exactly like a
        straggler and must not trigger a cascade."""
        self._cooldown_until = now + self.cooldown_s
        self._candidate = None
        self._streak = 0


def ledger_straggler_probe(
    workdir: str, world_size: int, *, threshold: float
) -> Tuple[Optional[int], Optional[Dict]]:
    """The default live straggler source: merge the CURRENT world's
    per-process ledgers (process indices < ``world_size`` — stale ledgers of
    evicted/dead slots are excluded) and return ``(latest_compared_step,
    alert_at_that_step_or_None)`` in :meth:`EvictionPolicy.observe`'s shape.
    """
    from tensorflowdistributedlearning_tpu.obs import fleet as fleet_lib

    try:
        ledgers = [
            led
            for led in fleet_lib.discover_ledgers(workdir)
            if led.process_index < world_size
        ]
    except OSError:
        return None, None
    section = fleet_lib.straggler_section(
        ledgers, skew_threshold=threshold, max_alerts=10**6
    )
    if not section:
        return None, None
    # the newest cross-compared step: alerts carry steps; clean windows do
    # not surface individually, but the worst_window_counts/windows_compared
    # math runs over ALL shared steps — recover the newest via the per-ledger
    # windows directly
    latest = None
    per_host_steps = []
    for led in ledgers:
        steps = {
            int(e["step"])
            for e in led.events
            if e.get("event") == "step_window" and "step" in e
            and "step_time_ms" in e
        }
        if steps:
            per_host_steps.append(steps)
    if len(per_host_steps) >= 2:
        shared = set.intersection(*per_host_steps)
        if shared:
            latest = max(shared)
    if latest is None:
        return None, None
    alert = next(
        (
            {"worst_process": a["worst_process"], "skew": a["skew"]}
            for a in reversed(section.get("alerts", []))
            if a.get("step") == latest
        ),
        None,
    )
    return latest, alert


@dataclasses.dataclass
class ElasticResult:
    ok: bool
    exit_code: int
    world_size: int
    resizes: int
    restarts: int
    evictions: int = 0
    aborted: Optional[str] = None  # ABORT_* or None
    final_step: Optional[int] = None
    resize_downtime_s: float = 0.0
    # spawn -> first fresh ledger step, summed over post-resize generations:
    # the warmup (interpreter boot + restore + COMPILE) a resize actually
    # costs beyond the drain, and the number the AOT standby exists to shrink
    post_resize_settle_s: float = 0.0


class _Child:
    """One spawned host slot: a thin Popen wrapper the fake-spawn tests
    mirror (``poll``/``send_signal``/``kill``/``pid``)."""

    def __init__(self, proc: subprocess.Popen):
        self._proc = proc
        self.pid = proc.pid

    def poll(self) -> Optional[int]:
        return self._proc.poll()

    def send_signal(self, sig: int) -> None:
        try:
            self._proc.send_signal(sig)
        except (ProcessLookupError, OSError):
            pass

    def kill(self) -> None:
        try:
            self._proc.kill()
        except (ProcessLookupError, OSError):
            pass


class ElasticCoordinator:
    """Run an elastic multi-process training session rooted at ``workdir``.

    ``child_argv_fn(world_size, process_id, coordinator_address, generation)``
    builds one host slot's command (``coordinator_address`` is None for a
    single-host world — the child then runs plain single-process). The
    coordinator appends ``world_resize``/``host_evicted``/``elastic_*``
    events to the workdir's canonical ledger exactly like the restart
    supervisor does — between child generations, plus spawn markers whose
    interleaving with child lines is safe (O_APPEND single-line writes).

    ``plan_fn(world_size, measured_margin_bytes)`` returns the new world's
    plan header dict (``parallel/planner``) or None; the default is injected
    by the CLI with the run's model/train config closed over."""

    def __init__(
        self,
        child_argv_fn: Callable[[int, int, Optional[str], int], Sequence[str]],
        workdir: str,
        config: ElasticConfig,
        *,
        plan_fn: Optional[Callable[[int, Optional[int]], Optional[Dict]]] = None,
        standby_argv_fn: Optional[
            Callable[[int, int, Optional[str]], Optional[Sequence[str]]]
        ] = None,
        spawn: Optional[Callable[[Sequence[str], Dict[str, str]], _Child]] = None,
        straggler_probe: Optional[
            Callable[[int], Tuple[Optional[int], Optional[Dict]]]
        ] = None,
        env: Optional[Dict[str, str]] = None,
        sleep: Callable[[float], None] = time.sleep,
        clock: Callable[[], float] = time.time,
    ):
        self.workdir = workdir
        self.config = config
        self._argv_fn = child_argv_fn
        self._plan_fn = plan_fn
        # AOT standby seam: argv for one rank of a compile-only mini-world at
        # the given size — ``(world, pid, coordinator_address)``, mirroring
        # child_argv_fn (None: no standby possible for that size). The
        # standby must be a REAL world of ``world`` processes: XLA cache keys
        # hash the serialized backend topology, which is process-local (total
        # device count AND which devices belong to this rank), so only a
        # rank-for-rank replica of the future world produces entries the
        # resized pod can actually hit.
        self._standby_argv_fn = standby_argv_fn
        self._standby: List[_Child] = []
        self._standby_world: Optional[int] = None
        self._standby_t0 = 0.0
        self._standby_done: set = set()  # worlds already compiled into cache
        self._settles: Dict[int, float] = {}  # generation -> settle wall s
        self._spawn = spawn or self._spawn_subprocess
        self._probe = straggler_probe or (
            lambda world: ledger_straggler_probe(
                workdir, world, threshold=config.straggler_threshold
            )
        )
        self._env = env
        self._sleep = sleep
        self._clock = clock
        self._rng = random.Random(config.seed)
        self._children: List[Optional[_Child]] = []
        self._stop_signal: Optional[int] = None
        self.policy = EvictionPolicy(
            threshold=config.straggler_threshold,
            sustained=config.straggler_sustained,
            cooldown_s=config.eviction_cooldown_s,
            min_hosts=config.min_hosts,
        )

    # -- plumbing ----------------------------------------------------------

    def _spawn_subprocess(
        self, argv: Sequence[str], env: Dict[str, str]
    ) -> _Child:
        return _Child(subprocess.Popen(list(argv), env=env))

    def _child_env(self) -> Dict[str, str]:
        env = dict(self._env if self._env is not None else os.environ)
        # same contract as the restart supervisor: children know they are
        # supervised (stamps run headers, blocks supervisor recursion)
        env["TFDL_SUPERVISED_CHILD"] = "1"
        if self.config.devices_per_host:
            env["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count="
                f"{self.config.devices_per_host}"
            )
        return env

    # -- AOT warm standby --------------------------------------------------

    def _standby_env(self) -> Dict[str, str]:
        """Same env as a real child (identical forced device count — the
        standby rank's backend topology must match the future world's rank
        bit-for-bit, or its cache keys miss), plus the standby marker."""
        env = self._child_env()
        env["TFDL_AOT_STANDBY"] = "1"
        return env

    def _maybe_start_standby(self, world: int, generation: int, ledger) -> None:
        """Kick off the background compile of the world-1 step function —
        called once per generation, after the live world settled (its own
        compile is done, so the standby no longer competes with it). The
        standby is a full ``world-1``-process mini-world on a scratch
        workdir: cache keys are process-topology-bound, so only rank p of a
        real (world-1)-world writes the entry rank p of the resized pod
        will read."""
        if not self.config.aot_standby or self._standby_argv_fn is None:
            return
        target = world - 1
        if target < self.config.min_hosts or target in self._standby_done:
            return
        if self._standby:
            if self._standby_world == target and any(
                c.poll() is None for c in self._standby
            ):
                return  # already compiling exactly this world
            self._kill_standby()  # stale target — the world moved on
        try:
            coord = f"127.0.0.1:{free_port()}" if target > 1 else None
            procs: List[_Child] = []
            env = self._standby_env()
            for pid in range(target):
                argv = self._standby_argv_fn(target, pid, coord)
                if not argv:
                    for c in procs:
                        c.kill()
                    return
                procs.append(self._spawn(list(argv), env))
            self._standby = procs
        except Exception as e:  # noqa: BLE001 — the standby is an
            # optimization; a failed spawn must never touch the live world
            logger.warning("aot standby spawn at world %d failed: %s",
                           target, e)
            self._kill_standby()
            return
        self._standby_world = target
        self._standby_t0 = self._clock()
        ledger.event(
            "aot_standby",
            action="start",
            target_world=target,
            generation=generation,
            procs=len(self._standby),
            pid=self._standby[0].pid,
        )

    def _poll_standby(self, ledger) -> None:
        if not self._standby:
            return
        rcs = [c.poll() for c in self._standby]
        if any(rc is None for rc in rcs):
            return
        rc = next((r for r in rcs if r != 0), 0)
        ledger.event(
            "aot_standby",
            action="ready" if rc == 0 else "failed",
            target_world=self._standby_world,
            rc=rc,
            duration_s=round(self._clock() - self._standby_t0, 3),
        )
        if rc == 0:
            self._standby_done.add(self._standby_world)
        else:
            logger.warning(
                "aot standby for world %s exited rc=%s — next resize "
                "compiles cold", self._standby_world, rc,
            )
        self._standby = []

    def _kill_standby(self) -> None:
        for c in self._standby:
            try:
                c.kill()
            except Exception:  # noqa: BLE001 — already-dead child
                pass
        self._standby = []

    def _reap_standby(self, ledger) -> None:
        """The world is about to respawn: the standby's job is moot (the new
        generation compiles-or-loads RIGHT NOW) and on a shared box its
        processes would compete with the respawn for cores — the exact
        window the standby exists to shrink. Harvest a finished standby
        (its entries are on disk), kill a running one (every entry compiled
        so far is already written; only the tail is lost)."""
        if not self._standby:
            return
        self._poll_standby(ledger)
        if not self._standby:
            return
        ledger.event(
            "aot_standby",
            action="superseded",
            target_world=self._standby_world,
            duration_s=round(self._clock() - self._standby_t0, 3),
        )
        self._kill_standby()

    def _ledger(self):
        from tensorflowdistributedlearning_tpu.obs.ledger import RunLedger

        return RunLedger(self.workdir)

    def _progress(self) -> Optional[int]:
        from tensorflowdistributedlearning_tpu.resilience.supervisor import (
            ledger_progress,
        )

        return ledger_progress(self.workdir)

    def _backoff(self, attempt: int) -> float:
        from tensorflowdistributedlearning_tpu.resilience.retry import (
            backoff_delay,
        )

        return backoff_delay(
            attempt,
            base_delay_s=self.config.backoff_base_s,
            max_delay_s=self.config.backoff_max_s,
            jitter_frac=self.config.jitter_frac,
            rng=self._rng,
        )

    def _plan_header(
        self, world: int, margin: Optional[int]
    ) -> Optional[Dict]:
        if self._plan_fn is None:
            return None
        try:
            return self._plan_fn(world, margin)
        except Exception as e:  # noqa: BLE001 — a failed what-if plan must
            # never block the resize itself; the new world's own fit will
            # validate its layout again anyway
            logger.warning("re-plan at world %d failed: %s", world, e)
            return {"error": str(e)[:300]}

    @staticmethod
    def _plan_lite(header: Optional[Dict]) -> Optional[Dict]:
        """The resize event's compact plan view (the full header already
        rides each generation's run_header)."""
        if not header:
            return None
        if "error" in header:
            return {"error": header["error"]}
        out: Dict = {"layout": header.get("layout")}
        predicted = header.get("predicted") or {}
        if predicted.get("total_bytes_per_chip") is not None:
            out["total_bytes_per_chip"] = predicted["total_bytes_per_chip"]
        if header.get("headroom_frac") is not None:
            out["headroom_frac"] = header["headroom_frac"]
        return out

    # -- signals -----------------------------------------------------------

    def _on_signal(self, signum, frame) -> None:
        self._stop_signal = signum
        for child in self._children:
            if child is not None and child.poll() is None:
                child.send_signal(signal_lib.SIGTERM)

    def _install_signals(self) -> Dict[int, object]:
        prev: Dict[int, object] = {}
        for sig in (signal_lib.SIGTERM, signal_lib.SIGINT):
            try:
                prev[sig] = signal_lib.signal(sig, self._on_signal)
            except ValueError:  # non-main thread
                pass
        return prev

    @staticmethod
    def _restore_signals(prev: Dict[int, object]) -> None:
        for sig, disposition in prev.items():
            try:
                signal_lib.signal(sig, disposition)
            except (ValueError, TypeError):
                pass

    # -- generations -------------------------------------------------------

    def _spawn_world(self, world: int, generation: int) -> None:
        coord = f"127.0.0.1:{free_port()}" if world > 1 else None
        env = self._child_env()
        self._children = []
        for pid in range(world):
            argv = list(self._argv_fn(world, pid, coord, generation))
            self._children.append(self._spawn(argv, env))

    def _alive(self) -> List[int]:
        return [
            i
            for i, c in enumerate(self._children)
            if c is not None and c.poll() is None
        ]

    def _drain(self) -> float:
        """SIGTERM every live child (the preemption seam: final checkpoint +
        sidecar + exit 75 where the collectives still work), bounded by
        ``drain_timeout_s``, then SIGKILL the rest. Returns the drain wall
        time."""
        t0 = self._clock()
        for i in self._alive():
            self._children[i].send_signal(signal_lib.SIGTERM)
        deadline = t0 + self.config.drain_timeout_s
        while self._alive() and self._clock() < deadline:
            self._sleep(self.config.poll_interval_s)
        stragglers = self._alive()
        for i in stragglers:
            logger.warning(
                "child %d did not drain within %.0fs — killing (resume falls "
                "back to the last complete checkpoint)",
                i, self.config.drain_timeout_s,
            )
            self._children[i].kill()
        # reap: give the kills a moment to register
        deadline = self._clock() + 5.0
        while self._alive() and self._clock() < deadline:
            self._sleep(self.config.poll_interval_s)
        return self._clock() - t0

    # -- the session -------------------------------------------------------

    def run(self) -> ElasticResult:  # noqa: C901 — the state machine
        cfg = self.config
        ledger = self._ledger()
        prev_handlers = self._install_signals()
        world = cfg.hosts
        generation = 0
        restarts = 0
        resizes = 0
        evictions = 0
        no_progress = 0
        resize_downtime = 0.0
        prev_step = self._progress()
        margin = None
        plan_header = self._plan_header(world, None)
        ledger.event(
            "elastic_start",
            hosts=cfg.hosts,
            min_hosts=cfg.min_hosts,
            devices_per_host=cfg.devices_per_host,
            step=prev_step,
            **({"plan": self._plan_lite(plan_header)} if plan_header else {}),
        )

        resized_gens: set = set()  # generations spawned BY a resize

        def finish(res: ElasticResult) -> ElasticResult:
            res.post_resize_settle_s = round(
                sum(s for g, s in self._settles.items() if g in resized_gens),
                3,
            )
            ledger.event(
                "elastic_end",
                ok=res.ok,
                world_size=res.world_size,
                resizes=res.resizes,
                restarts=res.restarts,
                evictions=res.evictions,
                aborted=res.aborted,
                step=res.final_step,
                resize_downtime_s=round(res.resize_downtime_s, 3),
                post_resize_settle_s=res.post_resize_settle_s,
            )
            return res

        try:
            while True:
                self._spawn_world(world, generation)
                ledger.event(
                    "world_spawn",
                    generation=generation,
                    world_size=world,
                    pids=[c.pid for c in self._children if c is not None],
                )
                event = self._monitor(world, ledger, generation)
                step = self._progress()
                if self._stop_signal is not None or event["kind"] == "signaled":
                    # the coordinator itself was told to stop: children were
                    # already forwarded SIGTERM by the handler — wait them out
                    # and report like the restart supervisor's signaled stop
                    self._drain()
                    rc = event.get("rc", 0) or 0
                    return finish(
                        ElasticResult(
                            ok=rc == 0,
                            exit_code=rc,
                            world_size=world,
                            resizes=resizes,
                            restarts=restarts,
                            evictions=evictions,
                            aborted=None if rc == 0 else ABORT_SIGNALED,
                            final_step=step,
                            resize_downtime_s=resize_downtime,
                        )
                    )
                if event["kind"] == "done":
                    return finish(
                        ElasticResult(
                            ok=True,
                            exit_code=0,
                            world_size=world,
                            resizes=resizes,
                            restarts=restarts,
                            evictions=evictions,
                            final_step=step,
                            resize_downtime_s=resize_downtime,
                        )
                    )

                # membership change or crash: drain whatever still runs
                drain_t0 = self._clock()
                self._drain()
                self._reap_standby(ledger)
                last_step = prev_step
                step = self._progress()
                progressed = step is not None and (
                    prev_step is None or step > prev_step
                )
                prev_step = step

                if event["kind"] in (RESIZE_HOST_DEATH, RESIZE_EVICTION):
                    # a resize is a deliberate membership change, not a crash
                    # loop: it must not feed the no-progress counter (two
                    # quick host losses during warm-up would otherwise abort
                    # the FIRST ordinary crash before any restart was tried)
                    no_progress = 0
                    new_world = world - 1
                    if new_world < cfg.min_hosts:
                        ledger.event(
                            "elastic_abort",
                            reason=ABORT_MIN_HOSTS,
                            world_size=world,
                            min_hosts=cfg.min_hosts,
                            step=step,
                        )
                        return finish(
                            ElasticResult(
                                ok=False,
                                exit_code=event.get("rc", 1) or 1,
                                world_size=world,
                                resizes=resizes,
                                restarts=restarts,
                                evictions=evictions,
                                aborted=ABORT_MIN_HOSTS,
                                final_step=step,
                                resize_downtime_s=resize_downtime,
                            )
                        )
                    if resizes >= cfg.max_resizes:
                        ledger.event(
                            "elastic_abort",
                            reason=ABORT_RESIZE_BUDGET,
                            resizes=resizes,
                            step=step,
                        )
                        return finish(
                            ElasticResult(
                                ok=False,
                                exit_code=event.get("rc", 1) or 1,
                                world_size=world,
                                resizes=resizes,
                                restarts=restarts,
                                evictions=evictions,
                                aborted=ABORT_RESIZE_BUDGET,
                                final_step=step,
                                resize_downtime_s=resize_downtime,
                            )
                        )
                    if event["kind"] == RESIZE_EVICTION:
                        evictions += 1
                        ledger.event(
                            "host_evicted",
                            process_index=event["process"],
                            skew=event.get("skew"),
                            world_size=world,
                            step=step,
                        )
                    from tensorflowdistributedlearning_tpu.parallel import (
                        planner as planner_lib,
                    )

                    margin = planner_lib.measured_margin_from_workdir(
                        self.workdir
                    )
                    old_plan = plan_header
                    plan_header = self._plan_header(new_world, margin)
                    resizes += 1
                    self.policy.notify_resize(self._clock())
                    downtime = self._clock() - drain_t0
                    resize_downtime += downtime
                    from tensorflowdistributedlearning_tpu.resilience.supervisor import (  # noqa: E501
                        shell_rc,
                    )

                    ledger.event(
                        "world_resize",
                        old_world=world,
                        new_world=new_world,
                        reason=event["kind"],
                        generation=generation,
                        rc=(
                            shell_rc(event["rc"])
                            if event.get("rc") is not None else None
                        ),
                        # the host slot that left the world (dead or evicted);
                        # evicted_process names only DELIBERATE evictions
                        process_index=event.get("process"),
                        evicted_process=(
                            event.get("process")
                            if event["kind"] == RESIZE_EVICTION else None
                        ),
                        # last OBSERVED ledger progress at drain time; the
                        # actual restore point is the new generation's
                        # `resumed` event (restore_latest may fall back past
                        # a checkpoint torn by the drain)
                        progress_step=step,
                        downtime_s=round(downtime, 3),
                        measured_margin_bytes=margin,
                        plan_old=self._plan_lite(old_plan),
                        plan_new=self._plan_lite(plan_header),
                    )
                    logger.warning(
                        "world resize %d -> %d (%s) at step %s — %.1fs "
                        "downtime",
                        world, new_world, event["kind"], step, downtime,
                    )
                    world = new_world
                    generation += 1
                    resized_gens.add(generation)
                    continue

                # crash / stall: same-shape restart, budgeted like Supervisor
                no_progress = 0 if progressed else no_progress + 1
                abort = None
                if no_progress >= cfg.crash_loop_tolerance:
                    abort = ABORT_CRASH_LOOP
                elif restarts >= cfg.max_restarts:
                    abort = ABORT_RESTART_BUDGET
                if abort:
                    ledger.event(
                        "elastic_abort",
                        reason=abort,
                        rc=event.get("rc"),
                        restarts=restarts,
                        step=step,
                    )
                    return finish(
                        ElasticResult(
                            ok=False,
                            exit_code=event.get("rc", 1) or 1,
                            world_size=world,
                            resizes=resizes,
                            restarts=restarts,
                            evictions=evictions,
                            aborted=abort,
                            final_step=step,
                            resize_downtime_s=resize_downtime,
                        )
                    )
                restarts += 1
                backoff = self._backoff(restarts)
                logger.warning(
                    "generation %d %s (rc=%s) at step %s — same-shape "
                    "restart %d/%d in %.2fs",
                    generation, event["kind"], event.get("rc"), step,
                    restarts, cfg.max_restarts, backoff,
                )
                self._sleep(backoff)
                if self._stop_signal is not None:
                    return finish(
                        ElasticResult(
                            ok=False,
                            exit_code=event.get("rc", 1) or 1,
                            world_size=world,
                            resizes=resizes,
                            restarts=restarts - 1,
                            evictions=evictions,
                            aborted=ABORT_SIGNALED,
                            final_step=step,
                            resize_downtime_s=resize_downtime,
                        )
                    )
                ledger.event(
                    "restart",
                    attempt=restarts,
                    rc=event.get("rc"),
                    reason=event["kind"],
                    step=step,
                    # the progress point BEFORE this generation died — the
                    # same forensic pair the restart supervisor writes
                    prev_step=last_step,
                    backoff_s=round(backoff, 3),
                    downtime_s=round(self._clock() - drain_t0, 3),
                )
                generation += 1
        finally:
            # finish() already ledgered elastic_end on every return path;
            # this only covers an unexpected exception escaping the loop
            self._kill_standby()
            self._restore_signals(prev_handlers)
            ledger.close()

    # -- per-generation monitor --------------------------------------------

    def _monitor(self, world: int, ledger, generation: int = 0) -> Dict:
        """Watch one generation until it completes or a membership/crash
        event fires. Returns ``{"kind": ...}`` with kind one of ``done``,
        ``signaled``, :data:`RESIZE_HOST_DEATH`, :data:`RESIZE_EVICTION`,
        ``crash`` or ``stall`` (+ ``rc``/``process``/``skew`` context).

        The first FRESH ledger step past the spawn-time watermark marks the
        generation as settled: ``world_settled`` is ledgered with the
        spawn->step wall time (boot + restore + compile — the real post-drain
        warmup a resize costs), and the AOT standby for the next world size
        starts only then, so its compile never races the live world's own."""
        cfg = self.config
        spawn_t = self._clock()
        last_progress_t = spawn_t
        last_step = self._progress()
        settled = False
        next_straggler_t = spawn_t + cfg.straggler_poll_s
        # heartbeat bookkeeping: the ledger reparse is O(file size), so it
        # runs on its own (>= 1s) cadence and only when the canonical ledger
        # actually GREW — progress cannot advance without a new line
        ledger_path = os.path.join(self.workdir, "telemetry.jsonl")
        heartbeat_poll_s = max(1.0, cfg.straggler_poll_s)
        next_heartbeat_t = spawn_t + heartbeat_poll_s
        last_ledger_size = -1
        while True:
            if self._stop_signal is not None:
                return {"kind": "signaled", "rc": 0}
            exited = {
                i: c.poll()
                for i, c in enumerate(self._children)
                if c is not None and c.poll() is not None
            }
            failed = {i: rc for i, rc in exited.items() if rc != 0}
            if failed:
                # a nonzero exit while process 0 ALREADY finished cleanly is
                # teardown noise of a completed run, not a membership event
                if exited.get(0) == 0:
                    logger.warning(
                        "run complete; ignoring late nonzero exits: %s",
                        failed,
                    )
                    return {"kind": "done"}
                proc, rc = next(iter(sorted(failed.items())))
                if rc in _SIGKILL_RCS:
                    return {
                        "kind": RESIZE_HOST_DEATH, "process": proc, "rc": rc,
                    }
                kind = "preempt" if rc == EXIT_PREEMPTED else "crash"
                return {"kind": "crash", "rc": rc, "process": proc,
                        "crash_kind": kind}
            if len(exited) == len(self._children):
                return {"kind": "done"}
            now = self._clock()
            # heartbeat: ledger step progress is the fleet's pulse (the same
            # cadence also drives settle detection, so it runs even with the
            # stall timeout disabled)
            if now >= next_heartbeat_t:
                next_heartbeat_t = now + heartbeat_poll_s
                try:
                    size = os.stat(ledger_path).st_size
                except OSError:
                    size = -1
                if size != last_ledger_size:
                    last_ledger_size = size
                    step = self._progress()
                    if step != last_step:
                        last_step = step
                        last_progress_t = now
                        if not settled:
                            settled = True
                            settle_s = now - spawn_t
                            self._settles[generation] = settle_s
                            ledger.event(
                                "world_settled",
                                generation=generation,
                                world_size=world,
                                step=step,
                                settle_s=round(settle_s, 3),
                            )
                            self._maybe_start_standby(
                                world, generation, ledger
                            )
                if (
                    cfg.heartbeat_timeout_s
                    and now - last_progress_t > cfg.heartbeat_timeout_s
                ):
                    return {"kind": "stall", "rc": None}
                self._poll_standby(ledger)
            # straggler watch: only meaningful with >= 2 hosts
            if world > 1 and now >= next_straggler_t:
                next_straggler_t = now + cfg.straggler_poll_s
                try:
                    step, alert = self._probe(world)
                except Exception as e:  # noqa: BLE001 — a probe hiccup (torn
                    # ledger line mid-read) must never kill the coordinator
                    logger.debug("straggler probe failed: %s", e)
                    step, alert = None, None
                victim = self.policy.observe(now, world, step, alert)
                if victim is not None:
                    logger.warning(
                        "evicting straggler host %d (skew %.2f sustained "
                        "across %d windows)",
                        victim, float((alert or {}).get("skew", 0.0)),
                        cfg.straggler_sustained,
                    )
                    return {
                        "kind": RESIZE_EVICTION,
                        "process": victim,
                        "skew": (alert or {}).get("skew"),
                    }
            self._sleep(cfg.poll_interval_s)


