"""Ring attention: exact blockwise sequence-parallel attention over a device ring.

The reference framework had no attention anywhere (pure CNNs — SURVEY §5.7), so
this is a beyond-parity capability: the transformer-side long-context story that
complements ``parallel/spatial.py``'s halo-exchange convolutions. Sequences too
long for one chip's HBM are sharded over the ``sequence`` mesh axis; each device
holds one Q/K/V block and the K/V blocks rotate around the ring with one
``lax.ppermute`` hop per step (ICI neighbor traffic, like the halo exchange),
while a numerically-stable online softmax accumulates the exact full-attention
result — no approximation, activation memory O(S/n) per chip.

This is the blockwise/ring formulation of Liu et al., "Ring Attention with
Blockwise Transformers for Near-Infinite Context" (arXiv:2310.01889), built on
XLA collectives instead of hand-written comm: the ``ppermute`` rotation overlaps
with the per-block attention math under XLA's latency-hiding scheduler.

Everything here runs inside ``shard_map``; ``make_ring_attention`` wraps the
sharded kernel into a jitted callable over a framework mesh. ``lax.scan`` (not a
Python loop) carries the rotation so the ring has one trace regardless of degree,
and reverse-mode AD works out of the box (ppermute's transpose is the inverse
rotation).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS, SEQUENCE_AXIS

# Large-negative mask value: -inf would poison rows whose every key is masked
# (exp(-inf - -inf) = nan). A row with NO visible key returns exact zeros in
# both formulations: the reference zeroes it explicitly, the ring zeroes
# masked probability columns so the denominator stays 0 and the final guard
# maps 0/0 to 0. Unreachable for causal SELF-attention (the diagonal is
# always visible) — it only engages under ``kv_mask`` padding masks.
_MASK_VALUE = -1e30


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Plain full-sequence softmax attention (the oracle ring_attention must
    reproduce). Shapes [B, S, H, D]; accumulates in float32.

    ``kv_mask`` ([B, S] bool, True = real key) excludes padding keys.
    ``segment_ids`` ([B, S] int) isolates packed documents: a query attends
    only to keys with ITS OWN segment id. Both masks compose with ``causal``;
    a query row with no visible key returns zeros."""
    orig_dtype = q.dtype
    q, k, v = (x.astype(jnp.float32) for x in (q, k, v))
    scale = 1.0 / jnp.sqrt(q.shape[-1])
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    b = scores.shape[0]
    s_q, s_k = scores.shape[-2], scores.shape[-1]
    # visibility [B, s_q, s_k]: causality AND padding AND segment identity
    visible = jnp.ones((b, s_q, s_k), bool)
    if causal:
        visible = visible & jnp.tril(jnp.ones((s_q, s_k), bool))[None]
    if kv_mask is not None:
        visible = visible & kv_mask[:, None, :]
    if segment_ids is not None:
        visible = visible & (
            segment_ids[:, :, None] == segment_ids[:, None, :]
        )
    scores = jnp.where(visible[:, None], scores, _MASK_VALUE)
    weights = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhqk,bkhd->bqhd", weights, v)
    if kv_mask is not None or segment_ids is not None:
        # rows with NO visible key would otherwise be a uniform softmax over
        # masked slots; zero them explicitly (see _MASK_VALUE note)
        any_visible = visible.any(axis=-1)
        out = jnp.where(any_visible[:, :, None, None], out, 0.0)
    return out.astype(orig_dtype)


def _ring_perm(n: int):
    """K/V rotation i -> i+1 (each device receives its predecessor's block)."""
    return [(i, (i + 1) % n) for i in range(n)]


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    axis_name: str = SEQUENCE_AXIS,
    causal: bool = False,
    kv_mask: Optional[jax.Array] = None,
    segment_ids: Optional[jax.Array] = None,
) -> jax.Array:
    """Exact attention with Q/K/V sharded [B, S/n, H, D] on ``axis_name``.

    Must run inside ``shard_map``. Each of the ``n`` ring steps attends this
    device's Q block to the currently-held K/V block (online-softmax
    accumulation in float32), then rotates K/V one hop. ``causal`` masks by
    GLOBAL positions: query ``axis_index*S_loc + i`` may only attend to keys at
    global positions <= its own, so the sharded result matches
    ``attention_reference(causal=True)`` on the gathered sequence exactly.

    ``kv_mask`` ([B, S/n] bool, sharded like K on ``axis_name``; True = real
    key) excludes padding keys — the variable-length-batch form.
    ``segment_ids`` ([B, S/n] int, sharded the same way) isolates packed
    documents: a query attends only to keys sharing ITS segment id. Both
    rotate around the ring WITH their K/V block (the key-side slice travels;
    the query-side slice stays local). A query row whose every visible key is
    masked returns zeros, matching ``attention_reference``.
    """
    n = lax.axis_size(axis_name)
    my_idx = lax.axis_index(axis_name)
    orig_dtype = q.dtype
    q32 = q.astype(jnp.float32)
    b, s_loc, h, d = q32.shape
    scale = 1.0 / jnp.sqrt(jnp.asarray(d, jnp.float32))

    # online-softmax state: running max m, denominator l, numerator o — derived
    # from q so the carries inherit q's varying-manual-axes type (a plain
    # jnp.zeros carry would be unvarying and fail scan's vma check)
    zeros_bhsd = jnp.transpose(q32, (0, 2, 1, 3)) * 0.0
    o0 = zeros_bhsd
    m0 = zeros_bhsd[..., :1] + _MASK_VALUE
    l0 = zeros_bhsd[..., :1]

    q_pos = my_idx * s_loc + jnp.arange(s_loc)  # global query positions
    q_seg = segment_ids  # this device's query-side segment ids (never rotate)

    def block_update(o, m, l, k_blk, v_blk, mask_blk, seg_blk, step_no):
        # the block held at ring step t originated on device (my_idx - t) mod n
        src = (my_idx - step_no) % n
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", q32, k_blk.astype(jnp.float32)) * scale
        )
        # visibility [B, s_q, s_k] (True = may attend); None = all visible
        visible = None
        if causal:
            k_pos = src * s_loc + jnp.arange(s_loc)
            visible = jnp.broadcast_to(
                (q_pos[:, None] >= k_pos[None, :])[None], (b, s_loc, s_loc)
            )
        if mask_blk is not None:
            pad = jnp.broadcast_to(mask_blk[:, None, :], (b, s_loc, s_loc))
            visible = pad if visible is None else visible & pad
        if seg_blk is not None:
            same = q_seg[:, :, None] == seg_blk[:, None, :]
            visible = same if visible is None else visible & same
        if visible is not None:
            scores = jnp.where(visible[:, None], scores, _MASK_VALUE)
        m_new = jnp.maximum(m, scores.max(axis=-1, keepdims=True))
        correction = jnp.exp(m - m_new)
        p = jnp.exp(scores - m_new)
        if visible is not None and (mask_blk is not None or seg_blk is not None):
            # exp(MASK - MASK) = 1 would leak masked slots into rows whose
            # running max is still _MASK_VALUE (no visible key yet); zero the
            # masked columns outright so l counts only real keys
            p = p * visible[:, None].astype(p.dtype)
        l = l * correction + p.sum(axis=-1, keepdims=True)
        o = o * correction + jnp.einsum(
            "bhqk,bkhd->bhqd", p, v_blk.astype(jnp.float32)
        )
        return o, m_new, l

    # step 0 attends to the locally-held block before any rotation; the scan
    # then does [rotate, attend] for steps 1..n-1 — so exactly n-1 rotations
    # happen and no ppermute's result is discarded
    o, m, l = block_update(o0, m0, l0, k, v, kv_mask, segment_ids, 0)

    def step(carry, step_no):
        o, m, l, k_blk, v_blk, mask_blk, seg_blk = carry
        k_blk = lax.ppermute(k_blk, axis_name, _ring_perm(n))
        v_blk = lax.ppermute(v_blk, axis_name, _ring_perm(n))
        if mask_blk is not None:
            mask_blk = lax.ppermute(mask_blk, axis_name, _ring_perm(n))
        if seg_blk is not None:
            seg_blk = lax.ppermute(seg_blk, axis_name, _ring_perm(n))
        o, m, l = block_update(o, m, l, k_blk, v_blk, mask_blk, seg_blk, step_no)
        return (o, m, l, k_blk, v_blk, mask_blk, seg_blk), None

    if n > 1:
        # None carries are fine: their slots stay None through every iteration
        # (scan treats None as an empty pytree)
        carry = (o, m, l, k, v, kv_mask, segment_ids)
        carry, _ = lax.scan(step, carry, jnp.arange(1, n))
        o, _, l = carry[0], carry[1], carry[2]
    # rows with no visible key (all keys masked) have l == 0: the guard turns
    # their 0/0 into exact zeros, matching attention_reference's convention
    # (and is a no-op on the unmasked path, where l >= exp(0) per real key)
    out = o / jnp.maximum(l, 1e-30)
    return jnp.transpose(out, (0, 2, 1, 3)).astype(orig_dtype)  # [B, S/n, H, D]


def make_ring_attention(
    mesh: Mesh,
    *,
    causal: bool = False,
    masked: bool = False,
    segmented: bool = False,
    batch_axis: Optional[str] = BATCH_AXIS,
    sequence_axis: str = SEQUENCE_AXIS,
):
    """Jitted sequence-parallel attention over ``mesh``: takes GLOBAL [B, S, H, D]
    arrays (sharded batch over ``batch_axis``, sequence over ``sequence_axis``)
    and returns the global attention output with the same sharding.

    Extra per-token inputs (GLOBAL [B, S], sequence-sharded) are appended to
    the signature in declaration order:
      ``masked=True``    -> ``kv_mask`` (bool, True = real key; padding form)
      ``segmented=True`` -> ``segment_ids`` (int; packed-document isolation)
    e.g. both flags give ``fn(q, k, v, kv_mask, segment_ids)``."""
    spec = P(batch_axis, sequence_axis, None, None)
    tok_spec = P(batch_axis, sequence_axis)
    extra_specs = ([tok_spec] if masked else []) + ([tok_spec] if segmented else [])

    def fn(q, k, v, *extras):
        it = iter(extras)
        kv_mask = next(it) if masked else None
        segment_ids = next(it) if segmented else None
        return ring_attention(
            q,
            k,
            v,
            axis_name=sequence_axis,
            causal=causal,
            kv_mask=kv_mask,
            segment_ids=segment_ids,
        )

    return jax.jit(
        jax.shard_map(
            fn,
            mesh=mesh,
            in_specs=(spec, spec, spec, *extra_specs),
            out_specs=spec,
        )
    )
