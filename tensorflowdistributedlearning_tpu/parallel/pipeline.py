"""Pipeline parallelism: a GPipe-style microbatched runner over mesh shards.

The reference had no pipeline parallelism (SURVEY §2.3: data parallelism was its
only strategy), so — like the tensor and sequence axes — this is a beyond-parity
capability, built compiler-first: the schedule is a ``lax.scan`` whose per-tick
body applies THIS shard's stage and hands activations to the next shard over one
``ppermute`` ICI hop. Because the whole schedule is expressed as traced JAX ops,
reverse-mode autodiff differentiates straight through it — the backward pass
(reversed pipeline with transposed ppermutes) is derived by the compiler, not
hand-written.

Scope: homogeneous stages — every pipeline stage must share one computation
graph (same ``stage_fn``, same param shapes), the classic transformer-layer
regime; in this framework's model family it maps exactly onto Xception's middle
flow (8 identical 728-wide sum-skip units, models/xception.py) and onto stacks
of equal-width residual units. Heterogeneous stage support (different shapes per
stage) would need per-stage padding and is out of scope.

Schedule: plain GPipe fill/drain — ``M`` microbatches over ``K`` stages take
``M + K - 1`` ticks, bubble fraction ``(K-1)/(M+K-1)``; choose ``M >> K``.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import MODEL_AXIS


def pipeline_apply(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    my_stage_params: Any,
    x_microbatches: jax.Array,
    *,
    axis_name: str = MODEL_AXIS,
) -> jax.Array:
    """Run ``K`` pipeline stages over ``M`` microbatches inside ``shard_map``.

    ``my_stage_params``: THIS shard's stage parameters (shard the stacked
    [K, ...] param tree over ``axis_name`` in the enclosing shard_map's
    in_specs and squeeze the leading 1). ``x_microbatches``: [M, mb, ...],
    replicated across the axis (only stage 0 consumes it). Returns the
    pipeline output [M, mb, ...], replicated across the axis.

    Stage ``k`` processes microbatch ``m`` at tick ``t = m + k``; activations
    move to stage ``k+1`` via a neighbor ``ppermute`` each tick.

    Delegates to ``pipeline_apply_aux`` (the one copy of the fill/drain
    schedule) with an empty aux stream.
    """
    out, _ = pipeline_apply_aux(
        lambda p, x: (stage_fn(p, x), ()),
        my_stage_params,
        x_microbatches,
        axis_name=axis_name,
    )
    return out


def pipeline_apply_aux(
    stage_fn: Callable[[Any, jax.Array], tuple],
    my_stage_params: Any,
    x_microbatches: jax.Array,
    *,
    axis_name: str = MODEL_AXIS,
) -> tuple:
    """``pipeline_apply`` for stages that also EMIT per-tick auxiliary state:
    ``stage_fn(params, x) -> (y, aux)``. Returns ``(out, aux_mean)`` where
    ``aux_mean`` averages this stage's aux over its M REAL microbatch ticks —
    stage ``k`` processes real work at ticks ``k .. k+M-1``; fill/drain ticks
    (whose input is the zero padding or a neighbor's garbage) are excluded.

    Built for BatchNorm-bearing pipeline stages (Xception's middle flow): the
    aux is the per-microbatch updated running stats, and because flax's update
    is affine in the batch statistic (``new = m*old + (1-m)*mu_i``), the MEAN
    of per-microbatch updates equals ONE update with the microbatch-averaged
    statistic — the same single-update-per-step bookkeeping as the plain step.
    """
    k_stages = lax.axis_size(axis_name)
    idx = lax.axis_index(axis_name)
    m_micro = x_microbatches.shape[0]
    ticks = m_micro + k_stages - 1

    pad = jnp.zeros((k_stages - 1,) + x_microbatches.shape[1:], x_microbatches.dtype)
    inject = jnp.concatenate([x_microbatches, pad], axis=0)
    perm = [(i, i + 1) for i in range(k_stages - 1)]

    def tick(buf, x_t):
        inp = jnp.where(idx == 0, x_t, buf)
        y, aux = stage_fn(my_stage_params, inp)
        buf_next = lax.ppermute(y, axis_name, perm)
        return buf_next, (y, aux)

    zero = jnp.zeros_like(x_microbatches[0])
    if hasattr(lax, "pcast"):
        buf0 = lax.pcast(zero, axis_name, to="varying")
    else:  # pragma: no cover - older jax
        buf0 = lax.pvary(zero, (axis_name,))
    _, (ys, auxs) = lax.scan(tick, buf0, inject[:ticks])

    tail = lax.dynamic_slice_in_dim(ys, k_stages - 1, m_micro, axis=0)
    out = lax.psum(
        jnp.where(idx == k_stages - 1, tail, jnp.zeros_like(tail)), axis_name
    )
    # this stage's real ticks: a device-varying dynamic slice (each shard
    # starts at its own stage index), then the microbatch mean
    aux_mean = jax.tree.map(
        lambda a: jnp.mean(
            lax.dynamic_slice_in_dim(a, idx, m_micro, axis=0), axis=0
        ),
        auxs,
    )
    return out, aux_mean


def stack_stage_params(param_trees) -> Any:
    """Stack K per-stage param pytrees on a new leading axis (shard it over the
    model axis with ``P(MODEL_AXIS, ...)`` in_specs)."""
    return jax.tree.map(lambda *leaves: jnp.stack(leaves), *param_trees)


def stage_in_spec() -> P:
    """in_spec for stacked stage params: leading (stage) axis over the model
    mesh axis."""
    return P(MODEL_AXIS)


def make_pipeline_fn(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    mesh: Mesh,
    *,
    donate: bool = False,
) -> Callable:
    """Jitted end-to-end pipeline forward: ``f(stacked_params, x_microbatches)``.

    ``stacked_params``: [K, ...] per-stage params (K = the mesh's model-axis
    size); ``x_microbatches``: [M, mb, ...]. Output: [M, mb, ...]. Used
    standalone or as a building block inside a larger shard_mapped step.
    """

    def run(stacked_params, x_microbatches):
        k = mesh.shape[MODEL_AXIS]
        n_stages = jax.tree_util.tree_leaves(stacked_params)[0].shape[0]
        if n_stages != k:
            # a proper multiple would SILENTLY run only every (n/k)-th stage
            # after the per-shard squeeze below — reject anything but exact
            raise ValueError(
                f"{n_stages} pipeline stages on a model axis of size {k}; "
                "the stage count must equal the mesh's model-axis size"
            )

        def body(params_shard, x):
            my_params = jax.tree.map(lambda p: p[0], params_shard)
            return pipeline_apply(stage_fn, my_params, x)

        return jax.shard_map(
            body,
            mesh=mesh,
            in_specs=(stage_in_spec(), P()),
            out_specs=P(),
        )(stacked_params, x_microbatches)

    return jax.jit(run, donate_argnums=(0,) if donate else ())
