"""Multi-host SPMD support: process initialization and per-host data feeding.

The reference was strictly single-process/single-host — its strategy was built from
local GPUs only, with no cluster spec (reference: utils.py:6-8, model.py:114-121;
SURVEY §2.3 "Cross-host DP: NO"). The TPU-native build scales past that by design:
``jax.distributed`` brings every host's chips into one ``jax.devices()`` view, the
mesh spans them all, and XLA routes collectives over ICI within a slice and DCN
across slices. The only host-side code multi-host adds is here:

- ``initialize``: one call per process before any jax op (TPU pods auto-discover;
  explicit coordinator args supported for CPU/GPU clusters);
- ``global_shard_batch``: each process contributes ONLY its local shard of every
  global batch (``jax.make_array_from_process_local_data``), the per-host
  generalization of the reference's per-tower ``batch/n_gpus`` input_fn contract
  (reference: model.py:156-159, 298-299) — pair it with ``data.pipeline.host_shard``
  for which examples this process loads.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process to the jax.distributed cluster (no-op if already
    initialized). On TPU pods all arguments auto-discover from the TPU metadata;
    pass them explicitly for multi-host CPU/GPU runs.

    MUST run before any jax call that initializes the XLA backend (even
    ``jax.devices()``/``jax.process_count()``) — jax refuses to form a cluster
    afterwards. With explicit coordinator arguments a failure to join RAISES
    (silently degrading to per-host single-process training would be wrong
    training at pod scale); with auto-discovery a quiet single-process fallback
    is the correct behavior for laptop/CI runs.
    """
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    # already-initialized check WITHOUT touching the XLA backend
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None and is_initialized():
        return
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if explicit:
            raise
        # auto-discovery found no cluster: single-process run (the reference's
        # only mode)


def process_info() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def global_shard_batch(local_tree: Any, mesh: Mesh) -> Any:
    """Assemble a globally-sharded batch from THIS PROCESS's local examples.

    ``local_tree``: pytree of host arrays holding only this process's
    ``global_batch / process_count`` examples (in process order — use
    ``data.pipeline.host_shard`` to pick them). Returns jax Arrays sharded on the
    ``batch`` mesh axis spanning all hosts. Single-process, this is exactly
    ``mesh_lib.shard_batch``.
    """

    def place(x):
        x = np.asarray(x)
        spec = P(BATCH_AXIS, *([None] * (x.ndim - 1)))
        sharding = NamedSharding(mesh, spec)
        return jax.make_array_from_process_local_data(sharding, x)

    return jax.tree.map(place, local_tree)
