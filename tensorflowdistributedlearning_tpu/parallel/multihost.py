"""Multi-host SPMD support: process initialization and per-host data feeding.

The reference was strictly single-process/single-host — its strategy was built from
local GPUs only, with no cluster spec (reference: utils.py:6-8, model.py:114-121;
SURVEY §2.3 "Cross-host DP: NO"). The TPU-native build scales past that by design:
``jax.distributed`` brings every host's chips into one ``jax.devices()`` view, the
mesh spans them all, and XLA routes collectives over ICI within a slice and DCN
across slices. The only host-side code multi-host adds is here:

- ``initialize``: one call per process before any jax op (TPU pods auto-discover;
  explicit coordinator args supported for CPU/GPU clusters);
- ``global_shard_batch``: each process contributes ONLY its local shard of every
  global batch (``jax.make_array_from_process_local_data``), the per-host
  generalization of the reference's per-tower ``batch/n_gpus`` input_fn contract
  (reference: model.py:156-159, 298-299) — pair it with ``data.pipeline.host_shard``
  for which examples this process loads.
"""

from __future__ import annotations

import contextlib
from typing import Any, Dict, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import BATCH_AXIS

# Telemetry instance whose `barrier_wait` span times the multihost_utils sync
# points below (registered by the trainers for the run's lifetime). Module
# state rather than a parameter because the sync points are called from deep
# inside data/eval plumbing that has no telemetry handle — and there is at
# most one live training run per process.
_probe_telemetry = None


def instrument(telemetry) -> None:
    """Time every cross-process sync point in this module as ``telemetry``'s
    ``barrier_wait`` span. Per-window barrier-wait lands in the ledger's
    ``step_window`` events, and the fleet report (obs/fleet.py) reads the
    per-host asymmetry as straggler attribution: the slow host arrives last
    and waits ~0; everyone else's wait IS the skew."""
    global _probe_telemetry
    _probe_telemetry = telemetry


def uninstrument(telemetry=None) -> None:
    """Detach the barrier probe (pass the instance to only detach if it is
    still the registered one — a later run's probe must not be clobbered by
    an earlier run's teardown)."""
    global _probe_telemetry
    if telemetry is None or _probe_telemetry is telemetry:
        _probe_telemetry = None


@contextlib.contextmanager
def barrier_probe():
    """Span context around one multihost_utils sync point; no-op when no
    telemetry is instrumented (the single-process common case never even gets
    here — the sync points below all early-return at process_count 1)."""
    tel = _probe_telemetry
    if tel is None or not getattr(tel, "enabled", False):
        yield
        return
    from tensorflowdistributedlearning_tpu.obs.telemetry import SPAN_BARRIER

    with tel.span(SPAN_BARRIER):
        yield


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> None:
    """Join this process to the jax.distributed cluster (no-op if already
    initialized). On TPU pods all arguments auto-discover from the TPU metadata;
    pass them explicitly for multi-host CPU/GPU runs.

    MUST run before any jax call that initializes the XLA backend (even
    ``jax.devices()``/``jax.process_count()``) — jax refuses to form a cluster
    afterwards. With explicit coordinator arguments a failure to join RAISES
    (silently degrading to per-host single-process training would be wrong
    training at pod scale); with auto-discovery a quiet single-process fallback
    is the correct behavior for laptop/CI runs.
    """
    explicit = (
        coordinator_address is not None
        or num_processes is not None
        or process_id is not None
    )
    # already-initialized check WITHOUT touching the XLA backend
    is_initialized = getattr(jax.distributed, "is_initialized", None)
    if is_initialized is not None and is_initialized():
        return
    if explicit and not _platform_known_non_cpu():
        # explicit multi-process on the CPU backend (elastic drills, the gloo
        # integration tests, laptop pods): cross-process collectives need the
        # gloo implementation selected BEFORE the backend initializes — the
        # default CPU collectives are single-process only. Applied whenever
        # the configured platform is cpu OR unset (a CPU-only machine with no
        # JAX_PLATFORMS still lands on the cpu backend); the knob only
        # affects the CPU backend, so it is inert on TPU/GPU pods.
        # Best-effort: a jax build without it surfaces its real error below.
        try:
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        except Exception:  # noqa: BLE001 — older jax: no such config
            pass
    try:
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id,
        )
    except (ValueError, RuntimeError):
        if explicit:
            raise
        # auto-discovery found no cluster: single-process run (the reference's
        # only mode)


def _platform_known_non_cpu() -> bool:
    """Whether this process is EXPLICITLY configured for a non-CPU backend,
    checked WITHOUT initializing one (the env var / jax_platforms config both
    precede backend selection). Unset means the platform is decided by what
    the machine has — which on a CPU-only host is the cpu backend."""
    import os

    platforms = os.environ.get("JAX_PLATFORMS") or ""
    try:
        platforms = jax.config.jax_platforms or platforms
    except AttributeError:
        pass
    platforms = str(platforms).lower()
    return bool(platforms) and "cpu" not in platforms


def process_info() -> Dict[str, int]:
    return {
        "process_index": jax.process_index(),
        "process_count": jax.process_count(),
        "local_device_count": jax.local_device_count(),
        "global_device_count": jax.device_count(),
    }


def per_process_batch_size(global_batch: int) -> int:
    """This process's share of every global batch (``global_batch / process_count``)
    — the per-host generalization of the reference's per-tower ``batch/n_gpus``
    split (reference: model.py:156-159)."""
    p = jax.process_count()
    if global_batch % p != 0:
        raise ValueError(
            f"Global batch size {global_batch} must be divisible by the process "
            f"count {p}"
        )
    return global_batch // p


def eval_num_batches(global_n: int, per_process_batch: int) -> int:
    """Number of eval steps EVERY process must run for a ``global_n``-example eval
    set split round-robin across processes (``data.pipeline.host_shard``).

    All processes must execute the same number of collective-bearing jitted eval
    steps or they deadlock; the largest host shard (``ceil(global_n / P)``) sets
    the count, and smaller shards pad with valid=0 batches."""
    p = jax.process_count()
    max_shard = -(-global_n // p)
    return max(1, -(-max_shard // per_process_batch))


def all_processes_max_batches(local_n: int, per_process_batch: int) -> int:
    """Equalized eval step count when each process holds its OWN record shards
    (sizes unknown globally): every process contributes ceil(local_n / batch)
    and all run the cross-process maximum, padding with valid=0 batches
    (``data.records.ClassificationRecords.batches(pad_to_batches=...)``)."""
    mine = max(1, -(-local_n // per_process_batch)) if local_n else 1
    if jax.process_count() == 1:
        return mine
    from jax.experimental import multihost_utils

    with barrier_probe():
        counts = multihost_utils.process_allgather(np.asarray(mine, np.int32))
    return int(np.max(counts))


def process_local_rows(global_batch: int, mesh: Mesh) -> np.ndarray:
    """Row indices of a batch-axis-sharded global batch owned by THIS process.

    Computed exactly from the sharding's device→index map, so it is correct for
    any device ordering. Single-process this is ``arange(global_batch)``. Use it
    to slice a batch every host holds in full (e.g. a test set) down to the local
    chunk ``global_shard_batch`` expects, and to know which output rows
    ``fetch``'s allgather attributes to which input rows."""
    sharding = NamedSharding(mesh, P(BATCH_AXIS))
    index_map = sharding.devices_indices_map((global_batch,))
    me = jax.process_index()
    rows = [
        np.arange(
            idx[0].start if idx[0].start is not None else 0,
            idx[0].stop if idx[0].stop is not None else global_batch,
        )
        for d, idx in index_map.items()
        if d.process_index == me
    ]
    return np.unique(np.concatenate(rows))


def _leaf_spec(key: Optional[str], ndim: int, spatial: bool) -> P:
    """Batch-axis spec; under spatial (sequence) parallelism ``images`` are
    additionally H-sharded over the sequence axis."""
    from tensorflowdistributedlearning_tpu.parallel.mesh import SEQUENCE_AXIS

    if spatial and key == "images":
        return P(BATCH_AXIS, SEQUENCE_AXIS, *([None] * (ndim - 2)))
    return P(BATCH_AXIS, *([None] * (ndim - 1)))


def shard_replicated_batch(tree: Any, mesh: Mesh, *, spatial: bool = False) -> Any:
    """Shard a batch dict that EVERY process holds identically in full (e.g. a
    test set built on all hosts) onto the ``batch`` (and, for images under
    ``spatial``, ``sequence``) mesh axes. Single-process this is a plain
    ``device_put``; multi-process each host contributes only the rows its devices
    own."""

    def place(key, x):
        x = np.asarray(x)
        spec = _leaf_spec(key, x.ndim, spatial)
        sharding = NamedSharding(mesh, spec)
        if jax.process_count() == 1:
            return jax.device_put(x, sharding)
        rows = process_local_rows(x.shape[0], mesh)
        return jax.make_array_from_process_local_data(sharding, x[rows])

    return {k: place(k, v) for k, v in tree.items()}


def fetch(x: Any) -> np.ndarray:
    """Device→host fetch of a batch-sharded global array that works under
    multi-host (cross-process allgather so every host sees the full array);
    single-process it is a plain ``device_get``."""
    if jax.process_count() == 1:
        return np.asarray(jax.device_get(x))
    from jax.experimental import multihost_utils

    with barrier_probe():
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))


def global_shard_batch(local_tree: Any, mesh: Mesh, *, spatial: bool = False) -> Any:
    """Assemble a globally-sharded batch from THIS PROCESS's local examples.

    ``local_tree``: dict of host arrays holding only this process's
    ``global_batch / process_count`` examples (in process order — use
    ``data.pipeline.host_shard`` to pick them). Returns jax Arrays sharded on the
    ``batch`` mesh axis spanning all hosts. Single-process, this is exactly
    ``mesh_lib.shard_batch``. ``spatial`` additionally H-shards images over the
    sequence axis (multi-process spatial placement assumes each process's
    addressable devices cover whole sequence groups, as on TPU pod slices).
    """

    def place(key, x):
        x = np.asarray(x)
        sharding = NamedSharding(mesh, _leaf_spec(key, x.ndim, spatial))
        return jax.make_array_from_process_local_data(sharding, x)

    return {k: place(k, v) for k, v in local_tree.items()}
