"""SPMD device-mesh layer: the TPU-native replacement for the reference's
MirroredStrategy/NCCL distribution config (reference: model.py:114-121, utils.py:6-8)."""

from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
    SEQUENCE_AXIS,
    available_devices,
    batch_sharding,
    local_batch_size,
    make_mesh,
    replicate,
    replicated_sharding,
    shard_batch,
    shard_batch_stacked,
)
from tensorflowdistributedlearning_tpu.parallel.planner import (
    Layout,
    ParallelPlan,
    PlanError,
    Topology,
    plan,
    plan_for_config,
    render_plan_table,
    validate_config,
)
from tensorflowdistributedlearning_tpu.parallel.collectives import (
    pmean_tree,
    psum_tree,
    vma_of,
)
from tensorflowdistributedlearning_tpu.parallel.spatial import (
    halo_exchange,
    reduce_scatter,
    ring_all_gather,
    spatial_conv2d,
)
from tensorflowdistributedlearning_tpu.parallel.expert import (
    moe_apply,
    top1_dispatch,
)
from tensorflowdistributedlearning_tpu.parallel.ring_attention import (
    attention_reference,
    make_ring_attention,
    ring_attention,
)
from tensorflowdistributedlearning_tpu.parallel.pipeline import (
    make_pipeline_fn,
    pipeline_apply,
    stack_stage_params,
)
from tensorflowdistributedlearning_tpu.parallel.tensor import (
    make_train_step_gspmd,
    shard_state_tensor_parallel,
    shard_state_weight_update,
    tensor_parallel_specs,
)
from tensorflowdistributedlearning_tpu.parallel.zero import (
    apply_gradients_sharded,
    weight_update_spec,
    weight_update_specs,
)
from tensorflowdistributedlearning_tpu.parallel.multihost import (
    global_shard_batch,
    initialize as initialize_multihost,
    process_info,
)

__all__ = [
    "halo_exchange",
    "reduce_scatter",
    "ring_all_gather",
    "spatial_conv2d",
    "attention_reference",
    "make_ring_attention",
    "ring_attention",
    "global_shard_batch",
    "make_pipeline_fn",
    "moe_apply",
    "top1_dispatch",
    "make_train_step_gspmd",
    "pipeline_apply",
    "stack_stage_params",
    "shard_state_tensor_parallel",
    "shard_state_weight_update",
    "tensor_parallel_specs",
    "apply_gradients_sharded",
    "weight_update_spec",
    "weight_update_specs",
    "initialize_multihost",
    "process_info",
    "vma_of",
    "BATCH_AXIS",
    "MODEL_AXIS",
    "SEQUENCE_AXIS",
    "available_devices",
    "batch_sharding",
    "local_batch_size",
    "make_mesh",
    "replicate",
    "replicated_sharding",
    "shard_batch",
    "shard_batch_stacked",
    "pmean_tree",
    "psum_tree",
]
