"""Pytree collectives used inside `shard_map`-ped steps.

The reference never wrote a collective — gradient all-reduce lived inside
MirroredStrategy's cross-device ops (NCCL on GPU; reference: model.py:115-116). Here the
same reduction is an explicit `lax.psum`/`lax.pmean` over the named mesh axis, which XLA
lowers to ICI all-reduces within a slice and DCN collectives across slices.
"""

from __future__ import annotations

from typing import Any

import jax
from jax import lax

from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib


def vma_of(x: Any) -> frozenset:
    """The varying-manual-axes set of a traced value inside ``shard_map`` —
    empty when the value is replicated or outside shard_map. Single place to
    follow jax's aval API (``jax.typeof``; older versions only had
    ``jax.core.get_aval``)."""
    typeof = getattr(jax, "typeof", None)
    aval = typeof(x) if typeof is not None else jax.core.get_aval(x)
    return getattr(aval, "vma", None) or frozenset()


def psum_tree(tree: Any, axis_name: str = mesh_lib.BATCH_AXIS) -> Any:
    """Sum every leaf across the given mesh axis (gradient/metric reduction)."""
    return jax.tree.map(lambda x: lax.psum(x, axis_name), tree)


def pmean_tree(tree: Any, axis_name: str = mesh_lib.BATCH_AXIS) -> Any:
    """Mean every leaf across the given mesh axis (the MirroredStrategy gradient
    aggregation semantics: per-tower grads averaged into one update)."""
    return jax.tree.map(lambda x: lax.pmean(x, axis_name), tree)
