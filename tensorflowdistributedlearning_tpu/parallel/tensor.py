"""Tensor (model) parallelism via GSPMD sharding annotations.

The third mesh axis. The reference had no model parallelism at all (SURVEY §2.3:
its only strategy was MirroredStrategy data parallelism), so this is a
beyond-parity capability — and it is built the idiomatic TPU way: rather than
rewriting layers with explicit collectives (the shard_map/halo route the
sequence axis uses, where exactness demands hand phase control), tensor
parallelism annotates PARAMETER shardings over the ``model`` axis and lets
XLA's SPMD partitioner place the matching all-reduces/all-gathers on ICI — the
"pick a mesh, annotate shardings, let XLA insert collectives" recipe.

What gets sharded (the channel dimension is the TP-natural axis of a CNN):

- conv kernels  [kh, kw, C_in, C_out]  → sharded on C_out;
- conv biases / BN scale/offset/stats [C_out] → sharded likewise (they are
  per-output-channel vectors);
- dense kernels [D_in, D_out] → sharded on D_out (the classifier head);
- everything smaller (scalars, the 1-channel segmentation head) → replicated.

Optimizer state (Adam moments) shards identically to its parameter — pytree
structure mirrors params, so the same spec tree applies. Per-chip parameter and
optimizer memory drops by ~the model-axis degree, the reason TP exists.

Gradient semantics need no hand-written psum: the train step is plain jit
(not shard_map), so the loss-mean over the global batch IS the global mean and
GSPMD derives every reduction. BatchNorm statistics are computed over the full
global batch under GSPMD (jit sees the global tensor) — a deliberate semantic
difference from the shard_map data-parallel step's per-tower BN, noted in
``make_train_step_gspmd``'s docstring.
"""

from __future__ import annotations

import functools

from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tensorflowdistributedlearning_tpu.parallel.mesh import (
    BATCH_AXIS,
    MODEL_AXIS,
)


def _spec_for_leaf(leaf, axes: Tuple[Tuple[str, int], ...]) -> P:
    """Shard a leaf's trailing (output-channel/feature) dimension over the given
    (axis_name, degree) mesh axes — the single eligibility rule every sharding
    path here uses. Axes with degree 1 are dropped; if the trailing dim does not
    divide by the combined degree, axes are dropped from the right until it
    does (so TP+ZeRO degrades to TP-only, then to replicated)."""
    shape = jnp.shape(leaf)
    usable = [(a, d) for a, d in axes if d > 1]
    while usable:
        total = 1
        for _, d in usable:
            total *= d
        if shape and shape[-1] % total == 0:
            spec: list = [None] * len(shape)
            names = tuple(a for a, _ in usable)
            spec[-1] = names if len(names) > 1 else names[0]
            return P(*spec)
        usable = usable[:-1]
    return P()


def tensor_parallel_specs(tree: Any, mesh: Mesh) -> Any:
    """PartitionSpec pytree sharding every eligible leaf's trailing (channel)
    dimension over the ``model`` mesh axis."""
    axes = ((MODEL_AXIS, mesh.shape[MODEL_AXIS]),)
    return jax.tree.map(lambda leaf: _spec_for_leaf(leaf, axes), tree)


def tensor_parallel_spec_for_shape(shape, tp: int):
    """The tensor-parallel eligibility rule queryable by plain degree — no
    mesh needed. The parallelism planner predicts candidate layouts' exact
    per-chip param bytes through this, so the prediction and the placement
    (``tensor_parallel_specs`` above, which shares ``_spec_for_leaf``) can
    never disagree."""
    return _spec_for_leaf(
        jax.ShapeDtypeStruct(tuple(shape), jnp.float32), ((MODEL_AXIS, tp),)
    )


def _place_full_value(x, sharding: NamedSharding):
    """Place a host value (identical on every process — e.g. a seeded init)
    under ``sharding``. Single-process this is a plain device_put; multi-process
    it assembles the global array from each process's addressable slices via
    ``make_array_from_callback`` (device_put cannot target non-addressable
    devices)."""
    if jax.process_count() == 1:
        return jax.device_put(x, sharding)
    arr = np.asarray(x)
    return jax.make_array_from_callback(arr.shape, sharding, lambda idx: arr[idx])


def shard_state_tensor_parallel(state, mesh: Mesh):
    """Place a TrainState with params/batch_stats/opt_state sharded over the
    model axis (and replicated over batch/sequence); ``step`` stays replicated.

    The optimizer state mirrors the param tree structure (Adam's mu/nu), so the
    param specs apply leaf-for-leaf wherever shapes match. Works multi-host:
    every process holds the same seeded init, and each contributes its
    addressable shards."""

    def place_tree(tree):
        specs = tensor_parallel_specs(tree, mesh)
        return jax.tree.map(
            lambda x, s: _place_full_value(x, NamedSharding(mesh, s)),
            tree,
            specs,
        )

    # one sharding rule for everything: optimizer leaves either mirror a param
    # (Adam mu/nu — shard like it) or are scalars/counters (replicated by the
    # per-leaf rule)
    return state.replace(
        step=_place_full_value(state.step, NamedSharding(mesh, P())),
        params=place_tree(state.params),
        batch_stats=place_tree(state.batch_stats),
        opt_state=place_tree(state.opt_state),
    )


def shard_state_weight_update(state, mesh: Mesh):
    """Cross-replica weight-update (ZeRO-1 optimizer-state) sharding for the
    GSPMD path: the optimizer state additionally shards over the ``batch``
    axis — each data-parallel replica stores and updates only 1/dp of it —
    the technique of "Automatic Cross-Replica Sharding of Weight Update in
    Data-Parallel Training" (arXiv:2004.13336), which XLA implements natively
    on TPU. Delegates to ``parallel/zero.py`` (the canonical spec machinery
    shared with the shard_map trainers): params and batch stats keep their
    model-axis sharding, optimizer leaves shard along the batch axis on their
    largest divisible free dimension; numerics are identical to the
    replicated update. Pair with
    ``make_train_step_gspmd(weight_update_sharding=True)`` so the update
    itself runs under the matching constraints."""
    from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib

    return zero_lib.shard_state_weight_update(state, mesh, tensor_parallel=True)


def make_train_step_gspmd(
    mesh: Mesh,
    task,
    *,
    donate: bool = True,
    weight_update_sharding: bool = False,
) -> Callable:
    """jit (auto-SPMD) train step for meshes with a ``model`` axis degree > 1.

    Memoized on its arguments (like train/step.py's builders): repeated calls —
    across evals, trainer instances, tests — return the same jitted callable so
    each (mesh, task, model, shapes) combination compiles once per process.

    Differences from the shard_map step (train/step.py:make_train_step):

    - parallelism is derived by XLA's SPMD partitioner from the input shardings
      (batch sharded over ``batch``, params over ``model``) instead of being
      written as explicit collectives;
    - BatchNorm statistics are computed over the GLOBAL batch (jit sees global
      tensors), not per data-parallel shard — mathematically the synced-BN
      variant; use the shard_map step when exact per-tower BN parity with the
      reference is required.

    ``weight_update_sharding=True`` runs the optimizer update under ZeRO-1
    sharding constraints (``parallel/zero.py``): pass state placed with
    ``shard_state_weight_update`` so the optimizer leaves arrive (and leave,
    and are checkpointed) sharded over the data axis.
    """
    return _make_train_step_gspmd_cached(mesh, task, donate, weight_update_sharding)


@functools.lru_cache(maxsize=None)
def _make_train_step_gspmd_cached(
    mesh: Mesh, task, donate: bool, weight_update_sharding: bool = False
) -> Callable:
    def step(state, batch: Dict[str, jax.Array]):
        def loss_fn(params):
            outputs, mutated = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                batch["images"],
                train=True,
                mutable=["batch_stats", "aux_loss"],
            )
            loss = task.loss(outputs, batch)
            # model-sown auxiliary losses (MoE load balancing) — empty
            # collection for every non-MoE model
            for aux in jax.tree_util.tree_leaves(mutated.get("aux_loss", {})):
                loss = loss + aux
            return loss, (outputs, mutated.get("batch_stats", state.batch_stats))

        (loss, (outputs, new_stats)), grads = jax.value_and_grad(
            loss_fn, has_aux=True
        )(state.params)
        if weight_update_sharding:
            from tensorflowdistributedlearning_tpu.parallel import zero as zero_lib

            new_state = zero_lib.apply_gradients_sharded(
                state, grads, new_stats, mesh, tensor_parallel=True
            )
        else:
            new_state = state.apply_gradients(grads, new_stats)

        from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib

        scores = task.metric_scores(outputs, batch)
        metrics = {
            name: metrics_lib.Mean.empty().update(s) for name, s in scores.items()
        }
        metrics["loss"] = metrics_lib.Mean.empty().update(loss[None])
        return new_state, metrics

    jitted = jax.jit(step, donate_argnums=(0,) if donate else ())

    def run(state, batch: Dict[str, jax.Array]):
        # bind the step to its mesh: fail fast on batch/axis mismatches instead
        # of letting GSPMD quietly replicate an indivisible batch
        from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib

        mesh_lib.local_batch_size(int(batch["images"].shape[0]), mesh)
        return jitted(state, batch)

    return run


def make_eval_step_gspmd(mesh: Mesh, task) -> Callable:
    """jit (auto-SPMD) eval step for tensor-parallel state: inference forward,
    per-example loss so an optional ``valid`` mask weights correctly, Mean
    metric pytrees — the GSPMD twin of train/step.py:make_eval_step. Memoized —
    see ``make_train_step_gspmd``."""
    return _make_eval_step_gspmd_cached(mesh, task)


@functools.lru_cache(maxsize=None)
def _make_eval_step_gspmd_cached(mesh: Mesh, task) -> Callable:
    def step(state, batch: Dict[str, jax.Array]):
        outputs = state.apply_fn(
            {"params": state.params, "batch_stats": state.batch_stats},
            batch["images"],
            train=False,
        )
        loss = task.loss_per_example(outputs, batch)
        weights = batch.get("valid")

        from tensorflowdistributedlearning_tpu.ops import metrics as metrics_lib

        scores = task.metric_scores(outputs, batch)
        metrics = {
            name: metrics_lib.Mean.empty().update(s, weights)
            for name, s in scores.items()
        }
        metrics["loss"] = metrics_lib.Mean.empty().update(loss, weights)
        return metrics

    jitted = jax.jit(step)

    def run(state, batch: Dict[str, jax.Array]):
        from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib

        mesh_lib.local_batch_size(int(batch["images"].shape[0]), mesh)
        return jitted(state, batch)

    return run


def place_batch_gspmd(batch: Dict[str, np.ndarray], mesh: Mesh) -> Dict:
    """Shard a host batch over the batch axis for the gspmd step (model axis
    replicated for activations — GSPMD re-shards internally where profitable)."""

    def put(x):
        x = np.asarray(x)
        return jax.device_put(
            x, NamedSharding(mesh, P(BATCH_AXIS, *([None] * (x.ndim - 1))))
        )

    return {k: put(v) for k, v in batch.items()}
