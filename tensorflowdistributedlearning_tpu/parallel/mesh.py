"""Device mesh construction and sharding helpers.

This module is the TPU-native equivalent of the reference's entire "distribution" layer:
GPU discovery (reference: utils.py:6-8), MirroredStrategy construction over the first
``n_gpus`` devices (reference: model.py:115-116), and the per-tower batch-splitting math
(reference: model.py:156-159). Here:

- devices come from ``jax.devices()`` (all hosts' devices under multi-host SPMD, so
  cross-host data parallelism — absent from the reference, which was single-host only —
  falls out for free);
- replication + gradient all-reduce are expressed as a named ``Mesh`` axis over which
  ``shard_map``/``pjit`` emit XLA collectives on ICI/DCN, instead of NCCL calls;
- the mesh reserves named axes for model (tensor), and sequence (context) parallelism so
  future parallelism strategies compose without API changes.
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

# Canonical mesh-axis names. The reference only implemented data parallelism
# (reference: model.py:115-116); `model` and `sequence` are reserved for tensor and
# sequence/context parallelism so the mesh API is forward-compatible.
BATCH_AXIS = "batch"
MODEL_AXIS = "model"
SEQUENCE_AXIS = "sequence"


def available_devices(platform: Optional[str] = None) -> list:
    """Enumerate accelerator devices (reference: utils.py:6-8 enumerated GPUs via
    ``device_lib.list_local_devices``)."""
    if platform is None:
        return list(jax.devices())
    return list(jax.devices(platform))


def make_mesh(
    n_devices: Optional[int] = None,
    *,
    model_parallel: int = 1,
    sequence_parallel: int = 1,
    devices: Optional[Sequence[Any]] = None,
) -> Mesh:
    """Build a (batch, model, sequence) mesh.

    ``n_devices=None`` uses every visible device (the reference defaulted to the first
    ``n_gpus`` local GPUs, reference: model.py:114-116). The data-parallel degree is
    inferred as ``n_devices // (model_parallel * sequence_parallel)``.
    """
    if devices is None:
        devices = available_devices()
    if n_devices is not None:
        if n_devices > len(devices):
            raise ValueError(
                f"Requested {n_devices} devices but only {len(devices)} are visible"
            )
        devices = devices[:n_devices]
    n = len(devices)
    denom = model_parallel * sequence_parallel
    if n % denom != 0:
        raise ValueError(
            f"{n} devices not divisible by model_parallel*sequence_parallel={denom}"
        )
    # Multi-host: every data-parallel (batch-axis) shard must live within ONE
    # process — per-process data feeding (host_shard + global_shard_batch)
    # assumes each process's examples land on its own devices. A batch shard
    # spanning processes would silently assemble inconsistent data.
    if jax.process_count() > 1 and jax.local_device_count() % denom != 0:
        raise ValueError(
            f"model_parallel*sequence_parallel={denom} does not divide the "
            f"{jax.local_device_count()} devices local to each process; a "
            "data-parallel shard would span processes and per-process batch "
            "feeding would assemble inconsistent data. Lower the degree or "
            "use more chips per host."
        )
    dp = n // denom
    dev_array = np.asarray(devices).reshape(dp, model_parallel, sequence_parallel)
    return Mesh(dev_array, (BATCH_AXIS, MODEL_AXIS, SEQUENCE_AXIS))


def batch_sharding(mesh: Mesh, ndim: int = 1) -> NamedSharding:
    """Sharding that splits axis 0 over the batch mesh axis, replicating the rest."""
    spec = P(BATCH_AXIS, *([None] * (ndim - 1)))
    return NamedSharding(mesh, spec)


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding that fully replicates a value (how the reference's MirroredStrategy kept
    per-tower copies of variables in sync)."""
    return NamedSharding(mesh, P())


def shard_batch(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree of host arrays on the mesh, sharding axis 0 over ``batch``.

    TPU-native replacement for the reference's per-tower ``input_fn`` contract where each
    tower independently pulled ``batch/n_gpus`` examples (reference: model.py:156-159,
    298-299).
    """

    def _put(x):
        x = np.asarray(x)
        return jax.device_put(x, batch_sharding(mesh, x.ndim))

    return jax.tree.map(_put, tree)


def shard_batch_stacked(tree: Any, mesh: Mesh) -> Any:
    """Place K batches stacked on a leading axis: axis 0 is the scan (step)
    axis — replicated — and axis 1 is the example axis, sharded over
    ``batch``. This is the input contract of ``train.step.make_multi_train_step``
    (the device-side K-step loop); each leaf is ``[K, B, ...]`` where the same
    leaf fed per-step would be ``[B, ...]``."""

    def _put(x):
        x = np.asarray(x)
        if x.ndim < 2:
            raise ValueError(
                "shard_batch_stacked needs [K, B, ...] leaves (a scan axis "
                f"plus the example axis); got shape {x.shape}"
            )
        spec = P(None, BATCH_AXIS, *([None] * (x.ndim - 2)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return jax.tree.map(_put, tree)


def shard_batch_spatial(tree: Any, mesh: Mesh) -> Any:
    """Place a batch for sequence-parallel training: ``images`` sharded (batch,
    sequence) — axis 0 over data-parallel shards, axis 1 (H) over the sequence
    axis — and every other leaf (labels, valid) sharded on batch only. The H
    extent must divide the sequence-axis size."""

    def _put(key, x):
        x = np.asarray(x)
        if key == "images":
            if x.shape[1] % mesh.shape[SEQUENCE_AXIS] != 0:
                raise ValueError(
                    f"Spatial extent {x.shape[1]} must be divisible by the "
                    f"sequence-parallel degree {mesh.shape[SEQUENCE_AXIS]}"
                )
            spec = P(BATCH_AXIS, SEQUENCE_AXIS, *([None] * (x.ndim - 2)))
        else:
            spec = P(BATCH_AXIS, *([None] * (x.ndim - 1)))
        return jax.device_put(x, NamedSharding(mesh, spec))

    return {k: _put(k, v) for k, v in tree.items()}


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Place a pytree on the mesh fully replicated (params/optimizer state)."""
    sharding = replicated_sharding(mesh)
    return jax.tree.map(lambda x: jax.device_put(x, sharding), tree)


def local_batch_size(global_batch: int, mesh: Mesh) -> int:
    """Per-shard batch size; validates divisibility exactly as the reference did for its
    per-tower split (reference: model.py:156-159)."""
    n = mesh.shape[BATCH_AXIS]
    if global_batch % n != 0:
        raise ValueError(
            f"Batch size {global_batch} must be divisible by the data-parallel degree {n}"
        )
    return global_batch // n


def data_parallel_degree(mesh: Mesh) -> int:
    return mesh.shape[BATCH_AXIS]


def largest_divisible_dim(
    shape: Sequence[int], degree: int, *, taken: Optional[set] = None
) -> Optional[int]:
    """Index of the largest dimension of ``shape`` divisible by ``degree``,
    skipping indices in ``taken`` (dimensions another mesh axis already
    shards); None when nothing divides — the shared eligibility rule of the
    ZeRO-1 weight-update specs (parallel/zero.py). Picking the LARGEST
    divisible dimension (not a fixed one) keeps the replicated tail small:
    a conv kernel [3, 3, C_in, C_out] shards its widest channel dim, a bias
    [C] shards outright, and only scalars/tiny vectors stay whole."""
    taken = taken or set()
    best: Optional[int] = None
    for i, d in enumerate(shape):
        if i in taken or d % degree != 0:
            continue
        if best is None or d > shape[best]:
            best = i
    return best


def check_accum_divisibility(
    global_batch: int, mesh: Mesh, grad_accum_steps: int
) -> int:
    """Fail fast (before any compile) when the per-shard batch cannot split
    into ``grad_accum_steps`` equal microbatches; returns the per-shard batch.
    Shared by both trainers so the contract and message cannot drift."""
    local_bs = local_batch_size(global_batch, mesh)
    if local_bs % grad_accum_steps:
        raise ValueError(
            f"per-shard batch {local_bs} (global {global_batch} over "
            f"{data_parallel_degree(mesh)} data-parallel shards) is not "
            f"divisible by grad_accum_steps={grad_accum_steps}; raise the "
            "batch size or lower the accumulation factor"
        )
    return local_bs
