"""Named config registry — the BASELINE.json config ladder as one-call presets.

The reference's "configs" were notebook cells (SURVEY §5.6: batch 64, 10 000 steps,
2 GPUs, 5 folds hard-coded in Untitled.ipynb/Test.ipynb). Here every supported
configuration is a named ``(ModelConfig, TrainConfig)`` preset covering the
BASELINE.json ladder: CIFAR smoke -> ImageNet ResNet-50/101/152 + Xception-41 DP ->
bf16 large-batch pod config, plus the reference's own TGS-salt segmentation run.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig


@dataclasses.dataclass(frozen=True)
class Preset:
    model: ModelConfig
    train: TrainConfig
    global_batch: int
    description: str


def _imagenet_model(**kw) -> ModelConfig:
    base = dict(
        num_classes=1000,
        input_shape=(224, 224),
        input_channels=3,
        output_stride=None,  # standard stride-32 classification trunk
        dtype="bfloat16",
    )
    base.update(kw)
    return ModelConfig(**base)


# 90 epochs of ImageNet-1k at global batch 1024 (1.28M images): the standard
# ResNet recipe behind the 76%-top-1 north star (BASELINE.md) — SGD Nesterov
# momentum 0.9, lr linearly scaled 0.1 x (batch/256) = 0.4, 5-epoch linear
# warmup, cosine decay to ~0, weight decay 1e-4 on kernels only
# (Goyal et al., arXiv:1706.02677).
_IMAGENET_1K_TRAIN = TrainConfig(
    optimizer="sgd",
    lr=0.4,
    lr_schedule="cosine",
    lr_warmup_steps=6_255,
    lr_decay_steps=112_590,
    label_smoothing=0.1,
    weight_decay=1e-4,
)

PRESETS: Dict[str, Preset] = {
    # the reference's production config: TGS salt segmentation, 5-fold, batch 64,
    # Adam 1e-3 halving each 10k steps (reference: model.py:33, 457-462;
    # Untitled.ipynb cells 7-8)
    "tgs_salt": Preset(
        model=ModelConfig(),
        train=TrainConfig(),
        global_batch=64,
        description="Reference parity: ResNet-v2-beta + DeepLabV3+ head, 101x101x2, "
        "5-fold CV, Lovász hinge (reference: model.py defaults)",
    ),
    "tgs_salt_bf16": Preset(
        model=ModelConfig(dtype="bfloat16"),
        train=TrainConfig(),
        global_batch=64,
        description="TPU-native variant of the reference workload: identical "
        "architecture/loss with bf16 compute (params, loss, and metrics stay "
        "f32; convs/matmuls run at the MXU's bf16 rate)",
    ),
    # BASELINE.json "ResNet-50 single-tower CIFAR-10 (CPU smoke test)"
    "cifar10_smoke": Preset(
        model=ModelConfig(
            num_classes=10,
            input_shape=(32, 32),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=64,
            output_stride=None,
        ),
        train=TrainConfig(n_folds=2, checkpoint_every_steps=100),
        global_batch=64,
        description="CIFAR-10-shaped smoke config runnable on a CPU mesh",
    ),
    # the elastic/resilience drill shape: one step is milliseconds on a CPU
    # mesh, checkpoints land every 2 steps (dense resume points for
    # kill-and-resize drills), and every step writes a ledger window (the
    # straggler probe needs per-step cross-host comparisons). Micro-sized on
    # purpose: tests/bench_elastic drive REAL multi-process worlds with it.
    "elastic_smoke": Preset(
        model=ModelConfig(
            num_classes=4,
            input_shape=(16, 16),
            input_channels=3,
            n_blocks=(1, 1, 1),
            base_depth=8,
            width_multiplier=0.0625,
            output_stride=None,
        ),
        train=TrainConfig(
            checkpoint_every_steps=2,
            train_log_every_steps=1,
            augmentation="none",
        ),
        global_batch=8,
        description="Micro classification config for elastic-resize and "
        "kill-drill runs: millisecond steps on a CPU mesh, checkpoint "
        "every 2 steps, a ledger window every step",
    ),
    # BASELINE.json "ResNet-50 multi-tower data-parallel (ImageNet-1k)"
    "resnet50_imagenet": Preset(
        model=_imagenet_model(n_blocks=(3, 4, 6)),
        train=_IMAGENET_1K_TRAIN,
        global_batch=1024,
        description="ResNet-50 ImageNet-1k data-parallel, bf16",
    ),
    # Standard-width ResNet-50: the published 25.6M-param architecture that
    # ImageNet numbers (and BASELINE.md's 360 images/sec/chip V100 figure)
    # actually quote. The reference-family presets above run the reference's
    # ~3x-FLOPs wide layout (doubled stage widths + atrous stage,
    # reference: core/resnet.py:330-344); this one is the apples-to-apples
    # benchmark architecture.
    "resnet50_classic_imagenet": Preset(
        model=_imagenet_model(
            n_blocks=(3, 4, 6, 3),
            block_layout="classic",
            # measured ON (2026-08-01 v5e window): 2308.1 img/s/chip vs
            # 2281.16 with the plain stem (+1.2%, MFU 0.3357 vs 0.331);
            # logits are bitwise-equivalent (tests/test_space_to_depth.py)
            stem_space_to_depth=True,
        ),
        train=_IMAGENET_1K_TRAIN,
        global_batch=1024,
        description="Standard ResNet-50 (classic 64/128/256/512 widths) "
        "ImageNet-1k data-parallel, bf16, space-to-depth stem",
    ),
    # BASELINE.json "ResNet-101 / ResNet-152 deeper variants"
    "resnet101_imagenet": Preset(
        model=_imagenet_model(n_blocks=(3, 4, 23)),
        train=_IMAGENET_1K_TRAIN,
        global_batch=1024,
        description="ResNet-101 ImageNet-1k data-parallel, bf16",
    ),
    "resnet152_imagenet": Preset(
        model=_imagenet_model(n_blocks=(3, 8, 36)),
        train=_IMAGENET_1K_TRAIN,
        global_batch=1024,
        description="ResNet-152 ImageNet-1k data-parallel, bf16",
    ),
    # BASELINE.json "Xception multi-tower data-parallel (ImageNet-1k)"
    "xception41_imagenet": Preset(
        model=_imagenet_model(backbone="xception"),
        train=_IMAGENET_1K_TRAIN,
        global_batch=1024,
        description="Xception-41 ImageNet-1k data-parallel, bf16 (the backbone the "
        "reference shipped broken, fixed here — SURVEY §2.4.8-10)",
    ),
    # Beyond-parity: ViT-S/16 — the transformer classifier whose attention runs
    # as ring attention under sequence_parallel (parallel/ring_attention.py)
    "vit_s16_imagenet": Preset(
        model=_imagenet_model(
            backbone="vit",
            patch_size=16,
            embed_dim=384,
            vit_layers=12,
            num_heads=6,
            # measured ON (2026-08-01 device-dominated microbench): train
            # step is a tie, long-seq forward wins 1.14x, no measured
            # downside; the dispatch degrades to XLA above seq 1024 and
            # off-TPU (models/vit.py:_FUSED_MAX_SEQ)
            use_fused_attention=True,
        ),
        # transformers keep Adam (SGD momentum trains ViTs poorly); standard
        # lr 1e-3 + long warmup, sharing the 90-epoch cosine horizon; with
        # weight_decay the chain is AdamW — wd 0.1 is the DeiT/ViT-S recipe
        # (arXiv:2012.12877)
        train=dataclasses.replace(
            _IMAGENET_1K_TRAIN,
            optimizer="adam",
            lr=0.001,
            lr_warmup_steps=10_000,
            weight_decay=0.1,
            # global-norm clip 1.0 — the ViT/DeiT training stabilizer
            # (arXiv:2010.11929 App. B.1; rides the optimizer chain)
            grad_clip_norm=1.0,
        ),
        global_batch=1024,
        description="ViT-S/16 ImageNet-1k, bf16; sequence-parallelizable via "
        "ring attention (--sequence-parallel)",
    ),
    # Beyond-parity: Switch-style MoE ViT — every other block's FFN is a
    # top-1-routed 8-expert MoE with the load-balancing auxiliary loss
    # (arXiv:2101.03961); ~4x the FFN capacity of ViT-S at ~1x the per-token
    # FLOPs. Train data-parallel anywhere, or --expert-parallel 8 to place
    # one expert per chip with all-to-all dispatch.
    "vit_s16_moe_imagenet": Preset(
        model=_imagenet_model(
            backbone="vit",
            patch_size=16,
            embed_dim=384,
            vit_layers=12,
            num_heads=6,
            moe_experts=8,
            # same measured flip as vit_s16_imagenet (seq-gated, TPU-only)
            use_fused_attention=True,
        ),
        train=dataclasses.replace(
            _IMAGENET_1K_TRAIN,
            optimizer="adam",
            lr=0.001,
            lr_warmup_steps=10_000,
            weight_decay=0.1,
            # global-norm clip 1.0 — the ViT/DeiT training stabilizer
            # (arXiv:2010.11929 App. B.1; rides the optimizer chain)
            grad_clip_norm=1.0,
        ),
        global_batch=1024,
        description="ViT-S/16 Switch-MoE (8 experts, top-1 routing + load-"
        "balancing loss) ImageNet-1k, bf16; expert-parallelizable "
        "(--expert-parallel 8)",
    ),
    # BASELINE.json "ResNet-50 bfloat16 large-batch (8k) on v5e-64 pod"
    "resnet50_bf16_8k": Preset(
        model=_imagenet_model(n_blocks=(3, 4, 6), remat=True),
        # LARS with layer-wise trust ratios is what holds accuracy at batch 8k
        # (You et al., arXiv:1708.03888; the MLPerf ResNet recipe): base lr
        # linear-scaled to the batch, 10-epoch warmup, cosine decay, wd 1e-4
        # masked to kernels (BN/bias excluded from decay AND trust scaling)
        train=TrainConfig(
            optimizer="lars",
            lr=3.2,
            lr_schedule="cosine",
            lr_warmup_steps=1_564,   # 10 epochs
            lr_decay_steps=14_080,
            label_smoothing=0.1,
            weight_decay=1e-4,
            async_checkpointing=True,
            # ZeRO-1: at dp=64 the replicated LARS momentum + master math is
            # pure waste — shard the slots and the update across the data
            # axis (parallel/zero.py; numerics pinned identical by
            # tests/test_zero1.py, per-chip bytes recorded by bench.py)
            weight_update_sharding=True,
        ),
        global_batch=8192,
        description="ResNet-50 bf16 large-batch (8k) pod config (v5e-64: 128/chip), "
        "LARS optimizer, ZeRO-1 weight-update sharding",
    ),
}


def get_preset(name: str) -> Preset:
    if name not in PRESETS:
        raise ValueError(
            f"Unknown preset {name!r}; available: {sorted(PRESETS)}"
        )
    return PRESETS[name]


def resnet_depth_blocks(depth: int) -> Tuple[int, int, int]:
    """Stage sizes for the standard ResNet depths (units before the 3-unit atrous/
    final stage, matching the reference's (3,4,6)=ResNet-50 convention,
    reference: model.py:101-103, core/resnet.py:330-344)."""
    table = {50: (3, 4, 6), 101: (3, 4, 23), 152: (3, 8, 36)}
    if depth not in table:
        raise ValueError(f"Unsupported ResNet depth {depth}; choose from {sorted(table)}")
    return table[depth]
