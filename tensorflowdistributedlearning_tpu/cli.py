"""Command-line driver — the reference's notebook cells as a CLI.

The reference was driven by two notebooks that loaded Kaggle CSVs, binned mask
coverage into stratification classes, and called ``Model(...).train(X, y, 64, 10000)``
(reference: Untitled.ipynb cells 0-8, Test.ipynb cells 7-8; SURVEY §2.1 C13). This CLI
covers the same flows plus a synthetic smoke mode that needs no data on disk:

    python -m tensorflowdistributedlearning_tpu train   --data-dir D --model-dir M [...]
    python -m tensorflowdistributedlearning_tpu predict --test-dir T --model-dir M [...]
    python -m tensorflowdistributedlearning_tpu smoke   [--steps N]
"""

from __future__ import annotations

import argparse
import json
import logging
import os
import shutil
import sys
from typing import Dict, List, Optional

import numpy as np


def _add_common(p: argparse.ArgumentParser) -> None:
    p.add_argument("--model-dir", required=True)
    p.add_argument("--batch-size", type=int, default=64)
    p.add_argument("--n-devices", type=int, default=None)
    p.add_argument("--n-fold", type=int, default=5)
    p.add_argument("--seed", type=int, default=42)
    p.add_argument("--input-shape", type=int, nargs=2, default=(101, 101))
    p.add_argument("--n-blocks", type=int, nargs="+", default=(3, 4, 6))
    p.add_argument("--base-depth", type=int, default=256)
    p.add_argument("--backbone", choices=("resnet", "xception"), default="resnet")
    p.add_argument("--block-type", choices=("bottleneck", "basic_block"),
                   default="bottleneck")
    p.add_argument("--dtype", choices=("float32", "bfloat16"), default="float32")
    p.add_argument("--sequence-parallel", type=int, default=1,
                   help="H-shard the backbone over this many devices per "
                   "data-parallel replica (halo-exchange spatial parallelism)")
    p.add_argument("--sync-bn", action="store_true",
                   help="synchronized cross-shard BatchNorm: statistics over "
                   "the GLOBAL batch instead of per shard (cross-replica BN; "
                   "+7.8 points at digits scale, DIGITS_RUN.json)")
    p.add_argument("--model-parallel", type=int, default=1,
                   help="channel-shard params/optimizer over this many devices "
                   "per replica (tensor parallelism; the K-fold trainer runs "
                   "it in shard_map's hybrid auto-model mode)")
    p.add_argument("--weight-update-sharding", action="store_true",
                   help="ZeRO-1: shard optimizer state and the weight update "
                   "across the data-parallel axis — per-chip optimizer memory "
                   "drops ~dp-fold at neutral step time, numerics unchanged "
                   "(arXiv:2004.13336)")


def _add_host_loop(p: argparse.ArgumentParser) -> None:
    """Host-loop overlap knobs shared by the training commands (train/fit).

    Defaults are None so the config's own defaults (TrainConfig or the
    preset's) stay the single source of truth — the flags only override."""
    p.add_argument("--prefetch-depth", type=int, default=None,
                   help="host→device input prefetch depth: the loader thread "
                   "stays this many placed batches ahead of the train loop "
                   "(>= 1; per-window queue-depth telemetry shows underruns "
                   "in telemetry-report; default: the config's, 2)")
    p.add_argument("--dispatch-ahead", type=int, default=None,
                   help="host-device overlap budget: dispatch at most this "
                   "many unretired train steps ahead of the device, with log "
                   "windows deferring their metric fetch one window so the "
                   "device queue never drains on a log line; 0 = the "
                   "synchronous legacy loop (numerics identical either way; "
                   "default: the config's, 2)")
    p.add_argument("--data-workers", type=int, default=None,
                   help="parallel input-service workers (data/service.py): "
                   "N background read+decode workers execute the index-keyed "
                   "global-shuffle batch plan; batch CONTENT is worker-count "
                   "invariant, so this is pure throughput. 0 = the legacy "
                   "in-line input streams (default: the config's, 2)")


def _add_observability(p: argparse.ArgumentParser) -> None:
    """Tracing/health knobs shared by the training commands (train/fit).

    Defaults are None so the config's own defaults stay the single source of
    truth — the flags only override."""
    p.add_argument("--trace-sample-rate", type=float, default=None,
                   help="fraction of traces (per train step / eval pass / "
                   "checkpoint) persisted as `trace` ledger events, "
                   "exportable via `telemetry-report --export-trace` as "
                   "Chrome/Perfetto JSON; 0 disables tracing (the config "
                   "default)")
    p.add_argument("--nan-guard", choices=("warn", "abort", "off"),
                   default=None,
                   help="NaN/Inf loss guard action: warn (alert and keep "
                   "training), abort (alert then stop at a recorded "
                   "boundary), off; default: the config's (warn). Drill "
                   "with --inject-fault nan-loss@N")
    p.add_argument("--profile-every-windows", type=int, default=None,
                   help="continuous profiling cadence: capture a short "
                   "windowed jax.profiler trace every N log windows, parse "
                   "it into a per-op roofline, and ledger profile_capture/"
                   "op_roofline events (obs/profiler.py). 0 disables (the "
                   "config default); overhead is gated <=2%% by `bench.py "
                   "--profile-overhead`. Alert-triggered postmortem "
                   "captures fire regardless of this cadence")


def _add_compile_cache(p: argparse.ArgumentParser) -> None:
    """The shared cold-start knob (train/fit/serve/serve-fleet) —
    utils/compile_cache.py."""
    p.add_argument("--compile-cache-dir", default=None, metavar="DIR",
                   help="persistent XLA compile cache: executables land in "
                   "DIR keyed on module + jaxlib + flags + device kinds, so "
                   "a second same-shape run (or the next replica/resize) "
                   "LOADS instead of compiling. Hits/misses ride the compile "
                   "ledger events and telemetry-report's hit-ratio line; an "
                   "unwritable DIR warns and runs uncached")


def _add_planner(p: argparse.ArgumentParser) -> None:
    """Layout-selection knobs shared by the training commands (train/fit) —
    parallel/planner.py."""
    p.add_argument("--parallelism", choices=("explicit", "auto"),
                   default="explicit",
                   help="'auto' derives the whole (dp, tp, pp, spatial, "
                   "zero1) layout from the model's exact param/opt-state "
                   "accounting, the per-chip HBM budget, and the device "
                   "topology (parallel/planner.py); any parallelism flag "
                   "you set explicitly stays pinned (explicit flags win). "
                   "'explicit' (default) runs your flags verbatim, "
                   "validated through the same planner so indivisible "
                   "degrees fail fast with a named constraint. Either way "
                   "the chosen plan rides the run-header ledger event; "
                   "inspect candidates with the `plan` subcommand")
    p.add_argument("--hbm-budget-gb", type=float, default=None,
                   help="per-chip HBM budget in GiB for the planner's "
                   "feasibility gate (default: the backend's reported "
                   "bytes_limit; CPU builds report none)")


def _add_resilience(p: argparse.ArgumentParser) -> None:
    """Flags shared by the training commands (train/fit) — resilience/."""
    from tensorflowdistributedlearning_tpu.resilience.preempt import (
        EXIT_PREEMPTED,
    )

    p.add_argument("--inject-fault", default=None, metavar="SPEC",
                   help="deterministic fault injection for drills and tests: "
                   "KIND@AT[xCOUNT] with KIND in raise|sigterm|io-data|"
                   "io-read|io-ckpt|nan-loss (e.g. 'sigterm@12' preempts "
                   "after step 12; 'raise@5-20' crashes at a seeded-random "
                   "step; 'io-ckpt@1' makes the first checkpoint write fail "
                   "transiently; 'nan-loss@2' poisons the 2nd observed loss "
                   "window with NaN — the health-monitor drill)")
    p.add_argument("--max-restarts", type=int, default=None,
                   help="run under the restart supervisor: relaunch this "
                   "command after crashes/preemptions (exponential backoff + "
                   "jitter) up to this many times, aborting early when no "
                   "step progress is made between restarts; 0 (the default) "
                   "= unsupervised. Under --elastic this is the SAME-SHAPE "
                   "restart budget for plain crashes (default 3 there; an "
                   "explicit 0 disables same-shape restarts)")
    p.add_argument("--preempt-notice-file", default=None, metavar="PATH",
                   help="also treat the appearance of this file as a "
                   "preemption notice (for environments that cannot deliver "
                   "SIGTERM to the training process); same semantics as the "
                   "signal: final checkpoint at the next step boundary, "
                   f"exit code {EXIT_PREEMPTED}")


def _add_elastic(p: argparse.ArgumentParser) -> None:
    """Elastic multi-process training (parallel/elastic.py): run N host-slot
    processes under the elastic coordinator; a host death or sustained
    straggler triggers a checkpoint-coordinated world resize instead of a
    dead run."""
    p.add_argument("--elastic", type=int, default=0, metavar="HOSTS",
                   help="run this command as an elastic multi-process pod of "
                   "HOSTS host-slot processes (jax.distributed over gloo on "
                   "CPU; one process per host on real pods): a SIGKILLed/"
                   "OOMed host triggers a coordinated drain (preemption "
                   "checkpoints where collectives still work), a planner "
                   "re-plan at the new world size, and a resume at HOSTS-1 "
                   "with ZeRO-1 optimizer state resharded and the data "
                   "service re-dealt; plain crashes restart same-shape "
                   "under the usual budget. 0 = off")
    p.add_argument("--min-hosts", type=int, default=1,
                   help="never resize below this world size: a resize that "
                   "would cross it aborts the run instead (elastic_abort)")
    p.add_argument("--devices-per-host", type=int, default=None,
                   help="force this many XLA host-platform devices per child "
                   "process (the CPU pod harness; real TPU hosts expose "
                   "their chips without it)")
    p.add_argument("--drain-timeout", type=float, default=45.0,
                   help="seconds survivors get to finish their preemption "
                   "checkpoint during a resize drain before being killed "
                   "(a DEAD peer can wedge their collectives; resume then "
                   "falls back to the last complete checkpoint)")
    p.add_argument("--no-straggler-evict", action="store_true",
                   help="disable straggler-triggered host eviction (the "
                   "coordinator still resizes on host death)")
    p.add_argument("--evict-threshold", type=float, default=1.25,
                   help="straggler skew threshold (worst-host mean step time "
                   "/ fleet median) a window must cross to count toward "
                   "eviction — obs/fleet.py's straggler attribution")
    p.add_argument("--evict-sustained", type=int, default=3,
                   help="consecutive alerted windows naming the SAME host "
                   "before it is evicted (a clean window resets the streak "
                   "— flapping hosts never oscillate the world)")
    p.add_argument("--evict-cooldown", type=float, default=60.0,
                   help="seconds after any resize during which no eviction "
                   "fires (the resized fleet re-warms, which looks exactly "
                   "like a straggler)")
    p.add_argument("--aot-standby", action="store_true",
                   help="after each generation settles, background-compile "
                   "the NEXT world size's (world-1) step function into the "
                   "shared --compile-cache-dir from a rank-for-rank standby "
                   "mini-world on a scratch workdir (cache keys bind the "
                   "process-local topology), so a resize's respawn loads "
                   "its executables instead of rebuilding them (requires "
                   "--compile-cache-dir; ledgered as aot_standby events, "
                   "measured by world_settled.settle_s)")
    p.add_argument("--host-inject-fault", action="append", default=[],
                   metavar="HOST:SPEC",
                   help="drill: pass --inject-fault SPEC to host-slot HOST "
                   "of the INITIAL generation (e.g. '1:sigkill-step@6' "
                   "vanishes host 1 after step 6 — the headline host-death "
                   "resize drill)")
    # the coordinator's child-process seam: one host slot of an explicit
    # jax.distributed world (also usable by hand for multi-host CPU/GPU runs)
    p.add_argument("--coordinator-address", default=None, metavar="HOST:PORT",
                   help="join an explicit jax.distributed cluster at this "
                   "coordinator (multihost.initialize; TPU pods "
                   "auto-discover without it). Set by the elastic "
                   "coordinator for its children")
    p.add_argument("--num-processes", type=int, default=None,
                   help="world size of the explicit jax.distributed cluster")
    p.add_argument("--process-id", type=int, default=None,
                   help="this process's rank in the explicit cluster")


def _add_auto_promote(p: argparse.ArgumentParser) -> None:
    """Close the train->serve loop from the training CLI: after
    --export-serving, hand the fresh artifact straight to a live fleet's
    promotion controller. The exit status IS the promotion verdict."""
    p.add_argument("--auto-promote", action="store_true",
                   help="after --export-serving, promote the exported "
                   "artifact onto the live serve-fleet found via "
                   "--fleet-workdir/--router: quantize-check admission "
                   "(manifest gate), shadow-compared canary, rolling "
                   "restart, auto-rollback — exit 0 only when the fleet "
                   "completes the flip (what the flywheel controller runs)")
    p.add_argument("--fleet-workdir", default=None, metavar="DIR",
                   help="the live fleet's workdir: its router endpoint is "
                   "read from the run-header ledger event")
    p.add_argument("--router", default=None, metavar="URL",
                   help="the live fleet router's base URL (overrides "
                   "--fleet-workdir)")
    p.add_argument("--promote-model", default=None,
                   help="multi-tenant fleet: the registry model to promote")
    p.add_argument("--promote-shadow-secs", type=float, default=None,
                   help="shadow window length for the auto-promotion "
                   "(default: the controller's)")
    p.add_argument("--promote-min-requests", type=int, default=None,
                   help="shadow compare floor (PromoteConfig "
                   "shadow_min_requests)")
    p.add_argument("--promote-max-disagree", type=float, default=None,
                   help="class-disagreement ceiling for the shadow compare "
                   "— a RETRAINED candidate legitimately disagrees with "
                   "the incumbent more than a re-quantized one, loosen "
                   "accordingly")
    p.add_argument("--promote-max-abs-delta", type=float, default=None,
                   help="max |delta| ceiling on float outputs during shadow")
    p.add_argument("--promote-max-mean-delta", type=float, default=None,
                   help="mean |delta| ceiling on float outputs during shadow")
    p.add_argument("--promote-min-iou", type=float, default=None,
                   help="mask-IoU floor for the shadow compare")
    p.add_argument("--promote-max-p99-ratio", type=float, default=None,
                   help="canary latency gate (PromoteConfig max_p99_ratio)")
    p.add_argument("--promote-timeout", type=float, default=600.0,
                   help="seconds to wait for a terminal promotion state")


def build_parser() -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(
        prog="tensorflowdistributedlearning_tpu",
        description="TPU-native K-fold segmentation training framework",
    )
    sub = parser.add_subparsers(dest="command", required=True)

    p_train = sub.add_parser("train", help="K-fold cross-validated training")
    _add_common(p_train)
    p_train.add_argument("--data-dir", required=True,
                         help="directory with images/*.png and masks/*.png")
    p_train.add_argument("--lr", type=float, default=0.001)
    p_train.add_argument("--steps", type=int, default=10_000)
    p_train.add_argument("--save-best", type=int, default=5)
    p_train.add_argument("--checkpoint-every", type=int, default=500)
    p_train.add_argument("--eval-throttle-secs", type=int, default=300)
    p_train.add_argument("--export-serving", action="store_true",
                         help="after training, export the best fold's "
                         "standalone StableHLO serving artifact next to its "
                         "checkpoint ({fold_dir}/export/serving)")
    p_train.add_argument("--serving-dtype",
                         choices=("float32", "bfloat16", "int8",
                                  "int8-compute"),
                         default="float32",
                         help="post-training precision spec for "
                         "--export-serving (train/quantize.py): bfloat16 "
                         "casts params at export, int8 stores conv/dense "
                         "kernels as int8 with per-channel symmetric scales "
                         "(activations bf16), int8-compute stores the same "
                         "bytes and runs the matmul/conv arithmetic in int8 "
                         "via the quant kernels; quantized exports land in "
                         "export/serving-{spec} beside the float32 "
                         "reference and must pass quantize-check to ship")
    _add_auto_promote(p_train)
    _add_planner(p_train)
    _add_host_loop(p_train)
    _add_observability(p_train)
    _add_resilience(p_train)
    _add_compile_cache(p_train)

    p_pred = sub.add_parser("predict", help="fold x TTA ensemble prediction")
    _add_common(p_pred)
    p_pred.add_argument("--test-dir", required=True)
    p_pred.add_argument("--artifact-dir", default=None,
                        help="run inference from an exported StableHLO "
                        "serving artifact (through the bucketed serve "
                        "engine) instead of restoring checkpoints; "
                        "--model-dir is ignored")
    p_pred.add_argument("--no-tta", action="store_true",
                        help="disable test-time augmentation (single forward pass)")
    p_pred.add_argument("--output", default=None,
                        help="write predictions to this .npz (default: stdout summary)")
    p_pred.add_argument("--submission", default=None,
                        help="also write a Kaggle RLE submission csv here")

    p_smoke = sub.add_parser(
        "smoke", help="synthetic end-to-end training smoke (no data needed)"
    )
    p_smoke.add_argument("--steps", type=int, default=10)
    p_smoke.add_argument("--batch-size", type=int, default=8)
    p_smoke.add_argument("--n-devices", type=int, default=None)

    p_fit = sub.add_parser(
        "fit",
        help="single-run classification training from a named preset "
        "(streaming ImageFolder data; synthetic when --data-dir is omitted)",
    )
    p_fit.add_argument("--preset", required=True)
    p_fit.add_argument("--model-dir", required=True)
    p_fit.add_argument("--data-dir", default=None,
                       help="ImageFolder root with train/{class}/*.png "
                       "(+ optional val/); omitted = synthetic data")
    p_fit.add_argument("--steps", type=int, default=100)
    p_fit.add_argument("--batch-size", type=int, default=None,
                       help="global batch (default: the preset's)")
    p_fit.add_argument("--eval-every", type=int, default=None)
    p_fit.add_argument("--sequence-parallel", type=int, default=1)
    p_fit.add_argument("--sync-bn", action="store_true",
                       help="synchronized cross-shard BatchNorm (global-batch "
                       "statistics)")
    p_fit.add_argument("--model-parallel", type=int, default=1,
                       help="GSPMD tensor parallelism: shard params/optimizer "
                       "over this many devices per replica")
    p_fit.add_argument("--pipeline-parallel", type=int, default=1,
                       help="GPipe pipeline parallelism over ViT blocks: this "
                       "many stages per replica (backbone=vit presets only)")
    p_fit.add_argument("--pipeline-microbatches", type=int, default=None,
                       help="microbatches per local batch for the pipeline "
                       "schedule (default: one per stage; set >> stages to "
                       "shrink the fill/drain bubble)")
    p_fit.add_argument("--expert-parallel", type=int, default=1,
                       help="expert parallelism for MoE presets: one expert "
                       "per shard with all-to-all dispatch (must equal the "
                       "preset's moe_experts)")
    p_fit.add_argument("--weight-update-sharding", action="store_true",
                       default=None,
                       help="ZeRO-1: shard optimizer state and the weight "
                       "update across the data-parallel axis — per-chip "
                       "optimizer memory drops ~dp-fold at neutral step "
                       "time, numerics unchanged (arXiv:2004.13336); "
                       "default: the preset's setting")
    p_fit.add_argument("--eval-holdout-fraction", type=float, default=None,
                       help="with record shards and no val split: hold out "
                       "this fraction of train shards as the eval split")
    p_fit.add_argument("--optimizer", choices=("adam", "sgd", "lars"), default=None,
                       help="override the preset's optimizer (sgd = Nesterov "
                       "momentum, the standard ImageNet recipe; lars = "
                       "large-batch layer-wise scaling); requires "
                       "--lr when it differs from the preset's pairing")
    p_fit.add_argument("--lr", type=float, default=None,
                       help="override the preset's learning rate")
    p_fit.add_argument("--ema-decay", type=float, default=None,
                       help="track a parameter EMA at this decay (e.g. 0.9999) "
                       "and evaluate/export the averaged weights; 0 disables")
    p_fit.add_argument("--grad-accum", type=int, default=None,
                       help="accumulate gradients over this many sequential "
                       "microbatches per step (one optimizer update on their "
                       "mean): effective batch = accum x batch at one "
                       "microbatch's activation memory")
    p_fit.add_argument("--grad-clip", type=float, default=None,
                       help="clip gradients to this global l2 norm before the "
                       "optimizer update; 0 disables")
    p_fit.add_argument("--augmentation",
                       choices=("flip_crop", "crop", "none", "mixup", "cutmix"),
                       default=None,
                       help="override the preset's train augmentation policy "
                       "(crop drops the mirror — digits/text; none streams "
                       "batches untouched; mixup/cutmix add image/label "
                       "mixing on top of flip_crop)")
    p_fit.add_argument("--export-serving", action="store_true",
                       help="after training, export the best checkpoint's "
                       "standalone StableHLO serving artifact "
                       "({model_dir}/export/serving) and stamp its "
                       "drift_baseline (output distribution over the pinned "
                       "eval batch) into the manifest")
    p_fit.add_argument("--serving-dtype",
                       choices=("float32", "bfloat16", "int8",
                                "int8-compute"),
                       default="float32",
                       help="post-training precision spec for "
                       "--export-serving (quantized exports land in "
                       "export/serving-{spec}; int8-compute runs real int8 "
                       "matmul/conv arithmetic via ops/quant_kernels.py)")
    _add_auto_promote(p_fit)
    _add_planner(p_fit)
    _add_host_loop(p_fit)
    _add_observability(p_fit)
    _add_resilience(p_fit)
    _add_elastic(p_fit)
    _add_compile_cache(p_fit)

    p_plan = sub.add_parser(
        "plan",
        help="print the parallelism planner's candidate table for a model + "
        "batch + topology: chosen layout, predicted params/opt/activation "
        "bytes per chip (exact tree_bytes_per_device accounting for "
        "params+opt), headroom against the HBM budget, and why each "
        "rejected candidate lost (parallel/planner.py)",
    )
    p_plan.add_argument("--preset", default=None,
                        help="plan for a named preset's model+train config "
                        "(batch defaults to the preset's global batch)")
    p_plan.add_argument("--batch-size", type=int, default=None,
                        help="global batch (default: the preset's, else 64)")
    p_plan.add_argument("--n-devices", type=int, default=None)
    p_plan.add_argument("--hbm-gb", type=float, default=None,
                        help="per-chip HBM budget in GiB (default: the "
                        "backend's reported bytes_limit; CPU builds report "
                        "none — feasibility is then divisibility-only)")
    p_plan.add_argument("--grad-accum", type=int, default=None)
    # pin any subset of the layout; the planner fills the rest by score
    p_plan.add_argument("--model-parallel", type=int, default=None)
    p_plan.add_argument("--pipeline-parallel", type=int, default=None)
    p_plan.add_argument("--sequence-parallel", type=int, default=None)
    p_plan.add_argument("--expert-parallel", type=int, default=None)
    p_plan.add_argument("--weight-update-sharding", action="store_true",
                        default=None)
    # model args for preset-less planning (mirror `train`'s)
    p_plan.add_argument("--backbone", choices=("resnet", "xception", "vit"),
                        default="resnet")
    p_plan.add_argument("--input-shape", type=int, nargs=2, default=(101, 101))
    p_plan.add_argument("--n-blocks", type=int, nargs="+", default=(3, 4, 6))
    p_plan.add_argument("--base-depth", type=int, default=256)
    p_plan.add_argument("--block-type",
                        choices=("bottleneck", "basic_block"),
                        default="bottleneck")
    p_plan.add_argument("--dtype", choices=("float32", "bfloat16"),
                        default="float32")
    p_plan.add_argument("--num-classes", type=int, default=None,
                        help="classification head (default: the "
                        "segmentation head, like `train`)")
    p_plan.add_argument("--measured-margin-from", default=None,
                        metavar="WORKDIR",
                        help="close the activation-estimate feedback loop: "
                        "read the ledgered measured-vs-predicted "
                        "memory_watermark residual from this prior run's "
                        "workdir and add it to every candidate's budget "
                        "check (what the elastic coordinator does "
                        "automatically on re-plan)")
    p_plan.add_argument("--measured-costs-from", default=None,
                        metavar="WORKDIR",
                        help="close the cost-model feedback loop: score "
                        "candidates with the achieved FLOP/s and collective "
                        "bytes/s from this prior run's ledgered op_roofline "
                        "events (profile once with --profile-every-windows, "
                        "plan better forever after) instead of the analytic "
                        "peak-FLOPs table + ICI constant; the table then "
                        "shows measured vs analytic scores side by side and "
                        "the provenance rides the run header. Exits 2 when "
                        "the workdir has no roofline events")
    p_plan.add_argument("--json", action="store_true",
                        help="full machine-readable plan (chosen layout + "
                        "every candidate's verdict) instead of the table")

    p_serve = sub.add_parser(
        "serve",
        help="dynamic-batching HTTP inference server over an exported "
        "StableHLO artifact (bucketed compilation, bounded-queue "
        "backpressure, /v1/predict + /healthz + /metrics)",
    )
    p_serve.add_argument("--artifact-dir", default=None,
                         help="artifact directory from export_serving "
                         "(serving.stablehlo + manifest.json); required "
                         "unless --registry names the artifacts")
    p_serve.add_argument("--registry", default=None, metavar="PATH",
                         help="multi-tenant load: a registry.json "
                         "(serve/registry.py schema) — EVERY entry's "
                         "artifact loads into this replica as its own "
                         "engine + micro-batcher, requests route by the "
                         "payload's \"model\" key, and per-model SLOs / "
                         "bucket ladders / prewarm budgets apply")
    p_serve.add_argument("--model", default=None,
                         help="name this replica serves under (the registry "
                         "entry a fleet bound it to); stamps /healthz "
                         "identity, per-model metrics labels, and "
                         "serve_window events")
    p_serve.add_argument("--model-version", type=int, default=None,
                         help="registry version of the served artifact "
                         "(advertised on /healthz and /metrics; flips on "
                         "promote)")
    p_serve.add_argument("--prewarm-buckets", type=int, default=None,
                         help="warm only the first K buckets of the ladder "
                         "at spawn (smallest first); colder buckets compile "
                         "on first hit, ledgered per bucket as "
                         "serve/cold_bucket_hits — trades spawn-to-ready "
                         "time against first-request stalls")
    p_serve.add_argument("--visible-devices", default=None, metavar="IDS",
                         help="comma-separated accelerator ordinals this "
                         "replica may claim (exported as *_VISIBLE_DEVICES "
                         "before the runtime initializes) — how a "
                         "multi-tenant fleet places replicas on disjoint "
                         "chips")
    p_serve.add_argument("--host", default="127.0.0.1")
    p_serve.add_argument("--port", type=int, default=8000,
                         help="0 = any free port (printed on startup)")
    p_serve.add_argument("--buckets", type=int, nargs="+",
                         default=(1, 4, 16, 64),
                         help="batch-bucket ladder; each bucket is compiled "
                         "once at warmup, requests pad up to the smallest "
                         "fit — steady state never recompiles")
    p_serve.add_argument("--max-wait-ms", type=float, default=5.0,
                         help="micro-batcher coalescing window after the "
                         "first queued request")
    p_serve.add_argument("--queue-size", type=int, default=256,
                         help="bounded request queue; a full queue rejects "
                         "immediately with HTTP 429 (backpressure, not "
                         "unbounded memory)")
    p_serve.add_argument("--default-deadline-ms", type=float, default=None,
                         help="deadline applied to requests that carry none; "
                         "expired requests answer 504 without burning a "
                         "bucket slot")
    p_serve.add_argument("--workdir", default=None,
                         help="telemetry ledger dir (serve_window events in "
                         "{workdir}/telemetry.jsonl; default: the artifact "
                         "dir)")
    p_serve.add_argument("--window-secs", type=float, default=30.0,
                         help="ledger window cadence; 0 disables periodic "
                         "windows (final window still written on shutdown)")
    p_serve.add_argument("--trace-sample-rate", type=float, default=0.0,
                         help="fraction of requests whose queue/pad/compute "
                         "trace (keyed by the echoed x-request-id) persists "
                         "as `trace` ledger events; 0 disables tracing")
    p_serve.add_argument("--slo-p99-ms", type=float, default=None,
                         help="serving SLO: p99 latency target in ms, "
                         "enforced as a windowed error budget — breaches "
                         "write health_alert ledger events and flip /healthz "
                         "to status=degraded (the fleet-router drain signal)")
    p_serve.add_argument("--slo-error-budget", type=float, default=0.01,
                         help="fraction of requests per window allowed over "
                         "the p99 target before the SLO counts as breached "
                         "(0.01 = the p99 semantics)")
    p_serve.add_argument("--replica-id", type=int, default=0,
                         help="this replica's id in a serving fleet: stamped "
                         "on serve_window ledger events and /healthz, and "
                         "replica i>0 writes telemetry-{i}.jsonl so N "
                         "replicas sharing one --workdir produce per-replica "
                         "ledgers that telemetry-report merges (obs/fleet.py)")
    p_serve.add_argument("--inject-fault", default=None, metavar="SPEC",
                         help="serving-tier fault drill (resilience/faults.py"
                         "): 'sigkill@N' hard-kills this replica after its "
                         "Nth answered request — the deterministic mid-soak "
                         "replica death the fleet failover tests and "
                         "bench_serve --fleet's kill soak converge through")
    p_serve.add_argument("--seed", type=int, default=0,
                         help="seed for ranged --inject-fault specs")
    p_serve.add_argument("--capture-dir", default=None, metavar="DIR",
                         help="arm the traffic-capture tee (loop/capture.py): "
                         "sample accepted requests off the hot path into "
                         "record shards under DIR (self-labeled with the "
                         "served model's argmax), ledgered as capture_window "
                         "events — the raw material `records-ingest` folds "
                         "into a retraining dataset")
    p_serve.add_argument("--capture-fraction", type=float, default=1.0,
                         help="fraction of accepted requests the capture tee "
                         "samples (deterministic stride, not a coin flip)")
    p_serve.add_argument("--capture-quota-mb", type=float, default=64.0,
                         help="disk ceiling for captured shards: oldest "
                         "sealed shards are evicted first when the quota is "
                         "exceeded (the newest shard always survives)")
    p_serve.add_argument("--capture-records-per-shard", type=int, default=64,
                         help="records per sealed capture shard")
    p_serve.add_argument("--drift-threshold", type=float, default=None,
                         help="arm the DriftMonitor (obs/health.py): total-"
                         "variation distance between the serving output "
                         "class distribution and the artifact manifest's "
                         "promotion-time drift_baseline past this emits "
                         "drift_alert ledger events (the flywheel's retrain "
                         "trigger); requires a stamped baseline — skipped "
                         "with a warning otherwise")
    p_serve.add_argument("--drift-min-requests", type=int, default=20,
                         help="window floor before a drift verdict counts")
    p_serve.add_argument("--drift-sustain-windows", type=int, default=2,
                         help="consecutive over-threshold windows before the "
                         "alert fires (one weird window is noise)")
    _add_compile_cache(p_serve)

    p_fleet = sub.add_parser(
        "serve-fleet",
        help="multi-replica serving tier: N `serve` subprocesses (ephemeral "
        "ports, per-replica ledgers, restart-on-death supervision) behind a "
        "queue-depth/p99 load-balancing router with graceful 429 shedding, "
        "plus optional autoscaling on sustained queue depth and the SLO "
        "error budget (fleet_scale ledger events)",
    )
    p_fleet.add_argument("--artifact-dir", default=None,
                         help="artifact directory every replica serves "
                         "(export_serving output); required unless "
                         "--registry (or a registry.json in --workdir) "
                         "names per-model artifacts")
    p_fleet.add_argument("--registry", default=None, metavar="PATH",
                         help="multi-tenant fleet: a registry.json "
                         "(serve/registry.py schema). Each model entry "
                         "spawns its OWN replica set with its artifact, "
                         "bucket ladder, SLO, prewarm budget, fair-share "
                         "weight, and visible-device slots; the router "
                         "routes by the payload's \"model\" key and sheds "
                         "by fair share under saturation. When omitted, a "
                         "registry.json already in --workdir is picked up "
                         "automatically")
    p_fleet.add_argument("--chip-budget", type=int, default=None,
                         help="fleet-wide chip ceiling for per-model "
                         "autoscaling: sum(replicas x chips_per_replica) "
                         "never exceeds this — an over-budget scale-up is "
                         "ledgered as budget_deferred instead of applied")
    p_fleet.add_argument("--workdir", default=None,
                         help="shared fleet workdir: the controller writes "
                         "telemetry.jsonl, replica i telemetry-{i}.jsonl — "
                         "one telemetry-report merges the whole fleet "
                         "(default: the artifact dir)")
    p_fleet.add_argument("--host", default="127.0.0.1",
                         help="router bind host (replicas bind loopback)")
    p_fleet.add_argument("--port", type=int, default=8000,
                         help="router port; 0 = any free port (reported on "
                         "stdout and in the run-header ledger event)")
    p_fleet.add_argument("--replicas", type=int, default=2,
                         help="initial replica count")
    p_fleet.add_argument("--min-replicas", type=int, default=1)
    p_fleet.add_argument("--max-replicas", type=int, default=4)
    p_fleet.add_argument("--no-autoscale", action="store_true",
                         help="fix the fleet at --replicas (supervision and "
                         "routing still run; only scaling decisions are off)")
    p_fleet.add_argument("--queue-high", type=float, default=4.0,
                         help="autoscale pressure threshold: mean queued+"
                         "in-flight requests per replica that count as "
                         "sustained pressure")
    p_fleet.add_argument("--queue-low", type=float, default=0.25,
                         help="autoscale idle threshold (scale-down drain)")
    p_fleet.add_argument("--scale-sustain", type=int, default=3,
                         help="consecutive evaluations a signal must persist "
                         "before a scale decision")
    p_fleet.add_argument("--scale-cooldown-s", type=float, default=15.0,
                         help="seconds after a decision before the next may "
                         "fire")
    p_fleet.add_argument("--autoscale-interval-s", type=float, default=2.0,
                         help="seconds between autoscaler evaluations")
    p_fleet.add_argument("--poll-interval-s", type=float, default=0.5,
                         help="router -> replica /metrics poll cadence (the "
                         "queue-depth/p99/status the routing policy reads)")
    p_fleet.add_argument("--buckets", type=int, nargs="+",
                         default=(1, 4, 16, 64),
                         help="per-replica batch-bucket ladder")
    p_fleet.add_argument("--max-wait-ms", type=float, default=5.0,
                         help="per-replica continuous-batching coalesce "
                         "budget (idle arrivals only; backlog dispatches "
                         "immediately)")
    p_fleet.add_argument("--queue-size", type=int, default=256,
                         help="per-replica bounded request queue (full = "
                         "429 + Retry-After)")
    p_fleet.add_argument("--default-deadline-ms", type=float, default=None)
    p_fleet.add_argument("--window-secs", type=float, default=15.0,
                         help="replica + router ledger window cadence")
    p_fleet.add_argument("--slo-p99-ms", type=float, default=None,
                         help="per-replica serving SLO: breaches flip the "
                         "replica to status=degraded, which the router "
                         "routes around and the autoscaler scales on")
    p_fleet.add_argument("--slo-error-budget", type=float, default=0.01)
    p_fleet.add_argument("--max-restarts-per-replica", type=int, default=3,
                         help="supervision budget: a replica dying more "
                         "than this is abandoned (ledgered), not "
                         "crash-looped")
    p_fleet.add_argument("--replica-inject-fault", action="append",
                         default=None, metavar="ID:SPEC",
                         help="fault drill: pass --inject-fault SPEC to "
                         "replica ID's FIRST launch (e.g. '2:sigkill@200' "
                         "kills replica 2 after 200 answered requests; the "
                         "restart relaunches clean) — how the failover "
                         "tests and bench_serve --fleet's kill soak "
                         "schedule a deterministic mid-soak replica death")
    p_fleet.add_argument("--capture-dir", default=None, metavar="DIR",
                         help="arm every replica's traffic-capture tee: "
                         "replica i writes record shards under "
                         "DIR/replica-{i} (per-replica subdirs keep shard "
                         "sequences disjoint; records-ingest walks them "
                         "recursively)")
    p_fleet.add_argument("--capture-fraction", type=float, default=1.0)
    p_fleet.add_argument("--capture-quota-mb", type=float, default=64.0,
                         help="per-replica capture disk ceiling")
    p_fleet.add_argument("--capture-records-per-shard", type=int, default=64)
    p_fleet.add_argument("--drift-threshold", type=float, default=None,
                         help="arm every replica's DriftMonitor against the "
                         "artifact's stamped drift_baseline (drift_alert "
                         "ledger events — the flywheel retrain trigger)")
    p_fleet.add_argument("--drift-min-requests", type=int, default=20)
    p_fleet.add_argument("--drift-sustain-windows", type=int, default=2)
    _add_compile_cache(p_fleet)

    p_prom = sub.add_parser(
        "promote",
        help="roll a candidate artifact across a LIVE serve-fleet: "
        "quantize-check admission, shadow-compared canary (a traffic slice "
        "is duplicated to it, never answered from it), replica-by-replica "
        "rollout through the router's drain/readmit path, automatic "
        "rollback on accuracy/latency regression or canary crash-loop — "
        "the whole deployment ledgered (promotion_*/shadow_window events) "
        "and rendered by telemetry-report",
    )
    p_prom.add_argument("--candidate-dir", default=None,
                        help="the artifact directory to promote "
                        "(export_serving output); required unless --abort")
    p_prom.add_argument("--model", default=None,
                        help="multi-tenant fleet: promote ONLY this "
                        "registry model — its replicas roll, completion "
                        "flips its registry.json entry (version bump), and "
                        "every other tenant keeps serving untouched; "
                        "REQUIRED when the fleet serves more than one model")
    p_prom.add_argument("--reference-dir", default=None,
                        help="float32 reference for the quantize-check "
                        "admission gate (fingerprint pairing + accuracy "
                        "budgets); omitted = manifest-only admission")
    p_prom.add_argument("--workdir", default=None,
                        help="the live fleet's workdir: the router endpoint "
                        "is read from its run-header ledger event "
                        "(alternative to --router)")
    p_prom.add_argument("--router", default=None, metavar="URL",
                        help="the live fleet router's base URL (e.g. "
                        "http://127.0.0.1:8000); overrides --workdir")
    p_prom.add_argument("--shadow-secs", type=float, default=None,
                        help="shadow window length; 0 skips the shadow "
                        "phase (default: the controller's, 10)")
    p_prom.add_argument("--shadow-fraction", type=float, default=None,
                        help="slice of accepted traffic duplicated to the "
                        "canary (default 0.25)")
    p_prom.add_argument("--shadow-min-requests", type=int, default=None,
                        help="compared requests a shadow window needs "
                        "before it counts as evidence (an emptier window "
                        "HOLDS the phase; default 8)")
    p_prom.add_argument("--shadow-max-secs", type=float, default=None,
                        help="give up (roll back) when shadow traffic "
                        "stays below --shadow-min-requests this long "
                        "(default 120)")
    p_prom.add_argument("--min-iou", type=float, default=None,
                        dest="shadow_min_iou",
                        help="mask-IoU floor for the shadow compare "
                        "(default 0.90)")
    p_prom.add_argument("--max-disagree", type=float, default=None,
                        dest="shadow_max_disagree",
                        help="class-disagreement ceiling for the shadow "
                        "compare (default 0.10)")
    p_prom.add_argument("--max-abs-delta", type=float, default=None,
                        dest="shadow_max_abs_delta",
                        help="max |delta| ceiling on float outputs "
                        "(default 0.25)")
    p_prom.add_argument("--max-mean-delta", type=float, default=None,
                        dest="shadow_max_mean_delta",
                        help="mean |delta| ceiling on float outputs "
                        "(default 0.05)")
    p_prom.add_argument("--max-p99-ratio", type=float, default=None,
                        help="latency gate: canary/fleet p99 vs baseline "
                        "past this ratio (obs/compare noise-band verdict) "
                        "rolls back (default 1.5)")
    p_prom.add_argument("--observe-secs", type=float, default=None,
                        help="post-step observation dwell during rollout "
                        "(default 2)")
    p_prom.add_argument("--canary-inject-fault", default=None,
                        metavar="SPEC",
                        help="drill: pass `serve --inject-fault SPEC` to "
                        "the canary's FIRST launch (e.g. sigkill@25 kills "
                        "it mid-shadow; the monitor restarts it on the "
                        "candidate and the controller must converge)")
    p_prom.add_argument("--abort", action="store_true",
                        help="abort the fleet's in-flight promotion "
                        "(rolls back) instead of starting one")
    p_prom.add_argument("--timeout", type=float, default=600.0,
                        help="seconds to wait for a terminal state before "
                        "giving up (the promotion keeps running fleet-side)")
    p_prom.add_argument("--json", action="store_true",
                        help="print the final status as JSON instead of "
                        "the phase-by-phase progress log")

    p_qc = sub.add_parser(
        "quantize-check",
        help="accuracy gate between a float32 serving artifact and a "
        "quantized sibling: pinned eval batch, per-precision delta "
        "thresholds, quant_check ledger event; exit 1 on failure "
        "(promotion-pipeline gate)",
    )
    p_qc.add_argument("--reference-dir", required=True,
                      help="the float32 reference artifact directory")
    p_qc.add_argument("--candidate-dir", required=True,
                      help="the quantized candidate artifact directory "
                      "(its manifest quantization.dtype selects the "
                      "threshold set)")
    p_qc.add_argument("--batch-size", type=int, default=16,
                      help="pinned eval batch size (fixed-batch artifacts "
                      "pin their own)")
    p_qc.add_argument("--seed", type=int, default=0,
                      help="seed of the pinned eval batch")
    p_qc.add_argument("--max-abs-delta", type=float, default=None,
                      help="override the precision's max |delta| budget on "
                      "float outputs")
    p_qc.add_argument("--mean-abs-delta", type=float, default=None,
                      help="override the precision's mean |delta| budget")
    p_qc.add_argument("--min-iou", type=float, default=None,
                      help="override the precision's minimum mask IoU")
    p_qc.add_argument("--max-disagree", type=float, default=None,
                      help="override the precision's max class-disagreement "
                      "fraction")
    p_qc.add_argument("--allow-fingerprint-mismatch", action="store_true",
                      help="compare artifacts whose manifests carry "
                      "different source fingerprints (normally a hard fail: "
                      "the pair derives from different checkpoints)")
    p_qc.add_argument("--workdir", default=None,
                      help="telemetry ledger dir for the quant_check event "
                      "(default: the candidate dir)")

    p_ing = sub.add_parser(
        "records-ingest",
        help="fold captured traffic shards into a versioned training "
        "dataset: validate every candidate shard (full CRC re-read), dedup "
        "by content fingerprint against the dataset manifest, copy "
        "survivors in as train-*.tfrecord (+ .idx), bump the manifest "
        "version — idempotent (re-running is a ledgered no-op) and "
        "`fit --data-dir` can train on the result directly",
    )
    p_ing.add_argument("--capture-dir", required=True,
                       help="directory the serve-tier capture tee wrote "
                       "(walked recursively: per-replica subdirs merge)")
    p_ing.add_argument("--dataset-dir", required=True,
                       help="the versioned dataset root (dataset_manifest."
                       "json + train-*.tfrecord); created when missing")
    p_ing.add_argument("--prefix", default="train",
                       help="shard filename prefix (fit's split glob)")
    p_ing.add_argument("--workdir", default=None,
                       help="telemetry ledger dir for the records_ingest "
                       "event (default: the dataset dir)")
    p_ing.add_argument("--json", action="store_true",
                       help="print the ingest summary as JSON")

    p_fly = sub.add_parser(
        "flywheel",
        help="continuous-learning controller (loop/controller.py): watch a "
        "capture dir, ingest new traffic into the versioned dataset, and "
        "when the data-volume or drift trigger fires run the retrain "
        "command (everything after --), expecting it to train + "
        "--export-serving --auto-promote so its exit status is the "
        "promotion verdict — the full cycle ledgered as loop_trigger/"
        "loop_retrain/loop_promoted/loop_rejected events",
    )
    p_fly.add_argument("--capture-dir", required=True,
                       help="the serve-tier capture directory to ingest from")
    p_fly.add_argument("--dataset-dir", required=True,
                       help="versioned dataset the ingest step appends to "
                       "(and the retrain command should --data-dir)")
    p_fly.add_argument("--fleet-workdir", default=None,
                       help="the live fleet's workdir: scanned for "
                       "drift_alert events (the drift trigger) and the "
                       "default home of the flywheel's own ledger")
    p_fly.add_argument("--workdir", default=None,
                       help="flywheel telemetry ledger dir (default: "
                       "--fleet-workdir, written as a high-numbered "
                       "process ledger so telemetry-report merges it)")
    p_fly.add_argument("--min-new-records", type=int, default=256,
                       help="data-volume trigger: retrain once this many "
                       "new records accumulate since the last cycle; "
                       "0 disables (drift-only)")
    p_fly.add_argument("--no-drift-trigger", action="store_true",
                       help="ignore drift_alert events (volume-only)")
    p_fly.add_argument("--poll-secs", type=float, default=2.0,
                       help="ingest + trigger evaluation cadence")
    p_fly.add_argument("--max-cycles", type=int, default=None,
                       help="exit after this many retrain cycles (benches "
                       "and drills; default: run until signalled)")
    p_fly.add_argument("--max-wait-secs", type=float, default=None,
                       help="give up (exit 3) when no trigger fires for "
                       "this long")
    p_fly.add_argument("--cooldown-secs", type=float, default=0.0,
                       help="dwell after a cycle before the next trigger "
                       "may fire")
    p_fly.add_argument("retrain", nargs=argparse.REMAINDER,
                       help="the retrain command after `--`: CLI argv run "
                       "as a subprocess of this package's CLI (e.g. `-- fit "
                       "--preset elastic_smoke --data-dir DATASET "
                       "--export-serving --auto-promote --fleet-workdir W`)")

    sub.add_parser("presets", help="list the named BASELINE config presets")

    p_rep = sub.add_parser(
        "telemetry-report",
        help="render the goodput report from a workdir's telemetry.jsonl "
        "run ledger (+ xplane trace when one exists under it)",
    )
    p_rep.add_argument("workdir", nargs="?", default=None,
                       help="training workdir (model-dir) holding "
                       "telemetry.jsonl (+ telemetry-{i}.jsonl per extra "
                       "process/replica, merged automatically); optional "
                       "with --compare")
    p_rep.add_argument("--trace-dir", default=None,
                       help="xplane trace dir to merge (default: search the "
                       "workdir for *.xplane.pb)")
    p_rep.add_argument("--top", type=int, default=10,
                       help="device ops to list from the trace")
    p_rep.add_argument("--json", action="store_true",
                       help="machine-readable output")
    p_rep.add_argument("--export-trace", default=None, metavar="OUT_JSON",
                       help="instead of the report, export the last run's "
                       "sampled trace spans as Chrome/Perfetto trace-event "
                       "JSON (load in chrome://tracing or ui.perfetto.dev)")
    p_rep.add_argument("--straggler-threshold", type=float, default=None,
                       help="multi-host straggler alert threshold: a window "
                       "alerts when the slowest host's mean step time "
                       "exceeds this multiple of the fleet median "
                       "(default 1.25)")
    p_rep.add_argument("--registry-dir", default=None, metavar="DIR",
                       help="cross-run registry ({DIR}/runs.jsonl): "
                       "--register appends this workdir's summary row; "
                       "--compare operands may be registered run ids")
    p_rep.add_argument("--register", action="store_true",
                       help="append the workdir's run summary (config hash, "
                       "mesh, final metrics, goodput split, step-time "
                       "percentiles) to the registry and print the row")
    p_rep.add_argument("--compare", nargs=2, metavar=("RUN_A", "RUN_B"),
                       default=None,
                       help="instead of the report, emit structured "
                       "noise-aware deltas between two runs (workdir paths, "
                       "or registered run ids with --registry-dir): step "
                       "time, data/fetch wait, eval metrics, serving p99")

    p_top = sub.add_parser(
        "telemetry-top",
        help="live fleet console: a refreshing terminal view tailing the "
        "workdir's merged run ledgers (training goodput, serving backlog "
        "and p99, HBM headroom, chip-seconds cost rates, straggler and "
        "health flags); --once prints a single frame for scripts/CI",
    )
    p_top.add_argument("workdir",
                       help="the shared workdir whose telemetry.jsonl / "
                       "telemetry-{i}.jsonl ledgers to tail (a trainer's "
                       "model-dir or a serve/serve-fleet --workdir)")
    p_top.add_argument("--interval", type=float, default=2.0,
                       help="seconds between frame refreshes")
    p_top.add_argument("--once", action="store_true",
                       help="print one frame and exit (no screen clearing) — "
                       "the scripting/CI-smoke mode; an empty workdir "
                       "renders an honest 'no ledgers yet' frame, rc 0")

    p_idx = sub.add_parser(
        "records-index",
        help="write .idx count/offset sidecars for existing TFRecord shards "
        "(data/records.py write_shard_index) — new shards get them at "
        "write_classification_shards time; this backfills old datasets so "
        "count_records and the data service skip the full-file scan",
    )
    p_idx.add_argument("data_dir", help="directory holding *.tfrecord shards")
    p_idx.add_argument("--glob", default="*.tfrecord",
                       help="shard filename pattern (default: *.tfrecord)")

    p_doc = sub.add_parser(
        "doctor",
        help="diagnose the environment and (optionally) a dataset layout",
    )
    p_doc.add_argument("--data-dir", default=None,
                       help="dataset root to analyze: ImageFolder "
                       "({root}/train/{class}/*.png), record shards "
                       "({root}/train-*.tfrecord), or TGS-salt layout "
                       "({root}/images + {root}/masks)")
    p_doc.add_argument("--batch-size", type=int, default=None,
                       help="intended global batch: checked against the "
                       "device count and --grad-accum")
    p_doc.add_argument("--n-devices", type=int, default=None)
    p_doc.add_argument("--grad-accum", type=int, default=1)

    return parser


def _trainer(args):
    from tensorflowdistributedlearning_tpu.config import TrainConfig
    from tensorflowdistributedlearning_tpu.train.trainer import Trainer

    # host-loop overlap knobs only override when given; the TrainConfig
    # defaults are the single source of truth
    overlap = {}
    if getattr(args, "prefetch_depth", None) is not None:
        overlap["prefetch_depth"] = args.prefetch_depth
    if getattr(args, "dispatch_ahead", None) is not None:
        overlap["dispatch_ahead_steps"] = args.dispatch_ahead
    if getattr(args, "data_workers", None) is not None:
        overlap["data_service_workers"] = args.data_workers
    if getattr(args, "trace_sample_rate", None) is not None:
        overlap["trace_sample_rate"] = args.trace_sample_rate
    if getattr(args, "nan_guard", None) is not None:
        overlap["nan_guard"] = args.nan_guard
    if getattr(args, "profile_every_windows", None) is not None:
        overlap["profile_every_windows"] = args.profile_every_windows
    if getattr(args, "compile_cache_dir", None) is not None:
        overlap["compile_cache_dir"] = args.compile_cache_dir
    tcfg = TrainConfig(
        lr=getattr(args, "lr", 0.001),
        n_devices=args.n_devices,
        n_folds=args.n_fold,
        seed=args.seed,
        save_best=getattr(args, "save_best", 5),
        checkpoint_every_steps=getattr(args, "checkpoint_every", 500),
        eval_throttle_secs=getattr(args, "eval_throttle_secs", 300),
        sequence_parallel=getattr(args, "sequence_parallel", 1),
        model_parallel=getattr(args, "model_parallel", 1),
        sync_batch_norm=getattr(args, "sync_bn", False),
        weight_update_sharding=getattr(args, "weight_update_sharding", False),
        parallelism=getattr(args, "parallelism", None) or "explicit",
        hbm_budget_gb=getattr(args, "hbm_budget_gb", None),
        **overlap,
    )
    if tcfg.parallelism == "auto":
        # derive the layout BEFORE the Trainer builds its mesh; flags the
        # user set explicitly stay pinned (explicit flags win)
        import dataclasses

        from tensorflowdistributedlearning_tpu.config import ModelConfig
        from tensorflowdistributedlearning_tpu.parallel import multihost
        from tensorflowdistributedlearning_tpu.parallel import (
            planner as planner_lib,
        )

        multihost.initialize()
        mcfg = ModelConfig(
            backbone=args.backbone,
            input_shape=tuple(args.input_shape),
            n_blocks=tuple(args.n_blocks),
            base_depth=args.base_depth,
            block_type=args.block_type,
            dtype=args.dtype,
        )
        pinned = {}
        if getattr(args, "sequence_parallel", 1) != 1:
            pinned["sequence_parallel"] = args.sequence_parallel
        if getattr(args, "model_parallel", 1) != 1:
            pinned["model_parallel"] = args.model_parallel
        if getattr(args, "weight_update_sharding", False):
            pinned["weight_update_sharding"] = True
        run_plan = planner_lib.plan(
            mcfg, tcfg, args.batch_size, pinned=pinned, source="auto"
        )
        tcfg = dataclasses.replace(tcfg, **run_plan.overrides())
        plan_header = run_plan.header()
    else:
        plan_header = None
    return Trainer(
        args.model_dir,
        getattr(args, "data_dir", ""),
        train_config=tcfg,
        plan=plan_header,
        backbone=args.backbone,
        input_shape=tuple(args.input_shape),
        n_blocks=tuple(args.n_blocks),
        base_depth=args.base_depth,
        block_type=args.block_type,
        dtype=args.dtype,
    )


def _best_fold(results: List[dict]) -> int:
    """Index of the fold a deployment would serve: highest mean IOU, falling
    back to lowest loss for task metrics without one."""
    if any("metrics/mean_iou" in r for r in results):
        return max(
            range(len(results)),
            key=lambda i: results[i].get("metrics/mean_iou", float("-inf")),
        )
    return min(
        range(len(results)),
        key=lambda i: results[i].get("loss", float("inf")),
    )


def cmd_train(args) -> int:
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib

    trainer = _trainer(args)
    ids = pipeline_lib.discover_ids(args.data_dir)
    if not ids:
        print(f"No images found under {args.data_dir}/images", file=sys.stderr)
        return 1
    results = trainer.train(ids, batch_size=args.batch_size, steps=args.steps)
    out = {"folds": results, "n_params": trainer.params}
    if getattr(args, "export_serving", False) and results:
        fold = _best_fold(results)
        out["serving_fold"] = fold
        out["serving_artifact"] = _artifact_dir(trainer.export_serving(
            fold, serving_dtype=getattr(args, "serving_dtype", "float32")
        ))
        out["serving_dtype"] = getattr(args, "serving_dtype", "float32")
        _stamp_baseline(out["serving_artifact"])
        _attach_cache(args, out["serving_artifact"])
    print(json.dumps(out))
    if getattr(args, "auto_promote", False):
        if not out.get("serving_artifact"):
            print(
                "auto-promote: nothing exported — pass --export-serving",
                file=sys.stderr,
            )
            return 2
        return _auto_promote(args, out["serving_artifact"])
    return 0


def _artifact_dir(path: Optional[str]) -> Optional[str]:
    """Exporters return the serialized-module PATH; every consumer (stamp,
    promote, serve --artifact-dir) wants the artifact DIRECTORY."""
    if path and os.path.isfile(path):
        return os.path.dirname(path)
    return path


def _stamp_baseline(artifact_dir: Optional[str]) -> None:
    """Best-effort drift-baseline stamp on a fresh export: the serving
    tier's DriftMonitor needs the output distribution in the manifest, but
    a failed stamp must not fail the training run that produced the
    artifact."""
    if not artifact_dir:
        return
    from tensorflowdistributedlearning_tpu.serve.quant_check import (
        stamp_drift_baseline,
    )

    try:
        stamp_drift_baseline(artifact_dir)
    except Exception as e:  # noqa: BLE001 — the export must survive
        logging.getLogger(__name__).warning(
            "drift-baseline stamp failed for %s: %s", artifact_dir, e
        )


def _attach_cache(args, artifact_dir: Optional[str]) -> None:
    """With --compile-cache-dir set, ship the export's compiled bucket
    ladder beside the artifact (train/serving.py attach_compile_cache) so
    replicas loading it go ready without compiling. Best-effort: a failed
    attach costs replicas their warm start, never the export."""
    if not artifact_dir or not getattr(args, "compile_cache_dir", None):
        return
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    try:
        serving_lib.attach_compile_cache(artifact_dir)
    except Exception as e:  # noqa: BLE001 — the export must survive
        logging.getLogger(__name__).warning(
            "compile-cache attach failed for %s: %s", artifact_dir, e
        )


def _predict_from_artifact(args) -> int:
    """``predict --artifact-dir``: inference through the bucketed serve engine
    from a standalone exported artifact — no checkpoint plumbing, no model
    code, just the data path's preprocessing contract (normalize + Laplacian
    channel) replayed from the manifest."""
    import jax.numpy as jnp

    from tensorflowdistributedlearning_tpu.data import augment as augment_lib
    from tensorflowdistributedlearning_tpu.data import pipeline as pipeline_lib
    from tensorflowdistributedlearning_tpu.serve import InferenceEngine
    from tensorflowdistributedlearning_tpu.train import serving as serving_lib

    engine = InferenceEngine.from_artifact(args.artifact_dir)
    manifest = serving_lib.read_manifest(args.artifact_dir)
    nchw = manifest.get("data_format") == "NCHW"
    channels = manifest["input_shape"][1 if nchw else -1]

    test_ds = pipeline_lib.InMemoryDataset.from_directory(
        args.test_dir, with_masks=False
    )
    images = test_ds.images  # [N, H, W, 1] normalized
    if channels == 2:  # the segmentation contract: image + Laplacian channel
        images = np.asarray(augment_lib.add_laplace_channel(jnp.asarray(images)))
    if nchw:
        images = np.transpose(images, (0, 3, 1, 2))

    step = engine.max_batch_size
    chunks = [
        engine.infer(images[i : i + step]) for i in range(0, len(images), step)
    ]
    outputs = {
        k: np.concatenate([c[k] for c in chunks]) for k in chunks[0]
    }
    if args.submission and "mask" in outputs:
        from tensorflowdistributedlearning_tpu.data.kaggle import write_submission

        write_submission(args.submission, test_ds.ids, outputs["mask"])
    if args.output:
        np.savez(args.output, ids=np.asarray(test_ds.ids), **outputs)
        print(json.dumps({"written": args.output, "n": len(test_ds.ids)}))
    else:
        summary = {
            "n": len(test_ds.ids),
            "outputs": {k: list(v.shape) for k, v in outputs.items()},
            "bucket_hits": {str(b): n for b, n in engine.bucket_hits.items()},
        }
        if "mask" in outputs:
            summary["mean_mask_coverage"] = float(outputs["mask"].mean())
        print(json.dumps(summary))
    return 0


def cmd_predict(args) -> int:
    if getattr(args, "artifact_dir", None):
        return _predict_from_artifact(args)
    trainer = _trainer(args)
    pred = trainer.predict(
        args.test_dir, batch_size=args.batch_size, tta=not args.no_tta
    )
    if args.submission:
        from tensorflowdistributedlearning_tpu.data.kaggle import write_submission

        write_submission(args.submission, pred["ids"], pred["masks"])
    if args.output:
        np.savez(
            args.output,
            ids=np.asarray(pred["ids"]),
            probabilities=pred["probabilities"],
            masks=pred["masks"],
        )
        print(json.dumps({"written": args.output, "n": len(pred["ids"])}))
    else:
        coverage = float(pred["masks"].mean())
        print(json.dumps({"n": len(pred["ids"]), "mean_mask_coverage": coverage}))
    return 0


def cmd_smoke(args) -> int:
    """Synthetic segmentation training on whatever devices are visible."""
    import jax

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.data.synthetic import synthetic_batches
    from tensorflowdistributedlearning_tpu.models import build_model
    from tensorflowdistributedlearning_tpu.parallel import mesh as mesh_lib
    from tensorflowdistributedlearning_tpu.train import step as step_lib
    from tensorflowdistributedlearning_tpu.train.state import create_train_state

    # same tiny architecture the test suite standardizes on: a smoke run checks
    # wiring (mesh, SPMD step, metrics), not model capacity — and matching the
    # suite's canonical config lets one compiled executable serve both
    cfg = ModelConfig(
        input_shape=(32, 32), n_blocks=(1, 1, 1), base_depth=8, width_multiplier=0.0625
    )
    tcfg = TrainConfig(n_devices=args.n_devices)
    mesh = mesh_lib.make_mesh(args.n_devices)
    model = build_model(cfg)
    state = mesh_lib.replicate(
        create_train_state(
            model,
            step_lib.make_optimizer(tcfg),
            jax.random.PRNGKey(0),
            np.zeros((1, 32, 32, 2), np.float32),
        ),
        mesh,
    )
    train_step = step_lib.make_train_step(mesh, step_lib.SegmentationTask())
    first = last = None
    for batch in synthetic_batches(
        "segmentation", args.batch_size, steps=args.steps,
        input_shape=(32, 32), channels=2,
    ):
        state, metrics = train_step(state, mesh_lib.shard_batch(batch, mesh))
        scalars = step_lib.compute_metrics(jax.device_get(metrics))
        first = first if first is not None else scalars["loss"]
        last = scalars["loss"]
    print(json.dumps({
        "steps": args.steps,
        "devices": mesh_lib.data_parallel_degree(mesh),
        "first_loss": first,
        "last_loss": last,
    }))
    return 0


def cmd_fit(args) -> int:
    from tensorflowdistributedlearning_tpu.train.fit import fit_preset

    if (
        getattr(args, "coordinator_address", None) is not None
        or getattr(args, "num_processes", None) is not None
        or getattr(args, "process_id", None) is not None
    ):
        # explicit jax.distributed world (one host slot of an elastic pod, or
        # a hand-launched multi-host CPU/GPU run): must join BEFORE any jax
        # call initializes the backend — fit_preset's own initialize() is a
        # no-op once this has run
        from tensorflowdistributedlearning_tpu.parallel import multihost

        multihost.initialize(
            coordinator_address=args.coordinator_address,
            num_processes=args.num_processes,
            process_id=args.process_id,
        )
    result = fit_preset(
        args.preset,
        args.model_dir,
        compile_cache_dir=getattr(args, "compile_cache_dir", None),
        data_dir=args.data_dir,
        steps=args.steps,
        batch_size=args.batch_size,
        eval_every_steps=args.eval_every,
        sequence_parallel=args.sequence_parallel,
        sync_batch_norm=getattr(args, "sync_bn", False),
        model_parallel=args.model_parallel,
        pipeline_parallel=args.pipeline_parallel,
        pipeline_microbatches=args.pipeline_microbatches,
        expert_parallel=args.expert_parallel,
        weight_update_sharding=args.weight_update_sharding,
        optimizer=args.optimizer,
        lr=args.lr,
        eval_holdout_fraction=args.eval_holdout_fraction,
        augmentation=args.augmentation,
        ema_decay=args.ema_decay,
        grad_accum_steps=args.grad_accum,
        grad_clip_norm=args.grad_clip,
        prefetch_depth=args.prefetch_depth,
        dispatch_ahead_steps=args.dispatch_ahead,
        data_service_workers=args.data_workers,
        trace_sample_rate=args.trace_sample_rate,
        nan_guard=args.nan_guard,
        profile_every_windows=args.profile_every_windows,
        parallelism=args.parallelism,
        hbm_budget_gb=args.hbm_budget_gb,
        export_serving=(
            getattr(args, "serving_dtype", "float32")
            if getattr(args, "export_serving", False)
            else None
        ),
    )
    if result.serving_artifact:
        result.serving_artifact = _artifact_dir(result.serving_artifact)
        _stamp_baseline(result.serving_artifact)
        _attach_cache(args, result.serving_artifact)
    summary = {
        "preset": args.preset,
        "steps": result.steps,
        "n_params": result.n_params,
        "final_metrics": result.final_metrics,
    }
    if result.serving_artifact:
        summary["serving_artifact"] = result.serving_artifact
    print(json.dumps(summary))
    if getattr(args, "auto_promote", False):
        if not result.serving_artifact:
            print(
                "auto-promote: nothing exported — pass --export-serving",
                file=sys.stderr,
            )
            return 2
        return _auto_promote(args, result.serving_artifact)
    return 0


def cmd_plan(args) -> int:
    """Print the parallelism planner's candidate table (or the full JSON
    plan): how `--parallelism auto` would lay this model out on this
    topology, with exact predicted bytes/chip and a named reason for every
    rejected candidate. Exit status: 0 = a feasible layout exists, 1 = the
    planner found none (or the pinned spec is infeasible), 2 = usage."""
    import dataclasses

    from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig
    from tensorflowdistributedlearning_tpu.parallel import multihost
    from tensorflowdistributedlearning_tpu.parallel import planner as planner_lib

    multihost.initialize()
    if args.preset:
        from tensorflowdistributedlearning_tpu.configs import get_preset

        try:
            preset = get_preset(args.preset)
        except ValueError as e:
            print(f"plan: {e}", file=sys.stderr)
            return 2
        mcfg, tcfg = preset.model, preset.train
        batch = args.batch_size or preset.global_batch
    else:
        mcfg = ModelConfig(
            backbone=args.backbone,
            input_shape=tuple(args.input_shape),
            n_blocks=tuple(args.n_blocks),
            base_depth=args.base_depth,
            block_type=args.block_type,
            dtype=args.dtype,
            num_classes=args.num_classes,
        )
        tcfg = TrainConfig()
        batch = args.batch_size or 64
    replace = {"n_devices": args.n_devices}
    if args.grad_accum is not None:
        replace["grad_accum_steps"] = args.grad_accum
    if args.hbm_gb is not None:
        replace["hbm_budget_gb"] = args.hbm_gb
    # strip the preset's own layout: the table should show what AUTO would
    # pick, with only the flags the user passed pinned on top
    replace.update(
        model_parallel=1, pipeline_parallel=1, sequence_parallel=1,
        expert_parallel=1, weight_update_sharding=False,
    )
    tcfg = dataclasses.replace(tcfg, **replace)
    pinned = {
        key: value
        for key, value in (
            ("model_parallel", args.model_parallel),
            ("pipeline_parallel", args.pipeline_parallel),
            ("sequence_parallel", args.sequence_parallel),
            ("expert_parallel", args.expert_parallel),
            ("weight_update_sharding", args.weight_update_sharding),
        )
        if value is not None
    }
    margin = None
    if args.measured_margin_from:
        margin = planner_lib.measured_margin_from_workdir(
            args.measured_margin_from
        )
        if margin is None:
            print(
                f"plan: no measured watermark residual under "
                f"{args.measured_margin_from} (CPU backends ledger none) — "
                "planning without margin",
                file=sys.stderr,
            )
    measured_costs = None
    if args.measured_costs_from:
        measured_costs = planner_lib.measured_costs_from_workdir(
            args.measured_costs_from
        )
        if measured_costs is None:
            # same contract as telemetry-report on a missing ledger: rc 2
            # plus a one-line hint — measured costs were asked for and none
            # exist, so silently falling back would misprice every candidate
            print(
                f"plan: no op_roofline events under "
                f"{args.measured_costs_from} — run with "
                "--profile-every-windows N to ledger roofline captures, "
                "then re-plan",
                file=sys.stderr,
            )
            return 2
    try:
        result = planner_lib.plan(
            mcfg, tcfg, batch, pinned=pinned, measured_margin_bytes=margin,
            measured_costs=measured_costs,
        )
    except planner_lib.PlanError as e:
        print(f"plan: {e}", file=sys.stderr)
        return 1
    print(
        json.dumps(result.to_json())
        if args.json
        else planner_lib.render_plan_table(result)
    )
    return 0 if result.chosen.feasible else 1


def cmd_records_index(args) -> int:
    """Backfill ``.idx`` count/offset sidecars for on-disk record shards."""
    import glob as glob_lib
    import os

    from tensorflowdistributedlearning_tpu.data import records as records_lib

    paths = sorted(
        glob_lib.glob(os.path.join(args.data_dir, args.glob))
    )
    if not paths:
        print(f"no shards matching {args.glob!r} under {args.data_dir}",
              file=sys.stderr)
        return 1
    total = 0
    for path in paths:
        n = len(records_lib.write_shard_index(path))
        total += n
        print(f"{records_lib.shard_index_path(path)}: {n} record(s)")
    print(json.dumps({"shards": len(paths), "records": total}))
    return 0


def cmd_telemetry_report(args) -> int:
    """Goodput report from the run ledger(s) — throughput trend, step-time
    percentiles, data-wait/compile/eval time split, recompiles, the fleet
    merge for multi-process workdirs, top device ops when a trace exists
    (obs/report.py). Also the front door for the cross-run registry and
    run-vs-run compare (obs/compare.py)."""
    from tensorflowdistributedlearning_tpu.obs import compare as compare_lib
    from tensorflowdistributedlearning_tpu.obs.report import report_workdir

    try:
        if getattr(args, "compare", None):
            ref_a, ref_b = args.compare
            result = compare_lib.compare_workdirs(
                ref_a, ref_b, registry_dir=args.registry_dir
            )
            print(
                json.dumps(result)
                if args.json
                else compare_lib.render_compare(result)
            )
            return 0
        if args.workdir is None:
            print(
                "telemetry-report: a workdir is required unless --compare "
                "is given",
                file=sys.stderr,
            )
            return 2
        if getattr(args, "export_trace", None):
            from tensorflowdistributedlearning_tpu.obs.trace import (
                write_chrome_trace,
            )

            # fleet-aware: raises the no-ledger FileNotFoundError itself
            n = write_chrome_trace(args.workdir, args.export_trace)
            print(json.dumps({
                "written": args.export_trace,
                "span_events": n,
            }))
            return 0
        if getattr(args, "register", False):
            if not args.registry_dir:
                print(
                    "telemetry-report: --register requires --registry-dir",
                    file=sys.stderr,
                )
                return 2
            row = compare_lib.register_run(args.registry_dir, args.workdir)
            print(json.dumps(row))
            return 0
        kwargs = {}
        if getattr(args, "straggler_threshold", None) is not None:
            kwargs["straggler_threshold"] = args.straggler_threshold
        print(
            report_workdir(
                args.workdir,
                trace_dir=args.trace_dir,
                top=args.top,
                as_json=args.json,
                **kwargs,
            )
        )
    except FileNotFoundError as e:
        # a CI pipeline pointing at the wrong dir (or a run that never wrote
        # a ledger) must FAIL here, loudly — rc 2 + a one-line hint, never a
        # clean exit it can silently pass on
        print(f"telemetry-report: {e}", file=sys.stderr)
        return 2
    except ValueError as e:
        print(f"telemetry-report: {e}", file=sys.stderr)
        return 1
    return 0


def cmd_telemetry_top(args) -> int:
    """The live operator console (obs/top.py): tail the workdir's merged
    ledgers and refresh a one-screen fleet view; ``--once`` for scripting."""
    from tensorflowdistributedlearning_tpu.obs.top import top

    return top(args.workdir, interval_s=args.interval, once=args.once)


def cmd_serve(args) -> int:
    """Serve an exported artifact over HTTP: warm every bucket, run the
    micro-batcher behind /v1/predict, drain gracefully on SIGINT/SIGTERM.
    Request-path telemetry lands in {workdir}/telemetry.jsonl; render it with
    ``telemetry-report``. With ``--registry`` the replica loads EVERY model
    entry (its own engine + micro-batcher each) and routes requests by the
    payload's ``model`` key."""
    import os
    import signal

    from tensorflowdistributedlearning_tpu.serve.registry import (
        DEFAULT_MODEL,
        read_registry,
    )

    if not args.artifact_dir and not args.registry:
        print(
            "serve: one of --artifact-dir or --registry is required",
            file=sys.stderr,
        )
        return 2
    if args.visible_devices:
        # device placement must land BEFORE the accelerator runtime
        # initializes (the first jax import below): every runtime reads its
        # own variable, so export the mask under each spelling
        for var in (
            "CUDA_VISIBLE_DEVICES",
            "HIP_VISIBLE_DEVICES",
            "TPU_VISIBLE_CHIPS",
        ):
            os.environ[var] = args.visible_devices

    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.resilience import faults
    from tensorflowdistributedlearning_tpu.serve import (
        InferenceEngine,
        MicroBatcher,
        ServingServer,
        bind_ephemeral,
    )

    if getattr(args, "compile_cache_dir", None):
        # before the engines build: warmup must LOAD executables (the
        # artifact's shipped entries merge into this dir) instead of
        # compiling them — the load-not-compile replica path
        from tensorflowdistributedlearning_tpu.utils import compile_cache

        compile_cache.configure(args.compile_cache_dir)

    # every model this replica serves: (entry, fleet-default fallbacks
    # resolved). Single-artifact stays the one-entry degenerate case.
    entries = None
    if args.registry:
        registry = read_registry(
            os.path.dirname(os.path.abspath(args.registry)),
            path=args.registry,
        )
        entries = list(registry.models.values())
        if args.model:
            entries = [registry.entry(args.model)]
    # bind BEFORE telemetry: with --port 0 the kernel picks the port, and the
    # run header (written at Telemetry construction) must carry the REAL one
    # — it is how a fleet test/manager spawning N replicas learns each
    # endpoint without port races
    sock = bind_ephemeral(args.host, args.port)
    port = sock.getsockname()[1]
    workdir = (
        args.workdir
        or args.artifact_dir
        or os.path.dirname(os.path.abspath(args.registry))
    )
    run_info = {
        "kind": "serve",
        "replica": args.replica_id,
        "artifact_dir": args.artifact_dir,
        "buckets": list(args.buckets),
        "max_wait_ms": args.max_wait_ms,
        "queue_size": args.queue_size,
        "port": port,
        "endpoint": f"http://{args.host}:{port}",
    }
    if args.model:
        run_info["model"] = args.model
    if entries is not None:
        run_info["models"] = {e.name: e.version for e in entries}
    if args.visible_devices:
        run_info["visible_devices"] = args.visible_devices
    telemetry = Telemetry(
        workdir,
        trace_sample_rate=args.trace_sample_rate,
        # fleet contract: replica i>0 writes telemetry-{i}.jsonl, so N
        # replicas sharing one workdir leave per-replica ledgers the
        # telemetry-report merge attributes individually (obs/fleet.py)
        process_index=args.replica_id,
        run_info=run_info,
    )
    if getattr(args, "inject_fault", None):
        # the serving-tier drill seam: sigkill@N fires off the request path
        # (serve/server.py) — a replica that vanishes mid-soak, on schedule
        faults.install(args.inject_fault, seed=getattr(args, "seed", 0))
    # continuous-learning arms (loop/): both apply to the PRIMARY model only
    # — the same single-model rule as the promotion shadow tee
    capture = drift = None
    primary_dir = (
        args.artifact_dir if entries is None else entries[0].artifact_dir
    )
    if getattr(args, "capture_dir", None):
        from tensorflowdistributedlearning_tpu.loop.capture import (
            TrafficCapture,
        )

        capture = TrafficCapture(
            args.capture_dir,
            sample_fraction=args.capture_fraction,
            records_per_shard=args.capture_records_per_shard,
            quota_bytes=int(args.capture_quota_mb * (1 << 20)),
        )
    if getattr(args, "drift_threshold", None) is not None:
        from tensorflowdistributedlearning_tpu.obs import health as health_lib
        from tensorflowdistributedlearning_tpu.train import (
            serving as serving_lib,
        )

        baseline = serving_lib.read_manifest(primary_dir).get(
            "drift_baseline"
        )
        if not baseline:
            logging.getLogger(__name__).warning(
                "serve: --drift-threshold set but %s carries no "
                "drift_baseline — export with a current train/fit "
                "--export-serving (or promote through the controller) to "
                "stamp one; drift monitoring disabled",
                primary_dir,
            )
        else:
            try:
                drift = health_lib.DriftMonitor(
                    baseline,
                    threshold=args.drift_threshold,
                    min_requests=args.drift_min_requests,
                    sustain_windows=args.drift_sustain_windows,
                )
            except ValueError as e:
                logging.getLogger(__name__).warning(
                    "serve: drift monitoring disabled: %s", e
                )
    if entries is None:
        # single-artifact (possibly model-labelled, fleet-spawned) load
        engine = InferenceEngine.from_artifact(
            args.artifact_dir,
            buckets=args.buckets,
            registry=telemetry.registry,
            tracer=telemetry.tracer,
        )
        warmup_s = engine.warmup(
            telemetry=telemetry, budget=args.prewarm_buckets
        )
        batcher = MicroBatcher(
            engine,
            max_wait_ms=args.max_wait_ms,
            max_queue=args.queue_size,
            default_deadline_ms=args.default_deadline_ms,
        )
        server = ServingServer(
            engine,
            batcher,
            host=args.host,
            port=args.port,
            telemetry=telemetry,
            window_secs=args.window_secs,
            slo_p99_ms=args.slo_p99_ms,
            slo_error_budget=args.slo_error_budget,
            replica_id=args.replica_id,
            sock=sock,
            model=args.model or DEFAULT_MODEL,
            registry_version=args.model_version,
            capture=capture,
            drift_monitor=drift,
        )
        warmup_field = {str(b): s for b, s in warmup_s.items()}
        models_field = (
            {args.model: args.model_version or 1} if args.model else None
        )
    else:
        from tensorflowdistributedlearning_tpu.obs.metrics import (
            MetricsRegistry,
        )

        engines = []
        for i, entry in enumerate(entries):
            # one MetricsRegistry per tenant: the primary rides the
            # telemetry registry (legacy single-tenant metric names keep
            # meaning "the whole replica"), later tenants isolate theirs
            engines.append(
                InferenceEngine.from_artifact(
                    entry.artifact_dir,
                    buckets=entry.buckets or tuple(args.buckets),
                    registry=(
                        telemetry.registry if i == 0 else MetricsRegistry()
                    ),
                    tracer=telemetry.tracer,
                )
            )
        warmup_field = {}
        # warm the engines CONCURRENTLY (each ladder already compiles in
        # parallel; engines are independent executables), so a multi-tenant
        # replica goes ready in ~its slowest model's time, not the sum —
        # and arm the recompile detector once, strictly after EVERY engine:
        # no engine's warmup compiles are flagged as steady-state recompiles
        from concurrent.futures import ThreadPoolExecutor

        with ThreadPoolExecutor(
            max_workers=len(engines), thread_name_prefix="engine-warmup"
        ) as pool:
            futs = [
                pool.submit(
                    eng.warmup,
                    telemetry=telemetry,
                    budget=entry.prewarm_budget,
                    mark_warm=False,
                )
                for entry, eng in zip(entries, engines)
            ]
            for entry, fut in zip(entries, futs):
                warmup_field.update(
                    {f"{entry.name}/{b}": s for b, s in fut.result().items()}
                )
        telemetry.mark_warm()
        first = entries[0]
        batcher = MicroBatcher(
            engines[0],
            max_wait_ms=args.max_wait_ms,
            max_queue=args.queue_size,
            default_deadline_ms=args.default_deadline_ms,
        )
        server = ServingServer(
            engines[0],
            batcher,
            host=args.host,
            port=args.port,
            telemetry=telemetry,
            window_secs=args.window_secs,
            slo_p99_ms=(
                first.slo_p99_ms
                if first.slo_p99_ms is not None
                else args.slo_p99_ms
            ),
            slo_error_budget=(
                first.slo_error_budget
                if first.slo_error_budget is not None
                else args.slo_error_budget
            ),
            replica_id=args.replica_id,
            sock=sock,
            model=first.name,
            registry_version=first.version,
            capture=capture,
            drift_monitor=drift,
        )
        for entry, eng in zip(entries[1:], engines[1:]):
            server.add_model(
                entry.name,
                eng,
                MicroBatcher(
                    eng,
                    max_wait_ms=args.max_wait_ms,
                    max_queue=args.queue_size,
                    default_deadline_ms=args.default_deadline_ms,
                ),
                version=entry.version,
                slo_p99_ms=entry.slo_p99_ms,
                slo_error_budget=(
                    entry.slo_error_budget
                    if entry.slo_error_budget is not None
                    else 0.01
                ),
            )
        models_field = {e.name: e.version for e in entries}
    server.start()
    ready = {
        "serving": server.url,
        "port": server.port,
        "replica": args.replica_id,
        "buckets": list(server.engine.buckets),
        "warmup_s": warmup_field,
        "ledger": workdir,
    }
    if models_field:
        ready["models"] = models_field
    print(json.dumps(ready), flush=True)
    # resilience contract for the serving tier: SIGTERM = graceful drain
    server.install_signal_handlers((signal.SIGINT, signal.SIGTERM))
    try:
        server.wait()
    finally:
        server.shutdown()
        faults.uninstall()
    return 0


def cmd_serve_fleet(args) -> int:
    """The serving tier: N supervised replicas behind the queue-depth/p99
    router, with optional autoscaling — one SIGTERM drains the whole fleet.
    All ledgers (controller + replicas) land in one workdir; render the
    merged story with ``telemetry-report``."""
    import os
    import signal

    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve import (
        AutoscaleConfig,
        FleetConfig,
        ServeFleet,
        bind_ephemeral,
    )
    from tensorflowdistributedlearning_tpu.serve.registry import (
        RegistryError,
        read_registry,
        registry_path,
    )

    if not args.artifact_dir and not args.registry and not (
        args.workdir and os.path.exists(registry_path(args.workdir))
    ):
        print(
            "serve-fleet: one of --artifact-dir or --registry is required "
            "(or a registry.json in --workdir)",
            file=sys.stderr,
        )
        return 2
    try:
        if args.registry:
            registry = read_registry(
                os.path.dirname(os.path.abspath(args.registry)),
                path=args.registry,
            )
        else:
            # workdir registry.json is picked up automatically; a plain
            # --artifact-dir fleet synthesizes the implicit one-entry
            # registry (fully legacy behavior)
            registry = read_registry(
                args.workdir or args.artifact_dir,
                default_artifact_dir=args.artifact_dir,
            )
    except RegistryError as e:
        print(f"serve-fleet: {e}", file=sys.stderr)
        return 2
    # the fleet default artifact backs legacy replicas and rollback spawns;
    # with a registry and no --artifact-dir, the first entry's stands in
    default_artifact_dir = (
        args.artifact_dir or next(iter(registry.models.values())).artifact_dir
    )

    fault_specs = {}
    for item in args.replica_inject_fault or ():
        rid, _, spec = item.partition(":")
        if not spec or not rid.isdigit():
            print(
                f"serve-fleet: bad --replica-inject-fault {item!r} "
                "(expected ID:SPEC, e.g. 2:sigkill@200)",
                file=sys.stderr,
            )
            return 2
        fault_specs[int(rid)] = spec
    sock = bind_ephemeral(args.host, args.port)
    port = sock.getsockname()[1]
    workdir = args.workdir or args.artifact_dir or os.path.dirname(
        os.path.abspath(args.registry)
    )
    run_info = {
        "kind": "serve-fleet",
        "artifact_dir": default_artifact_dir,
        "replicas": args.replicas,
        "autoscale": not args.no_autoscale,
        "port": port,
        "endpoint": f"http://{args.host}:{port}",
    }
    if not registry.implicit:
        run_info["models"] = {
            name: e.version for name, e in registry.models.items()
        }
        if args.chip_budget is not None:
            run_info["chip_budget"] = args.chip_budget
    telemetry = Telemetry(workdir, run_info=run_info)
    fleet = ServeFleet(
        FleetConfig(
            artifact_dir=default_artifact_dir,
            workdir=workdir,
            registry=registry,
            buckets=tuple(args.buckets),
            max_wait_ms=args.max_wait_ms,
            queue_size=args.queue_size,
            window_secs=args.window_secs,
            default_deadline_ms=args.default_deadline_ms,
            slo_p99_ms=args.slo_p99_ms,
            slo_error_budget=args.slo_error_budget,
            max_restarts_per_replica=args.max_restarts_per_replica,
            fault_specs=fault_specs or None,
            capture_dir=getattr(args, "capture_dir", None),
            capture_fraction=getattr(args, "capture_fraction", 1.0),
            capture_quota_mb=getattr(args, "capture_quota_mb", 64.0),
            capture_records_per_shard=getattr(
                args, "capture_records_per_shard", 64
            ),
            drift_threshold=getattr(args, "drift_threshold", None),
            drift_min_requests=getattr(args, "drift_min_requests", 20),
            drift_sustain_windows=getattr(args, "drift_sustain_windows", 2),
            compile_cache_dir=getattr(args, "compile_cache_dir", None),
        ),
        router_host=args.host,
        router_sock=sock,
        telemetry=telemetry,
        autoscale=(
            None
            if args.no_autoscale
            else AutoscaleConfig(
                min_replicas=args.min_replicas,
                max_replicas=args.max_replicas,
                queue_high=args.queue_high,
                queue_low=args.queue_low,
                sustain=args.scale_sustain,
                cooldown_s=args.scale_cooldown_s,
            )
        ),
        autoscale_interval_s=args.autoscale_interval_s,
        poll_interval_s=args.poll_interval_s,
        window_secs=args.window_secs,
        chip_budget=args.chip_budget,
    )
    fleet.start(args.replicas)
    ready = {
        "router": fleet.url,
        "port": port,
        "replicas": [
            {"replica": rid, "endpoint": url}
            for rid, url in fleet.manager.endpoints()
        ],
        "autoscale": not args.no_autoscale,
        "ledger": workdir,
    }
    if not registry.implicit:
        ready["models"] = {
            name: e.version for name, e in registry.models.items()
        }
    print(json.dumps(ready), flush=True)
    fleet.install_signal_handlers((signal.SIGINT, signal.SIGTERM))
    try:
        fleet.wait()
    finally:
        fleet.shutdown()
        telemetry.close(kind="serve-fleet")
    return 0


def _resolve_router_url(router: Optional[str],
                        workdir: Optional[str]) -> Optional[str]:
    """Where the live fleet's router listens: ``router`` verbatim, or the
    ``endpoint`` of the last serve-fleet run header in ``workdir``'s ledger —
    the same merged-workdir contract everything else in the fleet rides."""
    if router:
        return router.rstrip("/")
    if not workdir:
        return None
    from tensorflowdistributedlearning_tpu.obs.ledger import read_ledger

    try:
        events = read_ledger(workdir)
    except (OSError, ValueError):
        return None
    for e in reversed(events):
        if e.get("event") == "run_header" and e.get("kind") == "serve-fleet":
            return (e.get("endpoint") or "").rstrip("/") or None
    return None


def _drive_promotion(url: str, payload: Dict, *, timeout: float = 600.0,
                     json_out: bool = False):
    """POST a start/abort to a live fleet's /admin/promotion and follow the
    phase history to a terminal state. Shared by ``promote`` and the
    ``--auto-promote`` path of train/fit (the flywheel's retrain leg).
    Returns ``(rc, final_status_or_None)``: rc 0 = complete, 1 = rolled
    back / refused / aborted / timed out, 2 = usage or connectivity."""
    import time as time_lib
    import urllib.error
    import urllib.request

    def call(method: str, body=None):
        req = urllib.request.Request(
            url + "/admin/promotion",
            data=json.dumps(body).encode() if body is not None else None,
            headers={"Content-Type": "application/json"},
            method=method,
        )
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read())

    try:
        status = call("POST", payload)
    except urllib.error.HTTPError as e:
        body = e.read().decode(errors="replace")
        print(f"promote: router answered {e.code}: {body}", file=sys.stderr)
        return 2, None
    except (OSError, ValueError) as e:
        print(f"promote: cannot reach router at {url}: {e}", file=sys.stderr)
        return 2, None

    terminal = ("complete", "rolled_back", "refused", "aborted", "idle")
    deadline = time_lib.monotonic() + timeout
    seen_phases = 0
    while True:
        history = status.get("history") or []
        if not json_out:
            for entry in history[seen_phases:]:
                detail = ", ".join(
                    f"{k}={v}"
                    for k, v in entry.items()
                    if k not in ("phase", "t") and v is not None
                )
                print(
                    f"promotion: {entry['phase']}"
                    + (f" ({detail})" if detail else ""),
                    flush=True,
                )
            seen_phases = len(history)
        if status.get("state") in terminal:
            break
        if time_lib.monotonic() >= deadline:
            print(
                f"promote: no terminal state after {timeout:.0f}s — "
                "the promotion is still running fleet-side; re-run to "
                "re-attach or pass --abort",
                file=sys.stderr,
            )
            return 1, status
        time_lib.sleep(0.5)
        try:
            status = call("GET")
        except (OSError, ValueError) as e:
            print(
                f"promote: lost the router mid-promotion: {e}",
                file=sys.stderr,
            )
            return 2, None
    if json_out:
        print(json.dumps(status))
    else:
        state = status.get("state")
        line = f"promotion {state}"
        if status.get("reason"):
            line += f": {status['reason']}"
        if status.get("artifacts"):
            line += f" — fleet artifacts: {status['artifacts']}"
        print(line, flush=True)
    return (0 if status.get("state") == "complete" else 1), status


def cmd_promote(args) -> int:
    """Drive a live fleet's promotion controller over /admin/promotion:
    start (or --abort), then follow the phase history until a terminal
    state. Exit status IS the verdict: 0 promoted, 1 rolled back / refused /
    aborted, 2 usage or connectivity errors."""
    import os

    if not args.abort and not args.candidate_dir:
        print(
            "promote: --candidate-dir is required (unless --abort)",
            file=sys.stderr,
        )
        return 2
    url = _resolve_router_url(args.router, args.workdir)
    if not url:
        print(
            "promote: no router found — pass --router URL, or --workdir "
            "pointing at a live serve-fleet's ledger dir",
            file=sys.stderr,
        )
        return 2

    if args.abort:
        payload = {"action": "abort"}
    else:
        payload = {
            "action": "start",
            "candidate_dir": os.path.abspath(args.candidate_dir),
        }
        if args.reference_dir:
            payload["reference_dir"] = os.path.abspath(args.reference_dir)
        if args.canary_inject_fault:
            payload["fault_spec"] = args.canary_inject_fault
        if args.model:
            payload["model"] = args.model
        for key in (
            "shadow_secs",
            "shadow_fraction",
            "shadow_min_requests",
            "shadow_max_secs",
            "shadow_min_iou",
            "shadow_max_disagree",
            "shadow_max_abs_delta",
            "shadow_max_mean_delta",
            "max_p99_ratio",
            "observe_secs",
        ):
            value = getattr(args, key, None)
            if value is not None:
                payload[key] = value
    rc, _ = _drive_promotion(
        url, payload, timeout=args.timeout, json_out=args.json
    )
    return rc


def _auto_promote(args, artifact_dir: str) -> int:
    """The ``--auto-promote`` tail of train/fit: hand the exported artifact
    to the live fleet's promotion controller and make the exit status the
    verdict. No ``reference_dir`` is sent — a retrained model carries a NEW
    source fingerprint, so the quantize-check pairing gate would refuse it;
    admission is manifest-parse, and the shadow compare (with the
    ``--promote-*`` bands) plus rollback is the real gate."""
    import os

    url = _resolve_router_url(
        getattr(args, "router", None), getattr(args, "fleet_workdir", None)
    )
    if not url:
        print(
            "auto-promote: no live fleet found — pass --router URL or "
            "--fleet-workdir pointing at the serve-fleet's ledger dir",
            file=sys.stderr,
        )
        return 2
    payload = {
        "action": "start",
        "candidate_dir": os.path.abspath(artifact_dir),
    }
    if getattr(args, "promote_model", None):
        payload["model"] = args.promote_model
    for flag, key in (
        ("promote_shadow_secs", "shadow_secs"),
        ("promote_min_requests", "shadow_min_requests"),
        ("promote_max_disagree", "shadow_max_disagree"),
        ("promote_max_abs_delta", "shadow_max_abs_delta"),
        ("promote_max_mean_delta", "shadow_max_mean_delta"),
        ("promote_min_iou", "shadow_min_iou"),
        ("promote_max_p99_ratio", "max_p99_ratio"),
    ):
        value = getattr(args, flag, None)
        if value is not None:
            payload[key] = value
    rc, status = _drive_promotion(
        url, payload, timeout=getattr(args, "promote_timeout", 600.0)
    )
    print(json.dumps({
        "auto_promote": True,
        "candidate_dir": os.path.abspath(artifact_dir),
        "state": (status or {}).get("state"),
        "rc": rc,
    }))
    return rc


def cmd_quantize_check(args) -> int:
    """Run the f32-vs-quantized accuracy gate (serve/quant_check.py) and
    ledger the verdict; exit status IS the gate."""
    from tensorflowdistributedlearning_tpu.obs import Telemetry
    from tensorflowdistributedlearning_tpu.serve.quant_check import (
        run_quant_check,
    )

    workdir = args.workdir or args.candidate_dir
    telemetry = Telemetry(
        workdir,
        run_info={
            "kind": "quant_check",
            "reference_dir": args.reference_dir,
            "candidate_dir": args.candidate_dir,
        },
    )
    try:
        result = run_quant_check(
            args.reference_dir,
            args.candidate_dir,
            batch_size=args.batch_size,
            seed=args.seed,
            thresholds={
                "max_abs_delta": args.max_abs_delta,
                "mean_abs_delta": args.mean_abs_delta,
                "min_iou": args.min_iou,
                "max_disagree": args.max_disagree,
            },
            allow_fingerprint_mismatch=args.allow_fingerprint_mismatch,
            telemetry=telemetry,
        )
    finally:
        telemetry.close()
    print(json.dumps(result))
    return 0 if result["passed"] else 1


def cmd_records_ingest(args) -> int:
    """One capture->dataset ingest pass (loop/ingest.py), ledgered as a
    ``records_ingest`` event. Idempotent: re-running over the same capture
    tree changes nothing (and says so)."""
    from tensorflowdistributedlearning_tpu.loop.ingest import ingest_shards
    from tensorflowdistributedlearning_tpu.obs import Telemetry

    telemetry = Telemetry(
        args.workdir or args.dataset_dir,
        run_info={
            "kind": "records-ingest",
            "capture_dir": args.capture_dir,
            "dataset_dir": args.dataset_dir,
        },
    )
    try:
        summary = ingest_shards(
            args.capture_dir,
            args.dataset_dir,
            prefix=args.prefix,
            telemetry=telemetry,
        )
    finally:
        telemetry.close(kind="records-ingest")
    if args.json:
        print(json.dumps(summary))
    else:
        print(
            f"ingest: dataset v{summary['version']} — "
            f"+{summary['new_shards']} shards "
            f"(+{summary['records_added']} records, "
            f"{summary['deduped']} duplicate, {summary['corrupt']} corrupt); "
            f"{summary['shards_total']} shards / "
            f"{summary['records_total']} records total"
        )
    return 0


def cmd_flywheel(args) -> int:
    """The continuous-learning daemon (loop/controller.py): ingest captured
    traffic, fire the retrain command on a data-volume or drift trigger,
    and let its --auto-promote exit status be the cycle's verdict."""
    import os
    import signal
    import subprocess

    from tensorflowdistributedlearning_tpu.loop.controller import (
        FLYWHEEL_PROCESS_INDEX,
        FlywheelConfig,
        FlywheelController,
    )
    from tensorflowdistributedlearning_tpu.obs import Telemetry

    retrain_argv = list(args.retrain or [])
    if retrain_argv and retrain_argv[0] == "--":
        retrain_argv = retrain_argv[1:]
    if not retrain_argv:
        print(
            "flywheel: no retrain command — append `-- fit --preset ... "
            "--data-dir DATASET --export-serving --auto-promote "
            "--fleet-workdir W`",
            file=sys.stderr,
        )
        return 2
    try:
        config = FlywheelConfig(
            capture_dir=args.capture_dir,
            dataset_dir=args.dataset_dir,
            fleet_workdir=(
                None if args.no_drift_trigger else args.fleet_workdir
            ),
            min_new_records=args.min_new_records,
            poll_secs=args.poll_secs,
            max_cycles=args.max_cycles,
            max_wait_secs=args.max_wait_secs,
            cooldown_secs=args.cooldown_secs,
        )
    except ValueError as e:
        print(f"flywheel: {e}", file=sys.stderr)
        return 2

    workdir = args.workdir or args.fleet_workdir or args.dataset_dir
    shared = args.fleet_workdir is not None and os.path.abspath(
        workdir
    ) == os.path.abspath(args.fleet_workdir)
    telemetry = Telemetry(
        workdir,
        # sharing the fleet's workdir: write a high-numbered per-process
        # ledger the report merges, NEVER the fleet controller's process-0
        # telemetry.jsonl
        process_index=FLYWHEEL_PROCESS_INDEX if shared else 0,
        run_info={
            "kind": "flywheel",
            "capture_dir": args.capture_dir,
            "dataset_dir": args.dataset_dir,
            "fleet_workdir": args.fleet_workdir,
            "retrain": retrain_argv,
        },
    )

    def retrain(trigger, ingest_summary):
        argv = [
            sys.executable, "-m", "tensorflowdistributedlearning_tpu",
            *retrain_argv,
        ]
        env = dict(os.environ)
        pkg_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env["PYTHONPATH"] = (
            pkg_root + os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH")
            else pkg_root
        )
        proc = subprocess.run(
            argv, capture_output=True, text=True, env=env, check=False
        )
        # the child's output is the cycle's audit trail — surface it
        sys.stdout.write(proc.stdout)
        sys.stderr.write(proc.stderr)
        result = {"rc": proc.returncode}
        # the retrain's JSON tail names the artifact: fit/train print
        # serving_artifact, the auto-promote verdict prints candidate_dir
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                obj = json.loads(line)
            except ValueError:
                continue
            if not isinstance(obj, dict):
                continue
            cand = obj.get("candidate_dir") or obj.get("serving_artifact")
            if cand:
                result["candidate_dir"] = cand
                break
        if result.get("candidate_dir"):
            try:
                from tensorflowdistributedlearning_tpu.train import (
                    serving as serving_lib,
                )

                manifest = serving_lib.read_manifest(result["candidate_dir"])
                result["fingerprint"] = (
                    manifest.get("quantization") or {}
                ).get("source_fingerprint")
            except (OSError, ValueError, KeyError):
                pass
        return result

    controller = FlywheelController(
        config, retrain_fn=retrain, telemetry=telemetry
    )

    def _on_signal(signum, frame):
        controller.stop()

    previous = {}
    for sig in (signal.SIGINT, signal.SIGTERM):
        previous[sig] = signal.signal(sig, _on_signal)
    try:
        rc = controller.run()
    finally:
        for sig, handler in previous.items():
            signal.signal(sig, handler)
        telemetry.close(kind="flywheel")
    print(
        json.dumps({
            "flywheel": True,
            "cycles": controller.cycles,
            "promoted": controller.promoted,
            "rejected": controller.rejected,
            "rc": rc,
        }),
        flush=True,
    )
    return rc


def cmd_presets(args) -> int:
    from tensorflowdistributedlearning_tpu.configs import PRESETS

    print(
        json.dumps(
            {
                name: {
                    "description": p.description,
                    "global_batch": p.global_batch,
                    "backbone": p.model.backbone,
                    "num_classes": p.model.num_classes,
                    "input_shape": list(p.model.input_shape),
                    "dtype": p.model.dtype,
                }
                for name, p in PRESETS.items()
            },
            indent=2,
        )
    )
    return 0


def cmd_doctor(args) -> int:
    """Environment + dataset diagnosis: one JSON report, no side effects
    beyond a lazy native-library build attempt. The closest reference
    analogue is `utils.get_available_gpus` (utils.py:6-8) — this covers the
    whole stack a training run depends on."""
    import glob
    import os

    report: dict = {"ok": True}

    def problem(msg: str) -> None:
        report["ok"] = False
        report.setdefault("problems", []).append(msg)

    # backend probe in a BOUNDED child: a down TPU tunnel makes jax.devices()
    # hang indefinitely in-process (observed on this environment for hours),
    # and a diagnosis tool that hangs on the most common failure is useless.
    # The child inherits the environment, so it probes the same backend the
    # training commands would use.
    import subprocess

    # Mirror utils/devices.apply_platform_env in the child: env vars alone are
    # too late once the axon sitecustomize has pre-imported jax, so route
    # JAX_PLATFORMS through jax.config before touching the backend. Without
    # this the probe initializes the tunnel platform even under
    # JAX_PLATFORMS=cpu and burns the full timeout.
    probe = (
        "import os, jax, json\n"
        "_p = os.environ.get('JAX_PLATFORMS')\n"
        "if _p:\n"
        "    try: jax.config.update('jax_platforms', _p)\n"
        "    except Exception: pass\n"
        "d = jax.devices(); "
        "print(json.dumps({'platform': jax.default_backend(), "
        "'n_devices': len(d), 'device_kind': d[0].device_kind, "
        "'process_count': jax.process_count()}))"
    )
    try:
        out = subprocess.run(
            [sys.executable, "-c", probe],
            capture_output=True,
            text=True,
            timeout=90,
        )
        lines = [ln for ln in out.stdout.splitlines() if ln.startswith("{")]
        if out.returncode == 0 and lines:
            report["backend"] = json.loads(lines[-1])
        else:
            problem(
                "backend probe failed: "
                + (out.stderr.strip().splitlines() or ["no output"])[-1][:200]
            )
            report["backend"] = {"error": "probe failed"}
    except subprocess.TimeoutExpired:
        problem(
            "backend init timed out after 90s — on this environment that "
            "means the TPU tunnel is down (jax.devices() hangs); retry "
            "later or force JAX_PLATFORMS=cpu"
        )
        report["backend"] = {"error": "init timeout (tunnel down?)"}

    from tensorflowdistributedlearning_tpu.data.records import _records_lib
    from tensorflowdistributedlearning_tpu.native import loader

    report["native"] = {
        "decode_io_cc": loader.native_available(),
        "records_cc": _records_lib() is not None,
    }
    for lib, present in report["native"].items():
        if not present:
            problem(
                f"native {lib} unavailable — the pure-Python fallback works "
                "but streams records/decodes images far slower (RECORDS_BENCH.json)"
            )

    n = args.n_devices or report["backend"].get("n_devices")
    if args.batch_size is not None and n is None:
        # Backend probe failed and the user gave no --n-devices: validating
        # divisibility against a guessed n=1 would bless batches the real
        # device count rejects. Report the section as unchecked instead.
        report["batch"] = {
            "global_batch": args.batch_size,
            "unchecked": "device count unknown (backend probe failed; "
            "pass --n-devices to check divisibility)",
        }
    elif args.batch_size is not None:
        batch: dict = {"global_batch": args.batch_size, "data_parallel": n}
        if args.batch_size % n:
            problem(
                f"batch {args.batch_size} not divisible by {n} devices "
                "(reference contract, model.py:156-159)"
            )
        elif args.grad_accum > 1 and (args.batch_size // n) % args.grad_accum:
            problem(
                f"per-shard batch {args.batch_size // n} not divisible by "
                f"grad_accum_steps={args.grad_accum}"
            )
        else:
            batch["per_shard"] = args.batch_size // n // args.grad_accum
        report["batch"] = batch

    if args.data_dir:
        d = args.data_dir
        data: dict = {"root": d}
        if not os.path.isdir(d):
            problem(f"data dir {d} does not exist")
        elif glob.glob(os.path.join(d, "train-*.tfrecord")):
            from tensorflowdistributedlearning_tpu.data import records as rec

            data["layout"] = "record-shards"
            for split in ("train", "val"):
                paths = sorted(
                    glob.glob(os.path.join(d, f"{split}-*.tfrecord"))
                )
                if not paths:
                    continue
                info = {"shards": len(paths)}
                try:
                    info["records"] = rec.count_records(paths)
                except ValueError as e:
                    problem(f"{split} shards corrupt: {e}")
                # like the batch check: when the probe failed the process
                # count is UNKNOWN — guessing 1 would bless a layout a real
                # multi-process run rejects; mark unchecked instead
                nproc = report["backend"].get("process_count")
                if split == "train" and nproc is None:
                    info["shards_per_process"] = "unchecked (backend probe failed)"
                elif split == "train" and len(paths) < nproc:
                    problem(
                        f"{len(paths)} train shards < {nproc} "
                        "processes — every process needs at least one"
                    )
                data[split] = info
        elif os.path.isdir(os.path.join(d, "train")):
            from tensorflowdistributedlearning_tpu.data import imagefolder

            data["layout"] = "imagefolder"
            try:
                ds = imagefolder.ImageFolder(
                    os.path.join(d, "train"), (32, 32), channels=3
                )
                data["train"] = {
                    "examples": len(ds),
                    "classes": ds.num_classes,
                }
            except Exception as e:  # noqa: BLE001 — report, don't crash
                problem(f"imagefolder scan failed: {e}")
        elif os.path.isdir(os.path.join(d, "images")):
            imgs = glob.glob(os.path.join(d, "images", "*.png"))
            masks = glob.glob(os.path.join(d, "masks", "*.png"))
            data["layout"] = "tgs-salt"
            data["images"], data["masks"] = len(imgs), len(masks)
            if len(imgs) != len(masks):
                problem(
                    f"{len(imgs)} images vs {len(masks)} masks — every "
                    "training image needs its mask"
                )
        else:
            problem(
                f"{d}: no recognized layout (expected train-*.tfrecord, "
                "train/{class}/, or images/ + masks/)"
            )
        report["data"] = data

    print(json.dumps(report, indent=2))
    return 0 if report["ok"] else 1


def _strip_flags(argv: List[str], names: List[str]) -> List[str]:
    """Remove ``--name VALUE`` / ``--name=VALUE`` (and bare ``--name`` for
    store-true flags whose next token is another flag) for every name in
    ``names``; everything else replays verbatim."""
    out: List[str] = []
    skip = False
    for token in argv:
        if skip:
            skip = False
            continue
        if token in names:
            skip = True
            continue
        if any(token.startswith(f"{name}=") for name in names):
            continue
        out.append(token)
    return out


def _strip_supervisor_flags(argv: List[str]) -> List[str]:
    """The child command the supervisor relaunches: this invocation minus
    ``--max-restarts`` (both ``--flag N`` and ``--flag=N`` forms) — every
    other flag, fault injection included, replays verbatim."""
    return _strip_flags(argv, ["--max-restarts"])


def _strip_elastic_flags(argv: List[str]) -> List[str]:
    """The child command the elastic coordinator launches: this invocation
    minus the coordinator-level knobs (children must never re-enter the
    coordinator), minus ``--max-restarts`` (the coordinator owns restarts),
    minus ``--batch-size``/``--inject-fault`` (re-issued per world size /
    per host slot)."""
    stripped = _strip_flags(argv, [
        "--elastic", "--min-hosts", "--devices-per-host", "--drain-timeout",
        "--evict-threshold", "--evict-sustained", "--evict-cooldown",
        "--host-inject-fault", "--max-restarts", "--batch-size",
        "--inject-fault",
    ])
    return [
        t for t in stripped
        if t not in ("--no-straggler-evict", "--aot-standby")
    ]


def _parse_host_faults(specs: List[str]) -> dict:
    """``--host-inject-fault HOST:SPEC`` entries -> {host_slot: fault_spec},
    validated eagerly (a typo'd drill must fail at parse time, not after the
    pod spawned)."""
    from tensorflowdistributedlearning_tpu.resilience import parse_fault_spec

    out = {}
    for item in specs:
        host, sep, spec = item.partition(":")
        if not sep or not host.isdigit() or not spec:
            raise SystemExit(
                f"fit: bad --host-inject-fault {item!r} (expected HOST:SPEC, "
                "e.g. 1:sigkill-step@6)"
            )
        parse_fault_spec(spec)  # raises ValueError on a bad spec
        out[int(host)] = spec
    return out


def _run_elastic(args, argv: List[str]) -> int:
    """``fit --elastic N``: re-exec this command as N host-slot child
    processes under the elastic coordinator (parallel/elastic.py). The
    GLOBAL batch scales with the world (per-host batch stays fixed, so the
    data-service sidecar re-validates across a resize and ZeRO-1 state
    reshards to the new dp); with ``--parallelism auto`` each generation's
    children re-derive their whole layout at the live world size, and the
    coordinator additionally ledgers the off-device what-if plan delta on
    every resize."""
    import os

    from tensorflowdistributedlearning_tpu.configs import get_preset
    from tensorflowdistributedlearning_tpu.parallel.elastic import (
        ElasticConfig,
        ElasticCoordinator,
    )
    from tensorflowdistributedlearning_tpu.resilience.supervisor import (
        shell_rc,
    )

    preset = get_preset(args.preset)
    hosts = args.elastic
    global_batch = args.batch_size or preset.global_batch
    if global_batch % hosts:
        raise SystemExit(
            f"fit: global batch {global_batch} not divisible by "
            f"--elastic {hosts} host(s)"
        )
    local_bs = global_batch // hosts
    host_faults = _parse_host_faults(args.host_inject_fault)
    base = _strip_elastic_flags(argv)

    def child_argv_fn(world, pid, coordinator, generation):
        child = [
            sys.executable, "-m", "tensorflowdistributedlearning_tpu",
            *base,
            "--batch-size", str(local_bs * world),
        ]
        if coordinator is not None:
            child += [
                "--coordinator-address", coordinator,
                "--num-processes", str(world),
                "--process-id", str(pid),
            ]
        if generation == 0 and pid in host_faults:
            child += ["--inject-fault", host_faults[pid]]
        return child

    _standby_scratch: dict = {}

    def standby_argv_fn(world, pid, coordinator):
        # One rank of the AOT standby mini-world: this same fit command,
        # pointed at a scratch workdir (shared by all standby ranks, like the
        # real pod shares --model-dir) with the next world's GLOBAL batch and
        # just enough steps to compile state-init + the train step. The
        # standby must be a rank-for-rank replica of the pod a resize would
        # spawn — cache keys bind the process-local backend topology, so
        # only rank p of a real `world`-process run writes the entry rank p
        # of the resized pod will load from --compile-cache-dir.
        import tempfile

        scratch = _standby_scratch.get(world)
        if scratch is None:
            scratch = tempfile.mkdtemp(prefix=f"tfdl-aot-standby-w{world}-")
            _standby_scratch[world] = scratch
        sb = _strip_flags(base, ["--model-dir", "--steps", "--eval-every"])
        sb = [t for t in sb if t not in ("--export-serving", "--auto-promote")]
        child = [
            sys.executable, "-m", "tensorflowdistributedlearning_tpu",
            *sb,
            "--model-dir", scratch,
            "--batch-size", str(local_bs * world),
            "--steps", "2",
            "--eval-every", "100000",
        ]
        if coordinator is not None:
            child += [
                "--coordinator-address", coordinator,
                "--num-processes", str(world),
                "--process-id", str(pid),
            ]
        return child

    aot_standby = bool(getattr(args, "aot_standby", False))
    if aot_standby and not getattr(args, "compile_cache_dir", None):
        print(
            "fit: --aot-standby needs --compile-cache-dir (the standby's "
            "compiles have nowhere to land) — standby disabled",
            file=sys.stderr,
        )
        aot_standby = False

    def plan_fn(world, measured_margin_bytes):
        # the coordinator's off-device what-if plan at the (new) world size:
        # a plain Topology, no devices touched — exactly the planner's
        # laptop-pod-planning contract. Children derive/validate their OWN
        # layout again when they start (--parallelism auto re-plans live).
        import jax

        from tensorflowdistributedlearning_tpu.parallel import (
            planner as planner_lib,
        )

        dph = args.devices_per_host or jax.local_device_count()
        budget = None
        if args.hbm_budget_gb:
            budget = int(args.hbm_budget_gb * (1 << 30))
        topo = planner_lib.Topology(
            n_devices=world * dph,
            local_device_count=dph,
            process_count=world,
            hbm_bytes_per_device=budget,
            device_kind=getattr(
                jax.devices()[0], "device_kind", jax.devices()[0].platform
            ),
        )
        # pin the layout flags the operator passed explicitly, so the what-if
        # plan describes the world the children will actually train (the
        # children re-validate/derive their own layout again at startup)
        pinned = {}
        if args.model_parallel != 1:
            pinned["model_parallel"] = args.model_parallel
        if args.pipeline_parallel != 1:
            pinned["pipeline_parallel"] = args.pipeline_parallel
        if args.sequence_parallel != 1:
            pinned["sequence_parallel"] = args.sequence_parallel
        if args.expert_parallel != 1:
            pinned["expert_parallel"] = args.expert_parallel
        if args.weight_update_sharding is not None:
            pinned["weight_update_sharding"] = args.weight_update_sharding
        return planner_lib.plan(
            preset.model,
            preset.train,
            local_bs * world,
            topology=topo,
            pinned=pinned,
            measured_margin_bytes=measured_margin_bytes,
        ).header()

    cfg = ElasticConfig(
        hosts=hosts,
        min_hosts=args.min_hosts,
        devices_per_host=args.devices_per_host,
        drain_timeout_s=args.drain_timeout,
        straggler_threshold=args.evict_threshold,
        straggler_sustained=(
            10**9 if args.no_straggler_evict else args.evict_sustained
        ),
        eviction_cooldown_s=args.evict_cooldown,
        # None (flag not given) = the elastic default of 3; an EXPLICIT 0
        # disables same-shape restarts (fail fast on deterministic crashes)
        max_restarts=3 if args.max_restarts is None else args.max_restarts,
        aot_standby=aot_standby,
        seed=getattr(args, "seed", 0),
    )
    child_env = dict(os.environ, TFDL_SUPERVISED_CHILD="1")
    try:
        result = ElasticCoordinator(
            child_argv_fn,
            args.model_dir,
            cfg,
            plan_fn=plan_fn,
            standby_argv_fn=standby_argv_fn if aot_standby else None,
            env=child_env,
        ).run()
    finally:
        # standby scratch workdirs hold throwaway checkpoints/ledgers; the
        # compiles they existed for are already in --compile-cache-dir
        for scratch in _standby_scratch.values():
            shutil.rmtree(scratch, ignore_errors=True)
    print(
        json.dumps(
            {
                "elastic": True,
                "ok": result.ok,
                "world_size": result.world_size,
                "resizes": result.resizes,
                "restarts": result.restarts,
                "evictions": result.evictions,
                "aborted": result.aborted,
                "final_step": result.final_step,
                "resize_downtime_s": result.resize_downtime_s,
                "post_resize_settle_s": result.post_resize_settle_s,
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    if result.ok:
        return 0
    return shell_rc(result.exit_code) or 1


def _run_supervised(args, argv: List[str]) -> int:
    """``train/fit --max-restarts N``: re-exec this command under the restart
    supervisor (resilience/supervisor.py), rooted at the model dir's run
    ledger for progress tracking and restart accounting."""
    import os

    from tensorflowdistributedlearning_tpu.resilience.supervisor import Supervisor

    # the env marker (checked in main()) makes supervisor recursion
    # structurally impossible even if a --max-restarts spelling survives the
    # argv strip (argparse accepts prefix abbreviations like --max-rest)
    child_env = dict(os.environ, TFDL_SUPERVISED_CHILD="1")
    result = Supervisor(
        [sys.executable, "-m", "tensorflowdistributedlearning_tpu",
         *_strip_supervisor_flags(argv)],
        workdir=args.model_dir,
        max_restarts=args.max_restarts,
        seed=getattr(args, "seed", 0),
        env=child_env,
    ).run()
    print(
        json.dumps(
            {
                "supervised": True,
                "ok": result.ok,
                "restarts": result.restarts,
                "aborted": result.aborted,
                "final_step": result.final_step,
                "downtime_s": result.downtime_s,
            }
        ),
        file=sys.stderr,
        flush=True,
    )
    if result.ok:
        return 0
    # a child killed by signal N reports rc=-N; surface the conventional
    # 128+N instead of a negative value the shell would fold mod 256
    from tensorflowdistributedlearning_tpu.resilience.supervisor import (
        shell_rc,
    )

    return shell_rc(result.exit_code) or 1


def main(argv: Optional[List[str]] = None) -> int:
    logging.basicConfig(level=logging.INFO)
    from tensorflowdistributedlearning_tpu.utils.devices import apply_platform_env

    apply_platform_env()
    raw_argv = list(argv) if argv is not None else sys.argv[1:]
    args = build_parser().parse_args(raw_argv)
    if args.command in ("train", "fit"):
        import os

        if getattr(args, "elastic", 0) > 0 and not os.environ.get(
            "TFDL_SUPERVISED_CHILD"
        ):
            return _run_elastic(args, raw_argv)
        if (getattr(args, "max_restarts", None) or 0) > 0 and not os.environ.get(
            "TFDL_SUPERVISED_CHILD"
        ):
            return _run_supervised(args, raw_argv)
        from tensorflowdistributedlearning_tpu.resilience import faults, preempt

        if getattr(args, "inject_fault", None):
            faults.install(args.inject_fault, seed=getattr(args, "seed", 0))
        # first SIGTERM/SIGINT: checkpoint at the next step boundary and exit
        # EXIT_PREEMPTED; a second signal kills immediately
        preempt.install(notice_file=getattr(args, "preempt_notice_file", None))
        from tensorflowdistributedlearning_tpu.obs.health import (
            HealthAbortError,
        )

        try:
            return {"train": cmd_train, "fit": cmd_fit}[args.command](args)
        except preempt.PreemptedError as e:
            print(
                json.dumps({"preempted": True, "step": e.step}), flush=True
            )
            return preempt.EXIT_PREEMPTED
        except HealthAbortError as e:
            # the NaN guard's abort action: the health_alert ledger event
            # precedes this exit; surface a structured verdict, not a
            # traceback
            print(
                json.dumps({"health_abort": True, "reason": str(e)}),
                flush=True,
            )
            return 1
        finally:
            # embedding callers (tests, notebooks) must not inherit the
            # process-global handler/injector past the command
            preempt.uninstall()
            faults.uninstall()
    return {
        "train": cmd_train,
        "predict": cmd_predict,
        "smoke": cmd_smoke,
        "fit": cmd_fit,
        "serve": cmd_serve,
        "serve-fleet": cmd_serve_fleet,
        "promote": cmd_promote,
        "quantize-check": cmd_quantize_check,
        "records-ingest": cmd_records_ingest,
        "flywheel": cmd_flywheel,
        "presets": cmd_presets,
        "plan": cmd_plan,
        "records-index": cmd_records_index,
        "telemetry-report": cmd_telemetry_report,
        "telemetry-top": cmd_telemetry_top,
        "doctor": cmd_doctor,
    }[args.command](args)


if __name__ == "__main__":
    raise SystemExit(main())
