"""Traffic capture tee: accepted serving requests -> record shards.

Generalizes the promotion controller's shadow duplication (serve/router.py)
from "mirror to a canary" to "persist as training data": a stride-sampled
subset of accepted ``/v1/predict`` requests is copied off the hot path into
the PR-12 record-shard format (``data/records.py`` framing, PNG payloads,
``.idx`` sidecars) under a bounded disk quota, self-labeled with the served
model's own argmax — the distillation-style signal the flywheel retrains on.

Hot-path contract, same as the shadow tee: ``maybe_capture`` only copies
the arrays and enqueues; PNG encode, framing, fsync and eviction all happen
on one background writer thread. A full queue DROPS the sample and counts
it (``tee_dropped`` in ``serve_window`` — capture loss is visible, never
silent). Sealed shards are installed atomically (tmp + ``os.replace``), so
an ingest scan never sees a half-written shard.
"""

from __future__ import annotations

import io
import logging
import os
import queue
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from tensorflowdistributedlearning_tpu.data import records as records_lib

logger = logging.getLogger(__name__)

CAPTURE_WINDOW_EVENT = "capture_window"

# sentinel that tells the writer thread to drain and exit
_STOP = object()


def to_uint8_image(arr: np.ndarray) -> np.ndarray:
    """Deterministic float->uint8 image conversion for PNG payloads.

    Serving inputs are normalized floats (standard-normal or [0,1] — the
    artifacts' pinned eval batches are standard-normal too); PNG wants
    uint8. [0,1] inputs scale by 255; anything else min-max scales per
    image. Pure function of the input array, so a captured record is
    byte-reproducible from the sample that produced it (the determinism
    contract tests/test_loop.py pins)."""
    arr = np.asarray(arr)
    if arr.dtype == np.uint8:
        return arr
    a = arr.astype(np.float64)
    if not np.all(np.isfinite(a)):
        raise ValueError("non-finite values in capture sample")
    lo, hi = float(a.min()), float(a.max())
    if 0.0 <= lo and hi <= 1.0:
        return np.round(a * 255.0).astype(np.uint8)
    if hi == lo:
        return np.zeros(a.shape, np.uint8)
    return np.round((a - lo) * (255.0 / (hi - lo))).astype(np.uint8)


def encode_example(image: np.ndarray, label: int) -> bytes:
    """One example -> one framed record payload: uint8 image as PNG behind
    ``encode_classification_record``. The single encode path shared by the
    writer thread and the determinism test — byte-identity holds because
    both run exactly this function."""
    from PIL import Image

    img = to_uint8_image(image)
    if img.ndim == 3 and img.shape[-1] == 1:
        img = img[..., 0]
    buf = io.BytesIO()
    Image.fromarray(img).save(buf, format="PNG")
    return records_lib.encode_classification_record(int(label), buf.getvalue())


def _label_array(outputs: Dict, n: int) -> np.ndarray:
    """Per-example self-labels from the served model's outputs: the first
    integer-valued output with one value per example (fit's serving_fn names
    it ``class``). No integer output -> label 0 for every example (the shard
    stays structurally valid; a later supervised join can relabel)."""
    for name in sorted(outputs):
        arr = np.asarray(outputs[name])
        if np.issubdtype(arr.dtype, np.integer) and arr.shape[:1] == (n,):
            return arr.reshape(n, -1)[:, 0] if arr.ndim > 1 else arr
    return np.zeros(n, np.int32)


class TrafficCapture:
    """The tee one serving replica arms (``serve --capture-dir``).

    Shards are named ``capture-{seq:05d}.tfrecord`` with ``.idx`` sidecars;
    ``records_per_shard`` examples seal a shard, ``close()`` seals a partial
    one. ``quota_bytes`` bounds sealed-shard disk use — over quota the
    OLDEST sealed shard is evicted first (the newest data is the most
    valuable to a retrain)."""

    def __init__(
        self,
        directory: str,
        *,
        sample_fraction: float = 1.0,
        records_per_shard: int = 64,
        quota_bytes: int = 64 << 20,
        queue_size: int = 256,
    ):
        if not 0.0 < sample_fraction <= 1.0:
            raise ValueError(
                f"sample_fraction must be in (0, 1], got {sample_fraction}"
            )
        if records_per_shard < 1:
            raise ValueError("records_per_shard must be >= 1")
        if quota_bytes < 1:
            raise ValueError("quota_bytes must be >= 1")
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self.records_per_shard = int(records_per_shard)
        self.quota_bytes = int(quota_bytes)
        self._stride = max(1, round(1.0 / sample_fraction))
        self._counter = 0
        self._queue: "queue.Queue" = queue.Queue(maxsize=queue_size)
        self._lock = threading.Lock()
        # window counters (drained by window_snapshot) + cumulative drops
        self._win: Dict[str, int] = self._zero_window()
        self.total_dropped = 0
        self.total_captured = 0
        self._pending: List[bytes] = []
        # (path, bytes) of sealed shards, oldest first — the eviction order
        self._sealed: List[Tuple[str, int]] = []
        # resume the sequence past shards a previous incarnation sealed (a
        # promotion restarts replicas into the same capture dir; starting at
        # 0 again would overwrite un-ingested data). Pre-existing shards are
        # NOT quota-tracked: this process never evicts data it did not write.
        self._seq = 1 + max(
            (
                int(f[len("capture-"):-len(".tfrecord")])
                for f in os.listdir(directory)
                if f.startswith("capture-")
                and f.endswith(".tfrecord")
                and f[len("capture-"):-len(".tfrecord")].isdigit()
            ),
            default=-1,
        )
        self._closed = False
        self._writer = threading.Thread(
            target=self._writer_loop, name="capture-writer", daemon=True
        )
        self._writer.start()

    @staticmethod
    def _zero_window() -> Dict[str, int]:
        return {
            "selected": 0,
            "captured": 0,
            "dropped": 0,
            "encode_failures": 0,
            "shards_sealed": 0,
            "shards_evicted": 0,
            "bytes_written": 0,
        }

    # -- hot path -------------------------------------------------------------

    def maybe_capture(self, instances: np.ndarray, outputs: Dict) -> None:
        """Stride-sample one ACCEPTED request; never blocks, never raises.
        Copies the batch (the caller's array goes back to the request pool)
        and enqueues for the writer thread; a full queue counts a drop."""
        with self._lock:
            self._counter += 1
            if self._counter % self._stride != 0 or self._closed:
                return
            self._win["selected"] += 1
        try:
            n = int(np.asarray(instances).shape[0])
            item = (np.array(instances, copy=True), _label_array(outputs, n))
        except Exception:  # noqa: BLE001 — a malformed output must not 500
            # the request that already answered successfully
            with self._lock:
                self._win["encode_failures"] += 1
            return
        try:
            self._queue.put_nowait(item)
        except queue.Full:
            with self._lock:
                self._win["dropped"] += 1
                self.total_dropped += 1

    # -- writer thread --------------------------------------------------------

    def _writer_loop(self) -> None:
        while True:
            item = self._queue.get()
            if item is _STOP:
                self._seal_pending()
                return
            images, labels = item
            for i in range(len(images)):
                try:
                    rec = encode_example(images[i], int(labels[i]))
                except Exception:  # noqa: BLE001 — one bad sample must not
                    # kill the writer for the replica's lifetime
                    with self._lock:
                        self._win["encode_failures"] += 1
                    continue
                self._pending.append(rec)
                with self._lock:
                    self._win["captured"] += 1
                    self.total_captured += 1
                if len(self._pending) >= self.records_per_shard:
                    self._seal_pending()

    def _seal_pending(self) -> None:
        if not self._pending:
            return
        path = os.path.join(self.directory, f"capture-{self._seq:05d}.tfrecord")
        self._seq += 1
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            records_lib.write_records(tmp, self._pending)
            os.replace(tmp, path)
            records_lib.write_shard_index(path)
        except OSError:
            logger.exception("capture shard seal failed: %s", path)
            self._pending = []
            return
        size = os.path.getsize(path)
        self._pending = []
        with self._lock:
            self._sealed.append((path, size))
            self._win["shards_sealed"] += 1
            self._win["bytes_written"] += size
        self._enforce_quota()

    def _enforce_quota(self) -> None:
        """Evict oldest-first until sealed bytes fit the quota (the newest
        shard always survives — evicting what was just written would make
        the tee a no-op at any quota below one shard)."""
        while True:
            with self._lock:
                total = sum(b for _, b in self._sealed)
                if total <= self.quota_bytes or len(self._sealed) <= 1:
                    return
                path, _ = self._sealed.pop(0)
                self._win["shards_evicted"] += 1
            for victim in (path, records_lib.shard_index_path(path)):
                try:
                    os.remove(victim)
                except FileNotFoundError:
                    pass

    # -- lifecycle / telemetry ------------------------------------------------

    def window_snapshot(self, drain: bool = True) -> Dict:
        """One ``capture_window`` record: this window's counters plus the
        live totals the report reads (cumulative drops stay visible even
        when every later window is clean)."""
        with self._lock:
            win = dict(self._win)
            if drain:
                self._win = self._zero_window()
            sealed_bytes = sum(b for _, b in self._sealed)
            out = {
                **win,
                "shards": len(self._sealed),
                "bytes_on_disk": sealed_bytes,
                "quota_bytes": self.quota_bytes,
                "total_captured": self.total_captured,
                "total_dropped": self.total_dropped,
            }
        return out

    def active(self) -> bool:
        with self._lock:
            return any(self._win.values()) or bool(self._pending)

    def close(self, timeout: float = 10.0) -> None:
        """Drain the queue, seal the partial shard, stop the writer. After
        close the tee drops silently-but-counted (the server may still be
        answering its last drained requests)."""
        with self._lock:
            if self._closed:
                return
            self._closed = True
        self._queue.put(_STOP)
        self._writer.join(timeout=timeout)

    def sealed_paths(self) -> List[str]:
        with self._lock:
            return [p for p, _ in self._sealed]
