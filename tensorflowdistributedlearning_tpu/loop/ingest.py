"""records-ingest: captured shards -> a versioned training dataset.

The bridge between the capture tee and the trainers: every sealed
``capture-*.tfrecord`` under a capture tree (replicas write per-replica
subdirectories) is CRC-validated record by record, content-fingerprinted,
and — when new — copied into the dataset directory under a ``train-``
prefixed, fingerprint-derived name the ``fit`` glob and the data service's
per-epoch shard re-deal (``data/service.py``) pick up directly.

``dataset_manifest.json`` is the dedup ledger and the version counter:
re-ingesting the same capture tree is a no-op (same fingerprints, same
version — idempotence is a tested contract), and the version bumps only
when the shard set actually changes, so a retrain can cite exactly which
dataset version it trained on. Manifest installs are atomic
(tmp + ``os.replace``); a torn ingest re-validates from the shards.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import shutil
import time
from typing import Dict, List, Optional

from tensorflowdistributedlearning_tpu.data import records as records_lib

logger = logging.getLogger(__name__)

INGEST_EVENT = "records_ingest"
MANIFEST_NAME = "dataset_manifest.json"


def read_dataset_manifest(dataset_dir: str) -> Dict:
    path = os.path.join(dataset_dir, MANIFEST_NAME)
    try:
        with open(path, encoding="utf-8") as f:
            manifest = json.load(f)
    except FileNotFoundError:
        return {"version": 0, "shards": [], "records_total": 0}
    if not isinstance(manifest.get("shards"), list):
        raise ValueError(f"{path}: malformed dataset manifest (no shard list)")
    return manifest


def _write_manifest(dataset_dir: str, manifest: Dict) -> None:
    path = os.path.join(dataset_dir, MANIFEST_NAME)
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(manifest, f, indent=2, sort_keys=True)
    os.replace(tmp, path)


def _discover_capture_shards(capture_dir: str) -> List[str]:
    """Every sealed capture shard under the tree, oldest-first per directory
    (seal order is encoded in the shard sequence number). Temp files from a
    mid-seal writer never match — installs are atomic renames."""
    found: List[str] = []
    for root, _dirs, files in os.walk(capture_dir):
        found.extend(
            os.path.join(root, f)
            for f in files
            if f.startswith("capture-") and f.endswith(".tfrecord")
        )
    return sorted(found)


def _validate_shard(path: str) -> Optional[Dict]:
    """Full CRC re-read + content fingerprint, or None when corrupt. The
    fingerprint hashes the RECORD PAYLOADS (not the file) so it is stable
    across framing rewrites and is the dedup identity."""
    digest = hashlib.md5()
    n = 0
    try:
        for rec in records_lib.read_records(path, verify=True):
            digest.update(rec)
            n += 1
    except (OSError, ValueError) as e:
        logger.warning("ingest: skipping corrupt shard %s: %s", path, e)
        return None
    if n == 0:
        return None
    return {
        "fingerprint": digest.hexdigest()[:16],
        "records": n,
        "bytes": os.path.getsize(path),
    }


def ingest_shards(
    capture_dir: str,
    dataset_dir: str,
    *,
    prefix: str = "train",
    telemetry=None,
) -> Dict:
    """One ingest pass; returns (and optionally ledgers) the summary.

    Accepted shards land as ``{prefix}-{fingerprint}.tfrecord`` + ``.idx``
    in ``dataset_dir`` — glob-compatible with ``fit --data-dir`` and
    deterministic, so the copy itself is idempotent too."""
    os.makedirs(dataset_dir, exist_ok=True)
    manifest = read_dataset_manifest(dataset_dir)
    seen = {s["fingerprint"] for s in manifest["shards"]}
    new_shards: List[Dict] = []
    deduped = corrupt = records_added = bytes_added = 0
    for path in _discover_capture_shards(capture_dir):
        info = _validate_shard(path)
        if info is None:
            corrupt += 1
            continue
        if info["fingerprint"] in seen:
            deduped += 1
            continue
        name = f"{prefix}-{info['fingerprint']}.tfrecord"
        dest = os.path.join(dataset_dir, name)
        tmp = f"{dest}.{os.getpid()}.tmp"
        shutil.copyfile(path, tmp)
        os.replace(tmp, dest)
        records_lib.write_shard_index(dest)
        entry = {
            **info,
            "name": name,
            "source": os.path.relpath(path, capture_dir),
            "ingested_t": round(time.time(), 3),
        }
        seen.add(info["fingerprint"])
        new_shards.append(entry)
        records_added += info["records"]
        bytes_added += info["bytes"]
    if new_shards:
        manifest["shards"].extend(new_shards)
        manifest["version"] = int(manifest.get("version", 0)) + 1
        manifest["records_total"] = sum(
            s["records"] for s in manifest["shards"]
        )
        _write_manifest(dataset_dir, manifest)
    summary = {
        "dataset_dir": dataset_dir,
        "capture_dir": capture_dir,
        "version": int(manifest.get("version", 0)),
        "new_shards": len(new_shards),
        "deduped": deduped,
        "corrupt": corrupt,
        "records_added": records_added,
        "bytes_added": bytes_added,
        "shards_total": len(manifest["shards"]),
        "records_total": int(manifest.get("records_total", 0)),
    }
    if telemetry is not None:
        # ledgered even when a no-op: "ingest ran and found nothing new" is
        # evidence the loop is alive, not an error to hide
        telemetry.event(INGEST_EVENT, **summary)
    return summary
