"""Continuous-learning flywheel: capture -> ingest -> drift -> retrain.

The loop that turns the serving stack into a learning system (ROADMAP
item 1). ``capture`` tees accepted traffic off the serving hot path into
record shards; ``ingest`` validates and dedups them into a versioned
dataset manifest; ``controller`` watches data volume and ``drift_alert``
events and fires ``fit --export-serving --auto-promote`` retrains, with
the promotion controller's admission + shadow-rollback as the safety net.
Every decision is ledgered (``loop_*`` events, docs/LEDGER_SCHEMA.md).
"""
