"""Flywheel controller: the supervised daemon that closes the loop.

One poll cycle: ingest whatever the capture tees sealed since last time,
then evaluate the two retrain triggers — enough new data
(``min_new_records``) or an unresolved ``drift_alert`` in the fleet's
ledger (obs/health.py DriftMonitor). A trigger fires ONE retrain through
the injected ``retrain_fn`` (the CLI wires a ``fit --export-serving
--auto-promote`` subprocess; tests inject a stub), whose exit status IS
the promotion verdict — the promotion controller's quantize-check
admission and shadow-compare rollback already guard the fleet, so the
flywheel never needs its own safety logic.

Every decision lands in the run ledger: ``loop_trigger`` -> ``loop_retrain``
-> ``loop_promoted`` | ``loop_rejected`` (docs/LEDGER_SCHEMA.md), the
history telemetry-report renders as the loop's audit trail.
"""

from __future__ import annotations

import dataclasses
import glob
import json
import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from tensorflowdistributedlearning_tpu.loop.ingest import ingest_shards

logger = logging.getLogger(__name__)

LOOP_TRIGGER_EVENT = "loop_trigger"
LOOP_RETRAIN_EVENT = "loop_retrain"
LOOP_PROMOTED_EVENT = "loop_promoted"
LOOP_REJECTED_EVENT = "loop_rejected"

# the flywheel's ledger slot when it shares the fleet's workdir: far above
# any replica id, so telemetry-{900}.jsonl never collides with a replica's
# per-process ledger and telemetry-report merges it like any other process
FLYWHEEL_PROCESS_INDEX = 900


@dataclasses.dataclass
class FlywheelConfig:
    capture_dir: str
    dataset_dir: str
    # where the serving fleet ledgers live — the drift_alert source; None
    # disables the drift trigger (volume-only loop)
    fleet_workdir: Optional[str] = None
    # data-volume trigger: newly ingested records since the last retrain;
    # 0 disables it (drift-only loop)
    min_new_records: int = 256
    poll_secs: float = 2.0
    # retrain cycles to run before exiting; None = run until signaled
    max_cycles: Optional[int] = None
    # give up when no trigger fires for this long SINCE THE LAST CYCLE
    # (or start) — the drill's "the loop must actually close" timeout
    max_wait_secs: Optional[float] = None
    cooldown_secs: float = 0.0

    def __post_init__(self):
        if self.min_new_records < 0:
            raise ValueError("min_new_records must be >= 0")
        if self.min_new_records == 0 and self.fleet_workdir is None:
            raise ValueError(
                "no trigger armed: min_new_records=0 disables the volume "
                "trigger and no fleet_workdir means no drift trigger"
            )
        if self.poll_secs <= 0:
            raise ValueError("poll_secs must be > 0")


def scan_drift_alerts(
    fleet_workdir: str, since_t: float = 0.0
) -> Optional[Dict]:
    """The newest UNRESOLVED ``drift_alert`` across every ledger in the
    fleet workdir (each replica writes its own telemetry-{i}.jsonl), newer
    than ``since_t``. A per-replica resolved alert retracts that replica's
    earlier firing; torn lines are skipped — readers ignore what they
    cannot parse, same as every other ledger consumer."""
    latest: Dict[str, Dict] = {}
    paths = glob.glob(os.path.join(fleet_workdir, "telemetry*.jsonl"))
    for path in sorted(paths):
        try:
            with open(path, encoding="utf-8") as f:
                for line in f:
                    if '"drift_alert"' not in line:
                        continue
                    try:
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if e.get("event") == "drift_alert":
                        latest[path] = e
        except OSError:
            continue
    live = [
        e
        for e in latest.values()
        if not e.get("resolved") and e.get("t", 0.0) > since_t
    ]
    return max(live, key=lambda e: e.get("t", 0.0)) if live else None


class FlywheelController:
    """``run()`` drives poll cycles until ``max_cycles`` retrains completed,
    ``max_wait_secs`` passed without a trigger, or ``stop()``.

    ``retrain_fn(trigger, ingest_summary) -> dict`` runs one retrain and
    must return at least ``{"rc": int}``; ``candidate_dir``/``fingerprint``
    keys ride into the verdict events when present. Exit status: 0 when
    every cycle promoted (and at least one ran), 1 when any retrain was
    rejected, 3 when the loop timed out without a single trigger."""

    def __init__(
        self,
        config: FlywheelConfig,
        *,
        retrain_fn: Callable[[Dict, Dict], Dict],
        telemetry=None,
        ingest_fn: Callable = ingest_shards,
    ):
        from tensorflowdistributedlearning_tpu.obs.telemetry import (
            NULL_TELEMETRY,
        )

        self.config = config
        self.telemetry = telemetry if telemetry is not None else NULL_TELEMETRY
        self.retrain_fn = retrain_fn
        self.ingest_fn = ingest_fn
        self._stop = threading.Event()
        self.records_since_retrain = 0
        self.cycles = 0
        self.promoted = 0
        self.rejected = 0
        # drift alerts at or before this wall-clock time are consumed: a
        # retrain answers every alert that preceded it
        self._drift_handled_t = 0.0

    def stop(self) -> None:
        self._stop.set()

    # -- triggers -------------------------------------------------------------

    def _evaluate_trigger(self) -> Optional[Dict]:
        cfg = self.config
        if (
            cfg.min_new_records > 0
            and self.records_since_retrain >= cfg.min_new_records
        ):
            return {
                "reason": "data_volume",
                "records_new": self.records_since_retrain,
                "min_new_records": cfg.min_new_records,
            }
        if cfg.fleet_workdir is not None:
            alert = scan_drift_alerts(
                cfg.fleet_workdir, since_t=self._drift_handled_t
            )
            if alert is not None:
                return {
                    "reason": "drift",
                    "records_new": self.records_since_retrain,
                    "drift_score": alert.get("score"),
                    "drift_threshold": alert.get("threshold"),
                    "drift_alert_t": alert.get("t"),
                    "alert_id": alert.get("alert_id"),
                }
        return None

    # -- one retrain cycle ----------------------------------------------------

    def _retrain(self, trigger: Dict, ingest_summary: Dict) -> None:
        cfg = self.config
        self.telemetry.event(
            LOOP_TRIGGER_EVENT,
            dataset_version=ingest_summary.get("version"),
            records_total=ingest_summary.get("records_total"),
            **trigger,
        )
        t0 = time.monotonic()
        try:
            result = self.retrain_fn(trigger, ingest_summary) or {}
        except Exception as e:  # noqa: BLE001 — a retrain crash is a
            # rejected cycle, not a dead daemon
            logger.exception("flywheel retrain failed")
            result = {"rc": -1, "error": f"{type(e).__name__}: {e}"}
        duration_s = round(time.monotonic() - t0, 3)
        rc = int(result.get("rc", -1))
        fields = {
            "rc": rc,
            "duration_s": duration_s,
            "reason": trigger["reason"],
            "dataset_version": ingest_summary.get("version"),
        }
        for k in ("candidate_dir", "fingerprint", "error"):
            if result.get(k) is not None:
                fields[k] = result[k]
        self.telemetry.event(LOOP_RETRAIN_EVENT, **fields)
        verdict = dict(fields)
        verdict.pop("reason", None)
        if rc == 0:
            self.promoted += 1
            self.telemetry.event(LOOP_PROMOTED_EVENT, **verdict)
        else:
            self.rejected += 1
            self.telemetry.event(LOOP_REJECTED_EVENT, **verdict)
        self.cycles += 1
        self.records_since_retrain = 0
        # the retrain answers everything that came before it, including
        # alerts the retrain itself may have taken minutes to address
        self._drift_handled_t = time.time()
        if cfg.cooldown_secs > 0:
            self._stop.wait(cfg.cooldown_secs)

    # -- main loop ------------------------------------------------------------

    def run(self) -> int:
        cfg = self.config
        waiting_since = time.monotonic()
        while not self._stop.is_set():
            summary = self.ingest_fn(
                cfg.capture_dir, cfg.dataset_dir, telemetry=self.telemetry
            )
            self.records_since_retrain += summary.get("records_added", 0)
            trigger = self._evaluate_trigger()
            if trigger is not None:
                self._retrain(trigger, summary)
                waiting_since = time.monotonic()
                if cfg.max_cycles is not None and self.cycles >= cfg.max_cycles:
                    break
                continue
            if (
                cfg.max_wait_secs is not None
                and time.monotonic() - waiting_since > cfg.max_wait_secs
            ):
                logger.warning(
                    "flywheel: no trigger within %.1fs — giving up",
                    cfg.max_wait_secs,
                )
                return 3 if self.cycles == 0 else (1 if self.rejected else 0)
            self._stop.wait(cfg.poll_secs)
        if self.cycles == 0:
            return 3
        return 1 if self.rejected else 0
