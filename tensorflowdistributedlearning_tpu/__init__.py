"""tensorflowdistributedlearning_tpu — a TPU-native (JAX/XLA/Flax) re-design of the
capabilities of gf712/TensorflowDistributedLearning.

The reference is a TF1 tf.contrib-era multi-GPU (MirroredStrategy) K-fold training
harness for binary semantic segmentation (reference: model.py:27-136). This package
provides the same capabilities designed TPU-first:

- SPMD data parallelism over a `jax.sharding.Mesh` (reference: model.py:115-121 used
  per-GPU towers + NCCL; here gradients are `psum`-reduced over the ICI mesh inside a
  single `shard_map`-ped train step).
- Flax ResNet-v2-beta + DeepLabV3+-style segmentation head and a fixed Xception-41
  backbone (reference: core/resnet.py, core/xception.py).
- Lovász hinge loss and Kaggle-style thresholded mIOU metrics as fixed-shape,
  jittable ops (reference: core/losses.py, core/metric.py).
- On-device augmentation with per-image PRNG keys (reference:
  preprocessing/preprocessing.py did host-side tf.data with a graph-time numpy RNG bug).
- K-fold orchestration, Orbax checkpointing with best-k export, and TTA prediction
  (reference: model.py:138-255).
"""

import importlib.util as _ilu

from tensorflowdistributedlearning_tpu.utils import jaxcompat as _jaxcompat

_jaxcompat.install()

from tensorflowdistributedlearning_tpu.config import ModelConfig, TrainConfig  # noqa: E402

__version__ = "0.1.0"


def __getattr__(name):
    # lazy: the trainer pulls in the full model/data stack
    if name == "Model" and _ilu.find_spec(
        "tensorflowdistributedlearning_tpu.train.trainer"
    ):
        from tensorflowdistributedlearning_tpu.train.trainer import Model

        return Model
    raise AttributeError(name)

__all__ = [
    "ModelConfig",
    "TrainConfig",
    "__version__",
]
