"""Parameter counting (reference: model.py:444-445 computed ``n_params`` by summing
variable shapes inside model_fn; here it is a pure pytree fold usable any time)."""

from __future__ import annotations

from typing import Any

import jax
import numpy as np


def count_params(params: Any) -> int:
    """Total number of scalar parameters in a pytree."""
    return int(sum(np.prod(x.shape) for x in jax.tree.leaves(params) if hasattr(x, "shape")))
